"""Headline benchmark: candidate-policy evaluations/sec on the default trace.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

What is measured: the full reference workload (16 nodes x 8,152 pods,
reference: benchmarks/traces/csv/openb_pod_list_default.csv) evaluated for a
population of parametric scheduling policies as a single vmapped XLA
program — the unit of work the reference performs per candidate in its
ProcessPoolExecutor (reference: funsearch/funsearch_integration.py:30-64:
re-parse trace, deep-copy state, run the Python event loop, ~0.2 s/eval,
SURVEY.md §6). Baseline: the reference's best implied throughput on its own
benchmark, max_workers(8) / 0.2 s = 40 evals/s/host.

A fitness-parity gate runs first (first_fit == 0.4292 etc. to 1e-4 — the
table publishes 4 decimals and the device runs float32,
reference README.md:25-31 table); the benchmark refuses to report a number
from a simulator that disagrees with the reference.

Env knobs: FKS_BENCH_POP (population size, default 16 — the axon TPU tunnel
kills device executions past ~60 s, which caps the per-call batch), and
FKS_BENCH_REPS (timed repetitions, default 3).
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_EVALS_PER_SEC = 40.0  # reference: 8 workers / 0.2 s per eval
PARITY = {"first_fit": 0.4292, "best_fit": 0.4465, "funsearch_4901": 0.4901}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from fks_tpu.data import TraceParser
    from fks_tpu.models import parametric, zoo
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim.engine import SimConfig, simulate

    pop_size = int(os.environ.get("FKS_BENCH_POP", "16"))
    reps = int(os.environ.get("FKS_BENCH_REPS", "3"))
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); pop={pop_size} reps={reps}")

    wl = TraceParser().parse_workload()
    log(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods")

    # ---- parity gate (scores are float32 on device; 1e-4 absolute covers
    # the README's 4-digit reporting precision)
    for name, want in PARITY.items():
        got = float(simulate(wl, zoo.ZOO[name]()).policy_score)
        if abs(got - want) > 1e-4:
            log(f"PARITY FAIL {name}: got {got:.6f} want {want:.4f}")
            print(json.dumps({
                "metric": "candidate policy evaluations/sec (8152-pod trace)",
                "value": 0.0, "unit": "evals/s", "vs_baseline": 0.0,
                "error": f"fitness parity failed for {name}"}))
            return 1
        log(f"parity ok {name}: {got:.4f}")

    # ---- throughput: one vmapped program evaluating the whole population
    key = jax.random.PRNGKey(0)
    params = parametric.init_population(key, pop_size, noise=0.1)
    ev = make_population_eval(wl, cfg=SimConfig())
    t0 = time.perf_counter()
    res = ev(params)
    jax.block_until_ready(res.policy_score)
    t_compile = time.perf_counter() - t0
    log(f"first call (compile+run): {t_compile:.1f}s; "
        f"scores [{float(jnp.min(res.policy_score)):.3f}, "
        f"{float(jnp.max(res.policy_score)):.3f}]")

    from fks_tpu.utils import ThroughputMeter, block_timed

    meter = ThroughputMeter()
    times = []
    for _ in range(reps):
        _, secs = block_timed(ev, params)
        times.append(secs)
        meter.add(pop_size, secs)
    best = min(times)
    evals_per_sec = pop_size / best
    log(f"steady-state: {best:.3f}s / {pop_size} evals; aggregate "
        f"{meter.summary()} (all reps: {[round(t, 3) for t in times]})")

    print(json.dumps({
        "metric": "candidate policy evaluations/sec (8152-pod trace)",
        "value": round(evals_per_sec, 2),
        "unit": "evals/s",
        "vs_baseline": round(evals_per_sec / BASELINE_EVALS_PER_SEC, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
