"""Headline benchmark: candidate-policy evaluations/sec on the default trace.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

What is measured: the full reference workload (16 nodes x 8,152 pods,
reference: benchmarks/traces/csv/openb_pod_list_default.csv) evaluated for a
population of parametric scheduling policies as vmapped XLA programs — the
unit of work the reference performs per candidate in its
ProcessPoolExecutor (reference: funsearch/funsearch_integration.py:30-64:
re-parse trace, deep-copy state, run the Python event loop, ~0.2 s/eval,
SURVEY.md §6). Baseline: the reference's best implied throughput on its own
benchmark, max_workers(8) / 0.2 s = 40 evals/s/host.

Protocol (each stage in its own subprocess so one wedged/killed device
call cannot take down the benchmark — the axon TPU tunnel kills device
executions over ~60 s and can leave the device wedged afterwards):

1. PARITY GATE (CPU subprocess): the exact engine (fks_tpu.sim.engine,
   bit-for-bit reference replica including the heap-layout-dependent retry
   rule) must reproduce first_fit/best_fit/funsearch_4901 fitness to 1e-4,
   and the flat engine's best_fit must land within 2e-2 (its one documented
   divergence is the retry-time rule; tests/test_flat_engine.py). Parity is
   backend-independent — running it on host CPU keeps the TPU for the
   throughput stage only (no extra device compiles to wedge).
2. THROUGHPUT (device subprocess, retried at a quarter of the chunk on
   failure):
   flat engine (fks_tpu.sim.flat), population evaluated in chunks sized to
   stay under the tunnel's kill window; the compiled program is reused by
   every chunk. Throughput = pop / best rep wall time (compile excluded).
   SimConfig.max_steps is capped at 4x pods for throughput lanes: a
   degenerate candidate that retries forever would otherwise hold every
   lane in its chunk to the 8x default budget; truncated lanes score 0
   exactly as documented in fks_tpu/sim/flat.py.

Env knobs: FKS_BENCH_POP (total population, default 512),
FKS_BENCH_CHUNK (per-device-call lanes, default 256),
FKS_BENCH_REPS (timed repetitions, default 2),
FKS_BENCH_ENGINE (auto|flat|exact|fused, default auto; "fused" = the
Pallas whole-loop-in-VMEM kernel, fks_tpu/sim/fused.py; "auto" tries
fused first and falls back to flat on any failure),
FKS_BENCH_DEADLINE_S (controller budget for ALL stages, default 1050 —
round 2's default of 2400 exceeded the driver's outer budget, so the
controller was SIGTERMed before its own deadline logic could emit the
fallback line; see also the signal write-ahead below),
FKS_RUN_DIR (flight-record the run: the controller writes stage results
as ``kind="bench_stage"`` metrics plus the headline into a fks_tpu.obs
run directory, renderable with ``python -m fks_tpu.cli report DIR``;
stage records carry ``compile_seconds`` — true XLA backend-compile time
from the jax.monitoring listener — separately from
``first_call_seconds``/``steady_state_seconds``).
3. CODE THROUGHPUT (device subprocess, best-effort): a generation of
   FakeLLM candidates lowered to VM register programs and run as one
   segmented batched launch — reported as ``code_evals_per_sec`` in the
   same JSON line (the apples-to-apples answer to the reference's ~40
   code-candidate evals/s/host). Runs sharded over the population mesh
   when >1 device is visible. Never fails the bench; falls back to the
   CURRENT round's session-recorded code measurement.

Stages run as ``python bench.py --stage
parity|throughput|codetput|budget|scale1k`` (argv, not env, so a leaked
variable can't turn the top-level run into a bare stage). The ``budget``
stage is standalone (not part of the controller's headline pipeline): it
measures the successive-halving eval-budget allocator
(fks_tpu.funsearch.budget) — pruned-vs-full device seconds per
generation at pop 64 x ``default8`` on the flat CPU engine — printing
``budget_speedup`` / ``budget_champion_match`` as its own JSON line,
gateable with ``--gate``. The ``scale1k`` stage is likewise standalone:
the large-cluster scale-tier headline (1k nodes x 100k synthetic pods
run to completion on the flat CPU engine with
``SimConfig.node_prefilter_k=64`` + ``state_pack`` and the
double-buffered segmented runner), printing ``scale1k_events_per_sec``
and a dense-vs-prefilter ``prefilter_speedup`` with a 1e-5
fitness-parity gate built in.

Fallback contract (round 6, revised round 14): when the device probe
fails, the CURRENT round's TPU-session measurement — never a prior
round's — rides along under ``banked_from`` with full provenance
(benchmarks/results/round*_tpu.jsonl, highest round number only). Round
5's variant promoted banked numbers into the headline unmarked, which a
prior round's stale file could silently feed. Round 14 reintroduces a
carried headline SAFELY: the last HEALTHY historical headline (via
fks_tpu.obs.history.RunHistory) fills ``value``/``vs_baseline`` with an
explicit ``stale_from_run`` provenance marker — obs.compare refuses a
stale candidate (stale is admissible as a baseline denominator only)
and obs.history marks stale records unhealthy, so a carried value can
neither win a regression gate nor chain into the next fallback. With no
healthy history either, ``value``/``vs_baseline`` stay 0.0.

Contract hardening (round 3): the controller installs SIGTERM/SIGINT/
SIGHUP handlers that print the fallback JSON line before exiting, so even
an outer `timeout`-style kill (BENCH_r02: rc=124, parsed:null) leaves one
parsable record on stdout. Only SIGKILL can now produce an empty record.

Failure taxonomy (round 7): when every probe attempt fails, the fallback
line additionally carries ``failure_taxonomy`` — per-attempt structured
records classified as timeout / sigill-risk (killed by signal) /
import-error / init-failure — so a post-mortem can tell a wedged tunnel
from a broken install without the stderr log. Each failed attempt is
also recorded as a ``probe_failure`` event when FKS_RUN_DIR is set.

Regression gating: ``python bench.py --gate BASELINE`` judges this run's
headline against a prior bench JSONL (or a flight-recorder run dir)
through fks_tpu.obs.compare; the verdict table goes to stderr, stdout
keeps the single-JSON-line contract, and a regression (default: >10%
evals/s drop) exits nonzero.
"""
import json
import os
import signal
import subprocess
import sys
import time

BASELINE_EVALS_PER_SEC = 40.0  # reference: 8 workers / 0.2 s per eval
PARITY = {"first_fit": 0.4292, "best_fit": 0.4465, "funsearch_4901": 0.4901}
METRIC = "candidate policy evaluations/sec (8152-pod trace)"

#: session stages whose result.evals_per_sec measures THIS metric (the
#: default 8,152-pod trace, parametric population). scale/scale100k run
#: synthetic traces and must not be banked as the headline.
_BANKABLE_STAGES = {"flat", "flatseed", "fused64", "fused256"}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


_RESULT_PRINTED = False


def _banked_measurement():
    """CURRENT-round session-recorded measurement of the headline metric.

    The TPU measurement session (tools/tpu_session.py) appends every
    stage result to benchmarks/results/round*_tpu.jsonl as it lands.
    When this bench run cannot reach the device (the axon tunnel wedges
    for hours at a time), the round's evidence still exists in that file
    — rounds 3 and 4 both recorded 0.0 headlines while holding live
    same-round measurements (VERDICT r4 weak #1). Only the HIGHEST round
    number's file is scanned: a prior round's number is that round's
    evidence, not this one's, and surfacing it as if current overstated
    the fallback in round 5. Returns ``(headline_record, code_record)``
    — the best parametric-population evals/s and the best code-candidate
    evals/s from the current round's file — either possibly None.
    """
    import glob
    import re
    results = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "results")

    def _round_no(p):
        m = re.search(r"round(\d+)_tpu\.jsonl$", os.path.basename(p))
        return int(m.group(1)) if m else -1

    paths = glob.glob(os.path.join(results, "round*_tpu.jsonl"))
    current = max((_round_no(p) for p in paths), default=-1)
    if current < 0:
        return None, None

    best = code_best = None
    for path in (p for p in paths if _round_no(p) == current):
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict) or not rec.get("ok"):
                continue
            res = rec.get("result") or {}
            src = {"file": os.path.basename(path), "stage": rec.get("stage"),
                   "ts": rec.get("ts")}
            if (rec.get("stage") in _BANKABLE_STAGES
                    and isinstance(res.get("evals_per_sec"), (int, float))):
                v = float(res["evals_per_sec"])
                if best is None or v > best["value"]:
                    best = {"value": v, **src,
                            "truncated": res.get("truncated")}
            # vmbatch partial rows land as stage vmbatch_pop{N}
            cv = res.get("code_evals_per_sec", rec.get("code_evals_per_sec"))
            if isinstance(cv, (int, float)) and cv > 0:
                if code_best is None or float(cv) > code_best["value"]:
                    code_best = {"value": float(cv), **src}
    return best, code_best


def _fallback_json(error: str, failure_taxonomy=None) -> str:
    """The benchmark's single-JSON-line contract, error form. A failed
    probe measured nothing THIS run, so the headline carries the last
    HEALTHY historical headline under an explicit ``stale_from_run``
    marker (module docstring, round 14) — downstream consumers that must
    not treat it as live (obs.compare candidates, obs.history health)
    key off that marker. The current round's session-recorded
    measurement, when one exists, rides along UNDER ``banked_from`` with
    full provenance. With neither, ``value``/``vs_baseline`` stay 0.0.

    This runs inside the kill-signal write-ahead handler, so both
    lookups are fully guarded: a filesystem race (or a half-installed
    fks_tpu import) there must not cost the single-JSON-line contract
    the handler exists to keep."""
    try:
        banked, code_banked = _banked_measurement()
    except Exception:  # noqa: BLE001 — contract over provenance
        banked = code_banked = None
    try:
        from fks_tpu.obs.history import RunHistory
        root = os.environ.get("FKS_BENCH_RESULTS_DIR") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "results")
        stale = RunHistory(root).last_healthy_headline()
    except Exception:  # noqa: BLE001 — contract over provenance
        stale = None
    payload = {"metric": METRIC, "value": 0.0, "unit": "evals/s",
               "vs_baseline": 0.0, "error": error}
    if failure_taxonomy:
        # structured per-attempt probe failures (kind: timeout /
        # sigill-risk / import-error / init-failure) — the last error
        # string alone erased WHICH way the device went away
        kinds = {}
        for a in failure_taxonomy:
            kinds[a["kind"]] = kinds.get(a["kind"], 0) + 1
        payload["failure_taxonomy"] = {"kinds": kinds,
                                       "attempts": failure_taxonomy}
    if stale is not None:
        payload["value"] = round(float(stale["value"]), 2)
        payload["vs_baseline"] = round(
            float(stale["value"]) / BASELINE_EVALS_PER_SEC, 3)
        payload["stale_from_run"] = stale
        # the donor's memory budgets ride along top-level so the budget
        # trend stays populated across a failed probe; the stale marker
        # keeps them baseline-only in obs.compare (candidate side skips)
        for key in ("peak_device_bytes", "exe_temp_bytes"):
            if key in stale:
                payload[key] = stale[key]
    if banked is not None:
        payload["banked_from"] = banked
    if stale is not None:
        payload["note"] = ("no live probe this run; headline carried "
                           "forward from the last healthy historical run "
                           "(stale_from_run provenance) — NOT a live "
                           "measurement")
    elif banked is not None:
        payload["note"] = ("no live probe this run; the current round's "
                           "session measurement is reported under "
                           "banked_from only")
    else:
        payload["note"] = ("no live measurement this run, no healthy "
                           "historical headline, and no recorded session "
                           "measurement in the current round's "
                           "benchmarks/results/round*_tpu.jsonl")
    if code_banked is not None:
        payload["code_banked_from"] = code_banked
    return json.dumps(payload)


def _print_result(line: str) -> None:
    """Print the result line with the handled kill signals BLOCKED, so
    there is no window in which the flag and the print disagree: before
    this call a kill writes the fallback, after it a kill writes nothing.
    (Flag-before-print risked a half-written only record; flag-after-print
    risked a 0.0 fallback line AFTER a complete success line, which the
    take-last-parsable-line driver would prefer.)"""
    global _RESULT_PRINTED
    mask = {signal.SIGTERM, signal.SIGINT, signal.SIGHUP}
    try:
        old = signal.pthread_sigmask(signal.SIG_BLOCK, mask)
    except (AttributeError, OSError, ValueError):  # non-main thread
        old = None
    try:
        print(line, flush=True)
        _RESULT_PRINTED = True
    finally:
        if old is not None:
            signal.pthread_sigmask(signal.SIG_SETMASK, old)


_RECORDER = None


def _controller_recorder():
    """Best-effort flight recorder for the controller when FKS_RUN_DIR is
    set. Lazy and fully guarded: importing fks_tpu pulls jax (package
    init), which the controller otherwise never does — and a broken
    recorder must never cost the single-JSON-line contract."""
    run_dir = os.environ.get("FKS_RUN_DIR", "")
    if not run_dir:
        return None
    try:
        from fks_tpu.obs.recorder import FlightRecorder
        return FlightRecorder(run_dir, meta={"command": "bench.py",
                                             "argv": sys.argv[1:]})
    except Exception as e:  # noqa: BLE001 — contract over telemetry
        log(f"FKS_RUN_DIR flight recorder disabled: {e}")
        return None


def _record(method: str, *a, **kw) -> None:
    """Guarded call on the controller recorder (no-op when absent)."""
    if _RECORDER is not None:
        try:
            getattr(_RECORDER, method)(*a, **kw)
        except Exception:  # noqa: BLE001 — contract over telemetry
            pass


def _fail(error: str, failure_taxonomy=None) -> int:
    _print_result(_fallback_json(error, failure_taxonomy))
    _record("annotate_meta", error=error)
    _record("finish", "error")
    _record("close")
    return 1


def _install_kill_writeahead():
    """If the controller is killed (outer timeout's SIGTERM, Ctrl-C, hangup)
    before it printed its result line, print the fallback JSON first —
    BENCH_r02 ended rc=124 with parsed:null precisely because the round-2
    controller had no answer to an external kill."""
    def handler(signum, frame):  # noqa: ARG001
        if not _RESULT_PRINTED:
            # os.write, not print: the buffered stdout writer may be
            # mid-write in the interrupted frame; a leading newline
            # guarantees this record starts its own line
            line = _fallback_json(
                f"controller killed by signal {signum} "
                "before completion (outer timeout?)")
            try:
                os.write(sys.stdout.fileno(), f"\n{line}\n".encode())
            except OSError:
                pass
        sys.exit(128 + signum)
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        try:
            if signal.getsignal(sig) is signal.SIG_IGN:
                continue  # keep nohup/detached immunity
            signal.signal(sig, handler)
        except (ValueError, OSError):  # non-main thread / unsupported
            pass


def _classify_probe_failure(returncode, stderr: str):
    """Structured failure taxonomy for one probe attempt (round-7: the
    fallback JSON previously carried only the LAST error string, erasing
    whether the probe timed out, crashed on a signal, or never imported):

    - ``timeout``       — subprocess exceeded its deadline (wedged tunnel)
    - ``sigill-risk``   — killed by a signal (negative returncode): the
                          classic symptom of an ISA mismatch / SIGILL or
                          an OOM SIGKILL, either of which would also kill
                          the throughput stage
    - ``import-error``  — jax (or a transitive dep) failed to import
    - ``init-failure``  — imported fine, backend initialization raised
    """
    if returncode is None:
        return "timeout", "device backend initialization timed out"
    if returncode < 0:
        sig = -returncode
        try:
            name = signal.Signals(sig).name
        except ValueError:
            name = str(sig)
        return "sigill-risk", f"probe killed by signal {name}"
    tail = (stderr or "")[-2000:]
    if "ImportError" in tail or "ModuleNotFoundError" in tail:
        return "import-error", "jax import failed in probe subprocess"
    return "init-failure", f"backend initialization failed (rc={returncode})"


def _probe_backend(budget_s: int):
    """The axon TPU tunnel can WEDGE (hang indefinitely) after a killed
    device execution; backend init then blocks forever. Probe device
    discovery in a subprocess so a wedged tunnel yields an error JSON
    instead of a hung benchmark. Wedges drain when the remote side
    finishes the orphaned execution, so retry while the budget lasts.
    ALL attempts and inter-attempt sleeps stay inside ``budget_s`` (the
    controller promises the driver a JSON line within its deadline).
    Returns ``(error, platform, attempts)``: (None, "tpu"/"cpu"/...,
    [...]) when healthy, (error string, None, [...]) otherwise —
    ``attempts`` is the structured per-attempt failure record
    (``{"attempt", "kind", "detail"}``, see ``_classify_probe_failure``)
    that rides into the fallback JSON and the flight recorder."""
    deadline = time.monotonic() + budget_s
    last = None
    attempt = 0
    attempts = []
    while True:
        remaining = deadline - time.monotonic()
        if remaining < 10:
            break
        attempt += 1
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=min(120, remaining), capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            kind, detail = _classify_probe_failure(None, "")
            last = f"{detail} (wedged tunnel?)"
            attempts.append({"attempt": attempt, "kind": kind,
                             "detail": last})
            _record("event", "probe_failure", attempt=attempt, kind=kind,
                    detail=last)
            log(f"backend probe attempt {attempt}: {last}")
            continue
        if r.returncode != 0:
            kind, detail = _classify_probe_failure(r.returncode, r.stderr)
            last = detail
            attempts.append({"attempt": attempt, "kind": kind,
                             "detail": detail})
            _record("event", "probe_failure", attempt=attempt, kind=kind,
                    detail=detail, rc=r.returncode)
            log(f"backend probe attempt {attempt} [{kind}] "
                f"rc={r.returncode}:\n{r.stderr[-2000:]}")
            time.sleep(max(0, min(30, deadline - time.monotonic())))
            continue
        plat = r.stdout.strip().splitlines()[-1] if r.stdout.strip() else ""
        return None, plat, attempts
    return (last or "backend probe budget exhausted"), None, attempts


# ---------------------------------------------------------------- stages


def _cost_estimates(fn, *args) -> dict:
    """XLA's static cost model for the jitted ``fn`` at these args:
    {"cost_flops": ..., "cost_bytes_accessed": ...}. AOT-only (lower →
    compile → cost_analysis), so it reuses the already-compiled program
    and costs no extra device time. Anything missing — a host-loop
    wrapper with no ``.lower``, a backend that doesn't publish the
    analysis — degrades to {} with a log line, never an error."""
    try:
        cost = fn.lower(*args).compile().cost_analysis()
    except Exception as e:  # noqa: BLE001 — estimates are best-effort
        log(f"cost_analysis unavailable: {type(e).__name__}: {e}")
        return {}
    # older jax returns a list of per-program dicts, newer a single dict
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for key, name in (("flops", "cost_flops"),
                      ("bytes accessed", "cost_bytes_accessed")):
        v = cost.get(key)
        if v is not None:
            out[name] = float(v)
    return out


def _memory_estimates(fn, *args, exe_key: str = "") -> dict:
    """Compiled-program memory footprint for the jitted ``fn`` at these
    args: {"peak_live_bytes": ..., "temp_bytes": ...}. Peak live =
    arguments + outputs + temporaries as reported by XLA's
    ``memory_analysis()`` — the compile-time answer to "does this shape
    fit", which CompileWatcher (a timing listener) cannot provide. Same
    AOT / degrade-to-{} contract as ``_cost_estimates``.

    The same two numbers also land under the budget-gate vocabulary
    (``peak_device_bytes``/``exe_temp_bytes`` — obs.compare judges both
    as must-not-regress), and when ``exe_key`` is set the executable is
    filed in the footprint ledger under component "bench"."""
    try:
        compiled = fn.lower(*args).compile()
        mem = compiled.memory_analysis()
    except Exception as e:  # noqa: BLE001 — estimates are best-effort
        log(f"memory_analysis unavailable: {type(e).__name__}: {e}")
        return {}
    out = {}
    try:
        temp = int(getattr(mem, "temp_size_in_bytes"))
        live = temp + int(getattr(mem, "argument_size_in_bytes")) \
            + int(getattr(mem, "output_size_in_bytes"))
    except (AttributeError, TypeError) as e:
        log(f"memory_analysis fields unavailable: {e}")
        return {}
    out["peak_live_bytes"] = live
    out["temp_bytes"] = temp
    out["peak_device_bytes"] = live
    out["exe_temp_bytes"] = temp
    if exe_key:
        try:
            from fks_tpu.obs.memory import record_footprint
            record_footprint("bench", exe_key, compiled)
        except Exception as e:  # noqa: BLE001 — ledger is best-effort
            log(f"footprint ledger unavailable: {e}")
    return out


def _ledger_budget_keys(*components: str) -> dict:
    """``peak_device_bytes``/``exe_temp_bytes`` out of the in-process
    footprint ledger (obs.memory): the largest predicted claim among the
    stage's compiled executables — serve engines file every AOT build
    there, so the stage payload carries the budget-gate vocabulary
    without re-lowering anything. Empty dict when nothing was filed
    (backend without memory_analysis)."""
    try:
        from fks_tpu.obs.memory import LEDGER
        recs = [r for r in LEDGER.records()
                if not components or r.get("component") in components]
    except Exception:  # noqa: BLE001 — budgets are best-effort
        return {}
    if not recs:
        return {}
    return {"peak_device_bytes": max(int(r.get("total_bytes", 0))
                                     for r in recs),
            "exe_temp_bytes": max(int(r.get("temp_bytes", 0))
                                  for r in recs)}


def stage_parity(engine: str) -> int:
    """CPU subprocess: exact-engine parity gate + flat-engine sanity."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data import TraceParser
    from fks_tpu.models import zoo
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import simulate

    wl = TraceParser().parse_workload()
    log(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods")
    for name, want in PARITY.items():
        got = float(simulate(wl, zoo.ZOO[name]()).policy_score)
        if abs(got - want) > 1e-4:
            log(f"PARITY FAIL {name}: got {got:.6f} want {want:.4f}")
            return 1
        log(f"parity ok {name}: {got:.4f}")
    if engine in ("flat", "fused"):  # fused shares the flat semantics
        got = float(flat.simulate(wl, zoo.ZOO["best_fit"]()).policy_score)
        if abs(got - PARITY["best_fit"]) > 2e-2:
            log(f"FLAT SANITY FAIL best_fit: {got:.4f}")
            return 1
        log(f"flat sanity ok best_fit: {got:.4f} "
            f"(exact {PARITY['best_fit']})")
    return 0


def stage_throughput(pop: int, chunk: int, reps: int, engine: str) -> int:
    """Device subprocess: chunked population throughput. Prints one JSON
    line {"evals_per_sec": ..., "compile_seconds": ..., ...} on success —
    ``compile_seconds`` is the TRUE XLA backend-compile time observed by
    the jax.monitoring listener (fks_tpu.obs.CompileWatcher), distinct
    from ``first_call_seconds`` (cold call: trace + lower + compile + run)
    and ``steady_state_seconds`` (best timed rep, compile excluded). The
    payload also embeds a ``device_profile`` attribution record — the
    shared StageProfiler (fks_tpu.obs.profiler) carves the stage into
    setup / compile / h2d / steady with the compile split, pad-lane
    occupancy, and est_flops_per_sec folded in — which the controller
    carries into the headline payload."""
    import jax
    import numpy as np

    from fks_tpu.data import TraceParser
    from fks_tpu.models import parametric
    from fks_tpu.obs import CompileWatcher, StageProfiler
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim.engine import SimConfig

    watcher = CompileWatcher().install()
    prof = StageProfiler(scope="bench", watcher=watcher)
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"pop={pop} chunk={chunk} reps={reps} engine={engine}")

    with prof.stage("setup", engine=engine, pop=pop):
        wl = TraceParser().parse_workload()
        # 2x pods = the retry-free event count; 4x leaves headroom for
        # normal retry traffic (retry-heavy champions reach ~28k events)
        # while keeping one degenerate lane from holding its chunk to the
        # 8x default budget (truncated lanes score 0; module docstring).
        cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
        key = jax.random.PRNGKey(0)
        params = parametric.init_population(key, pop, noise=0.1)
        if engine == "fused":
            from fks_tpu.sim import fused
            ev = fused.make_fused_population_run(wl, cfg,
                                                 lanes=min(64, chunk))
        else:
            ev = make_population_eval(wl, cfg=cfg, engine=engine)

    with prof.stage("compile", chunk=chunk) as hc:
        res = ev(params[:chunk])
        hc.sync(res.policy_score)
    t_compile = hc.record["wall_seconds"]
    n_trunc = int(np.asarray(res.truncated).sum())
    log(f"first chunk (compile+run): {t_compile:.1f}s; scores "
        f"[{float(np.min(res.policy_score)):.3f}, "
        f"{float(np.max(res.policy_score)):.3f}]; truncated {n_trunc}/{chunk}")

    if engine == "fused":
        # the CPU parity gate never executes Mosaic-compiled code, so gate
        # the fused kernel here: a small same-device population must match
        # the XLA flat engine (exact trajectories; f32 accumulators to ulp)
        with prof.stage("fused-gate"):
            ncheck = min(8, chunk)
            ref = make_population_eval(wl, cfg=cfg, engine="flat")(
                params[:ncheck])
            got = ev(params[:ncheck])
        if not np.array_equal(np.asarray(got.scheduled_pods),
                              np.asarray(ref.scheduled_pods)) or \
           not np.allclose(np.asarray(got.policy_score),
                           np.asarray(ref.policy_score),
                           rtol=2e-5, atol=2e-5):
            log(f"FUSED GATE FAIL: fused {np.asarray(got.policy_score)} "
                f"vs flat {np.asarray(ref.policy_score)}; scheduled "
                f"{np.asarray(got.scheduled_pods)} vs "
                f"{np.asarray(ref.scheduled_pods)}")
            return 1
        log(f"fused-vs-flat device gate ok ({ncheck} candidates)")

    # chunks must share the compiled program: slice then pad the tail to
    # the chunk width instead of re-jitting a smaller batch. Built once,
    # outside the timed loop, so host concat/transfer isn't charged to
    # the throughput number.
    with prof.stage("h2d") as hb:
        host_params = np.asarray(params)
        batches = []
        for lo in range(0, pop, chunk):
            batch = host_params[lo:lo + chunk]
            if batch.shape[0] < chunk:
                batch = np.concatenate(
                    [batch, host_params[:chunk - batch.shape[0]]], axis=0)
            batches.append(jax.device_put(batch))
        hb.sync(batches)

    cost = _cost_estimates(ev, batches[0])
    launched = len(batches) * chunk
    times = []
    with prof.stage("steady", reps=reps, real_count=pop,
                    padded_count=launched,
                    pad_waste_fraction=round(1.0 - pop / launched, 4)) as hs:
        if cost.get("cost_flops"):
            # static per-chunk FLOPs x launches prices the steady stage
            hs.annotate(cost_flops=cost["cost_flops"] * len(batches) * reps)
        for _ in range(reps):
            t0 = time.perf_counter()
            # dispatch every chunk before blocking: executions queue on
            # the device back-to-back and the tunnel's per-call round trip
            # is paid once, not once per chunk
            scores = [ev(batch).policy_score for batch in batches]
            hs.sync(scores)
            times.append(time.perf_counter() - t0)
    best = min(times)
    log(f"steady-state: {best:.3f}s / {pop} evals "
        f"({[round(t, 3) for t in times]}); XLA backend compile "
        f"{watcher.backend_compile_seconds:.1f}s "
        f"({watcher.backend_compile_count} programs)")
    print(json.dumps({
        "evals_per_sec": pop / best,
        "compile_seconds": round(watcher.backend_compile_seconds, 3),
        "backend_compiles": watcher.backend_compile_count,
        "first_call_seconds": round(t_compile, 3),
        "steady_state_seconds": round(best, 3),
        # scale-tier knobs ride in every stage payload so rounds with
        # different SimConfig defaults stay comparable
        "node_prefilter_k": cfg.node_prefilter_k,
        "state_pack": cfg.state_pack,
        # static per-chunk XLA cost (flops / bytes) for the compiled eval
        **cost,
        # per-stage device-time attribution (setup/compile/h2d/steady with
        # the compile split, pad-lane occupancy and est_flops_per_sec);
        # the controller carries it into the headline payload
        "device_profile": prof.summary(),
    }))
    return 0


def stage_codetput() -> int:
    """Device subprocess: CODE-candidate throughput — a generation of
    FakeLLM candidates lowered to VM register programs on the host
    (``vm.lower_fake_candidates``, the shared candidate source with the
    TPU session's vmbatch stage) and evaluated as one segmented batched
    launch, SHARDED over the population mesh when more than one device is
    visible (the apples-to-apples answer to the reference's ~40
    evals/s/host ProcessPool fan-out, reference:
    funsearch/funsearch_integration.py:535-562). Prints one JSON line
    {"code_evals_per_sec": ...}."""
    import jax
    import numpy as np

    from fks_tpu.data import TraceParser
    from fks_tpu.funsearch import vm
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.parallel import (
        make_sharded_code_eval, pad_population, population_mesh,
    )
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig

    watcher = CompileWatcher().install()
    pop = int(os.environ.get("FKS_BENCH_CODE_POP", "32"))
    cap = 256
    wl = TraceParser().parse_workload()
    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    progs, _ = vm.lower_fake_candidates(
        wl.cluster.n_padded, wl.cluster.g_padded, 2 * pop, capacity=cap)
    if len(progs) < 2 * pop:
        log(f"only {len(progs)} VM-able candidates (need {2 * pop})")
        return 1
    # segmented either way: no single device call outlives the tunnel's
    # ~60 s execution kill window
    devices = jax.devices()
    if len(devices) > 1:
        mesh = population_mesh(devices)
        sharded = make_sharded_code_eval(wl, mesh, cfg=cfg,
                                         elite_k=min(8, pop),
                                         engine="flat", seg_steps=4096)

        def run(stacked):
            padded, real = pad_population(stacked, mesh)
            return sharded(padded, real)[0]

        mode = f"sharded over {len(devices)} devices"
    else:
        seg = flat.make_segmented_population_run(wl, vm.score_static, cfg,
                                                 seg_steps=4096)
        state0 = flat.initial_state(wl, cfg)

        def run(stacked):
            return seg(stacked, state0)

        mode = "vmap on 1 device"
    log(f"code throughput mode: {mode}")
    t0 = time.perf_counter()
    res = run(vm.stack_programs(progs[:pop], capacity=cap))
    jax.block_until_ready(res.policy_score)
    first_call = time.perf_counter() - t0
    log(f"first launch (compile+run): {first_call:.1f}s")
    batch = vm.stack_programs(progs[pop:2 * pop], capacity=cap)
    t0 = time.perf_counter()
    res = run(batch)
    jax.block_until_ready(res.policy_score)
    best = time.perf_counter() - t0
    n_trunc = int(np.asarray(res.truncated)[:pop].sum())
    log(f"steady-state: {best:.3f}s / {pop} code evals "
        f"(truncated {n_trunc}/{pop}); XLA backend compile "
        f"{watcher.backend_compile_seconds:.1f}s")
    if len(devices) > 1:
        padded, real = pad_population(batch, mesh)
        cost = _cost_estimates(sharded, padded, real)
    else:
        # seg is a segmented HOST loop, not a jitted callable — the
        # helper logs "no .lower" and returns {}
        cost = _cost_estimates(seg, batch, state0)
    print(json.dumps({
        "code_evals_per_sec": pop / best, "mode": mode,
        "compile_seconds": round(watcher.backend_compile_seconds, 3),
        "backend_compiles": watcher.backend_compile_count,
        "first_call_seconds": round(first_call, 3),
        "steady_state_seconds": round(best, 3),
        "node_prefilter_k": cfg.node_prefilter_k,
        "state_pack": cfg.state_pack,
        **cost,
    }))
    return 0


def stage_budget(gate: str = "") -> int:
    """CPU subprocess: successive-halving eval-budget headline — the same
    generation of lowered FakeLLM candidates evaluated twice through the
    batched VM suite tier (flat engine), once unbudgeted (everyone pays
    ``default8`` x full trace) and once through the rung ladder (probe =
    ``smoke3`` at a quarter of the trace event budget, top 1/eta
    advancing). Prints one JSON line with ``budget_speedup`` (full /
    pruned device seconds, steady-state — both paths warmed first so
    compiles are excluded), ``budget_champion_match`` (1.0 when the
    pruned run crowns the same champion as the full run — ties by score,
    not index), and ``steady_state_recompiles`` (backend compiles
    observed during the timed passes; nonzero means a rung broke the
    compile-once-per-bucket contract)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import vm
    from fks_tpu.funsearch.backend import CodeEvaluator
    from fks_tpu.funsearch.budget import BudgetConfig
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.scenarios import get_suite
    from fks_tpu.scenarios.robust import RobustConfig
    from fks_tpu.sim.engine import SimConfig

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    pop = int(os.environ.get("FKS_BENCH_BUDGET_POP", "64"))
    eta = int(os.environ.get("FKS_BENCH_BUDGET_ETA", "4"))
    # small synthetic workload: the stage times a RATIO on one shape, so
    # it doesn't need the 8152-pod trace's wall time to make its point.
    # 200 pods, not fewer: tiny pod streams tie fake candidates' scores
    # so heavily that probe ranking degenerates to noise
    wl = synthetic_workload(8, 200, seed=3)
    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    suite = get_suite("default8", wl)
    robust = RobustConfig()
    budget = BudgetConfig(schedule="halving", eta=eta,
                          probe_suite="smoke3",
                          probe_steps=max(1, cfg.max_steps // 4))
    progs, _ = vm.lower_fake_candidates(
        wl.cluster.n_padded, wl.cluster.g_padded, pop, capacity=256)
    if len(progs) < pop:
        log(f"only {len(progs)} VM-able candidates (need {pop})")
        return 1
    codes = [f"bench_budget_{i}" for i in range(pop)]
    log(f"budget stage: pop={pop} eta={eta} "
        f"probe=smoke3@{budget.probe_steps} steps, full=default8")

    full = CodeEvaluator(wl, cfg, engine="flat", suite=suite,
                         robust=robust, vm_batch=True)
    pruned = CodeEvaluator(wl, cfg, engine="flat", suite=suite,
                           robust=robust, budget=budget)

    # warm both paths: compiles land here, not in the timed passes
    t0 = time.perf_counter()
    full._run_vm_batch(progs)
    pruned._run_vm_batch_budget(progs, codes)
    log(f"warm-up (compile+run, both paths): "
        f"{time.perf_counter() - t0:.1f}s; XLA backend compile "
        f"{watcher.backend_compile_seconds:.1f}s "
        f"({watcher.backend_compile_count} programs)")
    compiles_warm = watcher.backend_compile_count

    t0 = time.perf_counter()
    results_full = full._run_vm_batch(progs)
    full_s = time.perf_counter() - t0
    full_scores = np.array(
        [full._record_suite(codes[i], results_full[i]).score
         for i in range(pop)])

    t0 = time.perf_counter()
    recs = pruned._run_vm_batch_budget(progs, codes)
    pruned_s = time.perf_counter() - t0
    rung_dev_s = sum(r["device_seconds"] for r in pruned.last_budget_stats)
    n_pruned = sum(r["entered"] - r["survived"]
                   for r in pruned.last_budget_stats)
    recompiles = watcher.backend_compile_count - compiles_warm

    # champion parity by SCORE (fake candidates tie often; a different
    # index with the same full-suite fitness is still a match)
    champ_budget = int(np.argmax([r.score for r in recs]))
    match = float(abs(full_scores[champ_budget] - full_scores.max()) <= 1e-9)
    log(f"steady-state: full {full_s:.3f}s vs pruned {pruned_s:.3f}s "
        f"({n_pruned}/{pop} pruned at rung 0); champion match {match}; "
        f"recompiles in timed passes: {recompiles}")

    payload = {
        "budget_speedup": round(full_s / pruned_s, 3),
        "device_seconds_full": round(full_s, 4),
        "device_seconds_pruned": round(pruned_s, 4),
        "budget_champion_match": match,
        "population": pop,
        "pruned_candidates": n_pruned,
        "rung_device_seconds": round(rung_dev_s, 4),
        "steady_state_recompiles": recompiles,
        "backend_compiles": watcher.backend_compile_count,
        "compile_seconds": round(watcher.backend_compile_seconds, 3),
        "node_prefilter_k": cfg.node_prefilter_k,
        "state_pack": cfg.state_pack,
        **budget.describe(),
    }
    _record("metric", "bench_stage", payload, stage="budget",
            platform="cpu")
    rc = 0
    if gate:
        rc = _gate(gate, payload)
    _record("finish", "ok")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_preflight(gate: str = "") -> int:
    """CPU subprocess: static pre-flight headline — one FakeLLM candidate
    stream (grammar + junk at ``FKS_BENCH_PREFLIGHT_JUNK``) evaluated
    twice through CodeEvaluator (flat engine, batched VM tier): once with
    the fks_tpu.analysis pre-flight + fingerprint dedup OFF (every
    candidate pays sandbox/transpile/eval) and once ON (static rejects
    and AST-fingerprint duplicates never reach the pipeline). Prints one
    JSON line with ``preflight_reject_rate`` (statically rejected before
    sandbox, over the whole stream), ``fingerprint_dup_rate``, the
    steady-state wall delta, and a best-score parity audit (the analyzer
    must never change WHO wins, only what the batch costs)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import llm as llm_mod
    from fks_tpu.funsearch import template
    from fks_tpu.funsearch.backend import CodeEvaluator
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.sim.engine import SimConfig

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    pop = int(os.environ.get("FKS_BENCH_PREFLIGHT_POP", "64"))
    junk = float(os.environ.get("FKS_BENCH_PREFLIGHT_JUNK", "0.3"))
    wl = synthetic_workload(8, 200, seed=3)
    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    gen = llm_mod.FakeLLM(seed=7, junk_rate=junk)
    codes = [template.fill_template(gen.complete("")) for _ in range(pop)]
    log(f"preflight stage: pop={pop} junk_rate={junk}")

    off = CodeEvaluator(wl, cfg, engine="flat", vm_batch=True,
                        preflight=False, fp_dedup=False)
    on = CodeEvaluator(wl, cfg, engine="flat", vm_batch=True)

    # warm both paths: XLA compiles land here, not in the timed passes
    t0 = time.perf_counter()
    off.evaluate(codes)
    on.evaluate(codes)
    log(f"warm-up (compile+run, both paths): "
        f"{time.perf_counter() - t0:.1f}s; XLA backend compile "
        f"{watcher.backend_compile_seconds:.1f}s")
    compiles_warm = watcher.backend_compile_count

    t0 = time.perf_counter()
    res_off = off.evaluate(codes)
    off_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res_on = on.evaluate(codes)
    on_s = time.perf_counter() - t0
    recompiles = watcher.backend_compile_count - compiles_warm

    stats = on.last_eval_stats
    rejected = stats.get("preflight_rejected", 0)
    dupes = stats.get("fingerprint_duplicates", 0)
    # parity audit: the analyzer only skips losers, so the best score of
    # the stream must be bit-identical on both paths
    best_off = float(np.max([r.score for r in res_off]))
    best_on = float(np.max([r.score for r in res_on]))
    log(f"steady-state: off {off_s:.3f}s vs on {on_s:.3f}s "
        f"({rejected}/{pop} rejected pre-sandbox, {dupes} fp-dupes); "
        f"best score off {best_off:.6f} on {best_on:.6f}")

    payload = {
        "preflight_reject_rate": round(rejected / pop, 4),
        "fingerprint_dup_rate": round(dupes / pop, 4),
        "preflight_speedup": round(off_s / on_s, 3) if on_s else 0.0,
        "wall_seconds_off": round(off_s, 4),
        "wall_seconds_on": round(on_s, 4),
        "best_score_match": float(abs(best_off - best_on) <= 1e-9),
        "population": pop,
        "junk_rate": junk,
        "unique_evaluated": stats.get("unique", 0),
        "mean_static_work": stats.get("mean_static_work", 0),
        "steady_state_recompiles": recompiles,
        "backend_compiles": watcher.backend_compile_count,
        "compile_seconds": round(watcher.backend_compile_seconds, 3),
    }
    _record("metric", "bench_stage", payload, stage="preflight",
            platform="cpu")
    rc = 0
    if gate:
        rc = _gate(gate, payload)
    if payload["best_score_match"] != 1.0:
        log("PREFLIGHT PARITY FAIL: analyzer changed the stream's best "
            "score")
        rc = rc or 1
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_scale1k(gate: str = "") -> int:
    """CPU subprocess: large-cluster scale-tier headline — a 1k-node x
    100k-pod synthetic workload (data.synthetic, OpenB-shaped) run to
    completion through the flat engine's double-buffered segmented
    runner with top-k node prefiltering and packed state dtypes on
    (``SimConfig.node_prefilter_k`` / ``SimConfig.state_pack``). Prints
    one JSON line with ``scale1k_events_per_sec`` (events processed /
    wall, backend-compile time excluded) plus two dense-vs-prefilter
    ratio sub-benchmarks at smaller pod counts, each with a
    fitness-drift parity gate at 1e-5 (both use a first_fit-anchored
    candidate, whose lowest-index-feasible winner always survives the
    prefilter — drift is exactly 0):

    - ``prefilter_speedup``: the VM CODE-CANDIDATE tier, where the
      per-event node sweep dominates the step (a vmapped register-VM op
      executes EVERY opcode branch per node, so dense cost is ~capacity
      x opcodes x N; measured ~300 ms/step dense vs ~20 ms/step at k=64
      on CPU). This is the production FunSearch evaluation path and the
      tier the >= 3x acceptance claim is made on.
    - ``parametric_prefilter_speedup``: the parametric-weights tier,
      where the policy costs ~4 us/step dense at N=1000 and the step is
      queue-dominated — prefiltering cannot pay on CPU (< 1x, the
      documented negative result; see PROFILE.md round 11).

    Also attaches the compiled hot-segment program's static XLA
    cost/memory analysis.

    Env knobs: FKS_BENCH_SCALE_NODES (1000), FKS_BENCH_SCALE_PODS
    (100000), FKS_BENCH_SCALE_POP (4), FKS_BENCH_SCALE_PREFILTER_K (64),
    FKS_BENCH_SCALE_RATIO_PODS (4096, parametric ratio pair),
    FKS_BENCH_SCALE_VM_PODS (96 — the VM dense leg costs ~0.3 s/event on
    CPU, so the pod count stays small), FKS_BENCH_SCALE_SEG_STEPS
    (16384)."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.models import parametric
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    nodes = int(os.environ.get("FKS_BENCH_SCALE_NODES", "1000"))
    pods = int(os.environ.get("FKS_BENCH_SCALE_PODS", "100000"))
    pop = int(os.environ.get("FKS_BENCH_SCALE_POP", "4"))
    k = int(os.environ.get("FKS_BENCH_SCALE_PREFILTER_K", "64"))
    ratio_pods = int(os.environ.get("FKS_BENCH_SCALE_RATIO_PODS", "4096"))
    vm_pods = int(os.environ.get("FKS_BENCH_SCALE_VM_PODS", "96"))
    seg_steps = int(os.environ.get("FKS_BENCH_SCALE_SEG_STEPS", "16384"))
    log(f"scale1k: {nodes} nodes x {pods} pods, pop={pop}, "
        f"prefilter_k={k}, seg_steps={seg_steps}")

    # first_fit-anchored parametric lanes: bias-only weights score every
    # feasible node a constant, so argmax picks the lowest feasible index
    # — the case where prefilter parity is EXACT, making the ratios below
    # same-fitness comparisons, not approximate
    params = jnp.tile(
        jnp.asarray(parametric.seed_weights("first_fit"))[None], (pop, 1))

    def timed_run(wl, cfg, policy=parametric.score, prms=None):
        prms = params if prms is None else prms
        run = flat.make_segmented_population_run(
            wl, policy, cfg, seg_steps=seg_steps)
        state0 = flat.initial_state(wl, cfg)
        c0 = watcher.backend_compile_seconds
        t0 = time.perf_counter()
        res = run(prms, state0)
        jax.block_until_ready(res.policy_score)
        wall = time.perf_counter() - t0
        compile_s = watcher.backend_compile_seconds - c0
        events = int(np.asarray(res.events_processed).sum())
        # single-pass protocol (a second 100k-pod pass would double the
        # stage's wall time for no information): events/sec excludes the
        # measured backend-compile seconds but still carries the host
        # trace/lower overhead, so it reads slightly conservative
        eps = events / max(1e-9, wall - compile_s)
        return res, run, state0, eps, wall, compile_s

    def ratio_pair(wl, max_steps, policy, prms, tier):
        out = {}
        for label, cfg_r in (
                ("dense", SimConfig(max_steps=max_steps,
                                    track_ctime=False)),
                ("prefilter", SimConfig(max_steps=max_steps,
                                        track_ctime=False,
                                        node_prefilter_k=k,
                                        state_pack=True))):
            res_r, _, _, eps_r, wall_r, comp_r = timed_run(
                wl, cfg_r, policy, prms)
            out[label] = (eps_r, np.asarray(res_r.policy_score))
            log(f"{tier}[{label}]: {eps_r:.0f} events/s "
                f"(wall {wall_r:.2f}s, compile {comp_r:.2f}s)")
        speedup = out["prefilter"][0] / out["dense"][0]
        drift = float(np.max(np.abs(out["prefilter"][1]
                                    - out["dense"][1])))
        log(f"{tier} prefilter speedup: {speedup:.2f}x, "
            f"fitness drift {drift:.2e}")
        return out, speedup, drift

    # -- VM code-candidate ratio: the tier where the node sweep dominates
    # (and the >= 3x claim lives). The candidate is the template with
    # first_fit logic (score = 1.0): constant on feasible nodes, so the
    # argmax winner is the lowest feasible index — prefilter-exact — and
    # the full template feasibility prologue still pays the real VM cost.
    from fks_tpu.funsearch import template, vm
    wl_v = synthetic_workload(nodes, vm_pods, seed=1)
    code = template.TEMPLATE.replace(template.LOGIC_PLACEHOLDER,
                                     "score = 1.0")
    prog = vm.compile_policy(code, wl_v.cluster.n_padded,
                             wl_v.cluster.g_padded, capacity=256)
    stacked = vm.stack_programs([prog] * pop, capacity=256)
    _, vm_speedup, vm_drift = ratio_pair(
        wl_v, 4 * vm_pods, vm.score_static, stacked, "vm_ratio")

    # -- parametric ratio: the cheap-policy tier, reported as the honest
    # negative control (queue-dominated step; prefilter cannot pay here
    # on CPU)
    wl_r = synthetic_workload(nodes, ratio_pods, seed=1)
    ratio, par_speedup, par_drift = ratio_pair(
        wl_r, 4 * ratio_pods, parametric.score, params, "parametric_ratio")
    drift = max(vm_drift, par_drift)
    if drift > 1e-5:
        log(f"SCALE PARITY FAIL: prefilter fitness drift {drift:.2e} > 1e-5")
        return 1
    speedup = vm_speedup

    # -- headline: full-size completion run, prefilter + packed dtypes on
    wl = synthetic_workload(nodes, pods, seed=1)
    cfg = SimConfig(max_steps=4 * pods, track_ctime=False,
                    node_prefilter_k=k, state_pack=True)
    res, run, state0, eps, wall, compile_s = timed_run(wl, cfg)
    if bool(np.asarray(res.truncated).any()):
        log("SCALE FAIL: a lane hit max_steps before draining")
        return 1
    scheduled = int(np.asarray(res.scheduled_pods)[0])
    events = int(np.asarray(res.events_processed).sum())
    log(f"headline: {eps:.0f} events/s ({events} events, wall {wall:.2f}s, "
        f"compile {compile_s:.2f}s); {scheduled}/{pods} pods scheduled")

    # static analysis of the hot segment program (AOT — reuses shapes the
    # jit already compiled; best-effort either way)
    bstate0 = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (pop,) + leaf.shape), state0)
    analysis = {**_cost_estimates(run.advance, params, bstate0),
                **_memory_estimates(run.advance, params, bstate0,
                                    exe_key=f"scale1k,pop={pop}")}

    payload = {
        "scale1k_events_per_sec": round(eps, 1),
        "scale1k_wall_seconds": round(wall, 3),
        "compile_seconds": round(compile_s, 3),
        "backend_compiles": watcher.backend_compile_count,
        "events_processed": events,
        "scheduled_pods": scheduled,
        "nodes": nodes, "pods": pods, "population": pop,
        "seg_steps": seg_steps,
        "node_prefilter_k": k, "state_pack": True,
        # VM code-candidate tier: the headline dense-vs-k ratio
        "prefilter_speedup": round(speedup, 3),
        "vm_ratio_pods": vm_pods,
        # parametric tier: the negative control (queue-dominated step)
        "parametric_prefilter_speedup": round(par_speedup, 3),
        "dense_events_per_sec": round(ratio["dense"][0], 1),
        "prefilter_events_per_sec": round(ratio["prefilter"][0], 1),
        "ratio_pods": ratio_pods,
        "fitness_drift": drift,
        **analysis,
    }
    _record("metric", "bench_stage", payload, stage="scale1k",
            platform="cpu")
    # the schema-checked scale_tier record (tools/check_jsonl_schema.py):
    # shape + knobs + throughput, the cross-round comparable core
    _record("metric", "scale_tier", {
        "nodes": nodes, "pods": pods,
        "events_per_sec": round(eps, 1),
        "node_prefilter_k": k, "state_pack": True,
    }, platform="cpu")
    rc = 0
    if gate:
        rc = _gate(gate, payload)
    _record("finish", "ok")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_serve(gate: str = "") -> int:
    """CPU subprocess: champion-serving headline (fks_tpu.serve) — the
    cold/warm split the serving tier exists for. Builds a ServeEngine
    (latest repo champion, synthetic cluster, flat engine) with a single
    pod bucket and lane buckets covering batch sizes 1/8/64, then
    measures:

    - ``serve_cold_seconds``: the first batch-1 answer, compile included
      (what a cold process pays before the bucket is warm);
    - ``serve_p50_ms`` / ``serve_p99_ms``: per-answer wall latency over
      repeated warm batch-1 queries;
    - ``serve_qps`` (+ per-batch-size breakdown): answers/sec at batch
      sizes 1, 8 and 64 — the headline is the best observed, i.e. the
      coalescer's payoff at full occupancy;
    - ``steady_state_recompiles``: backend compiles observed during the
      warm passes — the zero-recompile contract, gated at 0 here.

    ``--devices N`` (or FKS_BENCH_SERVE_DEVICES) switches to the
    mesh-sharded occupancy sweep (``stage_serve_sharded``): same champion
    and cluster, the batch axis sharded across N virtual CPU devices.
    """
    devices = 0
    if "--devices" in sys.argv:
        devices = int(sys.argv[sys.argv.index("--devices") + 1])
    devices = devices or int(os.environ.get("FKS_BENCH_SERVE_DEVICES", "0"))
    if devices:
        return stage_serve_sharded(gate, devices)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ShapeEnvelope, latest_champion,
        load_champion,
    )

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    nodes = int(os.environ.get("FKS_BENCH_SERVE_NODES", "32"))
    qpods = int(os.environ.get("FKS_BENCH_SERVE_PODS", "24"))
    reps = int(os.environ.get("FKS_BENCH_SERVE_REPS", "20"))
    batches = (1, 8, 64)

    champ_path = latest_champion()
    champion = (load_champion(champ_path) if champ_path else
                ChampionSpec(code=template.fill_template("score = 1000")))
    # one pod bucket (every query is qpods-sized) keeps the stage about
    # the batch axis; lane buckets must cover the largest batch size
    bucket = max(32, qpods)
    envelope = ShapeEnvelope(max_pods=bucket, min_pod_bucket=bucket,
                             max_batch=max(batches))
    wl = synthetic_workload(nodes, 4 * qpods, seed=7)
    engine = ServeEngine(champion, wl, envelope=envelope, engine="flat")
    base = engine.base_pods
    queries = [[dict(base[(i + j) % len(base)]) for j in range(qpods)]
               for i in range(max(batches))]
    log(f"serve stage: {nodes} nodes, {qpods}-pod queries, champion "
        f"score={champion.score:.4f} tier={engine.policy_tier}")

    # cold: first batch-1 answer, compile included
    t0 = time.perf_counter()
    engine.answer_batch([queries[0]])
    cold_s = time.perf_counter() - t0
    engine.warmup(lane_buckets=[engine.envelope.lanes_for(b)
                                for b in batches])
    # prime each batch size once: the AOT executables are already warm,
    # but the EAGER host-side query stacking compiles its tiny stack/pad
    # programs on first use of each batch shape — those are part of the
    # cold cost, not a warm-path leak
    for b in batches:
        engine.answer_batch(queries[:b])
    compile_s = watcher.backend_compile_seconds
    compiles_warm = watcher.backend_compile_count

    # warm batch-1 latency distribution
    lat_ms = []
    for i in range(reps):
        t0 = time.perf_counter()
        engine.answer_batch([queries[i % len(queries)]])
        lat_ms.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lat_ms, 50))
    p99 = float(np.percentile(lat_ms, 99))

    # throughput per batch size (the batch axis is nearly free, so qps
    # should scale with occupancy until the vmap saturates the host)
    qps = {}
    for b in batches:
        n_rounds = max(1, reps // 4)
        t0 = time.perf_counter()
        for _ in range(n_rounds):
            engine.answer_batch(queries[:b])
        qps[b] = b * n_rounds / (time.perf_counter() - t0)
    recompiles = watcher.backend_compile_count - compiles_warm
    log(f"cold {cold_s:.2f}s; warm p50 {p50:.1f}ms p99 {p99:.1f}ms; "
        f"qps {' '.join(f'b{b}={qps[b]:.1f}' for b in batches)}; "
        f"recompiles in warm passes: {recompiles}")

    # tracing overhead: the same warm batch-1 requests through the
    # ServeService request path, recorder off vs on — the per-request
    # causal waterfall (fks_tpu.obs.trace_ctx) must be within noise
    # (compare.py gates trace_overhead_pct at +2.0 points absolute).
    # The traced run dir also yields the mean per-component split.
    import tempfile

    from fks_tpu.obs import FlightRecorder, trace_ctx
    from fks_tpu.obs.report import read_jsonl
    from fks_tpu.serve import ServeService

    def _service_mean_ms(recorder) -> float:
        svc = ServeService(engine, recorder=recorder, max_wait_s=0.0)
        try:
            t0 = time.perf_counter()
            for i in range(reps):
                svc.submit({"id": f"ovh-{i:03d}",
                            "pods": queries[i % len(queries)]}).result()
            return (time.perf_counter() - t0) * 1e3 / reps
        finally:
            svc.close()

    trace_comp_ms = {}
    with tempfile.TemporaryDirectory() as tmp:
        from fks_tpu.obs import NULL
        mean_off = _service_mean_ms(NULL)
        traced = FlightRecorder(os.path.join(tmp, "traced"))
        mean_on = _service_mean_ms(traced)
        traced.finish("ok")
        traced.close()
        spans = trace_ctx.trace_spans(
            read_jsonl(os.path.join(tmp, "traced", "events.jsonl")))
        for comp in trace_ctx.SERVE_COMPONENTS:
            secs = [float(s.get("seconds", 0.0)) for s in spans
                    if str(s.get("path", "")).rpartition("/")[2] == comp]
            trace_comp_ms[comp] = (sum(secs) / len(secs) * 1e3
                                   if secs else 0.0)
    trace_overhead_pct = ((mean_on - mean_off) / mean_off * 100.0
                          if mean_off > 0 else 0.0)
    log(f"trace overhead: {mean_off:.2f}ms off -> {mean_on:.2f}ms on "
        f"({trace_overhead_pct:+.2f}%); components "
        + " ".join(f"{c}={trace_comp_ms[c]:.3f}ms"
                   for c in trace_ctx.SERVE_COMPONENTS))

    payload = {
        "serve_cold_seconds": round(cold_s, 3),
        "serve_p50_ms": round(p50, 3),
        "serve_p99_ms": round(p99, 3),
        "serve_qps": round(max(qps.values()), 2),
        **{f"serve_qps_b{b}": round(v, 2) for b, v in qps.items()},
        "steady_state_recompiles": recompiles,
        "backend_compiles": watcher.backend_compile_count,
        "compile_seconds": round(compile_s, 3),
        "nodes": nodes, "query_pods": qpods, "reps": reps,
        "engine": "flat",
        "policy_tier": engine.policy_tier,
        "node_prefilter_k": engine.prefilter_k,
        "champion_score": round(champion.score, 4),
    }
    # snapshot-cache + upload accounting (new in round 17; additive keys,
    # so prior-round compare baselines are unaffected)
    cache = engine.snapshot_cache_stats()
    payload["snapshot_cache_hit_rate"] = round(cache["hit_rate"], 4)
    payload["serve_h2d_bytes_per_query"] = round(
        cache["h2d_bytes_per_query"], 1)
    # causal-tracing cost + mean waterfall split (round 18; additive keys)
    payload["trace_overhead_pct"] = round(trace_overhead_pct, 3)
    payload.update({f"trace_{c}_ms": round(v, 4)
                    for c, v in trace_comp_ms.items()})
    # memory budgets (round 20; additive keys gated must-not-regress)
    payload.update(_ledger_budget_keys("serve_aot"))
    _record("metric", "bench_stage", payload, stage="serve",
            platform="cpu")
    _record("metric", "snapshot_cache", dict(cache))
    rc = 0
    if recompiles:
        log(f"FAIL: {recompiles} recompiles on the warm path — a bucket "
            "shape leaked out of the AOT cache")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_serve_sharded(gate: str, devices: int) -> int:
    """CPU subprocess: mesh-sharded serving occupancy sweep — the round-17
    headline. The coalesced batch axis is sharded across ``devices``
    virtual CPU devices (one AOT executable spans the mesh), cluster
    snapshot tables are device-resident behind the content-hash cache,
    and query uploads ride the 16-bit ``state_pack`` path. Measures, at
    equal PER-DEVICE batch sizes 1/8/64:

    - ``serve_sharded_qps``: best global answers/sec over the sweep (the
      cross-round comparable; ``serve_qps_b{n}`` is the per-device-batch
      breakdown, global batch = n x devices);
    - ``serve_p50_ms`` / ``serve_p99_ms``: warm latency of a per-device
      batch-1 dispatch (``devices`` queries per answer_batch);
    - ``serve_h2d_bytes_per_query`` + ``h2d_seconds``/``steady_seconds``:
      upload-vs-execute attribution (StageProfiler h2d/steady stages);
    - ``snapshot_cache_hit_rate``: device-resident ktable reuse;
    - ``steady_state_recompiles``: gated at 0, same contract as the
      single-device stage.
    """
    import jax
    jax.config.update("jax_platforms", "cpu")
    if devices > 1:
        try:
            jax.config.update("jax_num_cpu_devices", devices)
        except AttributeError:
            # jax 0.4.x: virtual host-device count is an XLA flag, read
            # when the (cleared) backend initializes
            flags = os.environ.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{devices}").strip()
            from jax.extend import backend as _jexb
            _jexb.clear_backends()
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher, StageProfiler
    from fks_tpu.parallel.mesh import population_mesh
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ShapeEnvelope, latest_champion,
        load_champion,
    )

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    if len(jax.devices()) < devices:
        log(f"FAIL: need {devices} devices, backend has "
            f"{len(jax.devices())}")
        return 1
    mesh = population_mesh(jax.devices()[:devices])
    nodes = int(os.environ.get("FKS_BENCH_SERVE_NODES", "32"))
    qpods = int(os.environ.get("FKS_BENCH_SERVE_PODS", "24"))
    reps = int(os.environ.get("FKS_BENCH_SERVE_REPS", "20"))
    batches = (1, 8, 64)  # per-device coalesced batch sizes

    champ_path = latest_champion()
    champion = (load_champion(champ_path) if champ_path else
                ChampionSpec(code=template.fill_template("score = 1000")))
    bucket = max(32, qpods)
    envelope = ShapeEnvelope(max_pods=bucket, min_pod_bucket=bucket,
                             max_batch=max(batches))
    wl = synthetic_workload(nodes, 4 * qpods, seed=7)
    profiler = StageProfiler(scope="serve_sharded", watcher=watcher)
    engine = ServeEngine(champion, wl, envelope=envelope, engine="flat",
                         state_pack=True, mesh=mesh, profiler=profiler)
    base = engine.base_pods
    n_q = max(batches) * devices
    queries = [[dict(base[(i + j) % len(base)]) for j in range(qpods)]
               for i in range(n_q)]
    log(f"serve sharded stage: {devices} devices, {nodes} nodes, "
        f"{qpods}-pod queries, per-device batches {batches}, champion "
        f"score={champion.score:.4f} tier={engine.policy_tier}")

    # cold: first per-device-batch-1 answer, compile included
    t0 = time.perf_counter()
    engine.answer_batch(queries[:devices])
    cold_s = time.perf_counter() - t0
    engine.warmup(lane_buckets=[engine.envelope.lanes_for(b)
                                for b in batches])
    for b in batches:  # prime host-side stacking per global batch shape
        engine.answer_batch(queries[:b * devices])
    compiles_warm = watcher.backend_compile_count

    # warm latency at per-device batch 1 (devices queries per dispatch)
    lat_ms = []
    for _ in range(reps):
        t0 = time.perf_counter()
        engine.answer_batch(queries[:devices])
        lat_ms.append((time.perf_counter() - t0) * 1e3)
    p50 = float(np.percentile(lat_ms, 50))
    p99 = float(np.percentile(lat_ms, 99))

    # occupancy sweep: global throughput per per-device batch size
    qps = {}
    for b in batches:
        n_rounds = max(1, reps // 4)
        t0 = time.perf_counter()
        for _ in range(n_rounds):
            engine.answer_batch(queries[:b * devices])
        qps[b] = b * devices * n_rounds / (time.perf_counter() - t0)
    recompiles = watcher.backend_compile_count - compiles_warm

    summ = profiler.summary()
    by_stage = {s["stage"]: s for s in summ["stages"]}
    h2d_s = float(by_stage.get("h2d", {}).get("wall_seconds", 0.0))
    steady_s = float(by_stage.get("steady", {}).get("wall_seconds", 0.0))
    cache = engine.snapshot_cache_stats()
    log("occupancy sweep (per-device batch -> global qps):")
    for b in batches:
        log(f"  b{b:<3} x {devices} dev = {b * devices:>4} q/chunk  "
            f"{qps[b]:10.1f} qps")
    log(f"cold {cold_s:.2f}s; warm p50 {p50:.1f}ms p99 {p99:.1f}ms; "
        f"h2d {h2d_s:.3f}s steady {steady_s:.3f}s; cache hit rate "
        f"{cache['hit_rate']:.2f}; recompiles in warm passes: {recompiles}")

    payload = {
        "devices": devices,
        "serve_sharded_qps": round(max(qps.values()), 2),
        "serve_cold_seconds": round(cold_s, 3),
        "serve_p50_ms": round(p50, 3),
        "serve_p99_ms": round(p99, 3),
        **{f"serve_qps_b{b}": round(v, 2) for b, v in qps.items()},
        "serve_h2d_bytes_per_query": round(
            cache["h2d_bytes_per_query"], 1),
        "h2d_seconds": round(h2d_s, 3),
        "steady_seconds": round(steady_s, 3),
        "snapshot_cache_hit_rate": round(cache["hit_rate"], 4),
        "snapshot_cache_hits": int(cache["hits"]),
        "snapshot_cache_misses": int(cache["misses"]),
        "steady_state_recompiles": recompiles,
        "backend_compiles": watcher.backend_compile_count,
        "nodes": nodes, "query_pods": qpods, "reps": reps,
        "engine": "flat", "state_pack": True,
        "policy_tier": engine.policy_tier,
        "champion_score": round(champion.score, 4),
        # memory budgets (round 20; additive keys gated must-not-regress)
        **_ledger_budget_keys("serve_aot"),
    }
    _record("metric", "bench_stage", payload, stage="serve_sharded",
            platform="cpu")
    _record("metric", "snapshot_cache", dict(cache))
    rc = 0
    if recompiles:
        log(f"FAIL: {recompiles} recompiles on the warm path — a bucket "
            "shape leaked out of the sharded AOT cache")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_promote(gate: str = "") -> int:
    """CPU subprocess: promotion-pipeline headline (fks_tpu.pipeline) —
    the evolve→serve hot-swap path. Stands up a live ServeService on a
    seed champion, drops a better candidate into a fresh ledger, and
    runs one PromotionController poll end to end, measuring:

    - ``shadow_eval_seconds``: the full off-request-path cost of a
      candidate (bucket-ladder build + warmup + replayed-traffic shadow
      gates);
    - ``promote_swap_ms``: the atomic engine flip itself;
    - ``post_swap_recompiles``: backend compiles while serving live
      traffic on the freshly promoted engine — gated at 0 (the swap
      must inherit a fully warm ladder).

    Then the same promotion on the VM-native engine, head to head:

    - ``promotion_rebuild_s``: what the AOT flow pays off-path to bind
      a champion — the full bucket-ladder rebuild inside the factory;
    - ``promotion_swap_ms``: what the VM flow pays instead — transpile
      + pack + H2D upload into the resident executables;
    - ``vm_swap_h2d_bytes``: the entire device traffic of that swap;
    - ``vm_promote_compiles``: backend compiles across the VM
      promotion AND post-swap traffic — gated at 0 (the whole point:
      promotion never touches XLA).
    """
    import tempfile

    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.pipeline import (
        PromotionConfig, PromotionController, write_champion,
    )
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ServeService, ShapeEnvelope,
        VMServeEngine,
    )

    global _RECORDER
    _RECORDER = _controller_recorder()
    watcher = CompileWatcher().install()
    nodes = int(os.environ.get("FKS_BENCH_PROMOTE_NODES", "16"))
    envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    wl = synthetic_workload(nodes, 16, seed=3)
    incumbent = ServeEngine(
        ChampionSpec(code=template.fill_template("score = 1000"),
                     score=0.4, source="<bench-seed>"),
        wl, envelope=envelope, engine="flat")
    incumbent.warmup()
    service = ServeService(incumbent, max_wait_s=0.002)
    base = incumbent.base_pods

    def traffic(n: int) -> None:
        futs = [service.submit(
            {"pods": [dict(base[(i + j) % len(base)]) for j in range(3)]})
            for i in range(n)]
        for f in futs:
            f.result(timeout=300)

    traffic(8)  # live traffic -> the replay buffer the shadow eval taps
    tmp = tempfile.mkdtemp(prefix="fks_promote_")
    candidate = ("score = 1000 + (node.cpu_milli_left - pod.cpu_milli)"
                 " / max(1, node.cpu_milli_total)")
    write_champion(tmp, template.fill_template(candidate), 0.9,
                   name="bench")
    ctrl = PromotionController(
        service, wl, ledger_dir=tmp,
        config=PromotionConfig(shadow_queries=4))
    rebuild = {"s": 0.0}
    aot_factory = ctrl._factory

    def timed_factory(champ):
        tb = time.perf_counter()
        eng = aot_factory(champ)
        rebuild["s"] = time.perf_counter() - tb
        return eng

    ctrl._factory = timed_factory
    t0 = time.perf_counter()
    verdict = ctrl.poll_once()
    shadow_s = time.perf_counter() - t0
    promoted = verdict.get("action") == "promoted"
    marks = watcher.backend_compile_count
    traffic(8)  # warm path on the promoted engine
    recompiles = watcher.backend_compile_count - marks
    service.close()
    log(f"promote stage: {verdict.get('action')} in {shadow_s:.2f}s, "
        f"rebuild {rebuild['s']:.2f}s, swap {ctrl.last_swap_ms:.3f}ms, "
        f"post-swap recompiles {recompiles}")

    # --- the VM-native flow: same promotion, zero-rebuild hot path
    vm_inc = VMServeEngine(
        ChampionSpec(code=template.fill_template("score = 1000"),
                     score=0.4, source="<bench-seed>"),
        wl, envelope=envelope, engine="flat")
    vm_inc.warmup()
    vm_service = ServeService(vm_inc, max_wait_s=0.002)
    vm_base = vm_inc.base_pods

    def vm_traffic(n: int) -> None:
        futs = [vm_service.submit(
            {"pods": [dict(vm_base[(i + j) % len(vm_base)])
                      for j in range(3)]})
            for i in range(n)]
        for f in futs:
            f.result(timeout=300)

    vm_traffic(8)
    vm_tmp = tempfile.mkdtemp(prefix="fks_promote_vm_")
    write_champion(vm_tmp, template.fill_template(candidate), 0.9,
                   name="bench-vm")
    vm_ctrl = PromotionController(
        vm_service, wl, ledger_dir=vm_tmp,
        config=PromotionConfig(shadow_queries=4))
    vm_marks = watcher.backend_compile_count
    vm_verdict = vm_ctrl.poll_once()
    vm_traffic(8)  # warm path on the swapped-in program
    vm_compiles = watcher.backend_compile_count - vm_marks
    vm_promoted = (vm_verdict.get("action") == "promoted"
                   and vm_verdict.get("engine_kind") == "vm")
    swap = dict(vm_inc.last_swap_breakdown)
    # warm swap: promoting the SAME champion source again must hit the
    # host-side transpile cache (vm_engine._lower_champion) — the ~60 ms
    # compile_policy cost drops out, leaving pack + H2D only
    vm_inc.swap_program(ChampionSpec(
        code=template.fill_template(candidate), score=0.9,
        source="<bench-warm>"))
    warm = dict(vm_inc.last_swap_breakdown)
    vm_service.close()
    log(f"promote stage (vm): {vm_verdict.get('action')} "
        f"kind={vm_verdict.get('engine_kind')}, swap "
        f"{swap.get('swap_ms', 0.0):.3f}ms "
        f"(h2d {swap.get('h2d_bytes', 0)}B), compiles {vm_compiles}")
    log(f"promote stage (vm warm): swap {warm.get('swap_ms', 0.0):.3f}ms "
        f"transpile {warm.get('transpile_ms', 0.0):.3f}ms "
        f"cache {warm.get('transpile_cache')} "
        f"({warm.get('transpile_cache_hits', 0)} hit / "
        f"{warm.get('transpile_cache_misses', 0)} miss)")

    payload = {
        "promote_swap_ms": ctrl.last_swap_ms,
        "shadow_eval_seconds": round(shadow_s, 3),
        "shadow_queries": int(ctrl.last_shadow.get("queries", 0)),
        "shadow_p99_ms": float(ctrl.last_shadow.get("p99_ms", 0.0)),
        "post_swap_recompiles": recompiles,
        "promoted": int(promoted),
        "backend_compiles": watcher.backend_compile_count,
        "promotion_rebuild_s": round(rebuild["s"], 3),
        "promotion_swap_ms": float(swap.get("swap_ms", 0.0)),
        "vm_swap_h2d_bytes": int(swap.get("h2d_bytes", 0)),
        "vm_swap_transpile_ms": float(swap.get("transpile_ms", 0.0)),
        "vm_swap_upload_ms": float(swap.get("h2d_ms", 0.0)),
        "vm_warm_swap_ms": float(warm.get("swap_ms", 0.0)),
        "vm_warm_transpile_ms": float(warm.get("transpile_ms", 0.0)),
        "vm_transpile_cache_hits": int(warm.get("transpile_cache_hits", 0)),
        "vm_transpile_cache_misses": int(
            warm.get("transpile_cache_misses", 0)),
        "vm_promote_compiles": vm_compiles,
        "vm_promoted": int(vm_promoted),
        "nodes": nodes, "engine": "flat",
        # memory budgets across both promotion paths (round 20)
        **_ledger_budget_keys("serve_aot", "serve_vm"),
    }
    _record("metric", "bench_stage", payload, stage="promote",
            platform="cpu")
    rc = 0
    if not promoted:
        log(f"FAIL: candidate not promoted: {verdict}")
        rc = 1
    if recompiles:
        log(f"FAIL: {recompiles} recompiles after the swap — the shadow "
            "ladder was not fully warm")
        rc = 1
    if not vm_promoted:
        log(f"FAIL: VM fast path did not promote: {vm_verdict}")
        rc = 1
    if vm_compiles:
        log(f"FAIL: {vm_compiles} backend compiles across the VM "
            "promotion — the swap must be rebuild-free")
        rc = 1
    if warm.get("transpile_cache") != "hit":
        log(f"FAIL: warm swap missed the transpile cache "
            f"({warm.get('transpile_cache')!r})")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_resilience(gate: str = "") -> int:
    """CPU subprocess: resilience-layer headline (fks_tpu.resilience) —
    the cost of staying up under overload and device loss. Measures:

    - ``shed_submit_us``: how fast a bounded-queue overflow submit is
      refused with a typed ``ShedError`` (load shedding must be far
      cheaper than serving — a slow rejection path IS an outage);
    - ``degrade_flip_ms``: wall time from the faulting request to its
      answer served on the exact-CPU fallback (fault classification +
      atomic ``swap_engine`` + same-batch retry, all on one request);
    - ``drain_ms``: SIGTERM-path drain of a service with queued tail
      traffic — every Future completed, replay buffer persisted.

    Gated invariants ride along: exactly one engine flip, 0.0 parity
    drift on the fallback answers, drain not stuck.
    """
    import tempfile
    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.resilience import DegradeConfig, DrainCoordinator, ShedError
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ServeService, ShapeEnvelope,
    )
    from fks_tpu.serve.batcher import RequestBatcher

    global _RECORDER
    _RECORDER = _controller_recorder()
    import dataclasses as _dc

    envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    wl = synthetic_workload(16, 16, seed=3)
    champion = ChampionSpec(code=template.fill_template("score = 1000"),
                            score=0.4, source="<bench-seed>")
    incumbent = ServeEngine(champion, wl, envelope=envelope, engine="flat")
    incumbent.warmup()
    fallback = ServeEngine(champion, wl,
                           envelope=_dc.replace(envelope, max_batch=1),
                           engine="exact")
    fallback.warmup()

    # -- shed latency: bounded batcher, worker provably parked in a
    # batch, queue full; each overflow submit must raise ShedError.
    blocked, entered = threading.Event(), threading.Event()

    def parked(queries, enq):
        entered.set()
        blocked.wait(60)
        return list(queries)

    b = RequestBatcher(parked, max_batch=1, max_wait_s=0.0, max_queue=2)
    shed_us = 0.0
    try:
        held = [b.submit("a")]
        entered.wait(30)
        held += [b.submit("b"), b.submit("c")]  # fills the queue
        reps = 200
        t0 = time.perf_counter()
        for _ in range(reps):
            try:
                b.submit("overflow")
            except ShedError:
                pass
        shed_us = (time.perf_counter() - t0) / reps * 1e6
        blocked.set()
        for f in held:
            f.result(30)
    finally:
        blocked.set()
        b.close()

    # -- degrade flip: one faulting request, answered on the fallback.
    flaky = FlakyEngineProxy(incumbent, failures=1)
    service = ServeService(flaky, max_wait_s=0.002)
    service.enable_degraded_mode(
        lambda: fallback, config=DegradeConfig(background_rebuild=False))
    base = incumbent.base_pods
    pods = [dict(base[j % len(base)]) for j in range(3)]
    t0 = time.perf_counter()
    ans = service.submit({"pods": [dict(p) for p in pods]}).result(300)
    flip_ms = (time.perf_counter() - t0) * 1e3
    drift = abs(ans["score"] - incumbent.reference_answer(pods)["score"])
    flips = service.degrade.healthz()["flips"]

    # -- drain: queued tail traffic, SIGTERM-path drain + persist.
    tail = [service.submit(
        {"pods": [dict(base[(i + j) % len(base)]) for j in range(3)]})
        for i in range(4)]
    tmp = tempfile.mkdtemp(prefix="fks_bench_res_")
    dc = DrainCoordinator(service, state_path=os.path.join(
        tmp, "serve_state.json"), grace_s=60.0)
    t0 = time.perf_counter()
    report = dc.drain()
    drain_ms = (time.perf_counter() - t0) * 1e3
    pending_after = sum(1 for f in tail if not f.done())

    log(f"resilience stage: shed {shed_us:.1f}us, flip {flip_ms:.1f}ms "
        f"(drift {drift}), drain {drain_ms:.1f}ms "
        f"({report.get('completed')} completed)")
    payload = {
        "shed_submit_us": round(shed_us, 1),
        "degrade_flip_ms": round(flip_ms, 2),
        "drain_ms": round(drain_ms, 2),
        "degrade_flips": flips,
        "degrade_parity_drift": drift,
        "drain_completed": report.get("completed"),
        "drain_stuck": bool(report.get("stuck")),
        "engine": "flat",
    }
    _record("metric", "bench_stage", payload, stage="resilience",
            platform="cpu")
    rc = 0
    if flips != 1 or drift != 0.0:
        log(f"FAIL: degrade flip invariants (flips={flips}, "
            f"drift={drift})")
        rc = 1
    if report.get("stuck") or pending_after:
        log(f"FAIL: drain left {pending_after} pending futures "
            f"(stuck={report.get('stuck')})")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_loadgen(gate: str = "") -> int:
    """CPU subprocess: sustained multi-tenant serving headline
    (fks_tpu.obs.workload) — concurrent open/closed-loop arrivals
    through the threaded HTTP front against a warm ServeService with
    accounting on. Measures the four gated keys:

    - ``loadgen_qps``: completed queries/sec across all tenants;
    - ``loadgen_p99_ms``: tail latency over completed requests (the
      open-loop tenants keep arriving under load, so the tail is
      honest);
    - ``loadgen_shed_rate``: 503-shed fraction of all arrivals;
    - ``loadgen_fairness_index``: Jain's index over per-tenant goodput.

    Plus ``steady_state_recompiles`` (gated at 0 — sustained traffic on
    a warm ladder must never touch XLA) and
    ``accounting_overhead_pct`` (per-request cost of the accountant +
    fingerprinter vs the disabled path, same warm engine — documented
    honest in PROFILE.md, within run-to-run noise).

    Env knobs: FKS_BENCH_LOADGEN_S (duration, default 6),
    FKS_BENCH_LOADGEN_TENANTS (arrival plan, default
    "a:closed:2,b:closed:2,c:open:25"), FKS_BENCH_LOADGEN_SHED_MAX
    (default 0.05), FKS_BENCH_LOADGEN_FAIRNESS_MIN (default 0.5 — the
    default mix is deliberately UNEQUAL, closed workers vs an open
    Poisson stream; the run_full_suite gate runs a symmetric two-tenant
    closed plan and demands 0.8).
    """
    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.obs.history import SLOConfig
    from fks_tpu.obs.workload import (
        http_client, parse_tenant_spec, run_loadgen, service_client,
    )
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ServeService, ShapeEnvelope,
        make_http_server,
    )

    global _RECORDER
    _RECORDER = _controller_recorder()
    duration = float(os.environ.get("FKS_BENCH_LOADGEN_S", "6"))
    plan = parse_tenant_spec(os.environ.get(
        "FKS_BENCH_LOADGEN_TENANTS", "a:closed:2,b:closed:2,c:open:25"))
    shed_max = float(os.environ.get("FKS_BENCH_LOADGEN_SHED_MAX", "0.05"))
    fair_min = float(os.environ.get("FKS_BENCH_LOADGEN_FAIRNESS_MIN",
                                    "0.5"))
    watcher = CompileWatcher().install()
    envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    wl = synthetic_workload(16, 16, seed=3)
    champion = ChampionSpec(code=template.fill_template("score = 1000"),
                            score=0.4, source="<bench-seed>")
    engine = ServeEngine(champion, wl, envelope=envelope, engine="flat")
    engine.warmup()
    service = ServeService(engine, max_wait_s=0.002,
                           slo=SLOConfig(p99_ms=100.0),
                           accounting=True, workload_every=50)
    server = make_http_server(service, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    # warmup through the full HTTP path, then mark the compile counter:
    # anything after this line is a steady-state recompile
    http_client(port)({"tenant": "warmup",
                       "pods": [dict(engine.base_pods[0])]})
    marks = watcher.backend_compile_count
    summary = run_loadgen(http_client(port), plan, duration_s=duration,
                          seed=0, recorder=_RECORDER)
    recompiles = watcher.backend_compile_count - marks
    server.shutdown()
    server.server_close()

    # accounting overhead: the same warm engine behind two fresh
    # services, accountant+fingerprinter on vs off, serial in-process
    # requests (no socket, no concurrency — isolates the per-request
    # accounting cost). Two alternating passes absorb drift.
    def pump(svc, n=120):
        send = service_client(svc)
        t0 = time.perf_counter()
        for i in range(n):
            send({"tenant": "ovh",
                  "pods": [dict(engine.base_pods[(i + j) % 4])
                           for j in range(2)]})
        return (time.perf_counter() - t0) / n * 1e3  # ms/request

    service.close()
    ms = {True: [], False: []}
    for acct in (False, True, True, False):
        svc = ServeService(engine, max_wait_s=0.002, accounting=acct)
        try:
            pump(svc, n=20)  # warm the service's own path
            ms[acct].append(pump(svc))
        finally:
            svc.close()
    on_ms = sum(ms[True]) / len(ms[True])
    off_ms = sum(ms[False]) / len(ms[False])
    overhead_pct = ((on_ms - off_ms) / off_ms * 100.0) if off_ms else 0.0

    log(f"loadgen stage: {summary['requests']} requests in "
        f"{summary['duration_s']}s — {summary['loadgen_qps']} qps, "
        f"p99 {summary['loadgen_p99_ms']}ms, shed "
        f"{summary['loadgen_shed_rate']}, fairness "
        f"{summary['loadgen_fairness_index']}, recompiles {recompiles}, "
        f"accounting {overhead_pct:+.1f}% ({on_ms:.3f} vs "
        f"{off_ms:.3f} ms/req)")
    payload = {
        "loadgen_qps": summary["loadgen_qps"],
        "loadgen_p50_ms": summary["loadgen_p50_ms"],
        "loadgen_p99_ms": summary["loadgen_p99_ms"],
        "loadgen_shed_rate": summary["loadgen_shed_rate"],
        "loadgen_fairness_index": summary["loadgen_fairness_index"],
        "loadgen_requests": summary["requests"],
        "loadgen_mode": summary["mode"],
        "loadgen_tenants": summary["tenant_count"],
        "steady_state_recompiles": recompiles,
        "accounting_overhead_pct": round(overhead_pct, 2),
        "accounting_on_ms": round(on_ms, 4),
        "accounting_off_ms": round(off_ms, 4),
        "engine": "flat",
    }
    _record("metric", "bench_stage", payload, stage="loadgen",
            platform="cpu")
    rc = 0
    if summary["requests"] == 0 or summary["completed"] == 0:
        log("FAIL: loadgen completed zero requests")
        rc = 1
    if summary["errors"]:
        log(f"FAIL: {summary['errors']} loadgen requests errored "
            "(shed is an outcome; errors are not)")
        rc = 1
    if summary["loadgen_shed_rate"] > shed_max:
        log(f"FAIL: shed rate {summary['loadgen_shed_rate']} > "
            f"{shed_max}")
        rc = 1
    if summary["loadgen_fairness_index"] < fair_min:
        log(f"FAIL: fairness {summary['loadgen_fairness_index']} < "
            f"{fair_min}")
        rc = 1
    if recompiles:
        log(f"FAIL: {recompiles} steady-state recompiles — sustained "
            "traffic must stay on the warm ladder")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_portfolio(gate: str = "") -> int:
    """CPU subprocess: multi-tenant portfolio serving headline
    (fks_tpu.portfolio) — four resident champions in ONE slot-vmapped
    VM executable behind the threaded HTTP front, two closed-loop
    tenants pinned to different slots, and one slot promoted MID-RUN.
    Measures the two gated keys:

    - ``portfolio_qps``: completed queries/sec through the routed
      front (all tenants, all slots, one executable);
    - ``portfolio_slot_swap_ms``: wall time of the mid-traffic slot
      promotion (transpile + pack + one slot-table H2D upload).

    Plus ``portfolio_p99_ms``, the per-slot request mix (both pinned
    slots must actually serve), and ``portfolio_promote_compiles``
    (gated at 0 — promoting one slot under live traffic must never
    touch XLA; the other slots' answers come from the same resident
    executable throughout).

    Env knobs: FKS_BENCH_PORTFOLIO_S (duration, default 6),
    FKS_BENCH_PORTFOLIO_TENANTS (default "a:closed:2,b:closed:2").
    """
    import threading

    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.obs.workload import (
        http_client, parse_tenant_spec, run_loadgen,
    )
    from fks_tpu.portfolio import PortfolioEngine, PortfolioService, Router
    from fks_tpu.serve import ChampionSpec, ShapeEnvelope, make_http_server

    global _RECORDER
    _RECORDER = _controller_recorder()
    duration = float(os.environ.get("FKS_BENCH_PORTFOLIO_S", "6"))
    plan = parse_tenant_spec(os.environ.get(
        "FKS_BENCH_PORTFOLIO_TENANTS", "a:closed:2,b:closed:2"))
    logics = (
        # raw-milli scores: genuinely distinct policies (the normalized
        # variants all tie at int(1000) and would mask routing bugs)
        "score = 1000",
        "score = node.cpu_milli_left - pod.cpu_milli",
        "score = node.memory_mib_left - pod.memory_mib",
        "score = pod.cpu_milli - node.cpu_milli_left",
    )
    champs = [ChampionSpec(code=template.fill_template(lg),
                           score=0.4 + 0.1 * i, source=f"<bench-{i}>")
              for i, lg in enumerate(logics)]
    watcher = CompileWatcher().install()
    envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    wl = synthetic_workload(16, 16, seed=3)
    engine = PortfolioEngine(champs, wl, envelope=envelope, engine="flat",
                             n_slots=5, recorder=_RECORDER)
    engine.warmup()
    router = Router(engine.n_slots, pins={"a": 1, "b": 2})
    service = PortfolioService(engine, router=router, max_wait_s=0.002,
                               accounting=True, recorder=_RECORDER)
    server = make_http_server(service, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()

    # warmup through the full HTTP path, then mark the compile counter:
    # anything after this line — INCLUDING the mid-run slot promotion —
    # is a steady-state recompile
    http_client(port)({"tenant": "warmup",
                       "pods": [dict(engine.base_pods[0])]})
    marks = watcher.backend_compile_count
    promoted = ChampionSpec(
        code=template.fill_template(
            "score = 3000 + (node.cpu_milli_left - pod.cpu_milli) "
            "/ max(1, node.cpu_milli_total)"),
        score=9.9, source="<bench-promoted>")
    swap_ms = []

    def _promote_midrun():
        time.sleep(duration / 2)
        t0 = time.perf_counter()
        old = engine.swap_slot(3, promoted)
        swap_ms.append((time.perf_counter() - t0) * 1e3)
        del old

    swapper = threading.Thread(target=_promote_midrun, daemon=True)
    swapper.start()
    summary = run_loadgen(http_client(port), plan, duration_s=duration,
                          seed=0, recorder=_RECORDER)
    swapper.join(timeout=30)
    recompiles = watcher.backend_compile_count - marks
    server.shutdown()
    server.server_close()
    service.close()
    slot_mix = list(engine.slot_requests)

    log(f"portfolio stage: {summary['requests']} requests in "
        f"{summary['duration_s']}s — {summary['loadgen_qps']} qps, "
        f"p99 {summary['loadgen_p99_ms']}ms, slot mix {slot_mix}, "
        f"slot swap {swap_ms[0] if swap_ms else None}ms, "
        f"recompiles {recompiles}")
    payload = {
        "portfolio_qps": summary["loadgen_qps"],
        "portfolio_p99_ms": summary["loadgen_p99_ms"],
        "portfolio_slot_swap_ms": (round(swap_ms[0], 3) if swap_ms
                                   else None),
        "portfolio_slot_mix": slot_mix,
        "portfolio_slots": engine.n_slots,
        "portfolio_capacity": engine.program_capacity,
        "portfolio_requests": summary["requests"],
        "portfolio_shed_rate": summary["loadgen_shed_rate"],
        "portfolio_promote_compiles": recompiles,
        "portfolio_routes": {k: v for k, v in router.routed.items() if v},
        "engine": "flat",
    }
    _record("metric", "bench_stage", payload, stage="portfolio",
            platform="cpu")
    rc = 0
    if summary["requests"] == 0 or summary["completed"] == 0:
        log("FAIL: portfolio loadgen completed zero requests")
        rc = 1
    if summary["errors"]:
        log(f"FAIL: {summary['errors']} portfolio requests errored")
        rc = 1
    if not swap_ms:
        log("FAIL: mid-run slot promotion never completed")
        rc = 1
    if recompiles:
        log(f"FAIL: {recompiles} recompiles across the mid-traffic slot "
            "promotion — a slot swap must stay a table upload")
        rc = 1
    for slot in (1, 2):
        if slot_mix[slot] == 0:
            log(f"FAIL: pinned slot {slot} served zero requests — "
                "routing or slot threading broke")
            rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


def stage_layout(gate: str = "") -> int:
    """CPU subprocess: measured layout sweep (fks_tpu.obs.layout) over
    the virtual 8-device dryrun mesh — enumerate every valid
    (candidate_shards x scenario_shards) layout of pop-64 x suite-8,
    one warm probe each, and land the two gated keys:

    - ``layout_best_over_default``: default-layout steady seconds over
      the best measured layout's (>= 1.0; how much the best layout
      beats the hard-coded default);
    - ``layout_pad_waste_frac``: the best layout's padded-lane waste.

    Plus ``layouts_probed`` (>= 3 required for the 8-device pop-64 x
    suite-8 shape) and ``layout_parity_max_abs`` (every layout's robust
    scores must match the default's within 1e-5 — a layout is a
    schedule, never a different answer). Single-process CPU meshes
    time-slice one host, so the ratio ranks layouts relatively;
    absolute speedups need real devices (PROFILE.md round 22).

    Env knobs: FKS_BENCH_LAYOUT_DEVICES (default 8), FKS_BENCH_LAYOUT_POP
    (default 64), FKS_BENCH_LAYOUT_SUITE (default "default8"),
    FKS_BENCH_LAYOUT_PARITY_MAX (default 1e-5).
    """
    devices = int(os.environ.get("FKS_BENCH_LAYOUT_DEVICES", "8"))
    # must precede the first backend init; the env route works on every
    # jax this repo supports (the stage runs in its own subprocess, so
    # jax cannot have initialized yet)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count"
            f"={devices}").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.obs.layout import explore_layouts
    from fks_tpu.scenarios import get_suite

    global _RECORDER
    _RECORDER = _controller_recorder()
    pop = int(os.environ.get("FKS_BENCH_LAYOUT_POP", "64"))
    suite_name = os.environ.get("FKS_BENCH_LAYOUT_SUITE", "default8")
    parity_max = float(os.environ.get("FKS_BENCH_LAYOUT_PARITY_MAX",
                                      "1e-5"))
    wl = synthetic_workload(16, 32, seed=0)
    suite = get_suite(suite_name, wl)
    history = None
    try:
        from fks_tpu.obs.history import RunHistory
        root = os.environ.get("FKS_BENCH_RESULTS_DIR") or os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "benchmarks", "results")
        if os.path.isdir(root):
            history = RunHistory(root)
    except Exception:  # noqa: BLE001 — the prior is best-effort
        history = None
    summary = explore_layouts(
        suite, population=pop, engine="flat", recorder=_RECORDER,
        history=history, workload_key=f"pop{pop}_{suite_name}")
    log(f"layout stage: {summary['layouts_probed']} layouts over "
        f"{summary['devices']} devices — best {summary['best_mesh_shape']}"
        f" ({summary['best_layout_key']}) at "
        f"{summary['best_steady_seconds']}s vs default "
        f"{summary['default_steady_seconds']}s "
        f"(ratio {summary['layout_best_over_default']}), parity "
        f"{summary['parity_max_abs']}")
    payload = {
        "layouts_probed": summary["layouts_probed"],
        "layout_best_over_default": summary["layout_best_over_default"],
        "layout_pad_waste_frac": summary["layout_pad_waste_frac"],
        "layout_parity_max_abs": summary["parity_max_abs"],
        "layout_devices": summary["devices"],
        "layout_candidates": summary["candidates"],
        "layout_scenarios": summary["scenarios"],
        "default_layout_key": summary["default_layout_key"],
        "best_layout_key": summary["best_layout_key"],
        "best_mesh_shape": summary["best_mesh_shape"],
        "default_steady_seconds": summary["default_steady_seconds"],
        "best_steady_seconds": summary["best_steady_seconds"],
        "engine": "flat",
    }
    _record("metric", "bench_stage", payload, stage="layout",
            platform="cpu")
    rc = 0
    if summary["layouts_probed"] < 3:
        log(f"FAIL: only {summary['layouts_probed']} valid layouts "
            f"probed for pop-{pop} x suite-{len(suite)} on "
            f"{summary['devices']} devices (need >= 3)")
        rc = 1
    if summary["parity_max_abs"] > parity_max:
        log(f"FAIL: layout parity {summary['parity_max_abs']} > "
            f"{parity_max} — a layout changed the answer, not just "
            "the schedule")
        rc = 1
    if gate:
        rc = rc or _gate(gate, payload)
    _record("finish", "ok" if rc == 0 else "fail")
    _record("close")
    print(json.dumps(payload))
    return rc


# ------------------------------------------------------------ controller


def _run_stage(stage: str, env_extra: dict, timeout_s: int):
    env = dict(os.environ)
    # same persistent XLA cache the TPU measurement session uses
    # (tools/tpu_session.py): the driver's end-of-round bench run then
    # reuses the session's compiles instead of spending its deadline
    # recompiling the same programs
    env.setdefault("JAX_COMPILATION_CACHE_DIR", os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "benchmarks", "results", ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    env.update(env_extra)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage],
            env=env, timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        err = (e.stderr or b"")
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        log(f"stage {stage} timed out after {timeout_s}s; stderr tail:\n"
            f"{err[-3000:]}")
        return None
    log(r.stderr[-4000:])
    if r.returncode != 0:
        log(f"stage {stage} rc={r.returncode}")
        return None
    return r.stdout


def _gate(baseline: str, payload: dict) -> int:
    """``bench.py --gate BASELINE``: judge this run's headline against a
    baseline (a prior bench JSONL or a flight-recorder run dir) through
    the shared comparator (fks_tpu.obs.compare). The verdict table goes
    to stderr — stdout keeps the single-JSON-line contract — and a
    regression turns the exit code nonzero."""
    import tempfile

    try:
        from fks_tpu.obs.compare import (
            compare_runs, format_comparison, has_regression,
        )
        fd, tmp = tempfile.mkstemp(suffix=".jsonl")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(json.dumps(payload) + "\n")
            rows = compare_runs(baseline, tmp)
        finally:
            os.unlink(tmp)
    except Exception as e:  # noqa: BLE001 — a broken gate must not erase
        log(f"--gate failed: {type(e).__name__}: {e}")  # the printed result
        return 1
    log(format_comparison(rows, baseline, "<this bench run>"))
    if has_regression(rows):
        _record("event", "alert", source="bench_gate", baseline=baseline,
                regressions=[r["metric"] for r in rows
                             if r["status"] == "REGRESSION"])
        return 1
    return 0


def main():
    stage = ""
    if "--stage" in sys.argv:
        stage = sys.argv[sys.argv.index("--stage") + 1]
    gate = ""
    if "--gate" in sys.argv:
        gate = sys.argv[sys.argv.index("--gate") + 1]
    pop = int(os.environ.get("FKS_BENCH_POP", "512"))
    chunk = min(int(os.environ.get("FKS_BENCH_CHUNK", "256")), pop)
    reps = int(os.environ.get("FKS_BENCH_REPS", "2"))
    engine = os.environ.get("FKS_BENCH_ENGINE", "auto")

    if stage:
        # stages need a concrete engine; the controller resolves "auto"
        # via env_extra — a bare stage invocation gets the flat default
        engine = "flat" if engine == "auto" else engine
    if stage == "parity":
        return stage_parity(engine)
    if stage == "throughput":
        return stage_throughput(pop, chunk, reps, engine)
    if stage == "codetput":
        return stage_codetput()
    if stage == "budget":
        # standalone headline for the eval-budget allocator; honors
        # --gate itself (it prints its own JSON line, not the
        # controller's)
        return stage_budget(gate)
    if stage == "preflight":
        # standalone static-analysis headline (pre-sandbox reject rate,
        # fingerprint dedup, wall delta); same --gate contract as budget
        return stage_preflight(gate)
    if stage == "scale1k":
        # standalone large-cluster scale-tier headline (1k nodes x 100k
        # pods, flat CPU); same self-contained --gate contract as budget
        return stage_scale1k(gate)
    if stage == "serve":
        # standalone champion-serving headline (cold vs warm latency,
        # batched qps, zero-recompile warm path); same --gate contract
        return stage_serve(gate)
    if stage == "promote":
        # standalone promotion-pipeline headline (shadow-eval cost, swap
        # latency, zero post-swap recompiles); same --gate contract
        return stage_promote(gate)
    if stage == "resilience":
        # standalone resilience headline (shed latency, degrade-flip
        # time, drain time, parity-drift invariants); same --gate
        # contract
        return stage_resilience(gate)
    if stage == "loadgen":
        # standalone multi-tenant load headline (sustained concurrent
        # qps, tail latency, shed rate, fairness, zero steady-state
        # recompiles, accounting overhead); same --gate contract
        return stage_loadgen(gate)
    if stage == "portfolio":
        # standalone portfolio-serving headline (routed multi-champion
        # qps through one slot-vmapped executable, mid-traffic slot
        # promotion latency, zero promote recompiles); same --gate
        # contract
        return stage_portfolio(gate)
    if stage == "layout":
        # standalone layout-sweep headline (valid layouts probed over
        # the dryrun mesh, best-vs-default steady ratio, pad waste,
        # robust-score parity); same --gate contract
        return stage_layout(gate)

    # controller (hard deadline so the driver always gets the JSON line;
    # every stage/probe timeout below is clamped to the remaining budget)
    _install_kill_writeahead()
    global _RECORDER
    _RECORDER = _controller_recorder()
    deadline = time.monotonic() + int(
        os.environ.get("FKS_BENCH_DEADLINE_S", "1050"))
    budget = lambda: int(deadline - time.monotonic())  # noqa: E731
    if budget() < 300:
        return _fail("FKS_BENCH_DEADLINE_S too small (need >= 300s)")
    # Dropping /root/.axon_site from PYTHONPATH (keeping other entries)
    # drops the axon sitecustomize from the parity subprocess: its
    # register() handshake at interpreter startup hangs EVERY python
    # process while the tunnel is wedged, CPU-only ones included
    # (observed live).
    repo = os.path.dirname(os.path.abspath(__file__))
    pypath = os.pathsep.join(
        [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon_site" not in p] + [repo])
    out = _run_stage("parity", {"JAX_PLATFORMS": "cpu", "PYTHONPATH": pypath},
                     timeout_s=min(600, max(60, budget() - 240)))
    if out is None:
        # stderr (already relayed by _run_stage) distinguishes a real
        # fitness mismatch ("PARITY FAIL ...") from a timeout/crash
        return _fail("parity gate did not pass (fitness mismatch, "
                     "timeout, or crash — see stderr)")

    err, platform, attempts = _probe_backend(budget_s=max(30, budget() - 180))
    if err:
        log(f"backend probe: {err}")
        return _fail(err, failure_taxonomy=attempts)
    log(f"device platform: {platform}")

    # "auto": try the fused Pallas kernel first, falling back to the XLA
    # flat engine on ANY fused failure (Mosaic compile, device gate,
    # timeout) — the headline should be the fastest engine that actually
    # works here. Off-TPU the fused kernel would run in the (slow) pallas
    # interpreter, so auto resolves straight to flat there.
    if engine == "auto":
        engines = ["fused", "flat"] if platform == "tpu" else ["flat"]
    else:
        engines = [engine]
    eng_i = 0
    while True:
        if budget() < 120:
            return _fail("benchmark deadline exhausted")
        out = _run_stage(
            "throughput",
            {"FKS_BENCH_POP": str(pop), "FKS_BENCH_CHUNK": str(chunk),
             "FKS_BENCH_REPS": str(reps),
             "FKS_BENCH_ENGINE": engines[eng_i]},
            timeout_s=min(900, budget()))
        if out is not None:
            break
        if eng_i + 1 < len(engines):
            eng_i += 1
            log(f"falling back to engine={engines[eng_i]}")
        elif chunk > 8:
            chunk //= 4
            pop = max(chunk, pop // 4)
            log(f"retrying throughput with chunk={chunk} pop={pop}")
        else:
            return _fail("throughput stage failed at minimum chunk size")
        if budget() < 120:
            return _fail("benchmark deadline exhausted")
        # keep the probe inside the deadline too (leave room for the rerun)
        err, _, attempts = _probe_backend(budget_s=max(30, budget() - 180))
        if err:
            log(f"backend probe: {err}")
            return _fail(err, failure_taxonomy=attempts)

    stage_res = None
    for line in reversed(out.strip().splitlines()):
        try:
            cand = json.loads(line)
            if isinstance(cand, dict) and "evals_per_sec" in cand:
                stage_res = cand
                break
        except json.JSONDecodeError:
            continue
    if stage_res is None:
        return _fail("throughput stage produced no parsable result")
    evals_per_sec = stage_res["evals_per_sec"]
    _record("metric", "bench_stage", stage_res, stage="throughput",
            engine=engines[eng_i], population=pop, chunk=chunk,
            platform=platform)

    # code-candidate throughput, best-effort (never fails the bench):
    # live measurement when the budget allows, else the freshest session
    # record — the apples-to-apples answer to the reference's ~40/s/host
    code_eps = None
    code_src = None
    if budget() > 240:
        out2 = _run_stage("codetput", {}, timeout_s=min(600, budget() - 60))
        if out2 is not None:
            for line in reversed(out2.strip().splitlines()):
                try:
                    cand = json.loads(line)
                    if isinstance(cand, dict) and "code_evals_per_sec" in cand:
                        code_eps = cand["code_evals_per_sec"]
                        code_src = "live"
                        _record("metric", "bench_stage", cand,
                                stage="codetput", platform=platform)
                        break
                except json.JSONDecodeError:
                    continue
    if code_eps is None:
        _, code_banked = _banked_measurement()
        if code_banked is not None:
            code_eps = code_banked["value"]
            code_src = {"banked_from": code_banked}

    payload = {
        "metric": METRIC,
        "value": round(evals_per_sec, 2),
        "unit": "evals/s",
        "vs_baseline": round(evals_per_sec / BASELINE_EVALS_PER_SEC, 3),
    }
    # compile-vs-steady-state split from the winning throughput stage
    # (PAPERS.md: evosax/Fast PBRL report the two separately; so do we),
    # plus the embedded StageProfiler attribution record
    for k in ("compile_seconds", "backend_compiles", "first_call_seconds",
              "steady_state_seconds", "cost_flops", "cost_bytes_accessed",
              "device_profile"):
        if k in stage_res:
            payload[k] = stage_res[k]
    if code_eps is not None:
        payload["code_evals_per_sec"] = round(code_eps, 2)
        payload["code_vs_reference_40eps"] = round(
            code_eps / BASELINE_EVALS_PER_SEC, 3)
        if code_src != "live":
            payload["code_source"] = code_src
    _record("metric", "headline", payload)
    _record("annotate_meta", value=payload["value"],
            vs_baseline=payload["vs_baseline"])
    rc = 0
    if gate:
        rc = _gate(gate, payload)
    _record("finish", "ok")
    _record("close")
    _print_result(json.dumps(payload))
    return rc


if __name__ == "__main__":
    sys.exit(main())
