"""Headline benchmark: candidate-policy evaluations/sec on the default trace.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

What is measured: the full reference workload (16 nodes x 8,152 pods,
reference: benchmarks/traces/csv/openb_pod_list_default.csv) evaluated for a
population of parametric scheduling policies as vmapped XLA programs — the
unit of work the reference performs per candidate in its
ProcessPoolExecutor (reference: funsearch/funsearch_integration.py:30-64:
re-parse trace, deep-copy state, run the Python event loop, ~0.2 s/eval,
SURVEY.md §6). Baseline: the reference's best implied throughput on its own
benchmark, max_workers(8) / 0.2 s = 40 evals/s/host.

Two-stage protocol:
1. PARITY GATE (exact engine, fks_tpu.sim.engine): first_fit/best_fit/
   funsearch_4901 fitness must reproduce the reference table to 1e-4 —
   the benchmark refuses to report from a simulator that disagrees with
   the reference. The exact engine replicates the reference bit-for-bit
   including its heap-layout-dependent retry rule.
2. THROUGHPUT (flat engine, fks_tpu.sim.flat, by default): the slot-per-pod
   event queue the TPU likes — identical semantics except the documented
   retry-time rule (time-order next deletion; measured fitness deltas on
   the published policies <= 0.029, tests/test_flat_engine.py). The flat
   engine's own best_fit score is additionally checked against the
   reference value to 2e-2 before timing.

The population is evaluated in chunks so no single device execution
exceeds the axon tunnel's ~60 s kill window; throughput = total evals /
total wall time across chunks (compile excluded; the compiled program is
reused by every chunk and every later generation).

Env knobs: FKS_BENCH_POP (total population, default 1024),
FKS_BENCH_CHUNK (per-device-call lanes, default 256),
FKS_BENCH_REPS (timed repetitions, default 2),
FKS_BENCH_ENGINE (flat|exact, default flat).
"""
import json
import os
import sys
import time

import numpy as np

BASELINE_EVALS_PER_SEC = 40.0  # reference: 8 workers / 0.2 s per eval
PARITY = {"first_fit": 0.4292, "best_fit": 0.4465, "funsearch_4901": 0.4901}


def log(*a):
    print(*a, file=sys.stderr, flush=True)


METRIC = "candidate policy evaluations/sec (8152-pod trace)"


def _fail(error: str) -> int:
    """The benchmark's single-JSON-line contract, error form."""
    print(json.dumps({"metric": METRIC, "value": 0.0, "unit": "evals/s",
                      "vs_baseline": 0.0, "error": error}))
    return 1


def _probe_backend(timeout_s: int = 120):
    """The axon TPU tunnel can WEDGE (hang indefinitely) after a killed
    device execution; backend init then blocks forever. Probe device
    discovery in a subprocess first so a wedged tunnel yields an error
    JSON instead of a hung benchmark. Returns None when healthy, else an
    error string (real init failures keep their stderr)."""
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return "device backend initialization timed out (wedged tunnel?)"
    if r.returncode != 0:
        log(f"backend probe failed rc={r.returncode}:\n{r.stderr[-2000:]}")
        return f"device backend initialization failed (rc={r.returncode})"
    return None


def main():
    err = _probe_backend()
    if err:
        log(f"backend probe: {err}")
        return _fail(err)

    import jax

    from fks_tpu.data import TraceParser
    from fks_tpu.models import parametric, zoo
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig, simulate

    pop = int(os.environ.get("FKS_BENCH_POP", "1024"))
    chunk = int(os.environ.get("FKS_BENCH_CHUNK", "256"))
    reps = int(os.environ.get("FKS_BENCH_REPS", "2"))
    engine = os.environ.get("FKS_BENCH_ENGINE", "flat")
    chunk = min(chunk, pop)
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind}); "
        f"pop={pop} chunk={chunk} reps={reps} engine={engine}")

    wl = TraceParser().parse_workload()
    log(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods")

    # ---- stage 1: parity gate on the exact engine (scores are float32 on
    # device; 1e-4 absolute covers the README's 4-digit precision)
    for name, want in PARITY.items():
        got = float(simulate(wl, zoo.ZOO[name]()).policy_score)
        if abs(got - want) > 1e-4:
            log(f"PARITY FAIL {name}: got {got:.6f} want {want:.4f}")
            return _fail(f"fitness parity failed for {name}")
        log(f"parity ok {name}: {got:.4f}")

    # flat-engine sanity: same trace, documented-retry-rule engine must
    # stay near the reference table (see module docstring)
    if engine == "flat":
        got = float(flat.simulate(wl, zoo.ZOO["best_fit"]()).policy_score)
        if abs(got - PARITY["best_fit"]) > 2e-2:
            log(f"FLAT SANITY FAIL best_fit: {got:.4f}")
            return _fail("flat-engine sanity check failed")
        log(f"flat sanity ok best_fit: {got:.4f} (exact {PARITY['best_fit']})")

    # ---- stage 2: throughput, chunked population
    key = jax.random.PRNGKey(0)
    params = parametric.init_population(key, pop, noise=0.1)
    ev = make_population_eval(wl, cfg=SimConfig(), engine=engine)

    t0 = time.perf_counter()
    res = ev(params[:chunk])
    jax.block_until_ready(res.policy_score)
    t_compile = time.perf_counter() - t0
    log(f"first chunk (compile+run): {t_compile:.1f}s; scores "
        f"[{float(np.min(res.policy_score)):.3f}, "
        f"{float(np.max(res.policy_score)):.3f}]")

    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        done = 0
        while done < pop:
            lo, hi = done, min(done + chunk, pop)
            n = hi - lo
            # chunks must share the compiled program: slice then pad to
            # the chunk width instead of re-jitting a smaller batch
            batch = params[lo:hi]
            if n < chunk:
                batch = np.concatenate(
                    [np.asarray(batch),
                     np.asarray(params[:chunk - n])], axis=0)
            r = ev(batch)
            jax.block_until_ready(r.policy_score)
            done = hi
        times.append(time.perf_counter() - t0)
    best = min(times)
    evals_per_sec = pop / best
    log(f"steady-state: {best:.3f}s / {pop} evals "
        f"({[round(t, 3) for t in times]})")

    print(json.dumps({
        "metric": METRIC,
        "value": round(evals_per_sec, 2),
        "unit": "evals/s",
        "vs_baseline": round(evals_per_sec / BASELINE_EVALS_PER_SEC, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
