"""Command-line harness: benchmark table, single runs, evolution.

TPU-native counterpart of the reference's script entry points — the
5-policy benchmark table (reference: tests/test_scheduler.py:223-361
``SchedulerTester`` + ``main``), the integration smoke run
(tests/test_integration.py:110-148), and the evolution CLI
(funsearch/funsearch_integration.py:682-706) — consolidated behind one
``argparse`` interface, which the reference lacks entirely (SURVEY.md §5:
"no argparse/env/CLI flags anywhere").

Usage:
    python -m fks_tpu.cli bench [--policies a,b,...] [--trace F] [--nodes F]
    python -m fks_tpu.cli simulate --policy best_fit [--validate]
    python -m fks_tpu.cli evolve [--config F] [--fake-llm] [--checkpoint F]
    python -m fks_tpu.cli scale [--nodes-count N] [--pods-count P] [--pop C]
    python -m fks_tpu.cli serve [--champion F] [--queries F | --http PORT]
    python -m fks_tpu.cli loadgen [--tenants SPEC] [--duration S] [--http]
    python -m fks_tpu.cli report RUN_DIR
    python -m fks_tpu.cli export-metrics RUN_DIR [--out F]
    python -m fks_tpu.cli watch RUN_DIR [--interval S] [--once]
    python -m fks_tpu.cli compare BASELINE CANDIDATE [--threshold m=rel:X]
    python -m fks_tpu.cli trends ROOT [--metric m,...] [--fail-on-alert]
    python -m fks_tpu.cli trace-diff --engines exact,flat [--policy P | --code F]
    python -m fks_tpu.cli scenarios [--suite NAME [--scenario I]]
    python -m fks_tpu.cli lint [PATHS...] [--write-pins | --no-pins]
    python -m fks_tpu.cli mem [--run-dir DIR | --sample | --drill NAME]
    python -m fks_tpu.cli traces

Every subcommand accepts ``--run-dir DIR`` to flight-record the run
(fks_tpu.obs): spans, compile/device telemetry, and per-generation
evolution ledger land in DIR as JSONL; ``report DIR`` renders the summary,
``export-metrics`` emits OpenMetrics text, ``watch`` live-tails with a
heartbeat liveness verdict, and ``compare`` gates a candidate run against
a baseline (nonzero exit on regression).
"""
from __future__ import annotations

import argparse
import contextlib
import dataclasses
import json
import sys


def _apply_platform_flags(args):
    import jax

    n_dev = getattr(args, "devices", 0)
    if n_dev and not getattr(args, "cpu", False):
        # fail loudly: silently falling back to one device is exactly the
        # footgun --devices exists to prevent
        raise SystemExit("--devices requires --cpu (it sizes the virtual "
                         "CPU device mesh)")
    if getattr(args, "cpu", False):
        # jax.config, not JAX_PLATFORMS env: the env route hangs when the
        # TPU tunnel is wedged (see .claude/skills/verify/SKILL.md)
        jax.config.update("jax_platforms", "cpu")
        if n_dev:
            # must precede first backend init (same constraint as
            # __graft_entry__.dryrun_multichip)
            legacy_xla = False
            try:
                jax.config.update("jax_num_cpu_devices", n_dev)
            except AttributeError:
                legacy_xla = True
                # jax 0.4.x has no jax_num_cpu_devices; the virtual
                # host-platform device count is an XLA flag there, read
                # when the (cleared) backend initializes — the same
                # fallback dryrun_multichip uses
                import os
                flags = os.environ.get("XLA_FLAGS", "")
                if "xla_force_host_platform_device_count" not in flags:
                    os.environ["XLA_FLAGS"] = (
                        f"{flags} --xla_force_host_platform_device_count"
                        f"={n_dev}").strip()
                from jax.extend import backend as _jexb
                _jexb.clear_backends()
            # n virtual device programs time-slicing few host cores skew
            # their arrival at collectives far past XLA-CPU's default
            # terminate timeout (observed: the 100k-pod mesh run died in
            # rendezvous on a 1-core container until these were raised;
            # README "Synthetic scale"). XLA_FLAGS is read at backend
            # creation, so appending here is still in time. The legacy
            # (jax 0.4.x) XLA predates these flags and aborts on unknown
            # XLA_FLAGS tokens, so skip them there.
            import os
            import sys
            if not legacy_xla:
                tokens = os.environ.get("XLA_FLAGS", "").split()
                names = {t.split("=")[0] for t in tokens}
                for f in ("--xla_cpu_collective_timeout_seconds=7200",
                          "--xla_cpu_collective_call_terminate_timeout_seconds"
                          "=7200"):
                    name = f.split("=")[0]
                    # token-boundary match, not substring: a user-set value
                    # for the SAME flag is honored (warn, since 40 s defaults
                    # hang the 100k-pod mesh run), and an unrelated flag
                    # sharing a prefix can't mask ours
                    if name in names:
                        if f not in tokens:
                            print(f"fks_tpu: honoring existing {name} from "
                                  "XLA_FLAGS", file=sys.stderr)
                        continue
                    tokens.append(f)
                try:  # private probe; best-effort warning only
                    initialized = bool(jax._src.xla_bridge._backends)
                except AttributeError:
                    initialized = False
                if initialized:  # appended too late to apply
                    print("fks_tpu: JAX backends already initialized; "
                          "XLA_FLAGS collective timeouts will not take "
                          "effect this run", file=sys.stderr)
                os.environ["XLA_FLAGS"] = " ".join(tokens)
    if getattr(args, "f64", False):
        jax.config.update("jax_enable_x64", True)


def _metrics_writer(args):
    """Context manager: a MetricsWriter when --metrics was given (opened up
    front so bad paths fail fast, closed on every exit path), else a null
    context yielding None."""
    if getattr(args, "metrics", ""):
        from fks_tpu.utils import MetricsWriter

        return MetricsWriter(args.metrics)
    return contextlib.nullcontext(None)


def _flight_recorder(args, command):
    """Context manager installing the process-wide flight recorder when
    ``--run-dir`` was given (fks_tpu.obs.recording), else the shared
    NullRecorder — identical API, zero filesystem writes. Opened up front
    so an unwritable run directory fails before any device work."""
    from fks_tpu import obs

    run_dir = getattr(args, "run_dir", "")
    if not run_dir:
        return obs.recording(obs.NULL)
    return obs.recording(obs.FlightRecorder(
        run_dir, meta={"command": command, "argv": sys.argv[1:]}))


def _parse_workload(args):
    from fks_tpu.data import TraceParser

    parser = TraceParser()
    return parser, parser.parse_workload(node_file=args.nodes,
                                         pod_file=args.trace)


def _add_trace_flags(p):
    p.add_argument("--trace", default="openb_pod_list_default.csv",
                   help="pod CSV under benchmarks/traces/csv/")
    p.add_argument("--nodes", default="gpu_models_filtered.csv",
                   help="node CSV under benchmarks/traces/csv/")


def _result_row(name, res, wall):
    import numpy as np

    return {
        "policy": name,
        "score": round(float(res.policy_score), 4),
        "scheduled": f"{int(res.scheduled_pods)}",
        "cpu%": round(100 * float(res.avg_cpu_utilization), 1),
        "mem%": round(100 * float(res.avg_memory_utilization), 1),
        "gpu%": round(100 * float(res.avg_gpu_count_utilization), 1),
        "milli%": round(100 * float(res.avg_gpu_memory_utilization), 1),
        "frag": round(float(res.gpu_fragmentation_score), 3),
        "snaps": int(res.num_snapshots),
        "events": int(res.events_processed),
        "max_nodes": int(res.max_nodes),
        "wall_s": round(wall, 3),
    }


def _print_table(rows):
    if not rows:
        return
    cols = list(rows[0].keys())
    widths = {c: max(len(c), *(len(str(r[c])) for r in rows)) for c in cols}
    line = "  ".join(c.rjust(widths[c]) for c in cols)
    print(line)
    print("-" * len(line))
    for r in rows:
        print("  ".join(str(r[c]).rjust(widths[c]) for c in cols))


def _pick_simulate(args):
    from fks_tpu.sim import get_engine
    return get_engine(getattr(args, "engine", "exact")).simulate


def cmd_bench(args):
    """The reference benchmark table (test_scheduler.py:287-331): every
    requested policy against the workload, jit-compiled, with wall time."""
    _apply_platform_flags(args)
    import jax.numpy as jnp

    from fks_tpu.models import zoo
    from fks_tpu.sim.engine import SimConfig
    from fks_tpu.utils import result_record

    from fks_tpu import obs

    simulate = _pick_simulate(args)
    _, wl = _parse_workload(args)
    names = (args.policies.split(",") if args.policies else list(zoo.ZOO))
    dtype = jnp.float64 if args.f64 else jnp.float32
    cfg = SimConfig(score_dtype=dtype, validate_invariants=args.validate)
    print(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods "
          f"({args.nodes} x {args.trace})", file=sys.stderr)
    rows = []
    with _flight_recorder(args, "bench") as rec, \
            obs.watch_compiles(rec), _metrics_writer(args) as metrics:
        if rec.enabled:
            rec.annotate_meta(engine=args.engine, trace=args.trace,
                              workload={"nodes": wl.num_nodes,
                                        "pods": wl.num_pods})
            obs.record_devices(rec)
        for name in names:
            if name not in zoo.ZOO:
                print(f"unknown policy {name!r}; have {list(zoo.ZOO)}",
                      file=sys.stderr)
                return 2
            with obs.span("policy", policy=name) as t:
                res = simulate(wl, zoo.ZOO[name](dtype=dtype), cfg)
                t.sync(res.policy_score)
            wall = t.seconds
            rows.append(_result_row(name, res, wall))
            if metrics:
                metrics.write("bench", result_record(res), policy=name,
                              wall_s=wall, trace=args.trace, nodes=args.nodes)
            rec.metric("bench", result_record(res), policy=name,
                       wall_s=wall, trace=args.trace, nodes=args.nodes)
            if args.validate and int(res.invariant_violations):
                print(f"WARNING: {name}: {int(res.invariant_violations)} "
                      "invariant violations", file=sys.stderr)
    _print_table(rows)
    return 0


def cmd_simulate(args):
    """Single policy, detailed output (reference: tests/test_integration.py
    style summary)."""
    _apply_platform_flags(args)
    import jax.numpy as jnp
    import numpy as np

    from fks_tpu.models import zoo
    from fks_tpu.sim.engine import SimConfig
    from fks_tpu.utils import result_record

    from fks_tpu import obs

    simulate = _pick_simulate(args)
    _, wl = _parse_workload(args)
    dtype = jnp.float64 if args.f64 else jnp.float32
    cfg = SimConfig(score_dtype=dtype, validate_invariants=args.validate)
    with _flight_recorder(args, "simulate") as rec, \
            obs.watch_compiles(rec), \
            _metrics_writer(args) as metrics:  # up front: bad paths fail fast
        with obs.span("simulate", policy=args.policy) as t:
            res = simulate(wl, zoo.ZOO[args.policy](dtype=dtype), cfg)
            t.sync(res.policy_score)
        wall = t.seconds
        n_pods = wl.num_pods
        gpu_pods = int(np.sum(np.asarray(wl.pods.num_gpu)[:n_pods] > 0))
        out = _result_row(args.policy, res, wall)
        out.update({
            "gpu_pods": gpu_pods, "cpu_only_pods": n_pods - gpu_pods,
            "success_rate": round(100 * int(res.scheduled_pods) / max(1, n_pods), 2),
            "failed": bool(res.failed), "truncated": bool(res.truncated),
            "invariant_violations": int(res.invariant_violations),
        })
        if metrics:
            metrics.write("simulate", result_record(res), policy=args.policy,
                          wall_s=wall, trace=args.trace, nodes=args.nodes)
        rec.metric("simulate", result_record(res), policy=args.policy,
                   wall_s=wall, trace=args.trace, nodes=args.nodes)
    print(json.dumps(out, indent=2))
    return 0


def _divergence_bound(trace: str, path: str = ""):
    """Latest measured flat-vs-exact divergence for ``trace`` from the
    divergence audit (tools/divergence_audit.py): ``(drift, cascades)``
    where drift is the arithmetic max|d| with retry-cascade rows excluded
    (falling back to max|d| for pre-cascade-era rows) and cascades counts
    panel policies whose flat run blew the event budget. None when no
    audit row exists."""
    import os

    path = path or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "results", "divergence_audit.jsonl")
    found = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if row.get("trace") == trace and \
                        row.get("max_abs_d") is not None:
                    found = row  # latest row wins
    except OSError:
        return None
    if found is None:
        return None
    drift = found.get("max_drift")
    if drift is None:
        drift = found["max_abs_d"]
    return float(drift), int(found.get("flat_cascades", 0))


def cmd_evolve(args):
    """Evolution loop (reference: funsearch_integration.py:682-706), with a
    hermetic --fake-llm mode and checkpoint/resume the reference lacks."""
    from fks_tpu.funsearch import EvolutionConfig, FakeLLM
    from fks_tpu.funsearch import evolution as evo
    from fks_tpu.sim.engine import SimConfig

    cfg = (EvolutionConfig.from_json(args.config) if args.config
           else EvolutionConfig())
    if args.generations is not None:
        cfg.generations = args.generations
    if args.parametric_rounds is not None:
        cfg.parametric_rounds = args.parametric_rounds
    if args.parity_sample is not None:
        cfg.parity_sample = args.parity_sample
    if args.parity_tol is not None:
        cfg.parity_tol = args.parity_tol
    if args.suite is not None:
        cfg.scenario_suite = args.suite
    if args.robust_agg is not None:
        cfg.robust_aggregation = args.robust_agg
    if args.budget is not None:
        cfg.budget_schedule = args.budget
    if args.budget_eta is not None:
        cfg.budget_eta = args.budget_eta
    if args.probe_suite is not None:
        cfg.probe_suite = args.probe_suite
    if args.probe_steps is not None:
        cfg.probe_steps = args.probe_steps
    if args.wal and not args.checkpoint:
        print("note: --wal without --checkpoint only protects the first "
              "generation; pass --checkpoint so every generation boundary "
              "is durable", file=sys.stderr)
    backend = FakeLLM(seed=cfg.seed) if args.fake_llm else None
    if backend is None and not cfg.llm.api_key:
        print("no API key in config; use --fake-llm for hermetic runs",
              file=sys.stderr)
        return 2
    if args.engine != "exact":
        # search on a fast engine ranks by a fitness that can differ from
        # the exact replica's; surface the bound MEASURED on this trace
        # (round-3 verdict weak #3) instead of a global number
        bound = _divergence_bound(args.trace)
        if bound is not None:
            drift, cascades = bound
            casc = (f"; {cascades} panel polic"
                    f"{'y' if cascades == 1 else 'ies'} hit a retry "
                    "cascade (flat score 0 — culled, never over-promoted)"
                    if cascades else "")
            print(f"note: measured flat-vs-exact drift on {args.trace}: "
                  f"max|d|={drift:.4f}{casc} (panel of seed + champion "
                  "policies; tools/divergence_audit.py). NEW BEST "
                  "admissions are exact-rescored; treat fast-engine "
                  "rankings within the drift bound as ties.",
                  file=sys.stderr)
        else:
            print(f"note: no divergence audit row for {args.trace}; run "
                  "tools/divergence_audit.py --traces "
                  f"{args.trace} for a measured flat-vs-exact bound",
                  file=sys.stderr)
    _apply_platform_flags(args)
    from fks_tpu import obs

    _, wl = _parse_workload(args)
    with _flight_recorder(args, "evolve") as rec, \
            obs.watch_compiles(rec), _metrics_writer(args) as metrics:
        if rec.enabled:
            rec.annotate_meta(engine=args.engine, trace=args.trace,
                              nodes=args.nodes,
                              workload={"nodes": wl.num_nodes,
                                        "pods": wl.num_pods})
            obs.record_devices(rec)
        on_gen = None
        if metrics:
            import dataclasses

            def on_gen(st):
                # streamed per generation: an interrupted evolution still
                # leaves a complete metric trail up to the crash point
                metrics.write("generation", dataclasses.asdict(st))
        fs = evo.run(wl, cfg, backend=backend,
                     sim_config=SimConfig(watchdog=args.watchdog),
                     checkpoint_path=args.checkpoint,
                     wal_path=args.wal, out_dir=args.out,
                     engine=args.engine, on_generation=on_gen,
                     profile=args.profile)
        if fs.best:
            rec.annotate_meta(best_score=fs.best[1],
                              best_exact=fs.best_exact,
                              generations=fs.generation)
        if fs.sentinel.alerts:
            rec.annotate_meta(parity_alerts=fs.sentinel.alerts)
    if fs.best:
        print(f"best fitness: {fs.best[1]:.4f}")
        # on interrupt evo.run already persisted champions — don't double-save
        if args.out and not getattr(fs, "interrupted", False):
            path = fs.save_top_policies(args.out, k=5)
            print(f"saved top policies to {path}")
            print(f"saved best policy to {fs.save_best_policy(args.out)}")
    if fs.sentinel.alerts:
        # the parity sentinel's nonzero-exit policy: drift beyond the
        # tolerance means the fitness selection trusted disagrees with the
        # exact reference evaluator — champions are saved above, but the
        # run must not read as clean to CI/driver scripts
        print(f"PARITY ALERT: {fs.sentinel.alerts} generation(s) exceeded "
              f"drift tolerance {cfg.parity_tol:g} (max drift "
              f"{fs.sentinel.max_drift:.3g}); see the run dir's alert "
              "events", file=sys.stderr)
        return 3
    if getattr(fs, "llm_outage", False):
        # distinct exit code: the run halted on the LLM-outage circuit
        # breaker (llm_outage ledger event + checkpoint written), so a
        # supervisor can tell "endpoint down, retry later" apart from a
        # failed search
        print(f"LLM OUTAGE: halted at generation {fs.generation} after "
              f"{fs.cfg.llm_outage_generations} consecutive generations "
              "with zero drafted candidates; checkpoint saved",
              file=sys.stderr)
        return 4
    return 0


def cmd_scale(args):
    """Synthetic scale run (BASELINE.json config 5 shape): N-node x P-pod
    generated trace, population-parallel evaluation, throughput report.
    Uses the device mesh when more than one device is visible, plain vmap
    otherwise. ``--code-pop N`` additionally measures the VM
    code-candidate tier (FakeLLM candidates lowered to register programs,
    sharded over the same mesh via make_sharded_code_eval)."""
    _apply_platform_flags(args)
    import jax

    from fks_tpu import obs
    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.models import parametric
    from fks_tpu.obs import span
    from fks_tpu.parallel import (
        make_population_eval, make_sharded_eval, pad_population,
        population_mesh,
    )
    from fks_tpu.sim.engine import SimConfig, resolve_auto_prefilter
    from fks_tpu.utils import ThroughputMeter

    with _flight_recorder(args, "scale") as rec, \
            obs.watch_compiles(rec), \
            _metrics_writer(args) as metrics:  # up front: bad paths fail fast
        node_park = None
        if getattr(args, "openb_nodes", False):
            from fks_tpu.data.traces import parse_node_yaml
            # repo-root-relative resolution (default_traces_dir), so the
            # vendored list loads from any cwd
            node_park = parse_node_yaml()
        wl = synthetic_workload(args.nodes_count, args.pods_count,
                                seed=args.seed, nodes=node_park)
        print(f"synthetic workload: {wl.num_nodes} nodes x {wl.num_pods} "
              f"pods, population {args.pop}"
              + (" (OpenB node park)" if node_park else ""),
              file=sys.stderr)
        if rec.enabled:
            rec.annotate_meta(engine=args.engine,
                              workload={"nodes": wl.num_nodes,
                                        "pods": wl.num_pods},
                              population=args.pop)
            obs.record_devices(rec)
        pop = parametric.init_population(
            jax.random.PRNGKey(args.seed), args.pop, noise=0.1)
        pk_override = getattr(args, "prefilter_k", None)
        if args.engine == "fused" and pk_override is None:
            pk = 0  # the fused kernel has no prefilter path; don't probe
        else:
            pk = resolve_auto_prefilter(
                parametric.score, jax.tree_util.tree_map(lambda x: x[0], pop),
                wl.cluster.n_padded, wl.cluster.g_padded,
                override=pk_override, recorder=rec)
        cfg = SimConfig(node_prefilter_k=pk,
                        state_pack=getattr(args, "state_pack", False))
        devices = jax.devices()
        try:
            if len(devices) > 1:
                mesh = population_mesh(devices)
                padded, real = pad_population(pop, mesh)
                obs.record_mesh(mesh, real_count=args.pop, recorder=rec)
                ev = make_sharded_eval(wl, mesh, cfg=cfg,
                                       elite_k=min(4, args.pop),
                                       engine=args.engine)
                with span("eval", population=args.pop) as t:
                    scores = t.sync(ev(padded, real)[0])[:real]
                mode = f"sharded over {len(devices)} devices"
            else:
                evp = make_population_eval(wl, cfg=cfg, engine=args.engine)
                with span("eval", population=args.pop) as t:
                    res = t.sync(evp(pop))
                scores = res.policy_score
                mode = "vmap on 1 device"
        except ValueError as e:
            if args.engine != "fused" or (
                    "VMEM" not in str(e)
                    and "node_prefilter_k" not in str(e)
                    and "state_pack" not in str(e)):
                raise  # only the fused kernel's guards get guidance
            print(f"error: {e}\n(try smaller --nodes-count/--pods-count, "
                  f"or --engine flat)", file=sys.stderr)
            return 2
        meter = ThroughputMeter()
        meter.add(args.pop, t.seconds)
        out = {
            "mode": mode, "engine": args.engine,
            "nodes": wl.num_nodes, "pods": wl.num_pods,
            "population": args.pop, "wall_s": round(t.seconds, 3),
            "evals_per_sec": round(meter.rate, 3),
            "score_min": round(float(scores.min()), 4),
            "score_max": round(float(scores.max()), 4),
            "node_prefilter_k": cfg.node_prefilter_k,
            "prefilter_auto": pk_override is None,
            "state_pack": cfg.state_pack,
            "openb_nodes": node_park is not None,
        }
        if getattr(args, "code_pop", 0) > 0:
            from fks_tpu.funsearch import vm
            from fks_tpu.parallel import make_sharded_code_eval
            from fks_tpu.sim import get_engine

            # the fused kernel evaluates parametric weights only; the VM
            # tier runs on the interpreter engines
            code_engine = "flat" if args.engine == "fused" else args.engine
            c = wl.cluster
            progs, _ = vm.lower_fake_candidates(
                c.n_padded, c.g_padded, args.code_pop, capacity=256)
            if len(progs) < args.code_pop:
                print(f"error: FakeLLM lowered only {len(progs)} VM "
                      f"candidates; lower --code-pop", file=sys.stderr)
                return 2
            stacked = vm.stack_programs(progs[: args.code_pop])
            # the code tier probes its OWN policy cost: VM register
            # programs are the expensive case the prefilter exists for,
            # so auto may choose k>0 here while the parametric tier above
            # stayed dense
            pk_code = resolve_auto_prefilter(
                vm.score_static, progs[0], c.n_padded, c.g_padded,
                override=pk_override, recorder=rec)
            ccfg = dataclasses.replace(cfg, node_prefilter_k=pk_code)
            if len(devices) > 1:
                cpadded, creal = pad_population(stacked, mesh)
                cev = make_sharded_code_eval(
                    wl, mesh, cfg=ccfg, elite_k=min(4, args.code_pop),
                    engine=code_engine)
                with span("code_eval", code_population=args.code_pop) as ct:
                    cres = ct.sync(cev(cpadded, creal)[0])
            else:
                mod = get_engine(code_engine)
                crun = mod.make_population_run_fn(wl, vm.score_static, ccfg)
                with span("code_eval", code_population=args.code_pop) as ct:
                    cres = ct.sync(crun(stacked, mod.initial_state(wl, ccfg)))
            cscores = cres.policy_score[: args.code_pop]
            cmeter = ThroughputMeter()
            cmeter.add(args.code_pop, ct.seconds)
            out.update({
                "code_population": args.code_pop,
                "code_engine": code_engine,
                "code_prefilter_k": pk_code,
                "code_wall_s": round(ct.seconds, 3),
                "code_evals_per_sec": round(cmeter.rate, 3),
                "code_score_max": round(float(cscores.max()), 4),
            })
        if metrics:
            metrics.write("scale", out)
        rec.metric("scale", out)
    print(json.dumps(out, indent=2))
    return 0


def cmd_serve(args):
    """Serve a pinned champion as a warm what-if query engine
    (fks_tpu.serve): build or load an artifact, optionally pre-compile
    every shape bucket, then answer queries over stdin/JSONL, a file, or
    a localhost HTTP listener. ``--selftest N`` instead runs the
    batched-vs-unbatched exact-parity sweep and exits nonzero on any
    drift — the run_full_suite serve gate."""
    _apply_platform_flags(args)
    from fks_tpu import obs
    from fks_tpu.serve import (
        ServeEngine, ServeService, ShapeEnvelope, latest_champion,
        load_champion, selftest,
    )
    from fks_tpu.serve.service import run_http, run_jsonl

    with _flight_recorder(args, "serve") as rec, obs.watch_compiles(rec):
        import os as _os
        from fks_tpu.serve.artifact import CHAMPION_DIR
        mesh = None
        if getattr(args, "devices", 0):
            # mesh-sharded serving: the platform flags above already
            # sized the virtual CPU mesh; shard the lane axis over it
            import jax
            from fks_tpu.parallel import population_mesh
            mesh = population_mesh(jax.devices()[:args.devices])
        ledger_dir = args.ledger_dir or CHAMPION_DIR
        promotion_log = (args.promotion_log
                         or _os.path.join(ledger_dir, "promotion.jsonl"))
        if args.artifact:
            engine = ServeEngine.load(args.artifact, recorder=rec, mesh=mesh)
        else:
            champ_path = args.champion
            if not champ_path and args.follow_ledger:
                # crash recovery: the promotion log outranks raw ledger
                # order — restart with whatever the last surviving
                # promotion shipped, not merely the best-scored file
                from fks_tpu.pipeline import PromotionLog
                active = PromotionLog(promotion_log).active()
                if active and _os.path.exists(active.get("champion", "")):
                    champ_path = active["champion"]
                    print(f"resuming promoted champion: {champ_path}",
                          file=sys.stderr)
            if not champ_path:
                champ_path = latest_champion(ledger_dir, recorder=rec)
            if not champ_path:
                print("error: no champion JSON found — pass --champion or "
                      "evolve one first (policies/discovered/)",
                      file=sys.stderr)
                return 2
            champion = load_champion(champ_path)
            _, wl = _parse_workload(args)
            build_kw = dict(
                envelope=ShapeEnvelope(max_pods=args.max_pods,
                                       max_batch=args.max_batch),
                engine=args.engine,
                prefilter_k=getattr(args, "prefilter_k", None),
                state_pack=getattr(args, "state_pack", False),
                mesh=mesh, recorder=rec)
            engine = None
            if getattr(args, "serve_engine", "aot") == "vm":
                from fks_tpu.funsearch.vm import VMUnsupported
                from fks_tpu.serve import VMServeEngine
                try:
                    engine = VMServeEngine(champion, wl, **build_kw)
                except VMUnsupported as e:
                    # coverage gap, not an error: serve it on the exact
                    # AOT closure engine and say so (the recorded event
                    # is what the vm_serve_gate / tests assert on)
                    rec.event("vm_swap", outcome="fallback",
                              champion=champ_path, detail=str(e))
                    print(f"champion not VM-lowerable ({e}); falling "
                          "back to the AOT closure engine",
                          file=sys.stderr)
            if engine is None:
                engine = ServeEngine(champion, wl, **build_kw)
        if rec.enabled:
            rec.annotate_meta(
                engine=engine.engine_name,
                engine_kind=engine.engine_kind,
                champion={"score": engine.champion.score,
                          "source": engine.champion.source},
                envelope=engine.envelope.to_json(),
                policy_tier=engine.policy_tier,
                prefilter_k=engine.prefilter_k)
        cap = getattr(engine, "program_capacity", None)
        print(f"serving champion score={engine.champion.score:.4f} "
              f"tier={engine.policy_tier} engine={engine.engine_name} "
              f"kind={engine.engine_kind}"
              + (f" capacity={cap}" if cap else "")
              + f" prefilter_k={engine.prefilter_k}", file=sys.stderr)
        if args.save_artifact:
            if args.warmup:
                engine.warmup()
            path = engine.save(args.save_artifact)
            print(f"artifact saved: {path}", file=sys.stderr)
        if args.selftest:
            result = selftest(engine, count=args.selftest,
                              pods_per_query=args.pods_per_query,
                              tol=args.audit_tol)
            if getattr(args, "serve_engine", "aot") == "vm":
                # did the requested VM binding actually engage, or did
                # the champion fall back to the AOT closure engine?
                result["vm_coverage"] = (1.0 if engine.engine_kind == "vm"
                                         else 0.0)
            if rec.enabled and "snapshot_cache" in result:
                rec.metric("snapshot_cache", **result["snapshot_cache"])
            print(json.dumps(result, indent=2))
            return 0 if result["ok"] else 1
        if args.warmup and not args.save_artifact:
            n = engine.warmup()
            print(f"warm: {n} bucket programs compiled", file=sys.stderr)
        if args.save_artifact and not (args.queries or args.http):
            return 0  # artifact-build invocation, nothing to serve
        slo = None
        if args.slo_p99_ms or args.slo_qps:
            from fks_tpu.obs.history import SLOConfig
            slo = SLOConfig(p99_ms=args.slo_p99_ms, qps=args.slo_qps,
                            error_budget=args.slo_error_budget)
        service = ServeService(engine, recorder=rec,
                               max_wait_s=args.max_wait_ms / 1e3,
                               audit_every=args.audit_every,
                               audit_tol=args.audit_tol, slo=slo,
                               max_queue=args.max_queue,
                               default_deadline_s=args.request_deadline_s,
                               accounting=args.accounting)
        if args.degraded_fallback:
            from fks_tpu.resilience import exact_fallback_factory

            # fallback + rebuild reuse the engine's own champion/workload;
            # the rebuild recreates the primary configuration warm
            service.enable_degraded_mode(
                exact_fallback_factory(engine.champion, _parse_workload(
                    args)[1], engine.envelope, recorder=rec),
                rebuild_factory=None)
            print("degraded-mode fallback armed (exact engine, batch 1)",
                  file=sys.stderr)
        drainer = None
        if args.drain_state:
            from fks_tpu.resilience import (DrainCoordinator,
                                            load_serve_state)

            if _os.path.exists(args.drain_state):
                try:
                    n = service.preload_replay(
                        load_serve_state(args.drain_state)["replay"])
                    print(f"replay buffer preloaded: {n} queries from "
                          f"{args.drain_state}", file=sys.stderr)
                except ValueError as e:
                    print(f"ignoring stale drain state: {e}",
                          file=sys.stderr)
            drainer = DrainCoordinator(service,
                                       state_path=args.drain_state,
                                       recorder=rec)
            if not drainer.install():
                print("warning: SIGTERM handler unavailable off the main "
                      "thread; drain runs on normal shutdown only",
                      file=sys.stderr)
        stop_follow = None
        if args.follow_ledger:
            from fks_tpu.obs.history import SLOConfig as _SLO
            from fks_tpu.pipeline import (
                PromotionConfig, PromotionController, follow_ledger,
            )
            controller = PromotionController(
                service, ledger_dir=ledger_dir, log_path=promotion_log,
                config=PromotionConfig(slo=slo if slo is not None
                                       else _SLO()),
                recorder=rec)
            # one synchronous poll before traffic (a champion newer than
            # the one we loaded promotes up front, deterministically),
            # then the background poll thread takes over
            first = controller.poll_once()
            if first.get("action") != "idle":
                print(f"promotion: {first}", file=sys.stderr)
            stop_follow, _ = follow_ledger(controller,
                                           interval=args.promote_interval)
        try:
            if args.http:
                print(f"listening on http://127.0.0.1:{args.http} "
                      "(POST /query, GET /stats, GET /healthz)",
                      file=sys.stderr)
                run_http(service, args.http,
                         deadline_s=args.request_deadline_s,
                         drain_coordinator=drainer)
                errors = 0
            elif args.queries and args.queries != "-":
                with open(args.queries) as f:
                    errors = run_jsonl(service, f)
            else:
                errors = run_jsonl(service)  # stdin
        finally:
            if stop_follow is not None:
                stop_follow.set()
            if drainer is not None and drainer.report is None:
                # normal shutdown still drains + persists (idempotent
                # with the SIGTERM path)
                drainer.drain()
            service.close()
            summary = service.summary()
            print(json.dumps(summary), file=sys.stderr)
    return 1 if errors else 0


def cmd_loadgen(args):
    """Drive a sustained multi-tenant arrival mix against a warm serve
    service (fks_tpu.obs.workload.run_loadgen) and print the summary —
    the four compare-gated keys ``loadgen_qps`` / ``loadgen_p99_ms`` /
    ``loadgen_shed_rate`` / ``loadgen_fairness_index`` plus per-tenant
    breakdowns. Accounting is always on: the run dir gets
    ``tenant_stats`` / ``workload_mix`` / ``loadgen_summary`` records
    alongside the serve metrics, so ``report`` / ``watch`` /
    ``export-metrics`` render the tenant view afterwards. Default is a
    hermetic template champion over a synthetic workload; ``--http``
    routes through the concurrent localhost HTTP front instead of the
    in-process client."""
    _apply_platform_flags(args)
    from fks_tpu import obs
    from fks_tpu.obs.history import SLOConfig
    from fks_tpu.obs.workload import (
        http_client, parse_tenant_spec, run_loadgen, service_client,
    )
    from fks_tpu.serve import (
        ChampionSpec, ServeEngine, ServeService, ShapeEnvelope,
        load_champion, make_http_server,
    )

    try:
        plan = parse_tenant_spec(args.tenants)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    with _flight_recorder(args, "loadgen") as rec, obs.watch_compiles(rec):
        if args.champion:
            champion = load_champion(args.champion)
            _, wl = _parse_workload(args)
        else:
            # hermetic default: a template champion over a synthetic
            # workload, so loadgen runs before any evolution has
            # produced a ledger (and repeat runs are bit-identical)
            from fks_tpu.data.synthetic import synthetic_workload
            from fks_tpu.funsearch import template

            champion = ChampionSpec(
                code=template.fill_template("score = 1000"),
                source="<loadgen-default>")
            wl = synthetic_workload(16, 32, seed=args.seed)
        engine = ServeEngine(
            champion, wl,
            envelope=ShapeEnvelope(max_pods=args.max_pods,
                                   max_batch=args.max_batch),
            engine=args.engine, recorder=rec)
        engine.warmup()  # measure serving, not first-call compiles
        slo = (SLOConfig(p99_ms=args.slo_p99_ms) if args.slo_p99_ms
               else None)
        service = ServeService(engine, recorder=rec, slo=slo,
                               max_queue=args.max_queue,
                               accounting=True,
                               workload_every=args.workload_every)
        if rec.enabled:
            rec.annotate_meta(tenants=args.tenants,
                              duration_s=args.duration,
                              front="http" if args.http is not None
                              else "in-process")
        server = None
        try:
            if args.http is not None:
                import threading

                server = make_http_server(service, args.http)
                port = server.server_address[1]
                threading.Thread(target=server.serve_forever,
                                 daemon=True).start()
                send = http_client(port)
                print(f"loadgen -> http://127.0.0.1:{port}/query",
                      file=sys.stderr)
            else:
                send = service_client(service)
            summary = run_loadgen(send, plan, duration_s=args.duration,
                                  seed=args.seed, recorder=rec)
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            service.close()
            # record the serve-side view: tenant_stats / workload_mix /
            # slo_burn rows land in the run dir even when the request
            # count never crossed a workload_every window
            service.summary()
    print(json.dumps(summary, indent=2))
    return 0


#: deterministic built-in portfolio: four template logics with distinct
#: placement behaviour, so the portfolio gate runs before any evolution
#: has produced a ledger (and repeat runs are bit-identical)
_PORTFOLIO_LOGICS = (
    # raw-milli scores, NOT the normalized "+fit/total" variants: those
    # collapse into all-tie constant policies under the template's
    # int() truncation, and four behaviorally identical slots could not
    # catch a cross-slot routing bug in the parity selftest
    "score = 1000",
    "score = node.cpu_milli_left - pod.cpu_milli",
    "score = node.memory_mib_left - pod.memory_mib",
    "score = pod.cpu_milli - node.cpu_milli_left",
)


def cmd_portfolio(args):
    """Multi-tenant champion-portfolio serving (fks_tpu.portfolio): N
    resident policies in ONE slot-vmapped VM executable, routed per
    request. ``--selftest N`` runs the per-slot parity sweep (every
    resident slot vs a single-champion VM engine, plus a mixed-slot
    batch) and then promotes one slot mid-traffic under a compile
    watcher — the run_full_suite portfolio gate. ``--http`` serves the
    routed front instead."""
    _apply_platform_flags(args)
    from fks_tpu import obs
    from fks_tpu.funsearch import template
    from fks_tpu.portfolio import (
        PortfolioEngine, PortfolioService, Router, portfolio_selftest,
        vm_coverage_split,
    )
    from fks_tpu.serve import ChampionSpec, ShapeEnvelope, load_champion
    from fks_tpu.serve.service import run_http

    with _flight_recorder(args, "portfolio") as rec, obs.watch_compiles(rec):
        mesh = None
        if getattr(args, "devices", 0):
            import jax
            from fks_tpu.parallel import population_mesh
            mesh = population_mesh(jax.devices()[:args.devices])
        if args.champion:
            champs = [load_champion(p) for p in args.champion]
            _, wl = _parse_workload(args)
        else:
            from fks_tpu.data.synthetic import synthetic_workload
            champs = [ChampionSpec(code=template.fill_template(lg),
                                   score=0.5 + 0.1 * i,
                                   source=f"<builtin-{i}>")
                      for i, lg in enumerate(_PORTFOLIO_LOGICS)]
            wl = synthetic_workload(16, 32, seed=args.seed)
        n_pad = wl.cluster.n_padded
        g_pad = wl.cluster.g_padded
        resident, outside = vm_coverage_split(champs, n_pad, g_pad)
        if not resident:
            print("error: no champion is VM-lowerable at this cluster "
                  "shape — a portfolio needs at least one resident slot",
                  file=sys.stderr)
            return 2
        for c in outside:
            print(f"champion {c.source or '<inline>'} outside the VM "
                  "vocabulary; excluded from the slot table (serve it "
                  "via the Router's AOT fallback)", file=sys.stderr)
        n_slots = args.slots or len(resident) + 1  # +1 spare shadow slot
        engine = PortfolioEngine(
            resident, wl, n_slots=n_slots,
            envelope=ShapeEnvelope(max_pods=args.max_pods,
                                   max_batch=args.max_batch),
            engine=args.engine, mesh=mesh, recorder=rec)
        if rec.enabled:
            rec.annotate_meta(
                engine_kind=engine.engine_kind, n_slots=engine.n_slots,
                program_capacity=engine.program_capacity,
                slots=[c.source for c in engine.slot_champions])
        print(f"portfolio: {len(resident)} resident / {len(outside)} "
              f"fallback champions, {engine.n_slots} slots, "
              f"capacity={engine.program_capacity}", file=sys.stderr)
        engine.warmup()
        if args.selftest:
            return _portfolio_selftest_run(args, engine, resident,
                                           portfolio_selftest, rec)
        pins = {}
        for spec in args.pin:
            tenant, _, slot = spec.partition("=")
            pins[tenant] = int(slot)
        ab = {}
        for spec in args.ab:
            slot, _, weight = spec.partition("=")
            ab[int(slot)] = float(weight)
        router = Router(engine.n_slots, pins=pins, ab_split=ab or None)
        service = PortfolioService(engine, router=router, recorder=rec,
                                   max_wait_s=args.max_wait_ms / 1e3,
                                   max_queue=args.max_queue,
                                   accounting=True)
        try:
            if args.http:
                print(f"listening on http://127.0.0.1:{args.http} "
                      "(POST /query, GET /stats, GET /healthz)",
                      file=sys.stderr)
                run_http(service, args.http)
            else:
                from fks_tpu.serve.service import run_jsonl
                run_jsonl(service)
        finally:
            service.close()
            print(json.dumps(service.summary()), file=sys.stderr)
    return 0


def _portfolio_selftest_run(args, engine, resident, portfolio_selftest,
                            rec):
    """The gate body: per-slot + mixed-batch parity, then one slot
    promoted mid-traffic with zero XLA compiles."""
    import threading

    from fks_tpu import obs
    from fks_tpu.funsearch import template
    from fks_tpu.serve import ChampionSpec

    result = portfolio_selftest(engine, count=args.selftest,
                                pods_per_query=args.pods_per_query,
                                tol=args.audit_tol)
    # mid-traffic slot promotion: hammer every resident slot from
    # threads while one slot's tables are swapped out and back — the
    # zero-compile contract under concurrency, on this exact build
    target = min(1, engine.n_slots - 1)
    promoted = ChampionSpec(
        code=template.fill_template(
            "score = 3000 + (node.cpu_milli_left - pod.cpu_milli) "
            "/ max(1, node.cpu_milli_total)"),
        score=9.9, source="<promoted>")
    base = engine.base_pods
    stop = threading.Event()
    errors = []

    def _hammer(slot):
        i = 0
        while not stop.is_set():
            q = [dict(base[(i + j) % len(base)]) for j in range(3)]
            try:
                ans = engine.answer_batch([q], slots=[slot])[0]
                if ans.get("score") is None:
                    errors.append(f"slot {slot}: empty answer")
            except Exception as e:  # noqa: BLE001 — surfaced in result
                errors.append(f"slot {slot}: {type(e).__name__}: {e}")
                return
            i += 1

    watcher = obs.CompileWatcher().install()
    try:
        threads = [threading.Thread(target=_hammer, args=(s,))
                   for s in range(min(len(resident), engine.n_slots))]
        for t in threads:
            t.start()
        old = engine.swap_slot(target, promoted)
        engine.swap_slot(target, old)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        compiles = watcher.backend_compile_count
    finally:
        stop.set()
        watcher.uninstall()
    result["swap"] = {"slot": target, "swaps": 2, "compiles": compiles,
                      "errors": errors[:5],
                      **{k: engine.last_swap_breakdown[k]
                         for k in ("swap_ms", "h2d_ms", "h2d_bytes")}}
    result["ok"] = bool(result["ok"] and compiles == 0 and not errors)
    if rec.enabled:
        rec.metric("portfolio_selftest", **{
            k: v for k, v in result.items() if k != "failures"})
    print(json.dumps(result, indent=2))
    return 0 if result["ok"] else 1


def cmd_pipeline(args):
    """Promotion-pipeline utilities (fks_tpu.pipeline). Default: print
    the promotion.jsonl state-machine status (per-attempt states, the
    active promotion, interrupted attempts, torn lines). ``--drill``
    runs the deterministic fault-injection drill matrix instead and
    exits nonzero on any failed drill — the run_full_suite promotion
    gate."""
    import os

    _apply_platform_flags(args)
    from fks_tpu import obs
    from fks_tpu.serve.artifact import CHAMPION_DIR

    ledger_dir = args.ledger_dir or CHAMPION_DIR
    log_path = args.log or os.path.join(ledger_dir, "promotion.jsonl")
    if args.drill:
        from fks_tpu.pipeline import run_drills

        with _flight_recorder(args, "pipeline") as rec, \
                obs.watch_compiles(rec):
            results = run_drills(log=lambda m: print(m, file=sys.stderr),
                                 only=args.only)
            ok = all(r["ok"] for r in results)
            if rec.enabled:
                rec.annotate_meta(drills=len(results), drills_ok=ok)
        print(json.dumps({"ok": ok, "drills": results}, indent=2))
        return 0 if ok else 1
    from fks_tpu.pipeline import PromotionLog

    print(json.dumps(PromotionLog(log_path).summary(), indent=2))
    return 0


def cmd_report(args):
    """Render a flight-recorder run directory (written by ``--run-dir``)
    back into a human-readable summary — generations table with a fitness
    sparkline, admit/reject breakdown, compile events, span hotspots — from
    the JSONL files alone (no in-process state)."""
    from fks_tpu.obs import render_report

    try:
        print(render_report(args.run_dir))
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    return 0


def cmd_export_metrics(args):
    """Render a flight-recorder run directory as OpenMetrics text
    exposition (``# TYPE``/``# HELP`` blocks, ``# EOF`` terminator) —
    scrape-able by any Prometheus textfile collector, no client library."""
    from fks_tpu.obs import to_openmetrics

    try:
        text = to_openmetrics(args.run_dir)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.out:
        # atomic replace: a scraper must never read a half-written file
        import os
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, args.out)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(text)
    return 0


def cmd_watch(args):
    """Live-tail a run directory: new generation/parity/bench records plus
    a heartbeat liveness verdict (HEALTHY / STALE / DEAD — thresholds at
    2x / 10x the run's own metric cadence) every ``--interval`` seconds.
    Exits 0 when the run finishes ok, 1 on error status or a dead run."""
    from fks_tpu.obs import watch

    try:
        return watch(args.run_dir, interval=args.interval, once=args.once)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    except KeyboardInterrupt:
        return 0


def cmd_spans(args):
    """Causal-trace viewer over a run directory's ``trace_span`` events
    (fks_tpu.obs.trace_ctx): list traces, render one request's latency
    waterfall (``--trace``), rank the slowest requests (``--slowest``),
    verify every served request reconstructs a complete waterfall
    (``--check-complete``, the run_full_suite trace gate), or print the
    per-generation critical path with the device-idle vs LLM-idle split
    (``--critical-path``, gated by ``--min-fraction``)."""
    from fks_tpu.obs import trace_ctx
    from fks_tpu.obs.report import load_run

    try:
        _meta, events, metrics = load_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    spans = trace_ctx.trace_spans(events)
    by = trace_ctx.traces_by_id(spans)

    if args.trace:
        match = by.get(args.trace)
        if match is None:  # allow unambiguous prefixes (ids are long)
            hits = [t for t in by if t.startswith(args.trace)]
            if len(hits) != 1:
                print(f"error: trace {args.trace!r} "
                      f"{'is ambiguous' if hits else 'not found'} "
                      f"({len(by)} traces in run)", file=sys.stderr)
                return 2
            match = by[hits[0]]
        print(trace_ctx.render_waterfall(match))
        return 0

    def _root(tid):
        roots = [s for s in by[tid] if not s.get("parent_id")]
        return roots[0] if len(roots) == 1 else None

    if args.check_complete:
        # every request the service REPORTED serving must reconstruct a
        # complete causally-linked waterfall — the metric stream is the
        # ground truth for what was served, the event stream must match
        served = [m for m in metrics if m.get("kind") == "serve_request"
                  and m.get("trace_id")]
        bad = [m["trace_id"] for m in served
               if not trace_ctx.waterfall_complete(by.get(m["trace_id"], []))]
        print(f"served requests: {len(served)}  "
              f"complete waterfalls: {len(served) - len(bad)}")
        for tid in bad[:10]:
            print(f"  INCOMPLETE {tid}")
        if not served:
            print("error: no traced serve_request metrics in run",
                  file=sys.stderr)
            return 1
        return 1 if bad else 0

    if args.critical_path:
        gens = sorted(t for t in by if _root(t) is not None
                      and _root(t).get("path") == "generation")
        if not gens:
            print("error: no generation traces in run", file=sys.stderr)
            return 1
        failed = 0
        print(f"{'trace':<22} {'wall s':>8} {'attr %':>7} "
              f"{'dev-idle s':>10} {'llm-idle s':>10}  bounding")
        for tid in gens:
            cp = trace_ctx.critical_path(by[tid])
            if not cp.get("ok"):
                failed += 1
                print(f"{tid:<22} (no root span)")
                continue
            frac = cp["attributed_fraction"]
            if frac < args.min_fraction:
                failed += 1
            print(f"{tid:<22} {cp['wall_seconds']:>8.3f} "
                  f"{frac * 100:>6.1f}% {cp['device_idle_seconds']:>10.3f} "
                  f"{cp['llm_idle_seconds']:>10.3f}  "
                  f"{cp['bounding_stage']}"
                  f"{'  << below min-fraction' if frac < args.min_fraction else ''}")
        return 1 if failed else 0

    order = sorted(
        by, key=lambda t: -max(float(s.get("seconds", 0.0))
                               for s in by[t]))
    if args.slowest:
        shown = [t for t in order
                 if _root(t) is not None
                 and _root(t).get("path") == trace_ctx.SERVE_ROOT]
        for tid in shown[: args.slowest]:
            print(trace_ctx.render_waterfall(by[tid]))
            print()
        if not shown:
            print("error: no serve/request traces in run", file=sys.stderr)
            return 1
        return 0

    print(f"{len(by)} traces, {len(spans)} spans")
    for tid in order[:30]:
        root = _root(tid)
        path = root.get("path", "?") if root else "(torn)"
        wall = max(float(s.get("seconds", 0.0)) for s in by[tid])
        print(f"  {tid:<24} {path:<16} {wall * 1e3:>10.3f} ms  "
              f"{len(by[tid])} spans")
    if len(by) > 30:
        print(f"  ... {len(by) - 30} more (use --trace/--slowest)")
    return 0


def cmd_compare(args):
    """Cross-run regression gate: diff two run dirs (or bench JSONL files)
    on the shared metric vocabulary — throughput, compile seconds, fitness
    best/median, parity drift, watchdog violation counts — and exit 1 when
    the candidate regresses past a threshold (fks_tpu.obs.compare).
    ``--baseline auto`` (the literal word as BASELINE) resolves the best
    healthy historical run under ``--history-root`` instead of a
    hand-picked path (fks_tpu.obs.history)."""
    from fks_tpu.obs import compare_runs, format_comparison, has_regression
    from fks_tpu.obs.compare import parse_threshold_overrides

    baseline = args.baseline
    if baseline == "auto":
        from fks_tpu.obs.history import resolve_auto_baseline

        root = args.history_root or _default_history_root()
        baseline = resolve_auto_baseline(root)
        if baseline is None:
            print(f"error: no healthy historical run under {root} to "
                  "auto-select as baseline", file=sys.stderr)
            return 2
        print(f"auto baseline: {baseline}", file=sys.stderr)
    try:
        thresholds = (parse_threshold_overrides(args.threshold)
                      if args.threshold else None)
        rows = compare_runs(baseline, args.candidate,
                            thresholds=thresholds)
    except (FileNotFoundError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(format_comparison(rows, baseline, args.candidate))
    return 1 if has_regression(rows) else 0


def _default_history_root() -> str:
    """benchmarks/results under the repo root — where bench.py banks
    headline evidence and run_full_suite lands its rows."""
    import os

    return os.environ.get("FKS_BENCH_RESULTS_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks", "results")


def cmd_trends(args):
    """Cross-run trend report (fks_tpu.obs.history): index every
    flight-recorder run dir and bench evidence file under ROOT, render
    per-metric timelines as sparklines, and flag regressions with the
    robust z-score pass. Exit code contract: 0 = rendered (alerts print
    but don't fail), 1 with ``--fail-on-alert`` when any metric alerted,
    2 = bad/empty root — scriptable like ``compare``
    (tools/run_full_suite.py's trends gate leans on it)."""
    from fks_tpu.obs.history import RunHistory
    from fks_tpu.obs.report import sparkline

    try:
        hist = RunHistory(args.root)
        hist.scan()
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not hist.entries:
        print(f"error: no runs indexed under {args.root}", file=sys.stderr)
        return 2
    if args.write_index:
        path = hist.write_index()
        print(f"indexed {len(hist.entries)} entries -> {path}",
              file=sys.stderr)
    metrics = ([m.strip() for m in args.metric.split(",") if m.strip()]
               if args.metric else None)
    reports = hist.trends(metrics=metrics, window=args.window, z=args.z)
    print(f"trend report: {len(hist.entries)} indexed entries "
          f"under {args.root}")
    total_alerts = 0
    with _flight_recorder(args, "trends") as rec:
        for rep in reports:
            rec.metric("trend_report",
                       {k: rep[k] for k in ("metric", "runs", "alerts",
                                            "higher_is_better", "window",
                                            "z", "values", "labels")})
            arrow = ("higher=better" if rep["higher_is_better"]
                     else "lower=better")
            print(f"\n{rep['metric']}  ({rep['runs']} runs, {arrow})")
            print(f"  {sparkline(rep['values'])}  latest "
                  f"{rep['values'][-1]:g}")
            for a in rep["alerts"]:
                total_alerts += 1
                print(f"  ALERT {a['direction']} at {a['run']}: "
                      f"{a['value']:g} vs prior median {a['median']:g} "
                      f"(robust z {a['z']:+.1f})")
    if not reports:
        print("\nno watched metrics present in the indexed entries")
    print(f"\n{total_alerts} trend alert(s)")
    if total_alerts and args.fail_on_alert:
        return 1
    return 0


def cmd_trace_diff(args):
    """Replay one policy through two engines with the decision trace on and
    report the first divergent scheduling step (fks_tpu.obs.tracing).
    Exit code contract: 0 = no divergence, 1 = divergence found, 2 = error
    — scriptable like ``compare`` (tools/run_full_suite.py's trace gate
    leans on the 0 path)."""
    _apply_platform_flags(args)
    from fks_tpu.obs import tracing
    from fks_tpu.sim.engine import SimConfig

    engines = [e.strip() for e in args.engines.split(",") if e.strip()]
    if len(engines) != 2:
        print(f"--engines needs exactly two comma-separated names, got "
              f"{engines}", file=sys.stderr)
        return 2
    bad = [e for e in engines if e not in ("exact", "flat")]
    if bad:
        print(f"unsupported trace engine(s) {bad}: the fused kernel does "
              "not carry the decision trace; use 'exact' and/or 'flat'",
              file=sys.stderr)
        return 2
    _, wl = _parse_workload(args)
    label = args.code or args.policy
    if args.scenario is not None:
        # replay on one suite scenario (fault-injected variants included:
        # both trace engines carry NODE_DOWN/NODE_UP rows) instead of the
        # base workload
        from fks_tpu.scenarios import get_suite

        try:
            suite = get_suite(args.suite, wl)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if not 0 <= args.scenario < len(suite):
            print(f"error: --scenario {args.scenario} out of range for "
                  f"suite {suite.name!r} ({len(suite)} scenarios)",
                  file=sys.stderr)
            return 2
        wl = suite.workloads[args.scenario]
        label = (f"{label}@{suite.name}"
                 f"[{args.scenario}:{suite.names[args.scenario]}]")
    code = ""
    if args.code:
        try:
            with open(args.code) as f:
                code = f.read()
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    try:
        param_policy, params = tracing.policy_params(
            wl, policy_name=args.policy, code=code)
    except Exception as e:  # noqa: BLE001 — bad policy/code is a usage error
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        return 2
    cfg_kw = {"cond_policy": True}
    if args.max_steps:
        cfg_kw["max_steps"] = args.max_steps
    # duplicate engine names (exact-vs-exact self-consistency) get #i tags
    # so the record's per-engine keys stay distinct
    names = [f"{e}#{i}" if engines.count(e) > 1 else e
             for i, e in enumerate(engines)]
    specs = [(name, eng, param_policy, params)
             for name, eng in zip(names, engines)]
    with _flight_recorder(args, "trace-diff") as rec:
        record = tracing.trace_diff(
            wl, specs, cfg=SimConfig(**cfg_kw), score_tol=args.tol,
            recorder=rec, label=label)
    print(tracing.format_diff(record))
    return 1 if record["divergent"] else 0


def cmd_lint(args):
    """Repo-wide JAX-invariant lint + jaxpr-pin gate (fks_tpu.analysis.
    lint): AST checks for trace-safety violations over the given paths,
    then the pinned-jaxpr manifest check (key entry points lowered with
    each Python-static SimConfig flag and hashed). Exit code contract:
    0 = clean, 1 = findings or pin drift, 2 = error — scriptable like
    ``compare`` (tools/run_full_suite.py's lint gate leans on it).
    ``--write-pins`` re-lowers and rewrites the manifest instead of
    checking it (exit 0)."""
    _apply_platform_flags(args)
    from fks_tpu.analysis import lint

    paths = args.paths or ["fks_tpu"]
    pins_path = args.pins or lint.PIN_MANIFEST
    findings = lint.lint_paths(paths)
    for f in findings:
        print(f)
    pin_msgs = []
    try:
        if args.write_pins:
            man = lint.write_pins(pins_path)
            print(f"wrote {len(man['pins'])} jaxpr pins -> {pins_path}")
        elif not args.no_pins:
            pin_msgs = lint.check_pins(pins_path)
            for m in pin_msgs:
                print(m)
    except Exception as e:  # noqa: BLE001 — broken lowering is an error,
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)  # not drift
        return 2
    ok = not findings and not pin_msgs
    with _flight_recorder(args, "lint") as rec:
        rec.metric("lint_report", {
            "paths": list(paths),
            "findings": [f.to_json() for f in findings],
            "pin_drift": list(pin_msgs),
            "ok": ok,
        })
    print(f"lint: {len(findings)} finding(s), {len(pin_msgs)} pin "
          f"message(s) -> {'clean' if ok else 'FAIL'}")
    return 0 if ok else 1


def cmd_scenarios(args):
    """Scenario-suite discovery and inspection (fks_tpu.scenarios): with no
    flags, list the registered suites; with ``--suite`` materialize one
    against the workload and print its summary (per-scenario parameters +
    fault-event counts); with ``--scenario I`` zoom into one scenario,
    including its concrete NODE_DOWN/NODE_UP timeline. ``--run-dir``
    additionally lands the suite summary in the flight-recorder trail as a
    ``scenario_suite`` metric, tying an evolve run's robust scores to the
    exact scenario family they were measured on."""
    from fks_tpu.scenarios import list_suites

    if not args.suite:
        print(json.dumps(list_suites(), indent=2))
        return 0
    _apply_platform_flags(args)
    import numpy as np

    from fks_tpu.ops.heap import KIND_NODE_DOWN
    from fks_tpu.scenarios import get_suite

    _, wl = _parse_workload(args)
    with _flight_recorder(args, "scenarios") as rec:
        try:
            suite = get_suite(args.suite, wl)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        desc = suite.describe()
        rec.metric("scenario_suite", desc)
        if args.scenario is None:
            print(json.dumps(desc, indent=2))
            return 0
        if not 0 <= args.scenario < len(suite):
            print(f"error: --scenario {args.scenario} out of range for "
                  f"suite {suite.name!r} ({len(suite)} scenarios)",
                  file=sys.stderr)
            return 2
        fe = suite.workloads[args.scenario].faults
        m = np.asarray(fe.mask)
        row = dict(desc["scenarios"][args.scenario], fault_timeline=[
            {"time": int(t), "node": int(nd),
             "kind": ("NODE_DOWN" if int(k) == KIND_NODE_DOWN
                      else "NODE_UP")}
            for t, nd, k in zip(np.asarray(fe.time)[m],
                                np.asarray(fe.node)[m],
                                np.asarray(fe.kind)[m])])
    print(json.dumps(row, indent=2))
    return 0


def cmd_mem(args):
    """Memory observability (fks_tpu.obs.memory). Three modes:

    - view (default): render the memory view of a recorded run from
      ``--run-dir``'s JSONL alone — the executable footprint ladder
      (every compiled program's predicted HBM claim, largest first),
      the per-mesh-layout roll-up, the watermark sampler's host/device
      table, and the leak sentinel's verdict per fenced loop;
    - ``--sample``: take one live watermark sample (host RSS +
      normalized per-device ``memory_stats``) and print it as JSON;
    - ``--drill NAME``: run one deterministic memory drill and exit
      0/1 on its verdict — ``vm_swap_leak`` hammers ``swap_program``
      against interleaved serve batches inside a live-array fence
      (zero net drift required), ``snapshot_cache_bound`` proves the
      device snapshot cache respects a byte ceiling under distinct
      query shapes. Both record into ``--run-dir`` when given."""
    if args.drill:
        _apply_platform_flags(args)
        from fks_tpu.obs import get_recorder
        from fks_tpu.obs.memory import run_drill

        kw = {}
        if args.drill == "vm_swap_leak":
            kw = {"swaps": args.swaps, "batches": args.batches}
        with _flight_recorder(args, "mem"):
            res = run_drill(args.drill, recorder=get_recorder(), **kw)
        print(json.dumps(res))
        return 0 if res.get("ok") else 1
    if args.sample:
        _apply_platform_flags(args)
        from fks_tpu.obs.memory import WatermarkSampler

        sampler = WatermarkSampler(enabled=True, trace_host=True)
        sampler.start()
        try:
            rec = sampler.sample(stage="cli")
        finally:
            sampler.stop()
        print(json.dumps(rec))
        return 0
    if not args.run_dir:
        print("error: mem needs --run-dir DIR (view mode), --sample, or "
              "--drill NAME", file=sys.stderr)
        return 2
    from fks_tpu.obs.report import _memory_section, load_run

    try:
        _meta, _events, metrics = load_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    lines = _memory_section(metrics)
    if not lines:
        print(f"(no memory records in {args.run_dir} — footprints land "
              "when an instrumented command compiles under --run-dir)")
        return 0
    print("\n".join(lines))
    return 0


def cmd_layout(args):
    """Layout observability (fks_tpu.obs.layout). Two modes:

    - view (default): render the per-layout cost ledger of a recorded
      run from ``--run-dir``'s JSONL alone — one row per
      (workload_key, mesh_layout, layout_key) with pad waste, lane-step
      occupancy, cost-analysis bytes, and the predicted HBM claim
      joined from the footprint ledger;
    - ``--explore``: enumerate the valid layouts of a (population x
      suite) shape over the virtual CPU mesh (``--cpu --devices N``) or
      the real devices, run one warm probe each, persist the best into
      ``RunHistory``, and print the summary JSON. Exit 1 when the
      CHOSEN layout (``--mesh-shape CxS``, default the candidates-only
      default layout) is measurably dominated by another probe — the
      scriptable seam run_full_suite's layout_gate leans on."""
    if args.explore:
        import os

        _apply_platform_flags(args)
        from fks_tpu.data.synthetic import synthetic_workload
        from fks_tpu.obs import get_recorder
        from fks_tpu.obs.layout import explore_layouts
        from fks_tpu.scenarios import get_suite

        wl = synthetic_workload(16, 32, seed=args.seed)
        suite = get_suite(args.suite, wl)
        wkey = f"pop{args.pop}_{args.suite}"
        history = None
        root = args.history_root or _default_history_root()
        if os.path.isdir(root):
            from fks_tpu.obs.history import RunHistory
            history = RunHistory(root)
        engine = args.engine if args.engine != "fused" else "flat"
        with _flight_recorder(args, "layout"):
            summary = explore_layouts(
                suite, population=args.pop, engine=engine,
                recorder=get_recorder(), history=history,
                workload_key=wkey)
        chosen = summary["default_layout_key"]
        chosen_steady = summary["default_steady_seconds"]
        if args.mesh_shape:
            match = [p for p in summary["probes"]
                     if p["mesh_shape"] == args.mesh_shape]
            if not match:
                shapes = [p["mesh_shape"] for p in summary["probes"]]
                print(f"error: --mesh-shape {args.mesh_shape} not among "
                      f"the valid layouts {shapes}", file=sys.stderr)
                return 2
            chosen = match[0]["layout_key"]
            chosen_steady = match[0]["steady_seconds"]
        best = summary["best_steady_seconds"]
        dominated = (summary["best_layout_key"] != chosen
                     and best > 0
                     and chosen_steady / best > 1.05)
        summary["chosen_layout_key"] = chosen
        summary["chosen_dominated"] = dominated
        print(json.dumps(summary, indent=2))
        if dominated:
            print(f"DOMINATED: chosen layout {chosen} is "
                  f"{chosen_steady / best:.2f}x slower than "
                  f"{summary['best_layout_key']}", file=sys.stderr)
            return 1
        return 0
    if not args.run_dir:
        print("error: layout needs --run-dir DIR (view mode) or "
              "--explore", file=sys.stderr)
        return 2
    from fks_tpu.obs.report import _layout_section, load_run

    try:
        _meta, _events, metrics = load_run(args.run_dir)
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    lines = _layout_section(metrics)
    if not lines:
        print(f"(no layout records in {args.run_dir} — ledger rows land "
              "when a sharded entry point runs under --run-dir)")
        return 0
    print("\n".join(lines))
    return 0


def cmd_traces(args):
    """Dataset discovery (reference: parser.py:103-115)."""
    from fks_tpu.data import TraceParser

    parser = TraceParser()
    print("node files:")
    for f in parser.get_available_node_files():
        print(f"  {f}")
    print("pod files:")
    for f in parser.get_available_pod_files():
        print(f"  {f}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fks_tpu", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (skip the TPU tunnel)")
    common.add_argument("--metrics", default="",
                        help="append JSONL metric records to this file")
    common.add_argument("--run-dir", default="",
                        help="flight-recorder run directory (meta.json, "
                             "events.jsonl, metrics.jsonl, heartbeat); "
                             "render afterwards with 'fks_tpu report DIR'")
    common.add_argument("--engine", choices=("exact", "flat", "fused"),
                        default="exact",
                        help="simulation engine: 'exact' replicates the "
                             "reference bit-for-bit; 'flat' is the TPU "
                             "throughput engine (documented retry-rule "
                             "divergence, fks_tpu.sim.flat); 'fused' is the "
                             "Pallas whole-loop-in-VMEM kernel (parametric "
                             "populations — 'scale' command only)")

    b = sub.add_parser("bench", help="policy comparison table", parents=[common])
    _add_trace_flags(b)
    b.add_argument("--policies", default="",
                   help="comma-separated zoo policy names (default: all)")
    b.add_argument("--f64", action="store_true",
                   help="float64 evaluator arithmetic (exact reference parity)")
    b.add_argument("--validate", action="store_true",
                   help="enable the per-event invariant audit")
    b.set_defaults(fn=cmd_bench)

    s = sub.add_parser("simulate", help="one policy, detailed JSON result", parents=[common])
    _add_trace_flags(s)
    s.add_argument("--policy", default="best_fit")
    s.add_argument("--f64", action="store_true")
    s.add_argument("--validate", action="store_true")
    s.set_defaults(fn=cmd_simulate)

    e = sub.add_parser("evolve", help="run FunSearch evolution", parents=[common])
    _add_trace_flags(e)
    e.add_argument("--config", default="", help="reference-format llm_config.json")
    e.add_argument("--fake-llm", action="store_true",
                   help="deterministic offline codegen backend")
    e.add_argument("--checkpoint", default="", help="evolution checkpoint path")
    e.add_argument("--wal", default="",
                   help="generation write-ahead log path "
                        "(fks_tpu.resilience.wal): drafted candidates and "
                        "eval outcomes are fsync'd mid-generation and the "
                        "loop checkpoints every generation — a kill "
                        "mid-generation resumes without re-spending LLM "
                        "calls or device evals (pair with --checkpoint)")
    e.add_argument("--out", default="", help="directory for champion JSONs")
    e.add_argument("--generations", type=int, default=None)
    e.add_argument("--parametric-rounds", type=int, default=None,
                   help="device-resident weight-evolution generations to "
                        "interleave per LLM generation (hybrid mode; the "
                        "champion is rendered to source and competes in "
                        "the code population)")
    e.add_argument("--watchdog", action="store_true",
                   help="enable the in-graph numerics watchdog "
                        "(SimConfig.watchdog): NaN/Inf policy scores are "
                        "masked to 0 and flagged in "
                        "SimResult.numeric_flags; violations land as "
                        "'watchdog' events in the run dir")
    e.add_argument("--parity-sample", type=int, default=None,
                   help="per generation, re-score this many sampled "
                        "population members through the exact reference "
                        "evaluator (JIT tier) and alert on fitness drift "
                        "(0 = off; exit 3 when any generation alerts)")
    e.add_argument("--parity-tol", type=float, default=None,
                   help="parity drift tolerance (default 1e-5; raise "
                        "above the measured divergence bound for "
                        "--engine flat)")
    e.add_argument("--suite", default=None,
                   help="score candidates by composite ROBUST fitness over "
                        "this scenario suite (fks_tpu.scenarios; try "
                        "'default8') instead of single-trace fitness — "
                        "one vmapped evaluation covers every scenario, "
                        "fault-injected variants included")
    e.add_argument("--robust-agg", choices=("mean", "min", "cvar"),
                   default=None,
                   help="how per-scenario scores fold into the robust "
                        "score (default mean; cvar = mean of the worst "
                        "quarter)")
    e.add_argument("--budget", choices=("none", "halving"), default=None,
                   help="eval-budget allocation over the suite "
                        "(fks_tpu.funsearch.budget): 'halving' probes the "
                        "whole generation cheaply, then only the top "
                        "1/eta advance to the full suite (requires "
                        "--suite; champion parity is sentinel-audited)")
    e.add_argument("--budget-eta", type=int, default=None,
                   help="survivor fraction denominator for --budget "
                        "halving (default 2: keep the top half)")
    e.add_argument("--probe-suite", default=None,
                   help="probe-rung suite name (default smoke3)")
    e.add_argument("--probe-steps", type=int, default=None,
                   help="probe-rung event budget (truncated trace "
                        "prefix; 0 = full trace on the probe suite)")
    e.add_argument("--profile", action="store_true",
                   help="attribute wall time per pipeline stage (codegen/"
                        "preflight/transpile/device-eval/rank/ledger) with "
                        "compile-vs-compute split and lane occupancy — "
                        "device_profile records in the run dir, rendered "
                        "by 'report'. Off compiles identical programs "
                        "(jaxpr-pinned)")
    e.set_defaults(fn=cmd_evolve)

    sc = sub.add_parser("scale", help="synthetic scale run + throughput",
                        parents=[common])
    sc.add_argument("--nodes-count", "--nodes", dest="nodes_count",
                    type=int, default=1000)
    sc.add_argument("--pods-count", "--pods", dest="pods_count",
                    type=int, default=100000)
    sc.add_argument("--pop", type=int, default=8)
    sc.add_argument("--seed", type=int, default=0)
    sc.add_argument("--prefilter-k", type=int, default=None,
                    help="SimConfig.node_prefilter_k: score only the "
                         "top-k statically-feasible nodes per event "
                         "(0 = dense scan, bit-identical to the default "
                         "program). Default: auto — a cheap policy-cost "
                         "probe enables the prefilter for expensive "
                         "policies on big node parks and leaves cheap "
                         "parametric scoring dense "
                         "(fks_tpu.sim.engine.resolve_auto_prefilter)")
    sc.add_argument("--state-pack", action="store_true",
                    help="SimConfig.state_pack: narrow flat-engine carry "
                         "columns to 16-bit where the value range "
                         "provably fits (exact integer packing)")
    sc.add_argument("--openb-nodes", action="store_true",
                    help="draw the node park from the vendored OpenB "
                         "node list (benchmarks/traces/node_yaml/, 1213 "
                         "nodes; --nodes-count selects a prefix) instead "
                         "of the synthetic archetype sampler")
    sc.add_argument("--code-pop", type=int, default=0,
                    help="also measure the VM code-candidate tier with N "
                         "FakeLLM-lowered register programs (0 = off); "
                         "sharded over the mesh when >1 device is visible")
    sc.add_argument("--devices", type=int, default=0,
                    help="with --cpu: number of virtual CPU devices to "
                         "mesh over (otherwise scale silently runs "
                         "single-device vmap; this replaces setting "
                         "XLA_FLAGS=--xla_force_host_platform_device_count)")
    sc.set_defaults(fn=cmd_scale)

    sv = sub.add_parser("serve",
                        help="serve a pinned champion as a warm what-if "
                             "query engine (JSONL/HTTP)", parents=[common])
    _add_trace_flags(sv)
    sv.add_argument("--champion", default="",
                    help="champion JSON from the evolution ledger "
                         "(default: best under policies/discovered/)")
    sv.add_argument("--artifact", default="",
                    help="load a saved serve artifact directory instead of "
                         "building from --champion/--trace")
    sv.add_argument("--serve-engine", choices=("aot", "vm"), default="aot",
                    help="champion binding: 'aot' bakes the policy into "
                         "per-champion closure executables (the exact "
                         "reference); 'vm' serves the champion as data — "
                         "register-program tables passed to champion-"
                         "agnostic executables, so a promotion hot-swap "
                         "is a table upload with zero XLA compiles "
                         "(VM-unlowerable champions fall back to aot)")
    sv.add_argument("--save-artifact", default="",
                    help="persist the built engine (artifact.json + XLA "
                         "compilation cache) to this directory")
    sv.add_argument("--max-pods", type=int, default=1024,
                    help="shape envelope: largest query (pods per what-if)")
    sv.add_argument("--max-batch", type=int, default=8,
                    help="shape envelope: largest coalesced request batch")
    sv.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="flush policy: max ms the oldest pending request "
                         "waits for batch-mates (default 5)")
    sv.add_argument("--request-deadline-s", type=float, default=60.0,
                    help="per-request deadline budget in seconds (default "
                         "60, the old hardcoded HTTP timeout); a request's "
                         "own deadline_ms field wins; shed/expired "
                         "requests answer a structured 503 with "
                         "Retry-After instead of hanging (0 = no "
                         "deadline)")
    sv.add_argument("--max-queue", type=int, default=0,
                    help="bounded request queue: admission control sheds "
                         "submits beyond this depth with a typed 503 "
                         "(0 = unbounded, the historical behaviour)")
    sv.add_argument("--degraded-fallback", action="store_true",
                    help="arm degraded-mode serving: on a classified "
                         "device fault, atomically flip to a reduced-"
                         "batch exact-CPU fallback engine (same champion "
                         "and ladder) and rebuild the primary off the "
                         "request path; recovery is probation-gated")
    sv.add_argument("--drain-state", default="",
                    help="on SIGTERM, drain the batcher and persist the "
                         "replay buffer + summary to this path (loaded "
                         "back on the next start to refill shadow-eval "
                         "replay traffic)")
    sv.add_argument("--prefilter-k", type=int, default=None,
                    help="SimConfig.node_prefilter_k override (default: "
                         "auto via the policy-cost probe)")
    sv.add_argument("--state-pack", action="store_true",
                    help="SimConfig.state_pack for the serving engine; "
                         "also engages the 16-bit packed query-upload "
                         "path (bit-identical answers, ~half the "
                         "H2D bytes per request table)")
    sv.add_argument("--devices", type=int, default=0,
                    help="mesh-sharded serving: size a virtual CPU "
                         "device mesh (requires --cpu) and shard the "
                         "coalesced batch axis over it — one AOT "
                         "executable per (lane, pod) bucket spans every "
                         "device (0 = single-device engine)")
    sv.add_argument("--warmup", action="store_true",
                    help="pre-compile every (lane, pod) shape bucket "
                         "before answering")
    sv.add_argument("--queries", default="",
                    help="answer request JSONL from this file ('-' or "
                         "empty = stdin), one answer line per request")
    sv.add_argument("--http", type=int, default=0,
                    help="serve a localhost HTTP listener on this port "
                         "instead of JSONL")
    sv.add_argument("--selftest", type=int, default=0,
                    help="run the batched-vs-unbatched exact-parity sweep "
                         "with N queries and exit (nonzero on drift) — "
                         "the run_full_suite serve gate")
    sv.add_argument("--pods-per-query", type=int, default=4,
                    help="query size for --selftest (default 4)")
    sv.add_argument("--audit-every", type=int, default=0,
                    help="ParitySentinel-audit every Nth served answer "
                         "against the unbatched exact engine (0 = off)")
    sv.add_argument("--audit-tol", type=float, default=1e-5,
                    help="audit/selftest score drift tolerance")
    sv.add_argument("--slo-p99-ms", type=float, default=0.0,
                    help="SLO: target p99 latency in ms (0 = unset); "
                         "burn-rate records land as slo_burn metrics — "
                         "'watch' alerts live, export-metrics publishes "
                         "fks_slo_* gauges")
    sv.add_argument("--slo-qps", type=float, default=0.0,
                    help="SLO: target sustained queries/sec (0 = unset)")
    sv.add_argument("--slo-error-budget", type=float, default=0.01,
                    help="fraction of requests allowed over the p99 "
                         "target (default 0.01; burn_rate = observed "
                         "over-fraction / this budget)")
    sv.add_argument("--follow-ledger", action="store_true",
                    help="run the promotion controller alongside serving: "
                         "tail the champion ledger, shadow-gate each new "
                         "champion, hot-swap on promotion, auto-rollback "
                         "on SLO burn (fks_tpu.pipeline)")
    sv.add_argument("--ledger-dir", default="",
                    help="champion ledger directory to follow (default: "
                         "policies/discovered/)")
    sv.add_argument("--promotion-log", default="",
                    help="promotion.jsonl path (default: "
                         "<ledger-dir>/promotion.jsonl)")
    sv.add_argument("--promote-interval", type=float, default=5.0,
                    help="seconds between ledger polls (default 5)")
    sv.add_argument("--accounting", action="store_true",
                    help="per-tenant accounting + query fingerprinting "
                         "(fks_tpu.obs.workload): tenant_stats / "
                         "workload_mix records in the run dir, "
                         "fks_tenant_* gauges from export-metrics, a "
                         "tenant table in 'report' (off by default — the "
                         "disabled path costs nothing per request)")
    sv.set_defaults(fn=cmd_serve)

    lg = sub.add_parser(
        "loadgen",
        help="drive a sustained multi-tenant arrival mix against a warm "
             "serve service and print the gated loadgen summary",
        parents=[common])
    _add_trace_flags(lg)
    lg.add_argument("--tenants", default="a:closed:2,b:closed:2,c:open:25",
                    help="arrival plan, comma-separated "
                         "name:mode:amount[:pods] — 'closed' amount = "
                         "worker count (submit-wait-repeat), 'open' "
                         "amount = Poisson qps (arrivals never wait on "
                         "responses); pods = pods per query (default 2)")
    lg.add_argument("--duration", type=float, default=5.0,
                    help="seconds to sustain the arrival plan (default 5)")
    lg.add_argument("--seed", type=int, default=0,
                    help="loadgen RNG seed (open-loop arrival gaps; also "
                         "the synthetic-workload seed)")
    lg.add_argument("--champion", default="",
                    help="champion JSON to serve (default: a hermetic "
                         "built-in template champion over a synthetic "
                         "workload)")
    lg.add_argument("--http", type=int, nargs="?", const=0, default=None,
                    help="route through the concurrent localhost HTTP "
                         "front on this port (bare --http = ephemeral "
                         "port) instead of the in-process client")
    lg.add_argument("--max-pods", type=int, default=64,
                    help="shape envelope: largest query (default 64)")
    lg.add_argument("--max-batch", type=int, default=4,
                    help="shape envelope: largest coalesced batch "
                         "(default 4)")
    lg.add_argument("--max-queue", type=int, default=0,
                    help="bounded queue depth for admission-control "
                         "shedding (0 = unbounded)")
    lg.add_argument("--slo-p99-ms", type=float, default=50.0,
                    help="per-tenant SLO p99 target feeding burn rates "
                         "(default 50; 0 = unset)")
    lg.add_argument("--workload-every", type=int, default=100,
                    help="emit tenant_stats/workload_mix every N served "
                         "requests (default 100)")
    lg.set_defaults(fn=cmd_loadgen)

    pf = sub.add_parser(
        "portfolio",
        help="serve N resident champions from ONE slot-vmapped VM "
             "executable with per-request routing (pin / affinity / "
             "A-B / coverage fallback)",
        parents=[common])
    _add_trace_flags(pf)
    pf.add_argument("--champion", action="append", default=[],
                    help="champion JSON to load into a slot (repeatable; "
                         "default: four deterministic built-in template "
                         "champions over a synthetic workload)")
    pf.add_argument("--slots", type=int, default=0,
                    help="slot-table size (default: resident champions "
                         "+ 1 spare shadow slot)")
    pf.add_argument("--seed", type=int, default=0,
                    help="synthetic-workload seed for the built-in "
                         "champion set (default 0)")
    pf.add_argument("--devices", type=int, default=0,
                    help="mesh-sharded serving: size a virtual CPU "
                         "device mesh (requires --cpu) and shard the "
                         "lane axis over it; the slot table is "
                         "replicated (0 = single-device engine)")
    pf.add_argument("--max-pods", type=int, default=64,
                    help="shape envelope: largest query (default 64)")
    pf.add_argument("--max-batch", type=int, default=4,
                    help="shape envelope: largest coalesced batch "
                         "(default 4)")
    pf.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="flush policy: max ms the oldest pending "
                         "request waits for batch-mates (default 5)")
    pf.add_argument("--max-queue", type=int, default=0,
                    help="bounded queue depth for admission-control "
                         "shedding (0 = unbounded)")
    pf.add_argument("--pin", action="append", default=[],
                    help="tenant pin rule tenant=slot (repeatable)")
    pf.add_argument("--ab", action="append", default=[],
                    help="A/B split rule slot=weight (repeatable; "
                         "weights normalized; assignment keyed by a "
                         "deterministic request-id hash)")
    pf.add_argument("--http", type=int, default=0,
                    help="serve a localhost HTTP listener on this port "
                         "instead of JSONL over stdin")
    pf.add_argument("--selftest", type=int, default=0,
                    help="run the per-slot + mixed-batch parity sweep "
                         "with N queries per slot, then promote one "
                         "slot mid-traffic under a compile watcher, "
                         "and exit (nonzero on drift or any compile) — "
                         "the run_full_suite portfolio gate")
    pf.add_argument("--pods-per-query", type=int, default=3,
                    help="query size for --selftest (default 3)")
    pf.add_argument("--audit-tol", type=float, default=1e-5,
                    help="selftest score drift tolerance")
    pf.set_defaults(fn=cmd_portfolio)

    pp = sub.add_parser(
        "pipeline", parents=[common],
        help="promotion-pipeline status / fault-injection drills")
    pp.add_argument("--ledger-dir", default="",
                    help="champion ledger directory (default: "
                         "policies/discovered/)")
    pp.add_argument("--log", default="",
                    help="promotion.jsonl path (default: "
                         "<ledger-dir>/promotion.jsonl)")
    pp.add_argument("--drill", action="store_true",
                    help="run the deterministic fault-injection drill "
                         "matrix (corrupt champion, device-eval error, "
                         "p99 regression, kill -9 at every state, "
                         "rollback-on-burn, zero-recompile swap, LLM "
                         "outage, plus the resilience matrix: deadline "
                         "storm, queue overload, device loss mid-batch, "
                         "degrade-then-recover, SIGTERM drain, WAL "
                         "resume) and exit nonzero on any failure — the "
                         "run_full_suite promotion gate")
    pp.add_argument("--only", default="",
                    help="comma-separated drill-name substrings: run only "
                         "the matching drills (e.g. "
                         "--only deadline_storm,wal_resume)")
    pp.set_defaults(fn=cmd_pipeline)

    r = sub.add_parser("report",
                       help="summarize a flight-recorder run directory")
    r.add_argument("run_dir", help="directory written by --run-dir")
    r.set_defaults(fn=cmd_report)

    x = sub.add_parser("export-metrics",
                       help="render a run directory as OpenMetrics text")
    x.add_argument("run_dir", help="directory written by --run-dir")
    x.add_argument("--out", default="",
                   help="write to this file (atomic replace) instead of "
                        "stdout — point a node_exporter textfile "
                        "collector at it")
    x.set_defaults(fn=cmd_export_metrics)

    w = sub.add_parser("watch",
                       help="live-tail a run directory with a heartbeat "
                            "liveness verdict")
    w.add_argument("run_dir", help="directory written by --run-dir")
    w.add_argument("--interval", type=float, default=5.0,
                   help="seconds between polls (default 5)")
    w.add_argument("--once", action="store_true",
                   help="print one snapshot + verdict and exit")
    w.set_defaults(fn=cmd_watch)

    sp = sub.add_parser("spans",
                        help="causal-trace viewer: per-request latency "
                             "waterfalls and evolve critical paths")
    sp.add_argument("run_dir", help="directory written by --run-dir")
    sp.add_argument("--trace", metavar="ID",
                    help="render the waterfall of one trace "
                         "(unambiguous id prefix accepted)")
    sp.add_argument("--slowest", type=int, metavar="N",
                    help="render the N slowest serve/request waterfalls")
    sp.add_argument("--check-complete", action="store_true",
                    help="exit 1 unless every traced serve_request "
                         "reconstructs a complete waterfall")
    sp.add_argument("--critical-path", action="store_true",
                    help="per-generation critical path with device-idle "
                         "vs LLM-idle seconds")
    sp.add_argument("--min-fraction", type=float, default=0.95,
                    help="with --critical-path: fail if any generation "
                         "attributes less than this fraction of its "
                         "wall (default 0.95)")
    sp.set_defaults(fn=cmd_spans)

    c = sub.add_parser("compare",
                       help="regression-gate a candidate run against a "
                            "baseline (exit 1 on regression)")
    c.add_argument("baseline", help="run dir or bench JSONL file")
    c.add_argument("candidate", help="run dir or bench JSONL file")
    c.add_argument("--threshold", default="",
                   help="comma-separated overrides, e.g. "
                        "'evals_per_sec=rel:0.2,best_score=abs:1e-4'")
    c.add_argument("--history-root", default="",
                   help="with BASELINE 'auto': the history root to select "
                        "the best healthy run from (default: "
                        "benchmarks/results, or $FKS_BENCH_RESULTS_DIR)")
    c.set_defaults(fn=cmd_compare)

    tr = sub.add_parser(
        "trends",
        help="cross-run trend report over a directory of run dirs / bench "
             "evidence (exit 1 with --fail-on-alert on regressions)")
    tr.add_argument("root",
                    help="directory holding flight-recorder run dirs "
                         "and/or bench JSONL evidence files (e.g. "
                         "benchmarks/results)")
    tr.add_argument("--metric", default="",
                    help="comma-separated metrics to watch (default: the "
                         "built-in TREND_METRICS vocabulary)")
    tr.add_argument("--window", type=int, default=5,
                    help="prior-run window the robust median/MAD is "
                         "computed over (default 5)")
    tr.add_argument("--z", type=float, default=3.5,
                    help="robust z-score threshold (MAD units, default "
                         "3.5; the MAD is floored at 2%% of the median so "
                         "flat series don't false-positive)")
    tr.add_argument("--fail-on-alert", action="store_true",
                    help="exit 1 when any watched metric alerts (the CI "
                         "gate mode)")
    tr.add_argument("--write-index", action="store_true",
                    help="persist the scanned entries to ROOT/history.jsonl "
                         "(atomic replace)")
    tr.add_argument("--run-dir", default="",
                    help="flight-recorder run directory for the "
                         "trend_report records")
    tr.set_defaults(fn=cmd_trends)

    td = sub.add_parser(
        "trace-diff",
        help="replay one policy through two engines with decision traces "
             "and report the first divergent step (exit 1 on divergence)")
    _add_trace_flags(td)
    td.add_argument("--engines", default="exact,flat",
                    help="two comma-separated engines from {exact, flat} "
                         "(the fused kernel cannot carry the trace); "
                         "repeat one (exact,exact) for a self-check")
    td.add_argument("--policy", default="best_fit",
                    help="zoo policy to replay (ignored with --code)")
    td.add_argument("--code", default="",
                    help="candidate source file to replay on the "
                         "funsearch VM instead of a zoo policy")
    td.add_argument("--suite", default="default8",
                    help="scenario suite providing --scenario variants "
                         "(default default8)")
    td.add_argument("--scenario", type=int, default=None,
                    help="replay on suite scenario INDEX (0-based) instead "
                         "of the base workload — fault-injected scenarios "
                         "diff NODE_DOWN/NODE_UP rows too")
    td.add_argument("--max-steps", type=int, default=0,
                    help="cap replay steps (0 = engine default)")
    td.add_argument("--tol", type=float, default=1e-5,
                    help="score/margin comparison tolerance (default 1e-5)")
    td.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU tunnel)")
    td.add_argument("--run-dir", default="",
                    help="flight-recorder run directory for the "
                         "decision_trace / trace_diff records")
    td.set_defaults(fn=cmd_trace_diff)

    sn = sub.add_parser("scenarios",
                        help="list scenario suites / describe one suite "
                             "or scenario", parents=[common])
    _add_trace_flags(sn)
    sn.add_argument("--suite", default="",
                    help="materialize this suite against the workload and "
                         "print its summary (omit to list registered "
                         "suites)")
    sn.add_argument("--scenario", type=int, default=None,
                    help="describe one scenario (0-based index) incl. its "
                         "fault timeline")
    sn.set_defaults(fn=cmd_scenarios)

    ln = sub.add_parser(
        "lint",
        help="JAX-invariant AST lints + jaxpr-pin drift gate "
             "(exit 1 on findings or drift)")
    ln.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: fks_tpu)")
    ln.add_argument("--pins", default="",
                    help="pin manifest path (default: "
                         "tests/fixtures/jaxpr_pins.json)")
    ln.add_argument("--write-pins", action="store_true",
                    help="recompute and rewrite the pin manifest instead "
                         "of checking it")
    ln.add_argument("--no-pins", action="store_true",
                    help="AST lints only (skip the jaxpr lowering sweep)")
    ln.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU tunnel)")
    ln.add_argument("--run-dir", default="",
                    help="flight-recorder run directory for the "
                         "lint_report record")
    ln.set_defaults(fn=cmd_lint)

    mm = sub.add_parser(
        "mem",
        help="memory observability: footprint ladder / watermark view "
             "of a run, one live sample, or a leak drill (exit 1 on a "
             "failed drill)",
        parents=[common])
    mm.add_argument("--drill",
                    choices=("vm_swap_leak", "snapshot_cache_bound"),
                    default="",
                    help="run one deterministic memory drill and exit "
                         "0/1 on its verdict")
    mm.add_argument("--swaps", type=int, default=50,
                    help="vm_swap_leak: swap_program iterations "
                         "(default 50)")
    mm.add_argument("--batches", type=int, default=200,
                    help="vm_swap_leak: interleaved serve batches "
                         "(default 200)")
    mm.add_argument("--sample", action="store_true",
                    help="take one live watermark sample (host RSS + "
                         "per-device memory_stats) and print it as JSON")
    mm.add_argument("--devices", type=int, default=0,
                    help="with --cpu: size of the virtual CPU device "
                         "mesh the drill runs against")
    mm.set_defaults(fn=cmd_mem)

    ly = sub.add_parser(
        "layout",
        help="layout observability: per-layout cost ledger view of a "
             "run, or --explore to measure every valid layout of a "
             "(population x suite x mesh) shape (exit 1 when the chosen "
             "layout is measurably dominated)",
        parents=[common])
    ly.add_argument("--explore", action="store_true",
                    help="enumerate + probe every valid layout and print "
                         "the summary JSON (persists the best into "
                         "RunHistory as a prior)")
    ly.add_argument("--devices", type=int, default=0,
                    help="with --cpu: size of the virtual CPU device "
                         "mesh to explore over")
    ly.add_argument("--pop", type=int, default=64,
                    help="explore population size (default 64)")
    ly.add_argument("--suite", default="default8",
                    help="scenario suite to explore (default: default8)")
    ly.add_argument("--mesh-shape", default="",
                    help="the chosen CxS layout to defend (e.g. 4x2); "
                         "default: the candidates-only default layout")
    ly.add_argument("--seed", type=int, default=0,
                    help="synthetic base-workload seed (default 0)")
    ly.add_argument("--history-root", default="",
                    help="RunHistory root for the layout prior (default: "
                         "benchmarks/results)")
    ly.set_defaults(fn=cmd_layout)

    t = sub.add_parser("traces", help="list available trace files")
    t.set_defaults(fn=cmd_traces)

    args = ap.parse_args(argv)
    if getattr(args, "engine", "exact") == "fused" and args.cmd != "scale":
        ap.error("--engine fused evaluates parametric populations only — "
                 "it applies to the 'scale' command (other commands run "
                 "single policies or arbitrary evolved code; use "
                 "'exact'/'flat' there)")
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
