"""The resilience drill matrix (PR-12 drill idiom, new failure modes).

Each drill takes the shared ``DrillStack`` (fks_tpu.pipeline.drills) and
returns a detail dict with an ``ok`` bool; ``run_drills`` mixes these
into the promotion matrix. Everything is event-gated and fault-driven —
no sleeps standing in for synchronization, no probabilities:

- deadline_storm       -> impossible-deadline requests fail with TYPED
                          resilience errors (shed at admission or
                          expired in queue), normal traffic unharmed
- queue_overload       -> a bounded queue sheds the overflow submit
                          with Retry-After while admitted work completes
- device_loss_mid_batch-> an injected device fault mid-batch flips the
                          service to the exact fallback and the SAME
                          batch is answered there, parity drift 0.0
- device_loss_sharded_serve -> the same fault against the MESH-SHARDED
                          engine (round-17 serve path) degrades to the
                          single-device exact fallback with 0.0 drift
                          and a live batcher (follow-up traffic answers)
- degrade_then_recover -> fault -> fallback -> rebuilt primary ->
                          probation -> normal, with ZERO post-recovery
                          recompiles (the rebuild was a warm engine)
- sigterm_drain        -> the drain coordinator completes every
                          in-flight Future, persists the replay buffer,
                          and a restarted service preloads it
- wal_resume_mid_generation -> a kill mid-generation resumes from the
                          WAL with zero LLM calls and zero device evals
                          for the interrupted generation
"""
from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Dict

from fks_tpu.resilience.deadline import ResilienceError, ShedError
from fks_tpu.resilience.degrade import DegradeConfig
from fks_tpu.resilience.drain import DrainCoordinator, load_serve_state


def _fallback_engine(stack) -> Any:
    """The reduced-batch exact fallback for the degrade drills, cached on
    the stack (the ladder compiles once per matrix run)."""
    if getattr(stack, "_resilience_fallback", None) is None:
        from fks_tpu.serve import ServeEngine

        env = dataclasses.replace(stack.envelope, max_batch=1)
        eng = ServeEngine(stack.incumbent.champion, stack.workload,
                          envelope=env, engine="exact")
        eng.warmup()
        stack._resilience_fallback = eng
    return stack._resilience_fallback


def _drill_deadline_storm(stack) -> Dict[str, Any]:
    """Every impossible-deadline request fails with a TYPED resilience
    error (never a hang, never a late answer); interleaved normal
    requests are answered."""
    service = stack.service()
    try:
        base = stack.incumbent.base_pods
        storm_failures = 0
        for i in range(4):
            q = {"id": f"storm{i}", "deadline_ms": 0.0,
                 "pods": [dict(base[i % len(base)])]}
            try:
                fut = service.submit(q)
            except ShedError:
                storm_failures += 1  # refused at admission
                continue
            try:
                fut.result(timeout=30)
            except ResilienceError:
                storm_failures += 1  # expired while queued
        answers = stack.traffic(service, 2)
        hz = service.healthz()
        return {"ok": (storm_failures == 4 and len(answers) == 2
                       and all("score" in a for a in answers)
                       and hz["shed_total"] + hz["expired"] >= 4),
                "typed_failures": storm_failures,
                "shed": hz["shed_total"], "expired": hz["expired"]}
    finally:
        service.close()


def _drill_queue_overload(stack) -> Dict[str, Any]:
    """A bounded queue sheds the overflow submit with a Retry-After hint
    while every admitted request still completes. Event-gated: the shed
    happens while the worker is PROVABLY inside the blocked batch."""
    import threading

    from fks_tpu.serve.batcher import RequestBatcher

    gate, entered = threading.Event(), threading.Event()

    def blocked(queries, enq):
        entered.set()
        if not gate.wait(30):
            raise RuntimeError("drill gate never released")
        return list(queries)

    b = RequestBatcher(blocked, max_batch=1, max_wait_s=0.0, max_queue=2)
    try:
        first = b.submit("a")
        if not entered.wait(30):
            return {"ok": False, "error": "worker never entered the batch"}
        admitted = [b.submit("b"), b.submit("c")]  # fills the queue
        shed_error = None
        try:
            b.submit("d")
        except ShedError as e:
            shed_error = e
        gate.set()
        done = [first.result(30)] + [f.result(30) for f in admitted]
        return {"ok": (shed_error is not None
                       and shed_error.retry_after_s is not None
                       and shed_error.retry_after_s > 0
                       and done == ["a", "b", "c"]
                       and b.admission.shed_queue_full == 1),
                "retry_after_s": getattr(shed_error, "retry_after_s", None),
                "completed": len(done)}
    finally:
        gate.set()
        b.close()


def _traffic_drift(stack, answers) -> float:
    """Max |served - exact reference| score drift for ``stack.traffic``
    answers — degraded-mode serving must stay bit-faithful."""
    drift = 0.0
    base = stack.incumbent.base_pods
    for i, ans in enumerate(answers):
        pods = [dict(base[(i + j) % len(base)]) for j in range(3)]
        ref = stack.incumbent.reference_answer(pods)
        drift = max(drift, abs(ans["score"] - ref["score"]))
    return drift


def _degrade_traffic_parity(stack, service, n: int) -> float:
    """Drive traffic and return max score drift vs the exact reference."""
    return _traffic_drift(stack, stack.traffic(service, n))


def _connected_traces(run_dir: str) -> Dict[str, Any]:
    """Read a degrade drill's flight-recorder dir back and verify the
    causal-trace contract (fks_tpu.obs.trace_ctx): every served request
    reconstructs a COMPLETE waterfall, and the faulted batch's requests
    carry a ``primary_attempt`` child — primary-fail -> fallback-retry
    linked on ONE trace."""
    from fks_tpu.obs import trace_ctx
    from fks_tpu.obs.report import read_jsonl

    events = read_jsonl(os.path.join(run_dir, "events.jsonl"))
    metrics = read_jsonl(os.path.join(run_dir, "metrics.jsonl"))
    by = trace_ctx.traces_by_id(trace_ctx.trace_spans(events))
    served = [m["trace_id"] for m in metrics
              if m.get("kind") == "serve_request" and m.get("trace_id")]
    complete = [t for t in served
                if trace_ctx.waterfall_complete(by.get(t, []))]
    retried = [t for t in served
               if any(s.get("path") == "serve/request/primary_attempt"
                      for s in by.get(t, []))]
    return {"served": len(served), "complete": len(complete),
            "retried": len(retried),
            "traces_ok": bool(served) and len(complete) == len(served)
            and bool(retried)}


def _drill_device_loss_mid_batch(stack) -> Dict[str, Any]:
    """An injected device fault mid-batch flips the service to the
    reduced-batch exact fallback and the SAME batch is retried there —
    the client sees an answer, not an error, and parity drift is 0.0."""
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.serve import ServeService

    flaky = FlakyEngineProxy(stack.incumbent, failures=1)
    service = ServeService(flaky, max_wait_s=0.002)
    service.enable_degraded_mode(
        lambda: _fallback_engine(stack),
        config=DegradeConfig(background_rebuild=False))
    try:
        drift = _degrade_traffic_parity(stack, service, 3)
        degrade = service.degrade.healthz()
        return {"ok": (flaky.faults_raised == 1
                       and degrade["state"] == "degraded"
                       and degrade["flips"] == 1
                       and degrade["last_fault"] == "device_fault"
                       and service.engine is _fallback_engine(stack)
                       and drift == 0.0),
                "state": degrade["state"], "flips": degrade["flips"],
                "parity_drift": drift}
    finally:
        service.close()


def _drill_degrade_then_recover(stack) -> Dict[str, Any]:
    """Fault -> fallback -> rebuilt primary -> probation -> normal. The
    rebuild hands back the WARM incumbent, so post-recovery traffic
    compiles zero new XLA programs."""
    from fks_tpu.obs import CompileWatcher, FlightRecorder
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.serve import ServeService

    flaky = FlakyEngineProxy(stack.incumbent, failures=1)
    with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
        # drill-local recorder: the primary-fail -> fallback-retry chain
        # must come back as ONE connected trace per request
        rec = FlightRecorder(tmp)
        service = ServeService(flaky, max_wait_s=0.002, recorder=rec)
        mgr = service.enable_degraded_mode(
            lambda: _fallback_engine(stack),
            rebuild_factory=lambda: stack.incumbent,
            config=DegradeConfig(probation_requests=2,
                                 background_rebuild=False))
        try:
            drift = _degrade_traffic_parity(stack, service, 2)  # fault+flip
            # the inline rebuild already finished; the next batch promotes
            # it into probation and the one after releases probation. Only
            # the SERVED path sits under the watcher — the parity reference
            # is computed after it (reference_answer compiles its own
            # programs)
            watcher = CompileWatcher().install()
            try:
                answers = stack.traffic(service, 4)
                recompiles = watcher.backend_compile_count
            finally:
                watcher.uninstall()
            drift = max(drift, _traffic_drift(stack, answers))
            hz = mgr.healthz()
        finally:
            service.close()
            rec.finish("ok")
            rec.close()
        traces = _connected_traces(tmp)
        return {"ok": (hz["state"] == "normal" and hz["recoveries"] == 1
                       and hz["flips"] == 1 and recompiles == 0
                       and service.engine is stack.incumbent
                       and drift == 0.0 and traces["traces_ok"]),
                "state": hz["state"], "recoveries": hz["recoveries"],
                "post_recovery_recompiles": recompiles,
                "parity_drift": drift, **traces}


def _drill_sigterm_drain(stack) -> Dict[str, Any]:
    """The drain coordinator completes every in-flight Future, persists
    the replay buffer + summary, and a restarted service preloads the
    replay — zero pending Futures, zero lost shadow traffic."""
    service = stack.service()
    closed = False
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            state_path = os.path.join(tmp, "serve_state.json")
            answers = stack.traffic(service, 3)
            base = stack.incumbent.base_pods
            tail = [service.submit({"id": f"t{i}",
                                    "pods": [dict(base[i % len(base)])]})
                    for i in range(2)]
            dc = DrainCoordinator(service, state_path=state_path,
                                  grace_s=30.0)
            dc.handle_signal()  # the SIGTERM path, invoked directly
            closed = True
            pending_after = [f for f in tail if not f.done()]
            state = load_serve_state(state_path)
            service2 = stack.service()
            try:
                preloaded = service2.preload_replay(state["replay"])
            finally:
                service2.close()
            report = dc.report or {}
            return {"ok": (len(answers) == 3 and not pending_after
                           and report.get("stuck") is False
                           and state["requests_served"] >= 3
                           and len(state["replay"]) >= 3
                           and preloaded == len(state["replay"])
                           and dc.drain() is report),  # idempotent
                    "drained_pending": report.get("pending"),
                    "completed": report.get("completed"),
                    "shed": report.get("shed"),
                    "replay_persisted": len(state["replay"])}
    finally:
        if not closed:
            service.close()


def _drill_wal_resume_mid_generation(stack) -> Dict[str, Any]:
    """kill -9 mid-generation (after the ledger committed, before the
    checkpoint/WAL commit landed): the resumed run replays the WAL with
    ZERO LLM calls and ZERO device evaluations for that generation."""
    from fks_tpu.funsearch import EvolutionConfig
    from fks_tpu.funsearch import evolution as evo
    from fks_tpu.pipeline.faults import CountingBackend, KillSwitch

    with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
        ck = os.path.join(tmp, "evo.json")
        wal = os.path.join(tmp, "wal.jsonl")

        def cfg():
            return EvolutionConfig(
                population_size=4, generations=2, elite_size=2,
                candidates_per_generation=2, max_workers=1, seed=3)

        fired = {}

        def kill_mid_gen2(stats):
            if stats.generation == 2 and not fired:
                fired["x"] = True
                raise KillSwitch("injected kill -9 mid-generation 2")

        backend = CountingBackend(seed=3)
        killed = False
        try:
            evo.run(stack.workload, cfg(), backend=backend,
                    checkpoint_path=ck, wal_path=wal,
                    on_generation=kill_mid_gen2, log=lambda _m: None)
        except KillSwitch:
            killed = True
        calls_before = backend.calls

        backend2 = CountingBackend(seed=3)
        fs = evo.run(stack.workload, cfg(), backend=backend2,
                     checkpoint_path=ck, wal_path=wal, log=lambda _m: None)
        return {"ok": (killed and backend2.calls == 0
                       and fs.wal_replayed_codes > 0
                       and fs.wal_replayed_evals > 0
                       and fs.evaluator.compile_count == 0
                       and fs.generation == 2 and fs.best is not None),
                "resume_llm_calls": backend2.calls,
                "replayed_codes": fs.wal_replayed_codes,
                "replayed_evals": fs.wal_replayed_evals,
                "resume_device_programs": fs.evaluator.compile_count}


def _drill_device_loss_sharded_serve(stack) -> Dict[str, Any]:
    """Losing a mesh lane mid-batch on the SHARDED serve engine (the
    round-17 mesh path: batch axis sharded over every visible device,
    packed uploads, device-resident snapshot cache) degrades to the
    single-device exact fallback — the same batch is answered there with
    0.0 drift, and the batcher is NOT wedged: follow-up traffic on the
    degraded service still completes."""
    import jax

    from fks_tpu.parallel.mesh import population_mesh
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.serve import ServeService

    if getattr(stack, "_resilience_sharded", None) is None:
        from fks_tpu.serve import ServeEngine

        eng = ServeEngine(stack.incumbent.champion, stack.workload,
                          envelope=stack.envelope, engine="flat",
                          state_pack=True,
                          mesh=population_mesh(jax.devices()))
        eng.warmup()
        stack._resilience_sharded = eng
    from fks_tpu.obs import FlightRecorder

    flaky = FlakyEngineProxy(stack._resilience_sharded, failures=1)
    with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
        rec = FlightRecorder(tmp)
        service = ServeService(flaky, max_wait_s=0.002, recorder=rec)
        service.enable_degraded_mode(
            lambda: _fallback_engine(stack),
            config=DegradeConfig(background_rebuild=False))
        try:
            drift = _degrade_traffic_parity(stack, service, 3)
            follow_up = stack.traffic(service, 2)  # batcher still alive
            degrade = service.degrade.healthz()
        finally:
            service.close()
            rec.finish("ok")
            rec.close()
        traces = _connected_traces(tmp)
        return {"ok": (flaky.faults_raised == 1
                       and degrade["state"] == "degraded"
                       and degrade["flips"] == 1
                       and degrade["last_fault"] == "device_fault"
                       and service.engine is _fallback_engine(stack)
                       and drift == 0.0
                       and len(follow_up) == 2
                       and all("score" in a for a in follow_up)
                       and traces["traces_ok"]),
                "state": degrade["state"], "flips": degrade["flips"],
                "parity_drift": drift,
                "mesh_devices": len(jax.devices()),
                "follow_up_answers": len(follow_up), **traces}


RESILIENCE_DRILLS = (
    _drill_deadline_storm,
    _drill_queue_overload,
    _drill_device_loss_mid_batch,
    _drill_device_loss_sharded_serve,
    _drill_degrade_then_recover,
    _drill_sigterm_drain,
    _drill_wal_resume_mid_generation,
)
