"""Admission control for the request coalescer: bounded queue + shed.

The controller answers one question at submit time — "can this request
still meet its deadline if we accept it?" — from two cheap signals it
maintains itself: the current queue depth and an EWMA of observed
per-request service time. ``projected_wait = depth * ewma_service_s``
is deliberately conservative (it ignores batching speedup), so the
shed decision errs toward refusing work the deadline would lose anyway;
a shed costs the client one Retry-After round-trip, a missed deadline
costs a full budget.

All counters are lock-guarded and the controller is shared between the
submitting threads and the batcher worker; it never blocks on anything.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional

from fks_tpu.resilience.deadline import Deadline, ShedError


@dataclasses.dataclass
class AdmissionConfig:
    """Knobs for the bounded queue + shed policy.

    - ``max_queue``: requests allowed in the queue (enqueued, not yet
      handed to a batch). 0 = unbounded, the historical behaviour.
    - ``default_deadline_s``: deadline attached to requests that do not
      carry their own ``deadline_ms``. 0 = none.
    - ``ewma_alpha``: weight of the newest batch in the service-time
      estimate (0 < alpha <= 1).
    - ``min_retry_after_s``: floor for the Retry-After hint, so a cold
      estimator never tells clients to hammer back immediately.
    """

    max_queue: int = 0
    default_deadline_s: float = 0.0
    ewma_alpha: float = 0.2
    min_retry_after_s: float = 0.05


class AdmissionController:
    """Queue-depth accounting + EWMA service-time estimate + the shed
    decision. One instance per ``RequestBatcher``."""

    def __init__(self, config: Optional[AdmissionConfig] = None):
        self.cfg = config or AdmissionConfig()
        self._lock = threading.Lock()
        self._depth = 0
        self._ewma_service_s: Optional[float] = None
        self.submitted = 0
        self.shed_queue_full = 0
        self.shed_deadline = 0
        self.expired = 0  # admitted but completed with DeadlineExceeded
        # optional per-tenant service-time source (tenant -> seconds, or
        # None while that tenant is cold): when the service runs with
        # accounting on, this is TenantAccountant.ewma_service_s, and a
        # shed request's Retry-After is priced at the SHEDDING tenant's
        # observed service time instead of the single global EWMA — a
        # slow tenant is told to back off longer, a fast one shorter
        # (first step of weighted-fair shedding). Never called under the
        # accountant's own lock from here (lock order: admission ->
        # accountant, and the accountant never calls admission).
        self.service_time_for: Optional[
            Callable[[str], Optional[float]]] = None

    # ------------------------------------------------------------ signals

    @property
    def depth(self) -> int:
        return self._depth

    @property
    def shed_total(self) -> int:
        return self.shed_queue_full + self.shed_deadline

    @property
    def shed_rate(self) -> float:
        """Fraction of all submit attempts refused at admission."""
        total = self.submitted + self.shed_total
        return self.shed_total / total if total else 0.0

    def note_batch(self, n: int, seconds: float) -> None:
        """Fold one completed batch into the service-time estimate."""
        if n <= 0:
            return
        per_item = max(0.0, float(seconds)) / n
        with self._lock:
            if self._ewma_service_s is None:
                self._ewma_service_s = per_item
            else:
                a = self.cfg.ewma_alpha
                self._ewma_service_s = (a * per_item
                                        + (1.0 - a) * self._ewma_service_s)

    def note_expired(self, n: int = 1) -> None:
        with self._lock:
            self.expired += n

    def projected_wait_s(self, extra_depth: int = 0) -> float:
        """Expected wait for a request arriving now: everything ahead of
        it priced at the EWMA service time (0.0 while the estimator is
        cold — never shed on a guess)."""
        est = self._ewma_service_s
        if est is None:
            return 0.0
        return (self._depth + extra_depth) * est

    def retry_after_s(self) -> float:
        """Client back-off hint: drain time for the current queue."""
        return max(self.cfg.min_retry_after_s, self.projected_wait_s())

    # ----------------------------------------------------------- decision

    def admit(self, deadline: Optional[Deadline],
              tenant: Optional[str] = None) -> None:
        """Admit (incrementing depth) or raise ``ShedError``. Called by
        ``RequestBatcher.submit`` before enqueueing. ``tenant`` (when the
        service threads it through) prices the shed hint per tenant; the
        shed DECISION stays global — fairness of refusal is the queue's
        concern, honesty of the back-off hint is the tenant's."""
        with self._lock:
            if self.cfg.max_queue and self._depth >= self.cfg.max_queue:
                self.shed_queue_full += 1
                raise ShedError(
                    f"queue full ({self._depth}/{self.cfg.max_queue})",
                    retry_after_s=self._retry_after_locked(tenant),
                    reason="queue_full")
            if deadline is not None:
                est = self._ewma_service_s
                projected = (self._depth + 1) * est if est is not None else 0.0
                if projected > deadline.remaining():
                    self.shed_deadline += 1
                    raise ShedError(
                        f"projected wait {projected * 1e3:.1f}ms exceeds "
                        "deadline budget "
                        f"{max(0.0, deadline.remaining()) * 1e3:.1f}ms",
                        retry_after_s=self._retry_after_locked(tenant),
                        reason="deadline_budget")
            self._depth += 1
            self.submitted += 1

    def release(self, n: int = 1) -> None:
        """Requests left the queue (handed to a batch, or drained)."""
        with self._lock:
            self._depth = max(0, self._depth - n)

    def _retry_after_locked(self, tenant: Optional[str] = None) -> float:
        est = self._ewma_service_s or 0.0
        if tenant and self.service_time_for is not None:
            tenant_est = self.service_time_for(tenant)
            if tenant_est:  # cold tenants fall back to the global EWMA
                est = float(tenant_est)
        return max(self.cfg.min_retry_after_s, self._depth * est)
