"""Always-on resilience layer (ISSUE 13): deadlines + load shedding,
degraded-mode serving, and preemption-safe drain/resume.

Three pillars, all HOST-SIDE control plane — nothing here touches the
sim/ lowering, so every compiled program (and the jaxpr-pin manifest)
is bit-identical whether resilience features are on or off:

- ``deadline`` / ``admission``: per-request deadline budgets threaded
  from the JSONL/HTTP fronts through the ``RequestBatcher``, a bounded
  queue with admission control that sheds (typed ``ShedError`` -> HTTP
  503 + Retry-After) when the projected wait exceeds the deadline, and
  deadline-expired Futures completed with ``DeadlineExceeded`` instead
  of hanging;
- ``degrade``: device-fault classification (XlaRuntimeError, watchdog
  NaN-flood, engine-build failure) that atomically flips a
  ``ServeService`` to a reduced-batch exact-CPU fallback engine via the
  existing ``swap_engine``, rebuilds the AOT engine off the request
  path, and gates auto-recovery through a probation window (the
  ``pipeline/controller.py`` probation idiom);
- ``drain`` / ``wal``: a SIGTERM coordinator that drains the batcher
  (completing or shedding every in-flight Future), persists the serve
  replay buffer, and a generation-level write-ahead log for the evolve
  loop (fsync'd, torn-tail tolerant like ``pipeline/state.py``) so a
  kill -9 mid-generation resumes without re-spending LLM calls or
  device evals for already-completed candidates.

Pure host code at import time (no jax) — the drills module imports the
serve stack lazily inside each drill.
"""
from fks_tpu.resilience.admission import AdmissionConfig, AdmissionController
from fks_tpu.resilience.deadline import (
    Deadline, DeadlineExceeded, ResilienceError, ShedError,
)
from fks_tpu.resilience.degrade import (
    DegradeConfig, DegradedModeManager, DeviceFault, EngineBuildError,
    NaNFlood, classify_fault, exact_fallback_factory,
)
from fks_tpu.resilience.drain import (
    DrainCoordinator, load_serve_state, persist_serve_state,
)
from fks_tpu.resilience.wal import GenerationWAL

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "Deadline",
    "DeadlineExceeded",
    "DegradeConfig",
    "DegradedModeManager",
    "DeviceFault",
    "DrainCoordinator",
    "EngineBuildError",
    "GenerationWAL",
    "NaNFlood",
    "ResilienceError",
    "ShedError",
    "classify_fault",
    "exact_fallback_factory",
    "load_serve_state",
    "persist_serve_state",
]
