"""Per-request deadline budgets and the typed resilience errors.

A ``Deadline`` is an absolute point on the monotonic clock; requests
carry one from the front (HTTP/JSONL ``deadline_ms`` field, or the
service default) through the batcher, so every layer can ask the same
two questions — ``remaining()`` and ``expired()`` — against one budget
instead of stacking independent timeouts.

The two failure modes are TYPED exceptions, not bare RuntimeErrors,
because the fronts must map them to structured responses (503 +
Retry-After) and the drill matrix asserts the exact class:

- ``ShedError``: admission control refused the request up front (queue
  full, or the projected wait already exceeds the deadline). Carries
  ``retry_after_s`` — the client hint the HTTP front forwards as a
  Retry-After header.
- ``DeadlineExceeded``: the request was admitted but its budget ran out
  before (or while) a batch could answer it; its Future completes with
  this error instead of hanging.

Pure host code, stdlib only.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional


class ResilienceError(RuntimeError):
    """Base class for typed serve-tier resilience failures.

    ``http_status``/``to_json()`` give the fronts one structured-body
    rendering for every subclass."""

    kind = "resilience"
    http_status = 503

    def __init__(self, message: str, *,
                 retry_after_s: Optional[float] = None,
                 reason: Optional[str] = None,
                 trace_id: Optional[str] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s
        self.reason = reason if reason is not None else self.kind
        # causal correlation (fks_tpu.obs.trace_ctx): set by the layer
        # that knows the request's trace, so a 503 body names the trace
        # whose flight-recorder spans explain it
        self.trace_id = trace_id

    def to_json(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"error": str(self), "kind": self.kind}
        if self.retry_after_s is not None:
            doc["retry_after_s"] = round(float(self.retry_after_s), 4)
        if self.trace_id:
            doc["trace_id"] = self.trace_id
        return doc


class ShedError(ResilienceError):
    """Admission control refused the request (queue full or the
    projected wait exceeds the deadline); retry after ``retry_after_s``."""

    kind = "shed"


class DeadlineExceeded(ResilienceError):
    """The request's deadline budget expired before it was answered."""

    kind = "deadline"


class Deadline:
    """An absolute monotonic-clock budget. ``Deadline.never()`` (or
    ``None`` where the API allows it) means no budget at all."""

    __slots__ = ("at",)

    def __init__(self, at: Optional[float]):
        self.at = at  # absolute time.perf_counter() point; None = never

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.perf_counter() + max(0.0, float(seconds)))

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    @classmethod
    def from_query(cls, query: Any,
                   default_s: float = 0.0) -> Optional["Deadline"]:
        """The request's own ``deadline_ms`` wins; otherwise the service
        default (0 = no deadline -> None)."""
        if isinstance(query, dict) and query.get("deadline_ms") is not None:
            return cls.after(float(query["deadline_ms"]) / 1e3)
        if default_s and default_s > 0:
            return cls.after(default_s)
        return None

    def remaining(self) -> float:
        if self.at is None:
            return float("inf")
        return self.at - time.perf_counter()

    def expired(self) -> bool:
        return self.at is not None and time.perf_counter() >= self.at

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        if self.at is None:
            return "Deadline(never)"
        return f"Deadline(remaining={self.remaining():.4f}s)"
