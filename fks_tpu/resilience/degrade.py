"""Degraded-mode serving: device-fault classification + the fallback flip.

When the warm AOT engine dies under a batch (an ``XlaRuntimeError``
device loss, a watchdog NaN flood, or a failed engine rebuild), the
service must keep answering — correctly, just slower. The manager here:

1. classifies the exception (``classify_fault``): only DEVICE faults
   degrade; request errors (ValueError from a malformed query) stay
   per-request failures;
2. atomically flips the ``ServeService`` to a reduced-batch exact-CPU
   fallback engine via the service's existing ``swap_engine`` (one
   attribute assignment — in-flight batches finish on whichever engine
   they pinned);
3. rebuilds the primary AOT engine OFF the request path (a background
   thread by default; synchronous under ``background_rebuild=False``
   for deterministic drills);
4. gates recovery through PROBATION, the ``pipeline/controller.py``
   idiom: the rebuilt engine is swapped back in, but the manager only
   declares ``normal`` after ``probation_requests`` clean requests — a
   fault inside the window re-degrades immediately and rebuilds again.

The manager owns no engine construction itself: callers hand it two
factories (``fallback_factory`` -> a warm exact engine,
``rebuild_factory`` -> a warm primary engine) so the drill matrix can
return cached warm engines and assert zero post-recovery recompiles.

Pure host code at import time.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Optional

#: exception type names that mean the DEVICE (or its runtime) failed —
#: matched by name so this module never imports jaxlib
_XLA_FAULT_TYPES = frozenset({
    "XlaRuntimeError", "JaxRuntimeError", "InternalError",
    "FailedPreconditionError", "DataLossError",
})


class DeviceFault(RuntimeError):
    """A device-side failure (real or injected) that warrants degrading
    to the fallback engine rather than failing the request."""


class NaNFlood(DeviceFault):
    """The watchdog saw non-finite scores flooding out of the engine —
    the compiled program is producing garbage; stop trusting it."""


class EngineBuildError(DeviceFault):
    """Building (or rebuilding) an AOT engine failed."""


def classify_fault(exc: BaseException) -> Optional[str]:
    """Map an exception to a device-fault kind, or None for request-level
    errors that must NOT degrade the service."""
    if isinstance(exc, NaNFlood):
        return "nan_flood"
    if isinstance(exc, EngineBuildError):
        return "engine_build"
    if isinstance(exc, DeviceFault):
        return "device_fault"
    if type(exc).__name__ in _XLA_FAULT_TYPES:
        return "xla_runtime"
    return None


@dataclasses.dataclass
class DegradeConfig:
    """Probation + rebuild knobs for ``DegradedModeManager``."""

    #: clean requests required on the rebuilt engine before the manager
    #: declares ``normal`` (the controller's probation-window idiom)
    probation_requests: int = 8
    #: rebuild the primary in a background thread (the production
    #: default); False rebuilds inline in ``on_fault`` so drills are
    #: single-threaded deterministic
    background_rebuild: bool = True


class DegradedModeManager:
    """State machine: ``normal -> degraded -> probation -> normal``.

    Wired into ``ServeService._handle_batch``: ``on_fault(exc)`` from the
    batch-failure path (returns True when the service was flipped and the
    batch should be retried on the fallback), ``after_batch(n)`` from the
    success path (drives recovery + probation accounting)."""

    def __init__(self, service: Any,
                 fallback_factory: Callable[[], Any],
                 rebuild_factory: Optional[Callable[[], Any]] = None,
                 config: Optional[DegradeConfig] = None,
                 recorder: Any = None):
        from fks_tpu import obs

        self.service = service
        self.cfg = config or DegradeConfig()
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self._fallback_factory = fallback_factory
        self._rebuild_factory = rebuild_factory
        self._lock = threading.RLock()
        self._fallback: Any = None  # memoized warm fallback engine
        self._rebuilt: Any = None  # rebuilt primary awaiting recovery
        self._rebuild_thread: Optional[threading.Thread] = None
        self._probation_mark = 0
        self.state = "normal"
        self.flips = 0
        self.recoveries = 0
        self.last_fault = ""

    # ------------------------------------------------------------- faults

    def on_fault(self, exc: BaseException) -> bool:
        """Classify; on a device fault flip the service to the fallback
        engine and kick off the rebuild. Returns True when the caller
        should retry its batch on the (now-swapped) fallback."""
        kind = classify_fault(exc)
        if kind is None:
            return False
        with self._lock:
            self.last_fault = kind
            if self.state == "degraded":
                return True  # already on the fallback; just retry
            fallback = self._get_fallback()
            if fallback is None:
                return False  # fallback build failed: fail the batch
            self.service.swap_engine(fallback)
            self.state = "degraded"
            self.flips += 1
            self._rebuilt = None
            self.recorder.event(
                "degraded", fault=kind, state="degraded",
                detail=f"{type(exc).__name__}: {exc}", flips=self.flips)
            self._start_rebuild()
            return True

    def _get_fallback(self) -> Any:
        if self._fallback is None:
            try:
                self._fallback = self._fallback_factory()
            except Exception as e:  # noqa: BLE001 — a fallback that cannot
                # build leaves nothing to degrade TO; surface the original
                # batch failure instead of masking it with this one
                self.recorder.event(
                    "degraded", fault="engine_build", state="dead",
                    detail=f"fallback build failed: {e}")
                return None
        return self._fallback

    def _start_rebuild(self) -> None:
        if self._rebuild_factory is None:
            return
        if self.cfg.background_rebuild:
            t = threading.Thread(target=self._rebuild,
                                 name="degrade-rebuild", daemon=True)
            self._rebuild_thread = t
            t.start()
        else:
            self._rebuild()

    def _rebuild(self) -> None:
        try:
            engine = self._rebuild_factory()
        except Exception as e:  # noqa: BLE001 — a failed rebuild keeps the
            # service on the fallback; the next fault retries the rebuild
            self.recorder.event(
                "degraded", fault="engine_build", state="degraded",
                detail=f"rebuild failed: {type(e).__name__}: {e}")
            return
        with self._lock:
            self._rebuilt = engine

    def wait_rebuilt(self, timeout: Optional[float] = None) -> bool:
        """Block until the background rebuild finished (drill/test hook)."""
        t = self._rebuild_thread
        if t is not None:
            t.join(timeout)
        return self._rebuilt is not None

    # ----------------------------------------------------------- recovery

    def after_batch(self, n: int = 1) -> None:
        """Success-path hook: promote a finished rebuild into probation,
        and release probation after enough clean requests."""
        with self._lock:
            if self.state == "degraded" and self._rebuilt is not None:
                self.service.swap_engine(self._rebuilt)
                self._rebuilt = None
                self.state = "probation"
                self._probation_mark = getattr(
                    self.service, "requests_served", 0)
                self.recorder.event(
                    "degraded", fault=self.last_fault, state="probation",
                    probation_requests=self.cfg.probation_requests)
            elif self.state == "probation":
                served = getattr(self.service, "requests_served", 0)
                if served - self._probation_mark >= self.cfg.probation_requests:
                    self.state = "normal"
                    self.recoveries += 1
                    self.recorder.event(
                        "degraded", fault=self.last_fault, state="normal",
                        recoveries=self.recoveries)

    # -------------------------------------------------------------- views

    def healthz(self) -> dict:
        return {"state": self.state, "flips": self.flips,
                "recoveries": self.recoveries, "last_fault": self.last_fault}


def exact_fallback_factory(champion, workload, envelope,
                           max_batch: int = 1,
                           recorder: Any = None) -> Callable[[], Any]:
    """A factory building the reduced-batch exact-CPU reference engine:
    the same champion and bucket ladder, ``engine="exact"``, batch cut to
    ``max_batch`` — correctness over throughput while degraded."""
    def build():
        import dataclasses as _dc

        from fks_tpu.serve import ServeEngine

        env = _dc.replace(envelope, max_batch=max_batch)
        eng = ServeEngine(champion, workload, envelope=env, engine="exact",
                          recorder=recorder)
        eng.warmup()
        return eng
    return build
