"""Generation-level write-ahead log for the evolve loop.

The expensive, non-reproducible spend in one generation is (a) the LLM
calls that draft candidates and (b) the device evaluations that score
them. A kill -9 mid-generation loses both today: the checkpoint only
lands at run end. The WAL makes that spend durable at the moment it
happens, with the ``pipeline/state.py`` durability idiom — every append
is write + flush + fsync, a torn trailing line is skipped (and counted)
on read, and the next append repairs the missing newline.

Record kinds (one JSON object per line):

- ``{"kind": "codes", "generation": g, "codes": [...]}`` — the drafted
  candidate sources, appended right after ``generate_many`` returns and
  BEFORE any evaluation. A resume of generation ``g`` replays these and
  issues ZERO LLM calls.
- ``{"kind": "eval", "generation": g, "key": ..., "score": ..., ...}``
  — one per evaluated candidate (keyed by code sha1). A resume skips
  the device eval for every candidate already recorded.
- ``{"kind": "commit", "generation": g}`` — the generation is fully
  committed (ledger + checkpoint); its records are dead weight, never
  replayed.

The driver (``FunSearch``) checkpoints at every generation boundary when
a WAL is attached, so the pending window is always exactly one
generation: restore the checkpoint, replay the WAL, lose nothing.

Pure host code — no jax, importable anywhere.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional


class GenerationWAL:
    """Append-only, fsync'd, torn-tail-tolerant generation log."""

    def __init__(self, path: str) -> None:
        self.path = os.fspath(path)
        self.records: List[Dict[str, Any]] = []
        self.skipped_lines = 0
        self._needs_newline = False
        self._load()

    # ------------------------------------------------------------- read

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        if not raw:
            return
        self._needs_newline = not raw.endswith(b"\n")
        for line in raw.decode("utf-8", errors="replace").splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                kind, gen = rec["kind"], int(rec["generation"])
            except (json.JSONDecodeError, KeyError, TypeError, ValueError):
                # torn line from a kill mid-write — count, don't raise
                self.skipped_lines += 1
                continue
            if kind not in ("codes", "eval", "commit"):
                self.skipped_lines += 1
                continue
            del gen
            self.records.append(rec)

    # ------------------------------------------------------------ write

    def _append(self, rec: Dict[str, Any]) -> None:
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "a") as f:
            # a torn tail has no newline; repair it so this record stays
            # its own parseable line
            f.write(("\n" if self._needs_newline else "")
                    + json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._needs_newline = False
        self.records.append(rec)

    @staticmethod
    def code_key(code: str) -> str:
        return hashlib.sha1(code.encode("utf-8")).hexdigest()

    def record_codes(self, generation: int, codes: List[str]) -> None:
        """Durably persist the drafted candidates BEFORE evaluation — the
        LLM spend is safe from this point on."""
        self._append({"kind": "codes", "generation": int(generation),
                      "codes": list(codes)})

    def record_eval(self, generation: int, record: Any) -> None:
        """Durably persist one candidate's evaluation outcome (an
        ``EvalRecord``-shaped object: code/score/error/scenario_scores/
        aggregation/budget_rung)."""
        self._append({
            "kind": "eval", "generation": int(generation),
            "key": self.code_key(record.code),
            "score": float(record.score),
            "error": record.error,
            "scenario_scores": record.scenario_scores,
            "aggregation": record.aggregation,
            "budget_rung": record.budget_rung,
        })

    def commit(self, generation: int) -> None:
        """The generation is fully committed (ledger + checkpoint landed);
        resumes will never replay it."""
        self._append({"kind": "commit", "generation": int(generation)})

    # ------------------------------------------------------------ views

    def committed(self, generation: int) -> bool:
        g = int(generation)
        return any(r["kind"] == "commit" and r["generation"] == g
                   for r in self.records)

    def pending_codes(self, generation: int) -> Optional[List[str]]:
        """The drafted codes for an UNCOMMITTED generation, or None when
        the generation has no codes record (or was already committed)."""
        if self.committed(generation):
            return None
        g = int(generation)
        for rec in reversed(self.records):
            if rec["kind"] == "codes" and rec["generation"] == g:
                return list(rec["codes"])
        return None

    def cached_evals(self, generation: int) -> Dict[str, Dict[str, Any]]:
        """code-key -> eval record for an uncommitted generation (empty
        when committed: nothing to replay)."""
        if self.committed(generation):
            return {}
        g = int(generation)
        out: Dict[str, Dict[str, Any]] = {}
        for rec in self.records:
            if rec["kind"] == "eval" and rec["generation"] == g:
                out[rec["key"]] = rec
        return out

    def summary(self) -> Dict[str, Any]:
        gens = sorted({r["generation"] for r in self.records})
        return {"path": self.path, "records": len(self.records),
                "skipped_lines": self.skipped_lines,
                "generations": gens,
                "committed": [g for g in gens if self.committed(g)]}
