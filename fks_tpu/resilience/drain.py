"""Preemption-safe drain: the SIGTERM coordinator + serve-state persistence.

Kubernetes (and every preemptible cloud host) delivers SIGTERM, waits a
grace period, then SIGKILLs. The coordinator turns that grace period
into a clean handoff:

1. stop admitting (new submits shed with a typed ``ShedError``),
2. drain the batcher — every already-enqueued Future is COMPLETED by a
   final batch pass, or shed with a typed error when the grace budget
   runs out; nothing is ever left hanging,
3. persist the serve replay buffer + summary (fsync'd tmp + rename, the
   ``pipeline/state.py`` durability idiom) so a restarted server can
   refill its shadow-eval replay source, and
4. run any registered callbacks (e.g. HTTP server shutdown).

The promotion state machine needs no help here: ``promotion.jsonl`` is
already fsync-per-append (``pipeline/state.py``), so its on-disk state
is consistent at any kill point by construction.

``install()`` registers the real signal handler (main thread only —
falls back gracefully elsewhere); drills and tests can call
``handle_signal``/``drain`` directly for determinism.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

STATE_VERSION = 1


def persist_serve_state(service: Any, path: str) -> str:
    """Durably persist the service's replay buffer + counters as one
    JSON document (write + flush + fsync the temp file, then atomic
    rename — a kill mid-persist leaves the previous state intact)."""
    state = {
        "version": STATE_VERSION,
        "ts": round(time.time(), 3),
        "requests_served": service.requests_served,
        "replay": service.recent_queries(10 ** 9),
        "summary": service.summary(record=False),
    }
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_serve_state(path: str) -> Dict[str, Any]:
    """Read a persisted serve state; raises ValueError on a torn or
    unknown-version document (callers should start fresh, not half-load)."""
    try:
        with open(path) as f:
            state = json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(f"{path}: torn serve state ({e})") from e
    if state.get("version") != STATE_VERSION:
        raise ValueError(
            f"{path}: unknown serve-state version {state.get('version')}")
    return state


class DrainCoordinator:
    """SIGTERM -> drain + persist, exactly once, from any thread."""

    def __init__(self, service: Any, *, state_path: str = "",
                 grace_s: float = 5.0, recorder: Any = None):
        from fks_tpu import obs

        self.service = service
        self.state_path = state_path
        self.grace_s = float(grace_s)
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self.requested = False
        self.report: Optional[Dict[str, Any]] = None
        self._callbacks: List[Callable[[], None]] = []
        self._prev: Dict[int, Any] = {}
        self._lock = threading.Lock()

    def add_callback(self, fn: Callable[[], None]) -> None:
        """Run after the drain completes (e.g. HTTP server shutdown)."""
        self._callbacks.append(fn)

    # ------------------------------------------------------------ signals

    def install(self, signals=(signal.SIGTERM,)) -> bool:
        """Register the handler; returns False when not on the main
        thread (signal.signal raises there) — callers then drain in
        their own shutdown path instead."""
        try:
            for sig in signals:
                self._prev[sig] = signal.signal(sig, self.handle_signal)
        except ValueError:
            self._prev.clear()
            return False
        return True

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()

    def handle_signal(self, signum=signal.SIGTERM, frame=None) -> None:
        self.requested = True
        self.drain()
        for fn in self._callbacks:
            try:
                fn()
            except Exception:  # noqa: BLE001 — shutdown callbacks must not
                pass  # keep the process alive past its grace period

    # -------------------------------------------------------------- drain

    def drain(self, grace_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain the service's batcher (complete or shed every in-flight
        Future), persist the replay buffer, record one ``drain`` event.
        Idempotent: the second call returns the first report."""
        with self._lock:
            if self.report is not None:
                return self.report
            t0 = time.perf_counter()
            report = self.service.drain(
                grace_s if grace_s is not None else self.grace_s)
            if self.state_path:
                try:
                    report["state_path"] = persist_serve_state(
                        self.service, self.state_path)
                except OSError as e:
                    report["persist_error"] = str(e)
            report["drain_s"] = round(time.perf_counter() - t0, 6)
            self.recorder.event(
                "drain", pending=report.get("pending", 0),
                completed=report.get("completed", 0),
                shed=report.get("shed", 0),
                persisted=bool(report.get("state_path")),
                drain_s=report["drain_s"])
            self.report = report
            return report
