"""fks_tpu: a TPU-native cluster-scheduling simulator + FunSearch evolution framework.

A from-scratch JAX/XLA re-design of the capabilities of
ttanv/funsearch-kubernetes-simulator (reference at /root/reference):

- ``fks_tpu.data``      -- trace ingest: OpenB/Alibaba CSVs -> padded device arrays
                           (reference: benchmarks/parser.py, simulator/entities.py)
- ``fks_tpu.ops``       -- device kernels: exact binary event heap, GPU sub-allocation,
                           the fused simulator step (reference: simulator/event_simulator.py,
                           simulator/main.py)
- ``fks_tpu.sim``       -- the jit-compiled discrete-event simulation + evaluator
                           (reference: simulator/main.py, simulator/evaluator.py)
- ``fks_tpu.models``    -- scheduling-policy families: hand-written zoo, parametric
                           linear/MLP scorers, bytecode-VM policies
                           (reference: tests/test_scheduler.py policy zoo)
- ``fks_tpu.parallel``  -- population vmap + mesh shard_map scale-out
                           (reference: ProcessPoolExecutor in funsearch_integration.py)
- ``fks_tpu.funsearch`` -- LLM codegen, sandbox/transpiler, evolution controller,
                           persistence (reference: funsearch/)
- ``fks_tpu.serve``     -- champion serving: pinned champion -> warm AOT-compiled
                           what-if query engine with request batching (no
                           reference analogue; the production tier)
- ``fks_tpu.utils``     -- config, logging, profiling.

The simulation core reproduces the reference's observable semantics exactly
(fitness parity well below 1e-5 on the shipped traces), including its
heap-layout-dependent retry scheduling, by replicating CPython's heapq
array layout on-device. All hot paths are jit-compiled lax loops; the
population axis is the parallelism dimension (vmap on chip, shard_map
across a TPU mesh).
"""

__version__ = "0.1.0"
