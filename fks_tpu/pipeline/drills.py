"""The deterministic fault-injection drill matrix.

``run_drills`` stands up a tiny warm serve stack (synthetic cluster,
exact engine, request coalescer) and walks every failure mode the
promotion pipeline claims to survive, asserting the PRECISE degraded
behaviour — serve keeps answering on the old champion throughout:

- corrupt champion JSON (torn mid-write)      -> REJECTED at load
- device-eval exception during the build      -> REJECTED, no crash
- injected p99 regression in shadow           -> REJECTED at shadow
- kill -9 after PENDING / SHADOW / PROMOTED   -> restart resumes to a
  consistent state from promotion.jsonl alone
- post-promotion SLO burn                     -> automatic ROLLED_BACK
- clean promotion                             -> zero warm-path
  recompiles around the hot swap (CompileWatcher)
- total LLM outage                            -> evolve loop halts with
  the llm_outage circuit breaker, checkpoint on disk

plus the resilience matrix (fks_tpu.resilience.drills): deadline storms,
queue overload, device loss mid-batch, degrade-then-recover, SIGTERM
drain, and WAL resume mid-generation.

Everything is seeded and fault-driven — no timing races, no
probabilities — so the matrix is a CI gate (``run_full_suite``), a CLI
(``cli pipeline --drill``), and a slow-tier test, all from one function.
Engines are cached per champion code so the matrix pays each XLA
compile once.
"""
from __future__ import annotations

import dataclasses
import os
import tempfile
from typing import Any, Callable, Dict, List

from fks_tpu.pipeline.controller import PromotionConfig, PromotionController
from fks_tpu.pipeline.faults import (
    FaultPlan, KillSwitch, OutageBackend, write_champion,
    write_corrupt_champion,
)

INCUMBENT_LOGIC = "score = 1000"
CANDIDATE_LOGIC = ("score = 1000 + (node.cpu_milli_left - pod.cpu_milli) "
                   "/ max(1, node.cpu_milli_total)")


class DrillStack:
    """Shared warm serving stack for the matrix: one incumbent engine,
    one candidate-engine cache, fresh ``ServeService`` + promotion log
    per drill (services are cheap; compiled ladders are not)."""

    def __init__(self) -> None:
        from fks_tpu.data.synthetic import synthetic_workload
        from fks_tpu.funsearch import template
        from fks_tpu.serve import ChampionSpec, ServeEngine, ShapeEnvelope

        self.workload = synthetic_workload(8, 16, seed=0)
        self.envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8,
                                      max_batch=2)
        self.incumbent_code = template.fill_template(INCUMBENT_LOGIC)
        self.candidate_code = template.fill_template(CANDIDATE_LOGIC)
        self._cache: Dict[str, Any] = {}
        self.incumbent = self.engine_for(
            ChampionSpec(code=self.incumbent_code, score=0.4,
                         source="<drill-seed>"))

    def engine_for(self, champ) -> Any:
        from fks_tpu.serve import ServeEngine

        key = champ.code
        if key not in self._cache:
            eng = ServeEngine(champ, self.workload, envelope=self.envelope)
            eng.warmup()
            self._cache[key] = eng
        return self._cache[key]

    def service(self):
        from fks_tpu.serve import ServeService

        return ServeService(self.incumbent, max_wait_s=0.002)

    def controller(self, service, tmp: str, *, faults=None,
                   **cfg_overrides) -> PromotionController:
        cfg = PromotionConfig(shadow_queries=2, **cfg_overrides)
        return PromotionController(
            service, self.workload, ledger_dir=tmp,
            log_path=os.path.join(tmp, "promotion.jsonl"), config=cfg,
            faults=faults, engine_factory=self.engine_for)

    def traffic(self, service, n: int = 3, pods: int = 3) -> List[dict]:
        base = self.incumbent.base_pods
        futs = [service.submit(
            {"id": f"d{i}",
             "pods": [dict(base[(i + j) % len(base)]) for j in range(pods)]})
            for i in range(n)]
        return [f.result(timeout=300) for f in futs]


def run_drills(log: Callable[[str], None] = print,
               only: str = "") -> List[Dict[str, Any]]:
    """Run the whole matrix; one result dict per drill, ``ok`` per drill.
    ``only`` is a comma-separated list of name substrings — the CLI's
    ``--only`` and the run_full_suite resilience gate run a subset
    without paying for the rest of the matrix."""
    from fks_tpu.resilience.drills import RESILIENCE_DRILLS

    stack = DrillStack()
    results = []
    filters = [t.strip() for t in only.split(",") if t.strip()]
    for drill in (_drill_corrupt_champion, _drill_device_eval_error,
                  _drill_p99_regression_rejected, _drill_kill_pending,
                  _drill_kill_shadow, _drill_kill_promoted,
                  _drill_rollback_on_burn, _drill_zero_recompile_swap,
                  _drill_vm_double_swap, _drill_portfolio_slot_promotion,
                  _drill_llm_outage,
                  *RESILIENCE_DRILLS):
        name = drill.__name__.replace("_drill_", "")
        if filters and not any(f in name for f in filters):
            continue
        try:
            detail = drill(stack)
            ok = bool(detail.pop("ok"))
        except Exception as e:  # noqa: BLE001 — a drill crash is a failure
            detail, ok = {"error": f"{type(e).__name__}: {e}"}, False
        log(f"drill {name}: {'ok' if ok else 'FAIL'} {detail}")
        results.append({"drill": name, "ok": ok, **detail})
    return results


def _drill_corrupt_champion(stack: DrillStack) -> Dict[str, Any]:
    """A torn champion JSON degrades to REJECTED; serving never stops."""
    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            path = write_corrupt_champion(tmp)
            ctrl = stack.controller(service, tmp)
            out = ctrl.poll_once(path)
            answers = stack.traffic(service, 2)
            return {"ok": (out["action"] == "rejected"
                           and "load_failed" in out["reason"]
                           and len(answers) == 2
                           and all("score" in a for a in answers)),
                    "action": out["action"], "reason": out.get("reason", "")}
    finally:
        service.close()


def _drill_device_eval_error(stack: DrillStack) -> Dict[str, Any]:
    """A device-eval exception while building the shadow engine degrades
    to REJECTED (build_failed), not a controller crash."""
    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            write_champion(tmp, stack.candidate_code, 0.9)
            ctrl = stack.controller(service, tmp,
                                    faults=FaultPlan(device_eval_error=True))
            out = ctrl.poll_once()
            answers = stack.traffic(service, 2)
            return {"ok": (out["action"] == "rejected"
                           and "build_failed" in out["reason"]
                           and len(answers) == 2),
                    "action": out["action"], "reason": out.get("reason", "")}
    finally:
        service.close()


def _drill_p99_regression_rejected(stack: DrillStack) -> Dict[str, Any]:
    """A fitness-winning candidate with an injected latency regression is
    rejected at shadow — it never reaches traffic."""
    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            stack.traffic(service, 3)
            write_champion(tmp, stack.candidate_code, 0.9)
            from fks_tpu.obs.history import SLOConfig

            ctrl = stack.controller(
                service, tmp, faults=FaultPlan(shadow_latency_ms=400.0),
                max_p99_regression=1.5, slo=SLOConfig(p99_ms=50.0))
            out = ctrl.poll_once()
            return {"ok": (out["action"] == "rejected"
                           and service.engine is stack.incumbent
                           and service.swaps == 0),
                    "action": out["action"], "reason": out.get("reason", "")}
    finally:
        service.close()


def _kill_drill(stack: DrillStack, state: str) -> Dict[str, Any]:
    """kill -9 right after ``state`` hits the log; then a fresh
    controller+service (a restarted process) resumes from the log."""
    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            cand = write_champion(tmp, stack.candidate_code, 0.9)
            ctrl = stack.controller(service, tmp,
                                    faults=FaultPlan(kill_after_state=state))
            killed = False
            try:
                ctrl.poll_once()
            except KillSwitch:
                killed = True
            # the crashed controller never took serving down
            survived = len(stack.traffic(service, 2)) == 2
            service2 = stack.service()
            try:
                ctrl2 = stack.controller(service2, tmp)
                rec = ctrl2.recover()
                if state == "PROMOTED":
                    # the log committed before the flip: restart must
                    # resolve to the candidate, with nothing left to do
                    out = ctrl2.poll_once()
                    ok = (killed and survived
                          and rec["active"] is not None
                          and ctrl2.active_champion() == cand
                          and out["action"] == "idle")
                else:
                    out = ctrl2.poll_once()
                    ok = (killed and survived and rec["interrupted"]
                          and out["action"] == "promoted"
                          and service2.engine.champion.score == 0.9)
                return {"ok": ok, "killed_after": state,
                        "recovered": out["action"]}
            finally:
                service2.close()
    finally:
        service.close()


def _drill_kill_pending(stack: DrillStack) -> Dict[str, Any]:
    return _kill_drill(stack, "PENDING")


def _drill_kill_shadow(stack: DrillStack) -> Dict[str, Any]:
    return _kill_drill(stack, "SHADOW")


def _drill_kill_promoted(stack: DrillStack) -> Dict[str, Any]:
    return _kill_drill(stack, "PROMOTED")


def _drill_rollback_on_burn(stack: DrillStack) -> Dict[str, Any]:
    """Post-promotion SLO burn inside the probation window rolls back to
    the last-good engine automatically."""
    from fks_tpu.obs.history import SLOConfig

    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            stack.traffic(service, 2)
            write_champion(tmp, stack.candidate_code, 0.9)
            ctrl = stack.controller(service, tmp, probation_requests=16)
            promoted = ctrl.poll_once()
            # production degrades after the swap: every request now
            # misses the (retroactively impossible) p99 target
            ctrl.cfg = dataclasses.replace(ctrl.cfg,
                                           slo=SLOConfig(p99_ms=1e-6))
            stack.traffic(service, 3)
            out = ctrl.check_probation()
            return {"ok": (promoted["action"] == "promoted"
                           and out is not None
                           and out["action"] == "rolled_back"
                           and service.engine is stack.incumbent
                           and ctrl.log.state_of(out["attempt"])
                           == "ROLLED_BACK"),
                    "promoted": promoted["action"],
                    "then": out["action"] if out else "nothing"}
    finally:
        service.close()


def _drill_zero_recompile_swap(stack: DrillStack) -> Dict[str, Any]:
    """A clean promotion: the hot swap plus post-swap traffic compile
    ZERO new XLA programs (the ladder was built off the request path)."""
    from fks_tpu.obs import CompileWatcher

    service = stack.service()
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            stack.traffic(service, 3)
            write_champion(tmp, stack.candidate_code, 0.9)
            ctrl = stack.controller(service, tmp)
            out = ctrl.poll_once()
            watcher = CompileWatcher().install()
            try:
                answers = stack.traffic(service, 4)
                recompiles = watcher.backend_compile_count
            finally:
                watcher.uninstall()
            return {"ok": (out["action"] == "promoted"
                           and service.engine.champion.score == 0.9
                           and recompiles == 0 and len(answers) == 4),
                    "action": out["action"], "recompiles": recompiles,
                    "swap_ms": ctrl.last_swap_ms}
    finally:
        service.close()


def _drill_vm_double_swap(stack: DrillStack) -> Dict[str, Any]:
    """The VM-native promotion fast path: TWO consecutive hot-swaps on
    a champion-as-data incumbent perform ZERO XLA compiles end to end —
    shadow eval, swap, and post-swap traffic are all table uploads into
    the warm executables (the ISSUE-16 vm_serve_gate contract)."""
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.serve import ChampionSpec, ServeService, VMServeEngine

    incumbent = VMServeEngine(
        ChampionSpec(code=stack.incumbent_code, score=0.4,
                     source="<drill-seed>"),
        stack.workload, envelope=stack.envelope)
    incumbent.warmup()
    service = ServeService(incumbent, max_wait_s=0.002)
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            stack.traffic(service, 3)
            ctrl = stack.controller(service, tmp)
            second = template.fill_template(
                "score = 2000 + (node.memory_mib_left - pod.memory_mib)"
                " / max(1, node.memory_mib_total)")
            watcher = CompileWatcher().install()
            try:
                write_champion(tmp, stack.candidate_code, 0.9)
                first = ctrl.poll_once()
                stack.traffic(service, 2)
                write_champion(tmp, second, 1.3)
                then = ctrl.poll_once()
                stack.traffic(service, 2)
                recompiles = watcher.backend_compile_count
            finally:
                watcher.uninstall()
            return {"ok": (first["action"] == "promoted"
                           and first.get("engine_kind") == "vm"
                           and then["action"] == "promoted"
                           and then.get("engine_kind") == "vm"
                           and service.engine is incumbent
                           and incumbent.vm_swaps == 2
                           and recompiles == 0),
                    "first": first["action"], "then": then["action"],
                    "recompiles": recompiles,
                    "vm_swaps": incumbent.vm_swaps,
                    "swap_ms": incumbent.last_swap_breakdown.get(
                        "swap_ms", 0.0)}
    finally:
        service.close()


def _drill_portfolio_slot_promotion(stack: DrillStack) -> Dict[str, Any]:
    """Per-slot promotion inside the shared portfolio executable: the
    FleetController stages the candidate in a spare shadow slot of the
    LIVE executable, evaluates it on mirrored traffic, and commits it
    into the target slot — zero XLA compiles end to end, and a
    bystander slot's answers are bit-identical across the whole
    lifecycle (promoting slot 1 must never perturb slot 2)."""
    from fks_tpu.funsearch import template
    from fks_tpu.obs import CompileWatcher
    from fks_tpu.portfolio import (
        FleetController, PortfolioEngine, PortfolioService, Router,
    )
    from fks_tpu.serve import ChampionSpec

    second = template.fill_template(
        "score = 2000 + (node.memory_mib_left - pod.memory_mib)"
        " / max(1, node.memory_mib_total)")
    champs = [
        ChampionSpec(code=stack.incumbent_code, score=0.4,
                     source="<slot0>"),
        ChampionSpec(code=stack.candidate_code, score=0.5,
                     source="<slot1>"),
        ChampionSpec(code=second, score=0.6, source="<slot2>"),
    ]
    engine = PortfolioEngine(champs, stack.workload,
                             envelope=stack.envelope, n_slots=4)
    engine.warmup()
    base = engine.base_pods
    bystander_q = [dict(base[j]) for j in range(3)]
    before = engine.answer_batch([bystander_q], slots=[2])[0]
    service = PortfolioService(engine, router=Router(engine.n_slots),
                               max_wait_s=0.002)
    try:
        with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
            stack.traffic(service, 3)
            ctrl = FleetController(
                service, stack.workload, slot=1, shadow_slot=3,
                ledger_dir=tmp,
                log_path=os.path.join(tmp, "promotion.jsonl"),
                config=PromotionConfig(shadow_queries=2))
            promoted_code = template.fill_template(
                "score = 3000 + (node.cpu_milli_left - pod.cpu_milli)"
                " / max(1, node.cpu_milli_total)")
            watcher = CompileWatcher().install()
            try:
                write_champion(tmp, promoted_code, 0.9)
                verdict = ctrl.poll_once()
                stack.traffic(service, 2)
                recompiles = watcher.backend_compile_count
            finally:
                watcher.uninstall()
            after = engine.answer_batch([bystander_q], slots=[2])[0]
            return {"ok": (verdict["action"] == "promoted"
                           and service.engine is engine
                           and engine.slot_swaps[1] >= 1
                           and recompiles == 0
                           and after["score"] == before["score"]
                           and after["placements"]
                           == before["placements"]),
                    "verdict": verdict["action"],
                    "recompiles": recompiles,
                    "slot_swaps": list(engine.slot_swaps),
                    "bystander_drift":
                        abs(after["score"] - before["score"])}
    finally:
        service.close()


def _drill_llm_outage(stack: DrillStack) -> Dict[str, Any]:
    """Total LLM outage: the evolve loop halts via the circuit breaker
    (llm_outage after N empty generations) with a checkpoint on disk,
    instead of spinning through the generation budget."""
    from fks_tpu.funsearch import EvolutionConfig
    from fks_tpu.funsearch import evolution as evo

    with tempfile.TemporaryDirectory(prefix="fks_drill_") as tmp:
        ck = os.path.join(tmp, "evo.json")
        cfg = EvolutionConfig(
            population_size=4, generations=6, elite_size=2,
            candidates_per_generation=2, max_workers=1, seed=3,
            early_stop_threshold=1.1, llm_outage_generations=2)
        backend = OutageBackend()
        fs = evo.run(stack.workload, cfg, backend=backend,
                     checkpoint_path=ck, out_dir=os.path.join(tmp, "out"),
                     log=lambda _m: None)
        return {"ok": (fs.llm_outage and fs.generation == 2
                       and os.path.exists(ck) and fs.best is not None
                       and backend.calls > 0),
                "halted_at_generation": fs.generation,
                "llm_calls": backend.calls}
