"""Fault-injection primitives for the promotion pipeline.

A ``FaultPlan`` is threaded through ``PromotionController`` and injects
failures at the exact seams production would break at: the LLM endpoint,
the device-side shadow evaluation, the champion JSON handoff, and the
process itself (kill mid-promotion). Every injection is deterministic —
the drill matrix (fks_tpu.pipeline.drills) asserts the precise degraded
behaviour, not a probability of it.

``KillSwitch`` models ``kill -9``: it is raised immediately AFTER a state
record has been durably appended to promotion.jsonl, which is the worst
honest moment to die — the log says one thing, the in-memory engines may
say another. Recovery must resolve the difference from the log alone.

Pure host code (no jax at module import).
"""
from __future__ import annotations

import dataclasses
import json
import os


class KillSwitch(RuntimeError):
    """Simulated ``kill -9`` right after a durable log append."""


class FaultInjected(RuntimeError):
    """A deliberately injected failure (device eval, LLM outage)."""


@dataclasses.dataclass
class FaultPlan:
    """Which failures to inject, and where.

    - ``device_eval_error``: the shadow-engine build raises (a device
      eval exception) — the attempt must degrade to REJECTED.
    - ``kill_after_state``: raise KillSwitch right after the named state
      (PENDING/SHADOW/PROMOTED/ROLLED_BACK) is appended to the log.
    - ``shadow_latency_ms``: pad every shadow-engine answer by this much
      — a deterministic p99 regression the latency/SLO gates must catch.
    """
    device_eval_error: bool = False
    kill_after_state: str = ""
    shadow_latency_ms: float = 0.0

    def maybe_kill(self, state: str) -> None:
        if self.kill_after_state and state == self.kill_after_state:
            raise KillSwitch(f"injected kill -9 after {state} was logged")

    def maybe_eval_error(self) -> None:
        if self.device_eval_error:
            raise FaultInjected("injected device-eval exception")

    def shadow_delay_s(self) -> float:
        return self.shadow_latency_ms / 1e3


NO_FAULTS = FaultPlan()


class OutageBackend:
    """An LLM backend whose every call fails — the total-outage drill for
    the evolve loop's llm_outage circuit breaker."""

    def __init__(self) -> None:
        self.calls = 0

    def complete(self, prompt: str) -> str:
        self.calls += 1
        raise FaultInjected("injected LLM outage")


class FlakyEngineProxy:
    """A serve engine that loses its device for the first ``failures``
    batches (raising a classified ``DeviceFault``), then recovers —
    the device-loss-mid-batch drill. Everything else delegates to the
    real warm engine, so parity assertions run against the same ladder."""

    def __init__(self, inner, failures: int = 1) -> None:
        self._inner = inner
        self._failures_left = failures
        self.faults_raised = 0

    def __getattr__(self, name):  # envelope, base_pods, reference_answer…
        return getattr(self._inner, name)

    def answer_batch(self, pod_lists):
        if self._failures_left > 0:
            self._failures_left -= 1
            self.faults_raised += 1
            from fks_tpu.resilience.degrade import DeviceFault
            raise DeviceFault("injected device loss mid-batch")
        return self._inner.answer_batch(pod_lists)


class CountingBackend:
    """A FakeLLM wrapper that counts ``complete`` calls — the WAL-resume
    drill's zero-LLM-calls assertion."""

    def __init__(self, seed: int = 0) -> None:
        from fks_tpu.funsearch import llm as llm_mod

        self._inner = llm_mod.FakeLLM(seed=seed)
        self.calls = 0

    def complete(self, prompt: str) -> str:
        self.calls += 1
        return self._inner.complete(prompt)

    def getstate(self):
        return self._inner.getstate()

    def setstate(self, state) -> None:
        self._inner.setstate(state)


def write_champion(directory: str, code: str, score: float,
                   name: str = "drill", generation: int = 1) -> str:
    """Write a well-formed champion JSON the way the evolve loop does
    (atomic tmp + rename), named so ``latest_champion`` can rank it."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory,
                        f"funsearch_{name}_score{score:.4f}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"code": code, "score": score, "generation": generation,
                   "timestamp": "drill"}, f)
    os.replace(tmp, path)
    return path


def write_corrupt_champion(directory: str, name: str = "corrupt") -> str:
    """A champion JSON torn mid-write — an evolve worker that dumped
    straight to the final path and died. Valid filename, invalid body."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"funsearch_{name}_score9.9999.json")
    with open(path, "w") as f:
        f.write('{"code": "def priority_function(pod, node):\\n  ')
    return path
