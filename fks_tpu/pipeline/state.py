"""Crash-safe promotion ledger: the append-only ``promotion.jsonl``
state machine.

Every promotion attempt is a sequence of single-line JSON records,
``PENDING -> SHADOW -> PROMOTED/REJECTED`` plus ``PROMOTED ->
ROLLED_BACK``. The file is the ONLY durable state the pipeline owns:

- every append is flushed AND fsync'd before the caller proceeds, so a
  ``kill -9`` immediately after a transition still finds that record on
  restart — the in-memory flip always happens after its log record;
- a kill mid-append leaves at most one torn trailing line; the reader
  skips (and counts) any unparsable line instead of raising, and the
  next append repairs the missing newline so the file stays valid JSONL;
- the latest record per attempt wins; interrupted attempts (last state
  PENDING or SHADOW) are re-runnable — the transition map allows
  re-entering PENDING/SHADOW so a restarted controller replays the
  attempt from the top;
- terminal states (REJECTED, ROLLED_BACK) are closed: no transition
  leaves them, so a rejected champion is never retried by accident.

Pure host code — no jax, importable anywhere (the schema checker and
``cli pipeline`` status path stay cheap).
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

STATES = ("PENDING", "SHADOW", "PROMOTED", "REJECTED", "ROLLED_BACK")
TERMINAL = frozenset({"REJECTED", "ROLLED_BACK"})

# current-state -> states an append may move the attempt to. PENDING and
# SHADOW admit re-entry (an interrupted attempt restarts from the top);
# PROMOTED only ever rolls back; terminal states admit nothing.
_ALLOWED: Dict[Optional[str], frozenset] = {
    None: frozenset({"PENDING"}),
    "PENDING": frozenset({"PENDING", "SHADOW", "REJECTED"}),
    "SHADOW": frozenset({"PENDING", "SHADOW", "PROMOTED", "REJECTED"}),
    "PROMOTED": frozenset({"ROLLED_BACK"}),
    "REJECTED": frozenset(),
    "ROLLED_BACK": frozenset(),
}


class PromotionLog:
    """Append-only promotion.jsonl with transition validation on write
    and torn-tail tolerance on read."""

    def __init__(self, path: str) -> None:
        self.path = os.fspath(path)
        self.records: List[Dict[str, Any]] = []
        self.skipped_lines = 0
        self._state: Dict[str, str] = {}
        self._needs_newline = False
        self._load()

    # ------------------------------------------------------------- read

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        if not raw:
            return
        self._needs_newline = not raw.endswith(b"\n")
        for line in raw.decode("utf-8", errors="replace").splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                aid, state = rec["attempt"], rec["state"]
            except (json.JSONDecodeError, KeyError, TypeError):
                # torn line from a kill mid-write — count, don't raise
                self.skipped_lines += 1
                continue
            if state not in STATES:
                self.skipped_lines += 1
                continue
            self.records.append(rec)
            self._state[str(aid)] = state

    # ------------------------------------------------------------ write

    def append(self, attempt: str, state: str, **detail) -> Dict[str, Any]:
        """Validate the transition, then durably append one record
        (write + flush + fsync). Raises ValueError on an illegal move."""
        if state not in STATES:
            raise ValueError(f"unknown promotion state {state!r} "
                             f"(expected one of {STATES})")
        current = self._state.get(attempt)
        if state not in _ALLOWED[current]:
            raise ValueError(
                f"illegal promotion transition for attempt {attempt}: "
                f"{current or '<new>'} -> {state}")
        rec = {"ts": round(time.time(), 3), "attempt": attempt,
               "state": state, **detail}
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(self.path, "a") as f:
            # a torn tail has no newline; repair it so this record stays
            # its own parseable line
            f.write(("\n" if self._needs_newline else "")
                    + json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())
        self._needs_newline = False
        self.records.append(rec)
        self._state[attempt] = state
        return rec

    # ------------------------------------------------------------ views

    def states(self) -> Dict[str, str]:
        """attempt id -> latest state."""
        return dict(self._state)

    def state_of(self, attempt: str) -> Optional[str]:
        return self._state.get(attempt)

    def interrupted(self) -> List[str]:
        """Attempts whose last record is PENDING or SHADOW — a controller
        died mid-attempt; they are safe to replay from the top."""
        return [a for a, s in self._state.items()
                if s in ("PENDING", "SHADOW")]

    def active(self) -> Optional[Dict[str, Any]]:
        """The latest PROMOTED record whose attempt was not since rolled
        back — what a restarted server should be serving."""
        for rec in reversed(self.records):
            if (rec["state"] == "PROMOTED"
                    and self._state.get(rec["attempt"]) == "PROMOTED"):
                return rec
        return None

    def summary(self) -> Dict[str, Any]:
        """Status payload for ``cli pipeline``: per-attempt states, the
        active promotion, interrupted attempts, torn-line count."""
        return {
            "path": self.path,
            "records": len(self.records),
            "skipped_lines": self.skipped_lines,
            "attempts": self.states(),
            "interrupted": self.interrupted(),
            "active": self.active(),
        }
