"""PromotionController: shadow-gated champion hot-swap with rollback.

The controller closes the loop between evolve and serve. It tails a
champion ledger directory (the evolve worker's ``--out`` dir) for new
champions; each candidate runs the promotion state machine recorded in
``promotion.jsonl`` (fks_tpu.pipeline.state):

1. PENDING   — candidate seen; cheap fitness gate (must beat the
               incumbent's score by ``min_score_gain``) before any
               device work.
2. SHADOW    — the candidate's full bucket ladder is built and warmed
               OFF the request path, then shadow-evaluated against a
               replay of recent live serve traffic: per-query parity vs
               its own unbatched exact reference (ParitySentinel), p99
               vs the incumbent on the same queries, SLO burn on the
               shadow latencies, and optionally the robust scenario
               suite (make_suite_eval + aggregate).
3. PROMOTED  — the PROMOTED record is appended FIRST (the log is the
               commit point), then the service's engine reference is
               flipped — one atomic attribute assignment, zero warm-path
               recompiles because the ladder is already compiled. A kill
               between append and flip resolves to the promoted champion
               on restart.
   REJECTED  — any gate failure; serve keeps answering on the incumbent.
4. probation — for the next ``probation_requests`` live requests the
               controller prices SLO burn on post-swap latencies; a
               burn > 1 swaps the last-good engine back and appends
               ROLLED_BACK (again: log first, then flip).

Attempt ids are content-addressed (sha1 of the champion file bytes), so
a restarted controller resumes the SAME attempt after ``kill -9`` and a
rewritten champion file is a new attempt.
"""
from __future__ import annotations

import dataclasses
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from fks_tpu import obs
from fks_tpu.obs import trace_ctx
from fks_tpu.obs.history import SLOConfig, slo_burn
from fks_tpu.funsearch.vm import VMUnsupported
from fks_tpu.pipeline.faults import FaultPlan, KillSwitch, NO_FAULTS
from fks_tpu.pipeline.state import PromotionLog, TERMINAL
from fks_tpu.serve.artifact import (
    CHAMPION_DIR, ChampionSpec, ServeEngine, latest_champion, load_champion,
)


@dataclasses.dataclass
class PromotionConfig:
    """Gates a candidate must clear before (and after) shipping."""
    min_score_gain: float = 0.0       # candidate.score - incumbent.score
    parity_tol: float = 1e-5          # shadow answer vs its exact reference
    shadow_queries: int = 4           # replayed live queries per shadow eval
    max_p99_regression: float = 2.0   # shadow p99 <= factor * incumbent p99
    probation_requests: int = 100     # live requests watched after a swap
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    suite: str = ""                   # optional robust scenario-suite gate
    robust_aggregation: str = "mean"


def attempt_id(path: str) -> str:
    """Content-addressed attempt id: sha1 of the champion file bytes."""
    h = hashlib.sha1()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()[:12]


class PromotionController:
    """Drives the promotion state machine over a live ``ServeService``.

    ``engine_factory(champion) -> warm ServeEngine`` is injectable so
    tests/drills can share compiled ladders; the default builds a
    ServeEngine with the incumbent's envelope/engine knobs and warms it.
    """

    def __init__(self, service, workload=None, *, ledger_dir: str = "",
                 log_path: str = "", config: Optional[PromotionConfig] = None,
                 recorder=None, faults: Optional[FaultPlan] = None,
                 engine_factory: Optional[Callable[..., Any]] = None) -> None:
        self.service = service
        self.cfg = config or PromotionConfig()
        self.ledger_dir = ledger_dir or CHAMPION_DIR
        self.log = PromotionLog(
            log_path or os.path.join(self.ledger_dir, "promotion.jsonl"))
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self.faults = faults or NO_FAULTS
        self.workload = workload
        self._factory = engine_factory or self._build_engine
        self.last_swap_ms = 0.0
        self.last_shadow: Dict[str, Any] = {}
        self._probation: Optional[Dict[str, Any]] = None
        # terminal attempts never retry; PROMOTED ones never re-promote.
        # Interrupted attempts (PENDING/SHADOW) stay eligible — that is
        # the kill -9 recovery path.
        self._done = {a for a, s in self.log.states().items()
                      if s in TERMINAL or s == "PROMOTED"}

    # -------------------------------------------------------- recovery

    def recover(self) -> Dict[str, Any]:
        """What a restarted controller finds in the log: the active
        promotion (what should be serving), interrupted attempts (will
        be replayed by the next poll), torn-line count."""
        return {"active": self.log.active(),
                "interrupted": self.log.interrupted(),
                "skipped_lines": self.log.skipped_lines}

    def active_champion(self) -> Optional[str]:
        """Champion path of the surviving promotion, if any — what a
        restarted server should load before taking traffic."""
        rec = self.log.active()
        return rec.get("champion") if rec else None

    # ------------------------------------------------------------ poll

    def poll_once(self, path: Optional[str] = None) -> Dict[str, Any]:
        """One supervision step: probation check first (rollback beats
        new work), then resolve the newest ledger champion and run the
        attempt if it has not been decided yet."""
        out = self.check_probation()
        if out is not None:
            return out
        path = path or latest_champion(self.ledger_dir,
                                       recorder=self.recorder)
        if path is None:
            return {"action": "idle", "reason": "no readable champion in "
                                                f"{self.ledger_dir}"}
        try:
            aid = attempt_id(path)
        except OSError as e:
            return {"action": "idle", "reason": f"unreadable champion: {e}"}
        if aid in self._done:
            return {"action": "idle", "attempt": aid,
                    "reason": "newest champion already decided"}
        return self._attempt(aid, path)

    # --------------------------------------------------------- attempt

    def _attempt(self, aid: str, path: str) -> Dict[str, Any]:
        """One promotion attempt under ONE causal trace: the trace id is
        derived from the content-addressed attempt id (``promo-<aid>``),
        so a restarted controller resuming the same attempt continues
        the SAME trace, and every ledger transition / shadow stage /
        swap event it writes correlates without threading ids."""
        ctx = (trace_ctx.TraceContext(f"promo-{aid}", trace_ctx.new_span_id())
               if getattr(self.recorder, "enabled", False) else None)
        t0 = time.perf_counter()
        with trace_ctx.activate(ctx):
            out = self._attempt_decide(aid, path)
            trace_ctx.emit(self.recorder, "promotion",
                           time.perf_counter() - t0, ctx=ctx, root=True,
                           attempt=aid, action=out.get("action", "?"))
        return out

    def _attempt_decide(self, aid: str, path: str) -> Dict[str, Any]:
        self._transition(aid, "PENDING", champion=path)
        try:
            champ = load_champion(path)
        except (ValueError, OSError) as e:
            return self._reject(aid, path, f"load_failed: {e}")
        # content link to the evolve generation that produced this
        # champion: the same sha1(code) the candidate marker spans carry
        trace_ctx.emit(self.recorder, "promotion/candidate", 0.0,
                       code_sha=hashlib.sha1(
                           champ.code.encode()).hexdigest()[:12],
                       attempt=aid, score=round(champ.score, 6))
        incumbent = self.service.engine
        inc_spec = self._incumbent_spec(incumbent)
        gain = champ.score - inc_spec.score
        if gain < self.cfg.min_score_gain or gain <= 0:
            return self._reject(
                aid, path,
                f"fitness: candidate {champ.score:.4f} vs incumbent "
                f"{inc_spec.score:.4f} (gain {gain:+.4f} < "
                f"required {max(self.cfg.min_score_gain, 0):g})")
        t0 = time.perf_counter()
        try:
            self.faults.maybe_eval_error()
            with obs.span("build", attempt=aid):
                shadow, engine_kind = self._build_shadow(champ, incumbent,
                                                         aid, path)
        except KillSwitch:
            raise
        except Exception as e:  # device eval / transpile / OOM — degrade
            return self._reject(aid, path,
                                f"build_failed: {type(e).__name__}: {e}")
        self._transition(aid, "SHADOW", champion=path,
                         engine_kind=engine_kind)
        # overlap the host-side transpile (~60ms on a cache miss) with
        # the shadow replay: by the time the gate passes, the commit
        # swap lowers from a warm cache entry (the swap's vm_swap /
        # slot_swap event records transpile_overlapped)
        if engine_kind == "vm" and hasattr(self.service.engine,
                                           "begin_overlapped_transpile"):
            self.service.engine.begin_overlapped_transpile(champ)
        try:
            with obs.span("shadow", attempt=aid):
                verdict = self._shadow_eval(
                    shadow, incumbent,
                    exact_reference=(engine_kind != "vm"))
        except KillSwitch:
            raise
        except Exception as e:
            return self._reject(aid, path,
                                f"shadow_eval_failed: "
                                f"{type(e).__name__}: {e}")
        verdict["shadow_seconds"] = round(time.perf_counter() - t0, 3)
        self.last_shadow = verdict
        if verdict["failures"]:
            return self._reject(aid, path, "; ".join(verdict["failures"]),
                                shadow=_strip(verdict))
        # commit point: PROMOTED lands in the log BEFORE the flip — a
        # kill between the two resolves to the new champion on restart
        self._transition(aid, "PROMOTED", champion=path,
                         previous=inc_spec.source,
                         engine_kind=engine_kind, shadow=_strip(verdict))
        t1 = time.perf_counter()
        old = self._commit_swap(champ, shadow, engine_kind)
        self.last_swap_ms = round((time.perf_counter() - t1) * 1e3, 3)
        trace_ctx.emit(self.recorder, "promotion/swap",
                       self.last_swap_ms / 1e3, attempt=aid,
                       engine_kind=engine_kind)
        self._done.add(aid)
        self._probation = {"attempt": aid, "champion": path,
                           "old_engine": old,
                           "mark": self.service.requests_served,
                           "t0": time.monotonic()}
        self.recorder.metric("promotion_event", attempt=aid,
                             state="SWAPPED", champion=path,
                             swap_ms=self.last_swap_ms,
                             engine_kind=engine_kind)
        return {"action": "promoted", "attempt": aid, "champion": path,
                "swap_ms": self.last_swap_ms, "engine_kind": engine_kind,
                "shadow": _strip(verdict)}

    def _incumbent_spec(self, incumbent) -> ChampionSpec:
        """The ChampionSpec a candidate competes against — the engine's
        resident champion here; the FleetController narrows it to ONE
        slot's champion."""
        return incumbent.champion

    def _commit_swap(self, champ: ChampionSpec, shadow, engine_kind: str):
        """The swap itself, returning the rollback handle: VM fast path
        uploads the candidate's tables INTO the resident engine
        (swap_engine dispatches on ChampionSpec — no rebuild was ever on
        this path); AOT path flips to the prebuilt shadow engine. The
        FleetController overrides this (and ``_restore``) with a per-slot
        table upload."""
        return self.service.swap_engine(
            champ if engine_kind == "vm" else shadow)

    def _restore(self, old) -> None:
        """Invert ``_commit_swap`` with its rollback handle."""
        self.service.swap_engine(old)

    def _build_shadow(self, champ: ChampionSpec, incumbent, aid: str,
                      path: str):
        """The candidate's shadow engine plus how the swap will bind it.

        VM fast path: an incumbent exposing ``shadow_for`` (the VM-native
        engine) lowers the candidate into a shadow VIEW sharing the warm
        champion-agnostic executables — zero XLA compiles on this
        process. ``VMUnsupported`` (candidate outside the VM vocabulary,
        or longer than the resident capacity bucket) records a fallback
        ``vm_swap`` event and degrades to the AOT closure build; any
        other failure (TranspileError, OOM) propagates to the caller's
        build_failed reject exactly as before."""
        if hasattr(incumbent, "shadow_for"):
            try:
                return incumbent.shadow_for(champ), "vm"
            except VMUnsupported as e:
                self.recorder.event(
                    "vm_swap", outcome="fallback", champion=path,
                    attempt=aid, detail=f"{type(e).__name__}: {e}")
        return self._factory(champ), "aot"

    # ----------------------------------------------------- shadow eval

    def _shadow_eval(self, shadow, incumbent,
                     exact_reference: bool = True) -> Dict[str, Any]:
        """Replay recent live traffic through the candidate, gate on
        parity / p99-vs-incumbent / SLO burn / robust suite.

        ``exact_reference=False`` (the VM fast path) skips the per-query
        unbatched exact reference: re-jitting it for the new champion
        would compile on the serving process, defeating the zero-compile
        swap. VM-vs-AOT score parity is instead guaranteed offline
        (tests/test_vm_serve.py and the run_full_suite vm_serve_gate);
        the replay still gates latency, SLO burn and the robust suite."""
        cfg = self.cfg
        queries = self.service.recent_queries(cfg.shadow_queries)
        if not queries:
            queries = self._synthetic_queries(incumbent, cfg.shadow_queries)
        failures: List[str] = []
        sentinel = obs.ParitySentinel(None, tol=cfg.parity_tol,
                                      recorder=self.recorder)
        delay = self.faults.shadow_delay_s()
        lat, inc_lat = [], []
        for i, q in enumerate(queries):
            t0 = time.perf_counter()
            ans = shadow.answer_batch([q])[0]
            lat.append((time.perf_counter() - t0 + delay) * 1e3)
            if exact_reference:
                ref = shadow.reference_answer(q)
                sentinel.audit_served(
                    f"shadow-{i}", ans["score"], ref["score"],
                    placements_match=ans["placements"] == ref["placements"],
                    source="shadow")
            t0 = time.perf_counter()
            incumbent.answer_batch([q])
            inc_lat.append((time.perf_counter() - t0) * 1e3)
        if sentinel.alerts:
            failures.append(
                f"parity: {sentinel.alerts}/{len(queries)} replayed answers "
                f"drifted > {cfg.parity_tol:g} from the exact reference")
        p99 = float(np.percentile(lat, 99)) if lat else 0.0
        inc_p99 = float(np.percentile(inc_lat, 99)) if inc_lat else 0.0
        if inc_p99 > 0 and p99 > cfg.max_p99_regression * inc_p99:
            failures.append(
                f"latency: shadow p99 {p99:.1f}ms > "
                f"{cfg.max_p99_regression:g}x incumbent p99 {inc_p99:.1f}ms")
        if cfg.slo.enabled and lat:
            burning = [b for b in slo_burn(cfg.slo, lat, sum(lat) / 1e3)
                       if b["slo"] == "p99_ms" and b["burn_rate"] > 1.0]
            if burning:
                failures.append(
                    f"slo: shadow replay burns "
                    f"{burning[0]['burn_rate']:.1f}x the p99 error budget")
        robust = inc_robust = None
        if cfg.suite:
            robust, inc_robust = self._robust_scores(shadow, incumbent)
            if robust < inc_robust:
                failures.append(
                    f"robust: suite {cfg.suite} score {robust:.4f} < "
                    f"incumbent {inc_robust:.4f}")
        return {"failures": failures, "queries": len(queries),
                "p99_ms": round(p99, 3), "incumbent_p99_ms": round(inc_p99, 3),
                "parity_alerts": sentinel.alerts,
                "parity_mode": ("exact_reference" if exact_reference
                                else "offline"),
                "robust": robust, "incumbent_robust": inc_robust}

    def _robust_scores(self, shadow, incumbent):
        """Robust scenario-suite gate: candidate must not lose ground on
        the whole suite (one vmapped eval per engine)."""
        from fks_tpu.scenarios import (
            RobustConfig, aggregate, get_suite, make_suite_eval,
        )
        suite = get_suite(self.cfg.suite, self._workload(incumbent))
        rc = RobustConfig(aggregation=self.cfg.robust_aggregation)
        out = []
        for eng in (shadow, incumbent):
            ev = make_suite_eval(suite, param_policy=eng.param_policy,
                                 engine=eng.engine_name)
            res = ev(eng.params)
            out.append(float(aggregate(np.asarray(res.policy_score), rc)))
        return out[0], out[1]

    def _workload(self, engine):
        if self.workload is not None:
            return self.workload
        from fks_tpu.data.entities import Workload
        from fks_tpu.serve.artifact import _pods_from_dicts
        return Workload(cluster=engine.cluster,
                        pods=_pods_from_dicts(engine.base_pods))

    def _synthetic_queries(self, engine, n: int) -> List[List[dict]]:
        """No live traffic yet (fresh service): slide windows over the
        engine's base pods, like ``serve --selftest`` does."""
        base = engine.base_pods
        per = max(1, min(3, engine.envelope.max_pods, len(base)))
        return [[dict(base[(i + j) % len(base)]) for j in range(per)]
                for i in range(n)]

    # ------------------------------------------------------- probation

    def check_probation(self) -> Optional[Dict[str, Any]]:
        """Price SLO burn on post-swap live latencies; roll back on a
        burn, release the probation after ``probation_requests``."""
        p = self._probation
        if p is None:
            return None
        served = self.service.requests_served - p["mark"]
        if served <= 0:
            return None
        if self.cfg.slo.enabled:
            lat = self.service.latencies_since(p["mark"])
            elapsed = max(1e-9, time.monotonic() - p["t0"])
            burning = [b for b in slo_burn(self.cfg.slo, lat, elapsed)
                       if b["burn_rate"] > 1.0]
            if burning:
                return self._rollback(p, burning)
        if served >= self.cfg.probation_requests:
            self._probation = None
            self.recorder.metric("promotion_event", attempt=p["attempt"],
                                 state="PROBATION_PASSED",
                                 champion=p["champion"], requests=served)
            return {"action": "probation_passed", "attempt": p["attempt"],
                    "requests": served}
        return None

    def _rollback(self, p: Dict[str, Any],
                  burning: List[dict]) -> Dict[str, Any]:
        aid = p["attempt"]
        burn = {k: burning[0][k] for k in ("slo", "burn_rate", "observed")
                if k in burning[0]}
        # log first (the durable commit), then flip back
        self._transition(aid, "ROLLED_BACK", champion=p["champion"],
                         reason="slo_burn", burn=burn)
        self._restore(p["old_engine"])
        self.recorder.event("rollback", attempt=aid, reason="slo_burn",
                            champion=p["champion"], **burn)
        self._probation = None
        return {"action": "rolled_back", "attempt": aid,
                "champion": p["champion"], "burn": burn}

    # --------------------------------------------------------- helpers

    def _build_engine(self, champ: ChampionSpec):
        """Default factory: the incumbent's serving knobs, fully warmed
        off the request path (every bucket x lane compiled here, so the
        swap itself compiles nothing)."""
        inc = self.service.engine
        eng = ServeEngine(champ, self._workload(inc), envelope=inc.envelope,
                          engine=inc.engine_name,
                          prefilter_k=inc.prefilter_k,
                          state_pack=inc.state_pack,
                          max_steps_factor=inc.max_steps_factor,
                          recorder=self.recorder)
        eng.warmup()
        return eng

    def _reject(self, aid: str, path: str, reason: str,
                **extra) -> Dict[str, Any]:
        self._done.add(aid)
        self._transition(aid, "REJECTED", champion=path, reason=reason,
                         **extra)
        return {"action": "rejected", "attempt": aid, "champion": path,
                "reason": reason}

    def _transition(self, aid: str, state: str, **detail) -> None:
        """Durable log append + promotion_event metric, THEN the kill
        hook — a drill kill always lands after the record is on disk.
        An active promotion trace stamps its id onto the metric (the
        durable log keeps its schema untouched)."""
        self.log.append(aid, state, **detail)
        ctx = trace_ctx.current()
        self.recorder.metric("promotion_event", attempt=aid, state=state,
                             **detail,
                             **({"trace_id": ctx.trace_id} if ctx else {}))
        self.faults.maybe_kill(state)


def follow_ledger(controller: PromotionController, interval: float = 5.0,
                  stop: Optional[threading.Event] = None):
    """Run the controller's poll loop on a daemon thread (the
    ``serve --follow-ledger`` engine room). A poll failure is recorded
    and swallowed — supervision must never take serving down."""
    stop = stop or threading.Event()

    def _loop() -> None:
        while not stop.is_set():
            try:
                controller.poll_once()
            except Exception as e:  # noqa: BLE001 — serve must survive
                controller.recorder.event(
                    "alert", source="promotion_poll",
                    detail=f"poll failed: {type(e).__name__}: {e}")
            stop.wait(interval)

    thread = threading.Thread(target=_loop, name="promotion-poll",
                              daemon=True)
    thread.start()
    return stop, thread


def _strip(verdict: Dict[str, Any]) -> Dict[str, Any]:
    """Shadow verdict without the failure list (already in ``reason``)."""
    return {k: v for k, v in verdict.items() if k != "failures"}
