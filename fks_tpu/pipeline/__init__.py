"""fks_tpu.pipeline — the continuous evolve→serve promotion pipeline.

Turns the evolve worker and the serving tier into one always-on,
self-healing service: a ``PromotionController`` tails the champion
ledger, shadow-evaluates each new candidate against replayed live
traffic (parity + p99 + SLO burn + optional robust scenario suite),
hot-swaps the warm AOT engine atomically on promotion, auto-rolls back
on post-promotion SLO burn, and records every attempt in a crash-safe
append-only ``promotion.jsonl`` state machine (fks_tpu.pipeline.state).
``FaultPlan`` + ``run_drills`` are the deterministic chaos harness
proving each failure mode degrades gracefully.

- ``state``      — PromotionLog: the durable PENDING→SHADOW→PROMOTED/
                   REJECTED/ROLLED_BACK record, kill -9 recoverable
- ``controller`` — PromotionController + PromotionConfig + the
                   ``serve --follow-ledger`` poll thread
- ``faults``     — FaultPlan / KillSwitch / OutageBackend injection
                   primitives (pure host)
- ``drills``     — the deterministic drill matrix (``cli pipeline
                   --drill``, the run_full_suite promotion gate)
"""
from fks_tpu.pipeline.controller import (
    PromotionConfig, PromotionController, attempt_id, follow_ledger,
)
from fks_tpu.pipeline.drills import run_drills
from fks_tpu.pipeline.faults import (
    FaultInjected, FaultPlan, KillSwitch, OutageBackend, write_champion,
    write_corrupt_champion,
)
from fks_tpu.pipeline.state import STATES, TERMINAL, PromotionLog

__all__ = [
    "STATES", "TERMINAL", "FaultInjected", "FaultPlan", "KillSwitch",
    "OutageBackend", "PromotionConfig", "PromotionController",
    "PromotionLog", "attempt_id", "follow_ledger", "run_drills",
    "write_champion", "write_corrupt_champion",
]
