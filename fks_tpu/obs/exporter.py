"""OpenMetrics export + heartbeat liveness for flight-recorder run dirs.

The recorder's JSONL surfaces are append-only and flushed per record, so
a run directory can be scraped WHILE the run is alive. Two consumers:

- ``to_openmetrics(run_dir)`` renders the run's metrics and event
  counters as OpenMetrics text exposition (``# TYPE``/``# HELP`` blocks,
  escaped labels, terminal ``# EOF``) — paste-able into any Prometheus
  textfile collector or pushgateway without a client library.
- ``run_health(run_dir)`` classifies liveness from the heartbeat file:
  a finished run is FINISHED; a live run whose heartbeat is younger than
  2x its observed cadence is HEALTHY, older is STALE, older than 10x (or
  no heartbeat at all on an unfinished run) is DEAD. Cadence is the
  median inter-record gap of the run's own metrics stream — a slow
  evolution run with 60 s generations is not flagged by a wall-clock
  constant tuned for fast benches.

``cli export-metrics`` and ``cli watch`` are thin shells over these.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from fks_tpu.obs.report import load_run

#: heartbeat age thresholds, in multiples of the observed cadence
STALE_FACTOR = 2.0
DEAD_FACTOR = 10.0
#: floor for the cadence estimate: sub-second generation gaps would make
#: any scrape interval look stale
MIN_CADENCE_SECONDS = 5.0

PREFIX = "fks"

#: fks_serve_latency_seconds histogram bucket bounds (seconds)
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: (metric suffix, source key, help) for per-generation gauges
GENERATION_GAUGES = (
    ("generation_best_score", "best_score", "best fitness in population"),
    ("generation_median_score", "median_score", "median population fitness"),
    ("generation_p10_score", "p10_score", "10th-percentile fitness"),
    ("generation_new_candidates", "new_candidates",
     "candidates evaluated this generation"),
    ("generation_accepted", "accepted", "candidates admitted"),
    ("generation_eval_seconds", "eval_seconds", "evaluation wall seconds"),
    ("generation_llm_seconds", "llm_seconds", "LLM wall seconds"),
    ("generation_evals_per_sec", "evals_per_sec", "evaluation throughput"),
    ("generation_programs_compiled", "programs_compiled",
     "unique XLA programs built"),
    ("generation_vm_candidates", "vm_candidates",
     "candidates served by the VM tier"),
    ("generation_budget_pruned", "budget_pruned",
     "candidates pruned by the eval-budget probe rung"),
    ("generation_budget_device_seconds", "budget_device_seconds",
     "device wall seconds across all budget rungs"),
    ("generation_vm_coverage", "vm_coverage",
     "fraction of unique candidates lowerable to the VM tier"),
)


def _escape_label(value: Any) -> str:
    """OpenMetrics label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(**kv: Any) -> str:
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in kv.items() if v is not None)
    return "{" + inner + "}" if inner else ""


def _num(v: Any) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f


class _Family:
    """One metric family: TYPE/HELP header plus its samples."""

    def __init__(self, name: str, mtype: str, help_: str):
        self.name, self.mtype, self.help = name, mtype, help_
        self.samples: List[str] = []

    def add(self, value: Any, **labels: Any) -> None:
        v = _num(value)
        if v is None:
            return
        body = f"{v:.10g}" if v != int(v) else str(int(v))
        self.samples.append(f"{self.name}{_labels(**labels)} {body}")

    def render(self) -> List[str]:
        if not self.samples:
            return []
        return [f"# TYPE {self.name} {self.mtype}",
                f"# HELP {self.name} {self.help}"] + self.samples


def to_openmetrics(run_dir: str) -> str:
    """Render a run directory as OpenMetrics text exposition."""
    meta, events, metrics = load_run(run_dir)
    run_id = meta.get("run_id", "?")
    fams: Dict[str, _Family] = {}

    def fam(suffix: str, mtype: str, help_: str) -> _Family:
        name = f"{PREFIX}_{suffix}"
        if name not in fams:
            fams[name] = _Family(name, mtype, help_)
        return fams[name]

    info = fam("run_info", "gauge",
               "run identity; value is always 1, identity in labels")
    info.add(1, run_id=run_id, command=meta.get("command"),
             status=meta.get("status", "?"))
    if "wall_seconds" in meta:
        fam("run_wall_seconds", "gauge", "total run wall time").add(
            meta["wall_seconds"], run_id=run_id)

    gens = [m for m in metrics if m.get("kind") == "generation"]
    for g in gens:
        gen = g.get("generation")
        for suffix, key, help_ in GENERATION_GAUGES:
            if key in g:
                fam(suffix, "gauge", help_).add(
                    g[key], run_id=run_id, generation=gen)
    if gens:
        fam("generations_total", "counter",
            "generations committed to the ledger").add(
            len(gens), run_id=run_id)

    for p in (m for m in metrics if m.get("kind") == "parity"):
        gen = p.get("generation")
        fam("parity_max_drift", "gauge",
            "max |fitness drift| vs exact reference this generation").add(
            p.get("max_drift"), run_id=run_id, generation=gen)
        fam("parity_checked", "gauge",
            "candidates parity-checked this generation").add(
            p.get("checked"), run_id=run_id, generation=gen)

    # eval-budget rung ladder (fks_tpu.funsearch.budget): per-rung entered/
    # survived/cost gauges, labeled by generation and rung index
    for b in (m for m in metrics if m.get("kind") == "budget_rung"):
        gen, rung = b.get("generation"), b.get("rung")
        fam("budget_rung_entered", "gauge",
            "candidates entering this budget rung").add(
            b.get("entered"), run_id=run_id, generation=gen, rung=rung)
        fam("budget_rung_survived", "gauge",
            "candidates surviving this budget rung").add(
            b.get("survived"), run_id=run_id, generation=gen, rung=rung)
        fam("budget_rung_device_seconds", "gauge",
            "device wall seconds spent in this budget rung").add(
            b.get("device_seconds"), run_id=run_id, generation=gen,
            rung=rung)
        if "segments" in b:
            fam("budget_rung_segments", "gauge",
                "segmented-runner dispatches in this budget rung").add(
                b.get("segments"), run_id=run_id, generation=gen, rung=rung)

    for s in (m for m in metrics if m.get("kind") == "bench_stage"):
        stage = s.get("stage", "?")
        for key in ("evals_per_sec", "code_evals_per_sec", "compile_seconds",
                    "first_call_seconds", "steady_state_seconds", "value",
                    "budget_speedup", "budget_champion_match"):
            if key in s:
                fam(f"bench_{key}", "gauge",
                    f"bench stage {key}").add(
                    s[key], run_id=run_id, stage=stage)

    # device-time attribution (fks_tpu.obs.profiler): per-stage split
    for d in (m for m in metrics if m.get("kind") == "device_profile"):
        stage = d.get("stage", "?")
        if stage == "__total__":
            fam("profile_attributed_fraction", "gauge",
                "share of measured wall attributed to profiler stages").add(
                d.get("attributed_fraction"), run_id=run_id,
                scope=d.get("scope"))
            fam("profile_idle_fraction", "gauge",
                "share of measured wall unattributed (idle/gaps)").add(
                d.get("idle_fraction"), run_id=run_id, scope=d.get("scope"))
            continue
        for key in ("wall_seconds", "compile_seconds", "compute_seconds",
                    "utilization_pct"):
            if key in d:
                fam(f"profile_stage_{key}", "gauge",
                    f"device-time attribution: stage {key}").add(
                    d[key], run_id=run_id, stage=stage, scope=d.get("scope"))

    # SLO burn rates (fks_tpu.obs.history.slo_burn): latest record per SLO
    latest_burn: Dict[str, dict] = {}
    for b in (m for m in metrics if m.get("kind") == "slo_burn"):
        latest_burn[str(b.get("slo", "?"))] = b
    for name in sorted(latest_burn):
        b = latest_burn[name]
        fam("slo_burn_rate", "gauge",
            "error-budget burn rate (>1 = violating the SLO)").add(
            b.get("burn_rate"), run_id=run_id, slo=name)
        fam("slo_target", "gauge", "declared SLO target").add(
            b.get("target"), run_id=run_id, slo=name)
        fam("slo_observed", "gauge", "observed SLI value").add(
            b.get("observed"), run_id=run_id, slo=name)

    # serve-tier health (fks_tpu.resilience): the latest serve summary's
    # queue/shed/degrade view — what /healthz reports, as gauges
    latest_serve = None
    for s in (m for m in metrics if m.get("kind") == "serve"):
        latest_serve = s
    if latest_serve is not None:
        s = latest_serve
        fam("serve_queue_depth", "gauge",
            "requests admitted but not yet batched").add(
            s.get("queue_depth"), run_id=run_id)
        fam("serve_shed_total", "gauge",
            "requests refused by admission control (queue full / "
            "deadline unmeetable / draining)").add(
            s.get("shed_total"), run_id=run_id)
        fam("serve_shed_rate", "gauge",
            "fraction of submit attempts shed at admission").add(
            s.get("shed_rate"), run_id=run_id)
        fam("serve_deadline_expired_total", "gauge",
            "admitted requests completed with DeadlineExceeded").add(
            s.get("expired"), run_id=run_id)
        if s.get("engine_state") is not None:
            fam("serve_degraded", "gauge",
                "1 while serving on the degraded fallback engine "
                "(degraded/probation), 0 when normal").add(
                0 if s.get("engine_state") == "normal" else 1,
                run_id=run_id, state=str(s.get("engine_state")))

    # per-tenant accounting (fks_tpu.obs.workload.TenantAccountant):
    # latest tenant_stats record per tenant — the fairness index is a
    # GLOBAL value every row carries, exported once unlabeled
    latest_tenant: Dict[str, dict] = {}
    for t in (m for m in metrics if m.get("kind") == "tenant_stats"):
        latest_tenant[str(t.get("tenant", "?"))] = t
    for name in sorted(latest_tenant):
        t = latest_tenant[name]
        fam("tenant_requests_total", "gauge",
            "requests completed for this tenant").add(
            t.get("requests"), run_id=run_id, tenant=name)
        fam("tenant_shed_total", "gauge",
            "requests shed at admission for this tenant").add(
            t.get("shed"), run_id=run_id, tenant=name)
        fam("tenant_expired_total", "gauge",
            "requests whose deadline expired while queued").add(
            t.get("expired"), run_id=run_id, tenant=name)
        fam("tenant_degraded_total", "gauge",
            "requests answered on the degraded fallback engine").add(
            t.get("degraded"), run_id=run_id, tenant=name)
        fam("tenant_ewma_ms", "gauge",
            "EWMA service time for this tenant (ms)").add(
            t.get("ewma_ms"), run_id=run_id, tenant=name)
        fam("tenant_p99_ms", "gauge",
            "p99 latency for this tenant (ms)").add(
            t.get("p99_ms"), run_id=run_id, tenant=name)
        fam("tenant_goodput_qps", "gauge",
            "completed requests per second for this tenant").add(
            t.get("goodput_qps"), run_id=run_id, tenant=name)
        fam("tenant_slo_burn_rate", "gauge",
            "per-tenant p99 error-budget burn rate (>1 = violating)").add(
            t.get("burn_rate"), run_id=run_id, tenant=name)
    if latest_tenant:
        any_row = latest_tenant[sorted(latest_tenant)[0]]
        fam("tenant_fairness_index", "gauge",
            "Jain's fairness index over per-tenant goodput "
            "(1 = even, 1/n = one tenant has it all)").add(
            any_row.get("fairness_index"), run_id=run_id)

    # workload-class mix (fks_tpu.obs.workload.QueryFingerprinter):
    # latest windowed distribution, one gauge per class
    latest_mix = None
    for m in (m for m in metrics if m.get("kind") == "workload_mix"):
        latest_mix = m
    if latest_mix is not None and isinstance(
            latest_mix.get("classes"), dict):
        for cls in sorted(latest_mix["classes"]):
            fam("workload_class_requests", "gauge",
                "requests in this workload class over the latest "
                "fingerprint window").add(
                latest_mix["classes"][cls], run_id=run_id,
                workload_class=cls)

    # loadgen summary (fks_tpu.obs.workload.run_loadgen): the latest
    # generated-load verdict, the four compare-gated keys as gauges
    latest_lg = None
    for m in (m for m in metrics if m.get("kind") == "loadgen_summary"):
        latest_lg = m
    if latest_lg is not None:
        m = latest_lg
        for key, help_ in (
                ("loadgen_qps", "sustained completed qps under load"),
                ("loadgen_p99_ms", "p99 client-observed latency (ms)"),
                ("loadgen_shed_rate", "fraction of requests shed"),
                ("loadgen_fairness_index",
                 "Jain fairness over per-tenant goodput under load")):
            fam(key, "gauge", help_).add(
                m.get(key), run_id=run_id, mode=m.get("mode"))

    # portfolio routing (fks_tpu.portfolio): per-slot routed-request
    # counts and per-rule routing decisions over the whole run, plus
    # per-slot promotion counts from slot_swap events
    route_slots: Dict[Any, int] = {}
    route_reasons: Dict[Any, int] = {}
    for m in (m for m in metrics if m.get("kind") == "portfolio_route"):
        slot = m.get("slot")
        route_slots[slot] = route_slots.get(slot, 0) + 1
        reason = m.get("reason")
        route_reasons[reason] = route_reasons.get(reason, 0) + 1
    for slot in sorted(route_slots, key=str):
        fam("portfolio_slot_requests", "gauge",
            "requests routed to this portfolio slot over the run "
            "(slot -1 = AOT coverage-fallback engine)").add(
            route_slots[slot], run_id=run_id, slot=slot)
    for reason in sorted(route_reasons, key=str):
        fam("portfolio_route_decisions", "gauge",
            "routing decisions by rule (pin / affinity / ab / default "
            "/ fallback / query)").add(
            route_reasons[reason], run_id=run_id, reason=reason)
    slot_swaps: Dict[Any, int] = {}
    for e in (e for e in events if e.get("kind") == "slot_swap"):
        slot = e.get("slot")
        slot_swaps[slot] = slot_swaps.get(slot, 0) + 1
    for slot in sorted(slot_swaps, key=str):
        fam("portfolio_slot_swaps", "gauge",
            "slot-table promotions into this portfolio slot "
            "(each one a zero-compile H2D upload)").add(
            slot_swaps[slot], run_id=run_id, slot=slot)

    # device-resident snapshot cache (ServeEngine content-hash ktable
    # cache): reuse vs upload economics of the sharded serve path
    latest_cache = None
    for c in (m for m in metrics if m.get("kind") == "snapshot_cache"):
        latest_cache = c
    if latest_cache is not None:
        c = latest_cache
        fam("serve_snapshot_cache_hits", "gauge",
            "query batches whose padded ktable was already device-"
            "resident").add(c.get("hits"), run_id=run_id)
        fam("serve_snapshot_cache_misses", "gauge",
            "query batches that uploaded a fresh ktable").add(
            c.get("misses"), run_id=run_id)
        fam("serve_snapshot_cache_entries", "gauge",
            "device buffers currently held by the LRU cache").add(
            c.get("entries"), run_id=run_id)
        fam("serve_snapshot_cache_hit_rate", "gauge",
            "hits / (hits + misses)").add(
            c.get("hit_rate"), run_id=run_id)
        fam("serve_h2d_bytes_per_query", "gauge",
            "host-to-device bytes shipped per answered query "
            "(post-packing, cache-discounted)").add(
            c.get("h2d_bytes_per_query"), run_id=run_id)

    # executable-footprint ledger (fks_tpu.obs.memory): the predicted
    # HBM claim of each compiled executable, latest record per
    # (component, exe_key) — what the run WILL hold resident, from
    # memory_analysis, before any allocator ever reports pressure
    latest_fp: Dict[Tuple[str, str], dict] = {}
    for m in (m for m in metrics if m.get("kind") == "memory_footprint"):
        latest_fp[(str(m.get("component", "?")),
                   str(m.get("exe_key", "?")))] = m
    for component, exe_key in sorted(latest_fp):
        m = latest_fp[(component, exe_key)]
        fam("mem_exe_temp_bytes", "gauge",
            "XLA scratch (temp) bytes reserved by this executable").add(
            m.get("temp_bytes"), run_id=run_id, component=component,
            exe=exe_key)
        fam("mem_exe_total_bytes", "gauge",
            "predicted HBM claim: temp + argument + output + "
            "generated-code bytes").add(
            m.get("total_bytes"), run_id=run_id, component=component,
            exe=exe_key)

    # watermark sampler (fks_tpu.obs.memory): the latest host/device
    # high-water sample; per-device rows carry the allocator's view
    latest_wm = None
    for m in (m for m in metrics if m.get("kind") == "memory_watermark"):
        latest_wm = m
    if latest_wm is not None:
        m = latest_wm
        fam("mem_host_rss_kb", "gauge",
            "host resident set size at the latest watermark sample").add(
            m.get("host_rss_kb"), run_id=run_id, stage=m.get("stage"))
        for d in (m.get("devices") or []):
            if not isinstance(d, dict):
                continue
            did = d.get("id", "?")
            fam("mem_device_bytes_in_use", "gauge",
                "device allocator bytes in use at the latest watermark "
                "sample").add(d.get("bytes_in_use"), run_id=run_id,
                              device=did, platform=d.get("platform"))
            fam("mem_device_peak_bytes", "gauge",
                "device allocator peak bytes in use").add(
                d.get("peak_bytes_in_use"), run_id=run_id, device=did,
                platform=d.get("platform"))

    # leak-sentinel verdicts (fks_tpu.obs.memory): net live-array drift
    # across each fenced hot loop, latest record per loop
    latest_leak: Dict[str, dict] = {}
    for m in (m for m in metrics if m.get("kind") == "leak_check"):
        latest_leak[str(m.get("loop", "?"))] = m
    for loop in sorted(latest_leak):
        m = latest_leak[loop]
        fam("mem_leak_drift_bytes", "gauge",
            "net live-array byte drift across the fenced loop").add(
            m.get("drift_bytes"), run_id=run_id, loop=loop)
        fam("mem_leak_ok", "gauge",
            "1 when the fenced loop stayed within drift tolerance").add(
            1 if m.get("ok") else 0, run_id=run_id, loop=loop)

    # per-layout cost ledger (fks_tpu.obs.layout): the roll-up per
    # (workload, mesh, layout) — pad waste and lane-step occupancy of
    # every layout the run exercised, plus the explorer's latest
    # steady-seconds probe per mesh shape
    layout_rows = [m for m in metrics if m.get("kind") == "layout_ledger"]
    if layout_rows:
        from fks_tpu.obs.layout import rollup_layouts  # deferred
        for a in rollup_layouts(
                layout_rows,
                footprints=[m for m in metrics
                            if m.get("kind") == "memory_footprint"]):
            labels = dict(run_id=run_id,
                          workload=a["workload_key"] or "-",
                          mesh=a["mesh_layout"] or "unsharded",
                          layout=a["layout_key"])
            fam("layout_pad_waste_fraction", "gauge",
                "worst padded-lane waste fraction recorded under this "
                "layout").add(a["pad_waste_fraction_max"], **labels)
            fam("layout_occupancy", "gauge",
                "real / launched lane-steps under this layout").add(
                a["occupancy"], **labels)
    latest_probe: Dict[str, dict] = {}
    for m in (m for m in metrics if m.get("kind") == "layout_probe"):
        latest_probe[str(m.get("mesh_shape", "?"))] = m
    for shape in sorted(latest_probe):
        m = latest_probe[shape]
        fam("layout_probe_seconds", "gauge",
            "best warm steady seconds measured for this mesh shape by "
            "the layout explorer").add(
            m.get("steady_seconds"), run_id=run_id, mesh=shape,
            layout=m.get("layout_key"))

    # per-request latency histogram with trace-id EXEMPLARS: each bucket
    # cites the slowest request that landed in it, so a fat-tail bucket
    # on a dashboard links straight to the ``cli spans --trace`` waterfall
    # explaining it
    hist = _latency_histogram(metrics, run_id)
    if hist is not None:
        fams[hist.name] = hist

    counts: Dict[str, int] = {}
    for e in events:
        kind = e.get("kind", "?")
        counts[kind] = counts.get(kind, 0) + 1
    ev = fam("events_total", "counter", "recorder events by kind")
    for kind in sorted(counts):
        ev.add(counts[kind], run_id=run_id, kind=kind)
    wd = fam("watchdog_violations_total", "counter",
             "watchdog numeric-guard events")
    wd.add(counts.get("watchdog", 0), run_id=run_id)
    al = fam("alerts_total", "counter", "alert events (parity drift etc.)")
    al.add(counts.get("alert", 0), run_id=run_id)

    compile_s = sum(float(e.get("seconds", 0.0)) for e in events
                    if e.get("kind") == "compile")
    if compile_s:
        fam("compile_seconds_total", "counter",
            "total XLA compile wall seconds").add(compile_s, run_id=run_id)

    health = run_health(run_dir, meta=meta, metrics=metrics)
    fam("heartbeat_age_seconds", "gauge",
        "seconds since the last heartbeat (-1: no heartbeat file)").add(
        health["age"] if health["age"] is not None else -1, run_id=run_id)
    fam("run_healthy", "gauge",
        "1 when finished or heartbeat within 2x cadence, else 0").add(
        1 if health["state"] in ("FINISHED", "HEALTHY") else 0,
        run_id=run_id)

    lines: List[str] = []
    for name in sorted(fams):
        lines.extend(fams[name].render())
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _latency_histogram(metrics: List[Dict[str, Any]],
                       run_id: str) -> Optional[_Family]:
    """``fks_serve_latency_seconds``: cumulative histogram over the run's
    ``serve_request`` latencies, with an OpenMetrics EXEMPLAR on every
    non-empty bucket — the slowest traced request that landed there
    (``# {trace_id="..."} value`` suffix), so hot buckets link to their
    causal waterfall."""
    lats: List[Tuple[float, Optional[str]]] = []
    for m in metrics:
        if m.get("kind") != "serve_request":
            continue
        v = _num(m.get("latency_ms"))
        if v is not None:
            lats.append((v / 1e3, m.get("trace_id")))
    if not lats:
        return None
    f = _Family(f"{PREFIX}_serve_latency_seconds", "histogram",
                "per-request serve latency (exemplars cite the slowest "
                "traced request per bucket)")
    lab = _labels(run_id=run_id)[1:-1]  # inner body, le= appended per bucket
    cum = 0
    lo = -1.0  # first bucket includes zero-latency samples
    for le in (*LATENCY_BUCKETS, float("inf")):
        inside = [(s, t) for s, t in lats if lo < s <= le] if le != float(
            "inf") else [(s, t) for s, t in lats if s > lo]
        cum += len(inside)
        le_s = "+Inf" if le == float("inf") else f"{le:.10g}"
        line = f'{f.name}_bucket{{{lab},le="{le_s}"}} {cum}'
        exemplar = max((p for p in inside if p[1]), default=None)
        if exemplar is not None:
            line += (f' # {{trace_id="{_escape_label(exemplar[1])}"}}'
                     f" {exemplar[0]:.6g}")
        f.samples.append(line)
        lo = le
    f.samples.append(
        f"{f.name}_sum{{{lab}}} {sum(s for s, _ in lats):.6g}")
    f.samples.append(f"{f.name}_count{{{lab}}} {len(lats)}")
    return f


def _heartbeat_age(run_dir: str) -> Optional[float]:
    """Seconds since the run's last heartbeat, None when absent/corrupt.

    Two clocks bound the age: the timestamp INSIDE the file (the
    writer's wall clock) and the file's mtime (the filesystem's clock).
    On a shared filesystem either can lag or lead — writer/reader clock
    skew, NFS attribute-cache delay — and a one-sided read flaps a
    healthy run between STALE and DEAD. The age is the SMALLER of the
    two (most recent evidence of life), clamped at zero against skew
    that puts the heartbeat in the future."""
    path = os.path.join(run_dir, "heartbeat")
    try:
        with open(path) as f:
            beat = json.load(f)
        now = time.time()
        age = now - float(beat["ts"])
        try:
            age = min(age, now - os.path.getmtime(path))
        except OSError:
            pass
        return max(0.0, age)
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _cadence(metrics: List[Dict[str, Any]]) -> float:
    """Median inter-record gap of the metrics stream (seconds), floored
    at MIN_CADENCE_SECONDS; the floor alone when under two records."""
    ts = sorted(float(m["ts"]) for m in metrics if _num(m.get("ts")))
    gaps = sorted(b - a for a, b in zip(ts, ts[1:]) if b > a)
    if not gaps:
        return MIN_CADENCE_SECONDS
    return max(MIN_CADENCE_SECONDS, gaps[len(gaps) // 2])


def run_health(run_dir: str, meta: Optional[dict] = None,
               metrics: Optional[list] = None) -> Dict[str, Any]:
    """Liveness verdict for a run dir: ``{"state", "age", "cadence"}``
    with state one of FINISHED / HEALTHY / STALE / DEAD (see module
    docstring for the thresholds)."""
    if meta is None or metrics is None:
        meta, _events, metrics = load_run(run_dir)
    age = _heartbeat_age(run_dir)
    cadence = _cadence(metrics or [])
    if meta.get("status") in ("ok", "error") or "finished" in meta:
        return {"state": "FINISHED", "age": age, "cadence": cadence,
                "status": meta.get("status")}
    if age is None:
        return {"state": "DEAD", "age": None, "cadence": cadence,
                "status": meta.get("status")}
    if age > DEAD_FACTOR * cadence:
        state = "DEAD"
    elif age > STALE_FACTOR * cadence:
        state = "STALE"
    else:
        state = "HEALTHY"
    return {"state": state, "age": age, "cadence": cadence,
            "status": meta.get("status")}


def health_line(run_dir: str) -> str:
    """One-line liveness summary, as shown by ``cli watch``/``report``."""
    h = run_health(run_dir)
    age = "-" if h["age"] is None else f"{h['age']:.0f}s"
    return (f"{h['state']}: heartbeat age {age} "
            f"(cadence ~{h['cadence']:.0f}s)")


def watch(run_dir: str, interval: float = 5.0, once: bool = False,
          out=None, clock=time.sleep) -> int:
    """Live-tail a run: print the latest generation/bench line plus the
    liveness verdict every ``interval`` seconds until the run finishes
    (or forever under an external watchdog). Returns 0 when the run
    finished ok, 1 when it finished in error or is DEAD."""
    import sys

    out = out or sys.stdout
    seen = 0
    while True:
        meta, _events, metrics = load_run(run_dir)
        fresh = metrics[seen:]
        seen = len(metrics)
        for m in fresh:
            kind = m.get("kind")
            if kind == "generation":
                out.write(f"gen {m.get('generation')}: "
                          f"best {m.get('best_score', 0.0):.4f} "
                          f"new {m.get('new_candidates', 0)} "
                          f"eval {m.get('eval_seconds', 0.0):.1f}s\n")
            elif kind == "parity":
                out.write(f"parity gen {m.get('generation')}: "
                          f"max drift {m.get('max_drift')}\n")
            elif kind == "bench_stage":
                v = m.get("value", m.get("evals_per_sec"))
                out.write(f"bench {m.get('stage', '?')}: {v}\n")
            elif kind == "leak_check":
                verdict = "ok" if m.get("ok") else "LEAK"
                out.write(f"leak {m.get('loop', '?')}: {verdict} "
                          f"drift {m.get('drift_count', 0)} arrays / "
                          f"{m.get('drift_bytes', 0)} bytes over "
                          f"{m.get('iterations', 0)} iters\n")
            elif kind == "slo_burn":
                rate = _num(m.get("burn_rate")) or 0.0
                line = (f"slo {m.get('slo', '?')}: burn {rate:.2f}x "
                        f"(observed {m.get('observed')} vs target "
                        f"{m.get('target')})")
                if rate > 1.0:
                    line = "SLO ALERT " + line
                out.write(line + "\n")
            elif kind == "tenant_stats":
                rate = _num(m.get("burn_rate")) or 0.0
                line = (f"tenant {m.get('tenant', '?')}: "
                        f"{m.get('requests', 0)} req "
                        f"p99 {m.get('p99_ms', 0.0)}ms "
                        f"shed {m.get('shed', 0)} "
                        f"burn {rate:.2f}x "
                        f"fair {m.get('fairness_index', 1.0)}")
                if rate > 1.0:
                    line = "TENANT SLO ALERT " + line
                out.write(line + "\n")
            elif kind == "workload_mix":
                classes = m.get("classes") or {}
                top = sorted(classes.items(), key=lambda kv: -kv[1])[:3]
                mix = " ".join(f"{c}={n}" for c, n in top)
                out.write(f"workload mix ({m.get('window', 0)} req, "
                          f"{m.get('distinct', 0)} classes): {mix}\n")
            elif kind == "loadgen_summary":
                out.write(f"loadgen [{m.get('mode', '?')}]: "
                          f"{m.get('loadgen_qps', 0.0)} qps "
                          f"p99 {m.get('loadgen_p99_ms', 0.0)}ms "
                          f"shed {m.get('loadgen_shed_rate', 0.0)} "
                          f"fair {m.get('loadgen_fairness_index', 1.0)}\n")
        h = run_health(run_dir, meta=meta, metrics=metrics)
        age = "-" if h["age"] is None else f"{h['age']:.0f}s"
        out.write(f"[{h['state']}] status={meta.get('status', '?')} "
                  f"heartbeat {age}\n")
        out.flush()
        if h["state"] == "FINISHED":
            return 0 if meta.get("status") == "ok" else 1
        if h["state"] == "DEAD":
            return 1
        if once:
            return 0
        clock(interval)
