"""The flight recorder: one run, one directory, everything the run did.

A ``FlightRecorder`` owns a run directory with a fixed layout:

- ``meta.json``     — run identity: run_id, command/argv, start/finish
                      timestamps, status, plus anything callers annotate
                      (rewritten atomically on every annotation);
- ``events.jsonl``  — append-only operational events (spans, compile
                      telemetry, device/mesh snapshots), one JSON object
                      per line with ``ts``/``kind``/``seq``;
- ``metrics.jsonl`` — append-only metric records (the evolution ledger's
                      per-generation rows, bench stage results), same
                      ``ts``/``kind`` framing as ``utils.MetricsWriter``
                      because it IS a ``MetricsWriter`` underneath;
- ``heartbeat``     — a tiny JSON file rewritten (atomic replace) on every
                      ledger commit, so an external watcher can tell a
                      slow run from a dead one without parsing the JSONL.

``cli report <run-dir>`` renders a run summary from these files alone — no
in-process state survives the run, by design (fks_tpu.obs.report).

The disabled path is a ``NullRecorder``: identical API, zero filesystem
writes, no conditionals anywhere in jitted code (all device-side numbers
recorded through this module come from values the eval paths already
return, or from host-side jax.monitoring listeners). The process-wide
active recorder defaults to the shared NullRecorder; ``recording(rec)``
installs a real one for a scope (the CLI does this for ``--run-dir``).
"""
from __future__ import annotations

import contextlib
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, Iterator, Optional

from fks_tpu.obs import trace_ctx
from fks_tpu.utils.logging import MetricsWriter, json_ready


class NullRecorder:
    """The disabled flight recorder: full API, zero filesystem writes.

    Shared default for every instrumented path, so instrumentation never
    needs an ``if recorder:`` guard (and the no-run-dir path stays
    near-zero overhead: each call is one no-op method dispatch).
    """

    enabled = False
    run_dir: Optional[str] = None
    run_id: Optional[str] = None

    def event(self, kind: str, **fields) -> None:
        pass

    def metric(self, kind: str, record: Optional[Dict[str, Any]] = None,
               **fields) -> None:
        pass

    def heartbeat(self) -> None:
        pass

    def annotate_meta(self, **fields) -> None:
        pass

    def finish(self, status: str = "ok") -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullRecorder":
        return self

    def __exit__(self, *exc) -> None:
        pass


class FlightRecorder(NullRecorder):
    """A live run directory (see module docstring for the layout)."""

    enabled = True

    def __init__(self, run_dir: str,
                 meta: Optional[Dict[str, Any]] = None) -> None:
        os.makedirs(run_dir, exist_ok=True)
        self.run_dir = os.fspath(run_dir)
        # sortable + collision-proof: wall-clock stamp, random suffix
        self.run_id = (time.strftime("%Y%m%d_%H%M%S") + "-"
                       + secrets.token_hex(3))
        self._t0 = time.time()
        self._meta: Dict[str, Any] = {
            "run_id": self.run_id,
            "started": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "started_ts": self._t0,
            "status": "running",
        }
        if meta:
            self._meta.update(meta)
        self._meta_lock = threading.Lock()
        self._write_meta()
        self._events = MetricsWriter(os.path.join(run_dir, "events.jsonl"))
        self._metrics = MetricsWriter(os.path.join(run_dir, "metrics.jsonl"))
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._closed = False
        self.heartbeat()

    # ----- the three write surfaces

    def event(self, kind: str, **fields) -> None:
        """Operational event -> ``events.jsonl`` (spans, compiles,
        device/mesh snapshots). ``seq`` is a process-wide monotonic
        counter so concurrent writers (compile listeners fire from the
        evaluator's thread pool) keep a total order even when ``ts``
        collides at clock resolution.

        An active trace context (fks_tpu.obs.trace_ctx) stamps its
        trace_id onto every event written under it — shed / degraded /
        drain / promotion events correlate to the request or attempt
        that caused them without each call site threading the id."""
        if "trace_id" not in fields:
            ctx = trace_ctx.current()
            if ctx is not None:
                fields["trace_id"] = ctx.trace_id
        with self._seq_lock:
            seq = self._seq
            self._seq += 1
        self._events.write(kind, seq=seq, **fields)

    def metric(self, kind: str, record: Optional[Dict[str, Any]] = None,
               **fields) -> None:
        """Metric record -> ``metrics.jsonl`` (ledger generations, bench
        stages); same schema as ``--metrics`` JSONL output."""
        self._metrics.write(kind, record, **fields)

    def heartbeat(self) -> None:
        """Atomically rewrite the heartbeat file with the current time —
        liveness for external watchers, no JSONL parsing required."""
        path = os.path.join(self.run_dir, "heartbeat")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"ts": time.time(), "run_id": self.run_id}, f)
        os.replace(tmp, path)

    # ----- meta lifecycle

    def annotate_meta(self, **fields) -> None:
        """Merge fields into ``meta.json`` (atomic rewrite) — final best
        score, workload shape, anything identity-grade rather than
        event-grade."""
        with self._meta_lock:
            self._meta.update(fields)
            self._write_meta()

    def finish(self, status: str = "ok") -> None:
        self.annotate_meta(
            status=status,
            finished=time.strftime("%Y-%m-%dT%H:%M:%S"),
            wall_seconds=round(time.time() - self._t0, 3))
        self.heartbeat()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._events.close()
            self._metrics.close()

    def _write_meta(self) -> None:
        path = os.path.join(self.run_dir, "meta.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._meta, f, indent=2, default=json_ready)
        os.replace(tmp, path)

    def __enter__(self) -> "FlightRecorder":
        return self

    def __exit__(self, exc_type, *exc) -> None:
        self.finish("ok" if exc_type is None else "error")
        self.close()


# ------------------------------------------------- process-wide recorder

NULL = NullRecorder()
_active: NullRecorder = NULL


def get_recorder() -> NullRecorder:
    """The process-wide active recorder (the shared NullRecorder unless a
    ``recording(...)`` scope is open). Instrumented paths default to this,
    so a CLI ``--run-dir`` reaches spans/ledgers deep in the stack without
    threading a recorder through every signature."""
    return _active


@contextlib.contextmanager
def recording(recorder: NullRecorder) -> Iterator[NullRecorder]:
    """Install ``recorder`` as the process-wide active recorder for the
    scope; on exit, finish (status from exception state), close, and
    restore the previous recorder. Null recorders pass through unchanged
    (finish/close are no-ops)."""
    global _active
    prev = _active
    _active = recorder
    try:
        yield recorder
    except BaseException:
        _active = prev
        recorder.finish("error")
        recorder.close()
        raise
    _active = prev
    recorder.finish("ok")
    recorder.close()
