"""Cross-run history: index runs, render trends, flag regressions, burn SLOs.

``cli compare`` is strictly pairwise and every bench probe's headline is
a single JSON line — until this module, the repo had no durable perf
trajectory. ``RunHistory`` indexes any root directory holding
flight-recorder run dirs and/or bench JSONL evidence files into a flat
``history.jsonl`` (one entry per run: timestamp, health, the comparator
metric vocabulary from ``obs.compare.extract_metrics``), and builds on
that index:

- ``timelines()``: per-metric (ts, value, run) series across the root;
- ``trends()``: regression flagging with a robust z-score over a sliding
  window of prior runs — deviation is measured in MAD units (floored at
  2% of the window median so deterministic series don't divide by zero),
  direction comes from ``obs.compare.DEFAULT_THRESHOLDS``, and
  consecutive flagged points collapse into ONE alert at the change
  point, so a level shift reads as a single regression event rather than
  an alert per subsequent run;
- ``best_healthy()``: the best healthy historical run for a metric —
  what ``cli compare --baseline auto`` resolves, replacing hand-picked
  baselines;
- ``last_healthy_headline()``: the newest healthy nonzero bench headline
  — what a FAILED bench probe's fallback JSON carries (with a
  ``stale_from_run`` marker) instead of a bare 0.0, so ``cli compare
  --gate`` keeps a real denominator.

Serve-tier SLOs ride along: ``SLOConfig`` declares p99/qps targets and
``slo_burn`` prices observed latencies against them as burn rates (the
multiple of the error budget being consumed — burn_rate > 1 means the
SLO is being violated), recorded as ``slo_burn`` metrics and surfaced by
``cli watch`` and the OpenMetrics exporter.

Health: a run dir is healthy when its meta status is ``ok`` and it
recorded no alert events; a bench file is healthy when it carries a
measured (nonzero, non-stale) headline. Stale fallback headlines are
indexed but never re-selected as baselines — staleness must not chain.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

from fks_tpu.obs.compare import DEFAULT_THRESHOLDS, extract_metrics

#: default index filename inside a history root
INDEX_NAME = "history.jsonl"

#: metrics the trend pass watches by default (ordered: headline first)
TREND_METRICS = (
    "evals_per_sec", "code_evals_per_sec", "compile_seconds",
    "best_score", "serve_p99_ms", "serve_qps", "scale1k_events_per_sec",
    "budget_speedup", "peak_device_bytes", "exe_temp_bytes",
    "loadgen_qps", "loadgen_p99_ms", "loadgen_shed_rate",
    "loadgen_fairness_index",
    "layout_best_over_default", "layout_pad_waste_frac",
)

#: filename of the measured-layout prior store inside a history root
LAYOUTS_NAME = "layouts.json"


# ------------------------------------------------------------------ index


def _file_has_key(path: str, key: str) -> bool:
    """Whether any JSON line in ``path`` carries ``key`` (cheap substring
    pre-filter, then a real parse of candidate lines)."""
    try:
        with open(path) as f:
            for line in f:
                if key not in line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(rec, dict) and key in rec:
                    return True
    except OSError:
        pass
    return False


class RunHistory:
    """An indexed view over a root of run dirs and bench JSONL files."""

    def __init__(self, root: str):
        self.root = os.fspath(root)
        self.entries: List[Dict[str, Any]] = []

    # ----- scanning

    def scan(self) -> List[Dict[str, Any]]:
        """Walk the root: every immediate subdirectory with a ``meta.json``
        is indexed as a flight-recorder run dir; every ``*.json`` /
        ``*.jsonl`` file (the index itself excluded) as bench evidence.
        Entries are sorted by timestamp — meta ``started_ts`` for run
        dirs, file mtime for bench files."""
        if not os.path.isdir(self.root):
            raise FileNotFoundError(f"history root {self.root!r} is not a "
                                    "directory")
        entries: List[Dict[str, Any]] = []
        for name in sorted(os.listdir(self.root)):
            path = os.path.join(self.root, name)
            if os.path.isdir(path):
                if os.path.exists(os.path.join(path, "meta.json")):
                    e = self._index_run_dir(path)
                    if e:
                        entries.append(e)
            elif name != INDEX_NAME and name.endswith((".json", ".jsonl")):
                e = self._index_bench_file(path)
                if e:
                    entries.append(e)
        entries.sort(key=lambda e: e["ts"])
        self.entries = entries
        return entries

    def _index_run_dir(self, path: str) -> Optional[Dict[str, Any]]:
        try:
            with open(os.path.join(path, "meta.json")) as f:
                meta = json.load(f)
            metrics = extract_metrics(path)
        except (OSError, ValueError, json.JSONDecodeError):
            return None  # a corrupt run dir must not kill the index
        ts = meta.get("started_ts")
        if ts is None:
            ts = os.path.getmtime(os.path.join(path, "meta.json"))
        healthy = (meta.get("status") == "ok"
                   and not metrics.get("alerts", 0.0)
                   and not metrics.get("watchdog_violations", 0.0))
        return {
            "run": os.path.basename(path.rstrip(os.sep)),
            "path": path,
            "source": "run_dir",
            "ts": float(ts),
            "run_id": meta.get("run_id", ""),
            "command": meta.get("command", ""),
            "status": meta.get("status", "?"),
            "healthy": bool(healthy),
            "stale": False,
            "metrics": {k: round(v, 6) for k, v in metrics.items()},
        }

    def _index_bench_file(self, path: str) -> Optional[Dict[str, Any]]:
        stale = _file_has_key(path, "stale_from_run")
        try:
            # stale carry-forwards are indexed (visible in the listing)
            # but marked: never healthy, never in timelines
            metrics = extract_metrics(path, allow_stale=stale)
        except (OSError, ValueError, TypeError):
            return None
        if not metrics:
            return None
        healthy = bool(metrics.get("evals_per_sec")
                       or metrics.get("code_evals_per_sec")) and not stale
        return {
            "run": os.path.basename(path),
            "path": path,
            "source": "bench",
            "ts": float(os.path.getmtime(path)),
            "status": "ok" if healthy else "unmeasured",
            "healthy": healthy,
            "stale": stale,
            "metrics": {k: round(v, 6) for k, v in metrics.items()},
        }

    def write_index(self, path: str = "") -> str:
        """Persist the scanned entries as one-entry-per-line JSONL (the
        durable trajectory other tools can tail); atomic replace."""
        if not self.entries:
            self.scan()
        path = path or os.path.join(self.root, INDEX_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for e in self.entries:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, path)
        return path

    # ----- measured layout priors (obs.layout.explore_layouts)

    def _layouts_path(self) -> str:
        return os.path.join(self.root, LAYOUTS_NAME)

    def _load_layouts(self) -> Dict[str, Any]:
        try:
            with open(self._layouts_path()) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}
        return doc if isinstance(doc, dict) else {}

    def record_layout_prior(self, workload_key: str, mesh_shape: str,
                            layout_key: str,
                            metrics: Optional[Dict[str, Any]] = None
                            ) -> str:
        """Persist the best MEASURED layout for (workload_key,
        mesh_shape) — what ``obs.layout.explore_layouts`` found — into
        ``layouts.json`` under the root; atomic replace, newest
        measurement wins. Returns the store path. The future layout
        autotuner reads this back (``layout_prior``) to seed its search
        instead of re-probing from scratch."""
        doc = self._load_layouts()
        doc[f"{workload_key}@{mesh_shape}"] = {
            "workload_key": workload_key,
            "mesh_shape": str(mesh_shape),
            "layout_key": layout_key,
            **(metrics or {}),
        }
        path = self._layouts_path()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def layout_prior(self, workload_key: str, mesh_shape: str
                     ) -> Optional[Dict[str, Any]]:
        """The stored best-layout record for (workload_key, mesh_shape),
        or None when never measured."""
        return self._load_layouts().get(f"{workload_key}@{mesh_shape}")

    # ----- timelines & trends

    def timelines(self) -> Dict[str, List[Tuple[float, float, str]]]:
        """Per-metric (ts, value, run-label) series over every entry that
        carries the metric, in timestamp order. Stale carry-forwards are
        excluded: a repeated old headline in the series would flatten the
        very level shift the trend pass exists to catch."""
        if not self.entries:
            self.scan()
        out: Dict[str, List[Tuple[float, float, str]]] = {}
        for e in self.entries:
            if e.get("stale"):
                continue
            for k, v in e["metrics"].items():
                out.setdefault(k, []).append((e["ts"], float(v), e["run"]))
        return out

    def trends(self, metrics: Optional[Iterable[str]] = None,
               window: int = 5, z: float = 3.5,
               min_history: int = 3) -> List[Dict[str, Any]]:
        """One ``trend_report`` record per watched metric: the series plus
        regression alerts from the robust z-score pass (module
        docstring). A point is flagged when its deviation from the
        median of up to ``window`` PRIOR points exceeds ``z`` MAD-units
        in the metric's bad direction; runs of consecutive flagged
        points collapse to one alert at the first (the change point)."""
        lines = self.timelines()
        watch = [m for m in (metrics or TREND_METRICS) if m in lines]
        reports: List[Dict[str, Any]] = []
        for name in watch:
            series = lines[name]
            th = DEFAULT_THRESHOLDS.get(name)
            higher_is_better = th.higher_is_better if th else True
            alerts: List[Dict[str, Any]] = []
            in_shift = False
            for i, (ts, val, run) in enumerate(series):
                prior = [v for _, v, _ in series[max(0, i - window):i]]
                if len(prior) < min_history:
                    in_shift = False
                    continue
                med = _median(prior)
                mad = _median([abs(v - med) for v in prior])
                # floor: deterministic series have MAD 0; 2% of the median
                # (plus an absolute epsilon) is the repo's noise scale
                mad = max(mad, 0.02 * abs(med), 1e-9)
                score = 0.6745 * (val - med) / mad
                bad = score < -z if higher_is_better else score > z
                if bad and not in_shift:
                    alerts.append({
                        "run": run, "ts": ts, "index": i,
                        "value": round(val, 6), "median": round(med, 6),
                        "z": round(score, 2),
                        "direction": "drop" if higher_is_better else "rise",
                    })
                in_shift = bad
            reports.append({
                "metric": name,
                "runs": len(series),
                "alerts": alerts,
                "higher_is_better": higher_is_better,
                "window": int(window),
                "z": float(z),
                "values": [round(v, 6) for _, v, _ in series],
                "labels": [r for _, _, r in series],
            })
        return reports

    # ----- baseline selection

    def best_healthy(self, metric: str = "evals_per_sec"
                     ) -> Optional[Dict[str, Any]]:
        """The healthy entry with the best value of ``metric`` (direction
        from the compare thresholds; ties break to the newest). None when
        no healthy entry carries it."""
        if not self.entries:
            self.scan()
        th = DEFAULT_THRESHOLDS.get(metric)
        higher = th.higher_is_better if th else True
        best: Optional[Dict[str, Any]] = None
        for e in self.entries:  # ts order: later entries win ties
            if not e["healthy"] or metric not in e["metrics"]:
                continue
            v = e["metrics"][metric]
            if best is None:
                best = e
                continue
            bv = best["metrics"][metric]
            if (v >= bv) if higher else (v <= bv):
                best = e
        return best

    def last_healthy_headline(self) -> Optional[Dict[str, Any]]:
        """The NEWEST healthy entry with a measured ``evals_per_sec``
        headline — the stale-fallback donor for a failed bench probe.
        Returns ``{"value", "run", "path", "ts"}``, plus the donor's
        memory budgets (``peak_device_bytes``/``exe_temp_bytes``) when it
        recorded them — a failed probe's fallback line can then keep the
        budget trend populated (explicitly stale: compare's candidate
        side ignores them), or None."""
        if not self.entries:
            self.scan()
        for e in reversed(self.entries):
            if e["healthy"] and e["metrics"].get("evals_per_sec"):
                out = {"value": e["metrics"]["evals_per_sec"],
                       "run": e["run"], "path": e["path"], "ts": e["ts"]}
                for key in ("peak_device_bytes", "exe_temp_bytes"):
                    if key in e["metrics"]:
                        out[key] = e["metrics"][key]
                return out
        return None


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def resolve_auto_baseline(root: str, metric: str = "evals_per_sec"
                          ) -> Optional[str]:
    """``cli compare --baseline auto``: the path of the best healthy
    historical run under ``root`` (best_healthy on the headline metric,
    falling back to the newest healthy entry of any shape). A missing
    root resolves to None — same answer as an empty one."""
    hist = RunHistory(root)
    try:
        hist.scan()
    except FileNotFoundError:
        return None
    best = hist.best_healthy(metric)
    if best is None:
        healthy = [e for e in hist.entries if e["healthy"]]
        best = healthy[-1] if healthy else None
    return best["path"] if best else None


# -------------------------------------------------------------------- SLOs


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Serve-tier service-level objectives. ``p99_ms``: target warm tail
    latency (the SLI is the fraction of requests slower than it;
    ``error_budget`` of them are allowed). ``qps``: target sustained
    throughput (the SLI is the relative shortfall against it). 0 leaves
    an objective unset."""

    p99_ms: float = 0.0
    qps: float = 0.0
    error_budget: float = 0.01

    @property
    def enabled(self) -> bool:
        return bool(self.p99_ms or self.qps)


def slo_burn(slo: SLOConfig, latencies_ms: List[float],
             elapsed_s: float) -> List[Dict[str, Any]]:
    """Price an observation window against the SLOs: one record per set
    objective — ``{"slo", "target", "observed", "burn_rate", ...}`` —
    where burn_rate is the multiple of the error budget the window is
    consuming (>1 = violating; the alerting threshold everywhere)."""
    records: List[Dict[str, Any]] = []
    n = len(latencies_ms)
    if slo.p99_ms and n:
        over = sum(1 for v in latencies_ms if v > slo.p99_ms) / n
        srt = sorted(latencies_ms)
        p99 = srt[min(n - 1, int(0.99 * n))]
        records.append({
            "slo": "p99_ms", "target": float(slo.p99_ms),
            "observed": round(float(p99), 3),
            "over_fraction": round(over, 4),
            "burn_rate": round(over / slo.error_budget, 3),
            "requests": n,
        })
    if slo.qps and elapsed_s > 0 and n:
        observed = n / elapsed_s
        shortfall = max(0.0, 1.0 - observed / slo.qps)
        records.append({
            "slo": "qps", "target": float(slo.qps),
            "observed": round(observed, 3),
            "over_fraction": round(shortfall, 4),
            "burn_rate": round(shortfall / slo.error_budget, 3),
            "requests": n,
        })
    return records


def record_slo_burn(slo: SLOConfig, latencies_ms: List[float],
                    elapsed_s: float, recorder=None) -> List[Dict[str, Any]]:
    """``slo_burn`` metrics onto ``recorder`` for each set objective;
    returns the records."""
    from fks_tpu.obs.recorder import get_recorder

    rec = recorder if recorder is not None else get_recorder()
    records = slo_burn(slo, latencies_ms, elapsed_s)
    for r in records:
        rec.metric("slo_burn", dict(r))
    return records
