"""Numerics watchdog: in-graph guards, host-side reporting, and the
online parity sentinel.

Three layers, one failure model — a candidate policy (or an engine bug)
produces a score that is NaN, Inf, or outside the fitness range, and the
search silently ranks garbage:

1. **In-graph guards** live in ``fks_tpu.sim.guards`` (re-exported here;
   the sim layer cannot import ``obs`` without a cycle). They are
   mask-and-flag, not checkify: non-finite policy scores are masked to 0
   ("refuse placement") and a sticky ``i32`` bitmask rides the loop
   carry into ``SimResult.numeric_flags``. Gated on the Python-static
   ``SimConfig.watchdog`` flag, so the disabled path compiles to the
   identical program — zero cost when off.
2. **Host reporting**: ``check_result`` OR-reduces a result's flag
   mask (scalar or per-lane) and emits a ``kind="watchdog"`` event on
   the flight recorder when any lane tripped.
3. **The parity sentinel** re-scores ``k`` sampled candidates per
   generation through the exact reference evaluator on the jit tier
   (``use_vm=False``) and records |Δfitness| into the ledger. Drift
   above ``tol`` (default 1e-5) means the VM lowering, the transpiler,
   or a fast engine disagrees with the reference replica — an
   ``alert`` event fires and the CLI exit policy turns it into a
   nonzero exit. The offline per-trace divergence audit
   (``audit_trace``/``panel_sources``, formerly
   ``tools/divergence_audit.py``) shares this module so there is one
   divergence engine.
"""
from __future__ import annotations

import contextlib
import glob
import json
import os
import random
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from fks_tpu.obs.recorder import get_recorder
# Re-exports: the jittable guards live in sim.guards (obs imports the sim
# layer transitively, so the dependency must point this way).
from fks_tpu.sim.guards import (  # noqa: F401
    FLAG_INF,
    FLAG_NAN,
    FLAG_NAMES,
    FLAG_RANGE,
    describe_flags,
    fitness_flags,
    sanitize_scores,
    score_flags,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def combined_flags(numeric_flags: Any) -> int:
    """OR-reduce a result's flag mask — a scalar, a per-lane array, or a
    nested batch — to one Python int."""
    import numpy as np

    arr = np.asarray(numeric_flags)
    if arr.size == 0:
        return 0
    return int(np.bitwise_or.reduce(arr.reshape(-1).astype(np.int64)))


def check_result(result, recorder=None, **context) -> int:
    """Inspect ``result.numeric_flags`` (any ``SimResult``-shaped object;
    objects without the field read as clean) and emit a ``watchdog``
    event when any lane tripped. Returns the combined bitmask."""
    rec = recorder if recorder is not None else get_recorder()
    flags = getattr(result, "numeric_flags", None)
    if flags is None:
        return 0
    mask = combined_flags(flags)
    if mask:
        rec.event("watchdog", flags=mask, kinds=describe_flags(mask),
                  **context)
    return mask


class ParitySentinel:
    """Online drift detector: per generation, re-score ``sample``
    candidates through the exact reference evaluator on the jit tier and
    compare against the fitness the search assigned them.

    The evolution loop already rescores CHAMPIONS through the exact
    engine's VM tier; the sentinel instead samples the broad population
    and goes through ``use_vm=False`` (direct transpile + jit), so it
    catches VM-lowering and transpiler drift that champion rescoring —
    which rides the same VM — cannot see. Results land in the run dir as
    ``kind="parity"`` metrics; drift above ``tol`` raises an ``alert``
    event and increments ``self.alerts`` (the CLI exit policy). With
    ``trace_diff=True`` (default) an alert additionally replays the worst
    offender through ``fks_tpu.obs.tracing.candidate_trace_diff`` and
    attaches the first divergent scheduling step to the alert event —
    best-effort, never fatal to the search.

    NOTE on tolerance: the default 1e-5 assumes the search engine is
    ``exact`` (integer/deterministic — any drift is a real lowering
    bug). The flat engine's documented retry-rule divergence reaches
    |d| <= 0.029 on published policies, so flat-engine runs should pass
    a tolerance above their measured per-trace bound (see
    ``audit_trace``).
    """

    def __init__(self, evaluator, sample: int = 0, tol: float = 1e-5,
                 seed: int = 0, recorder=None, trace_diff: bool = True):
        self.evaluator = evaluator
        self.sample = int(sample)
        self.tol = float(tol)
        self.rng = random.Random(seed)
        self.recorder = recorder if recorder is not None else get_recorder()
        self.trace_diff = bool(trace_diff)  # auto root-cause on alert
        self.alerts = 0
        self.checked = 0
        self.max_drift = 0.0
        self._ref = None  # lazily-built jit-tier exact evaluator

    def _reference(self):
        if self._ref is None:
            from fks_tpu.funsearch.backend import CodeEvaluator

            # suite/robust ride along: a scenario-suite search's fitness is
            # the robust aggregate, so the reference must fold the same
            # scenarios or every check would alert on an apples-to-oranges
            # comparison
            self._ref = CodeEvaluator(
                self.evaluator.workload, self.evaluator.cfg,
                engine="exact", use_vm=False,
                suite=getattr(self.evaluator, "suite", None),
                robust=getattr(self.evaluator, "robust", None))
        return self._ref

    @staticmethod
    def _cpu_device():
        """Pin reference rescoring to the host CPU (same rationale as
        ``FunSearch._exact_device``: never compete with the search for
        the accelerator; the exact engine is backend-independent)."""
        import jax

        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError:
            return contextlib.nullcontext()
        return jax.default_device(dev)

    def check(self, generation: int,
              population: Sequence[Tuple[str, float]]) -> Dict[str, Any]:
        """Sample up to ``self.sample`` members of ``population``
        (``(code, fitness)`` pairs), re-score each through the reference
        evaluator, and record the drift. Returns the generation's parity
        stats (also written as a ``parity`` metric)."""
        stats = {"generation": int(generation), "checked": 0,
                 "max_drift": 0.0, "alerts": 0}
        if self.sample <= 0 or not population:
            return stats
        picks = self.rng.sample(list(population),
                                min(self.sample, len(population)))
        drifts: List[float] = []
        failed = 0
        worst: Optional[Tuple[float, str]] = None  # (drift, code)
        with self._cpu_device():
            ref = self._reference()
            for code, fitness in picks:
                try:
                    rec = ref.evaluate_one(code)
                except Exception:  # noqa: BLE001 — a sentinel failure
                    failed += 1     # must never take down the search
                    continue
                if not rec.ok:
                    failed += 1
                    continue
                d = abs(float(rec.score) - float(fitness))
                drifts.append(d)
                if worst is None or d > worst[0]:
                    worst = (d, code)
        self.checked += len(drifts)
        gen_max = max(drifts) if drifts else 0.0
        self.max_drift = max(self.max_drift, gen_max)
        stats.update(checked=len(drifts), max_drift=round(gen_max, 8),
                     failed=failed)
        self.recorder.metric("parity", {
            "generation": int(generation), "checked": len(drifts),
            "failed": failed, "max_drift": round(gen_max, 8),
            "tol": self.tol})
        if gen_max > self.tol:
            self.alerts += 1
            stats["alerts"] = 1
            alert_fields = dict(
                source="parity", generation=int(generation),
                max_drift=round(gen_max, 8), tol=self.tol,
                detail=f"fitness drift {gen_max:.3g} exceeds "
                       f"tolerance {self.tol:.3g}")
            if self.trace_diff and worst is not None:
                div = self._diff_offender(worst[1], generation)
                if div is not None:
                    # the alert arrives with its root cause attached: the
                    # first scheduling step where the offender's search
                    # evaluation departed from the exact/jit reference
                    alert_fields["first_divergence"] = \
                        div.get("first_divergence")
                    alert_fields["diff_engines"] = div.get("engines")
            self.recorder.event("alert", **alert_fields)
        return stats

    def check_champion(self, generation: int, records) -> Dict[str, Any]:
        """Budget-pruning champion audit (fks_tpu.funsearch.budget):
        pruning may never change which candidate wins a generation, only
        how cheaply. The pruned run's champion is by construction a
        full-rung survivor; the only way it can be WRONG is a pruned
        candidate whose full-fidelity score would have beaten it. Rescore
        every pruned candidate plus the champion through the unpruned
        exact reference and alert (``source="budget_champion"``, feeding
        the CLI exit-3 policy) when any pruned candidate's reference
        score exceeds the champion's by more than ``tol``. Bounded work:
        at most candidates-per-generation exact rescores, memoized by
        the reference's own compile cache. Runs regardless of
        ``self.sample`` — the budget opt-in is the gate."""
        stats = {"generation": int(generation), "checked": 0,
                 "max_gap": 0.0, "alerts": 0}
        pruned = [r for r in records
                  if getattr(r, "budget_rung", None) == 0 and r.ok]
        survivors = [r for r in records
                     if getattr(r, "budget_rung", None) == 1 and r.ok]
        if not pruned or not survivors:
            return stats
        champion = max(survivors, key=lambda r: r.score)
        failed = 0
        gaps: List[Tuple[float, str]] = []
        with self._cpu_device():
            ref = self._reference()
            try:
                champ_ref = float(ref.evaluate_one(champion.code).score)
            except Exception:  # noqa: BLE001 — sentinel failures must
                return stats   # never take down the search
            for r in pruned:
                try:
                    rec = ref.evaluate_one(r.code)
                except Exception:  # noqa: BLE001
                    failed += 1
                    continue
                if not rec.ok:
                    failed += 1
                    continue
                gaps.append((float(rec.score) - champ_ref, r.code))
        self.checked += len(gaps) + 1
        worst = max(gaps, key=lambda g: g[0]) if gaps else (0.0, "")
        gap = max(0.0, worst[0])
        stats.update(checked=len(gaps) + 1, max_gap=round(gap, 8),
                     failed=failed)
        self.recorder.metric("parity", {
            "generation": int(generation), "checked": len(gaps) + 1,
            "failed": failed, "max_drift": round(gap, 8),
            "tol": self.tol, "source": "budget_champion"})
        if gap > self.tol:
            self.alerts += 1
            self.max_drift = max(self.max_drift, gap)
            stats["alerts"] = 1
            self.recorder.event(
                "alert", source="budget_champion",
                generation=int(generation), max_drift=round(gap, 8),
                tol=self.tol,
                detail=f"budget pruning dropped a candidate whose exact "
                       f"reference score beats the pruned run's champion "
                       f"by {gap:.3g} (tol {self.tol:.3g})")
        return stats

    def audit_served(self, request_id: str, served_score: float,
                     reference_score: float, placements_match: bool = True,
                     source: str = "serve") -> bool:
        """Audit one SERVED answer (fks_tpu.serve) against the unbatched
        exact-engine reference the serving engine computed for the same
        query. No evaluator needed (``ParitySentinel(None, ...)`` works):
        both scores arrive precomputed; the sentinel contributes the
        tolerance policy, the drift bookkeeping, and the shared
        ``parity`` metric / ``alert`` event plumbing so serving drift
        lands in the same dashboards as search drift. Returns True when
        the answer passes."""
        d = abs(float(served_score) - float(reference_score))
        ok = d <= self.tol and bool(placements_match)
        self.checked += 1
        self.max_drift = max(self.max_drift, d)
        self.recorder.metric("parity", {
            "generation": -1, "checked": 1, "failed": 0,
            "max_drift": round(d, 8), "tol": self.tol, "source": source,
            "request_id": str(request_id),
            "placements_match": bool(placements_match)})
        if not ok:
            self.alerts += 1
            why = (f"fitness drift {d:.3g} exceeds tolerance "
                   f"{self.tol:.3g}" if d > self.tol
                   else "placements diverge from the exact reference")
            self.recorder.event(
                "alert", source="serve_parity",
                request_id=str(request_id), max_drift=round(d, 8),
                tol=self.tol, detail=f"served answer {request_id}: {why}")
        return ok

    def _diff_offender(self, code: str, generation: int) -> Optional[dict]:
        """Best-effort root-cause localization for an alert: trace-diff
        the worst offender's search-tier evaluation against the exact
        reference (fks_tpu.obs.tracing.candidate_trace_diff). Never
        raises — the sentinel must not take down the search."""
        try:
            from fks_tpu.obs import tracing
            with self._cpu_device():
                return tracing.candidate_trace_diff(
                    self.evaluator, code, recorder=self.recorder,
                    label=f"parity_alert_gen{int(generation)}")
        except Exception as e:  # noqa: BLE001
            self.recorder.event("probe_failure", attempt="trace_diff",
                                error=f"{type(e).__name__}: {e}")
            return None


# ---------------------------------------------------------------------------
# Offline divergence audit (folded in from tools/divergence_audit.py —
# the tool is now a thin wrapper over these functions).
# ---------------------------------------------------------------------------

def panel_sources(top_k: int = 3) -> Dict[str, str]:
    """Seed policies + the top-k discovered champion sources by score."""
    from fks_tpu.funsearch import template

    sources = dict(template.seed_policies())
    champs = []
    for path in glob.glob(os.path.join(REPO, "policies", "discovered",
                                       "funsearch_*_score*.json")):
        try:
            with open(path) as f:
                doc = json.load(f)
            champs.append((float(doc["score"]), os.path.basename(path),
                           doc["code"]))
        except (KeyError, ValueError, OSError, json.JSONDecodeError):
            continue  # skip-and-continue: one bad file must not end it
    champs.sort(reverse=True)
    for score, name, code in champs[:top_k]:
        sources[f"champion_{score:.4f}"] = code
    return sources


def audit_trace(pod_file: str, sources: Dict[str, str],
                cfg_kw: Optional[dict] = None) -> dict:
    """Run a policy panel through BOTH engines on one trace; one JSONL
    row: per-policy exact/flat scores, |d|, and retry-cascade marks."""
    import jax

    from fks_tpu.data import TraceParser
    from fks_tpu.funsearch import vm
    from fks_tpu.sim import flat
    from fks_tpu.sim import engine as exact
    from fks_tpu.sim.engine import SimConfig

    wl = TraceParser().parse_workload(pod_file=pod_file)
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    cfg = SimConfig(cond_policy=True, **(cfg_kw or {}))
    runs = {
        "exact": (jax.jit(exact.make_param_run_fn(wl, vm.score, cfg)),
                  exact.initial_state(wl, cfg)),
        "flat": (jax.jit(flat.make_param_run_fn(wl, vm.score, cfg)),
                 flat.initial_state(wl, cfg)),
    }
    per_policy = {}
    events = scheduled = 0
    for name, code in sources.items():
        try:
            prog = vm.compile_policy(code, n, g, capacity=512)
        except Exception as e:  # noqa: BLE001 — skip, keep the audit going
            per_policy[name] = {"skipped": f"{type(e).__name__}"}
            continue
        scores, trunc, ev = {}, {}, {}
        for eng, (run, s0) in runs.items():
            res = run(prog, s0)
            scores[eng] = float(res.policy_score)
            trunc[eng] = bool(res.truncated) or bool(res.failed)
            ev[eng] = int(res.events_processed)
            if eng == "exact":
                events = max(events, ev[eng])
                scheduled = max(scheduled, int(res.scheduled_pods))
        per_policy[name] = {
            "exact": round(scores["exact"], 6),
            "flat": round(scores["flat"], 6),
            "flat_events": ev["flat"],  # cascade magnitude is visible here
            "abs_d": round(abs(scores["exact"] - scores["flat"]), 6),
            # truncated-on-flat-only marks a RETRY CASCADE: the flat
            # retry-time rule re-queues enough extra creations to blow the
            # event budget, zeroing the score. Distinct from arithmetic
            # drift — conservative for search (the candidate is culled,
            # never over-promoted), but it under-ranks a true champion.
            "flat_cascade": trunc["flat"] and not trunc["exact"],
        }
    ds = [p["abs_d"] for p in per_policy.values() if "abs_d" in p]
    drift = [p["abs_d"] for p in per_policy.values()
             if "abs_d" in p and not p["flat_cascade"]]
    return {
        "trace": pod_file, "num_pods": wl.num_pods,
        "num_nodes": wl.num_nodes,
        "max_events_processed": events, "max_scheduled": scheduled,
        "max_abs_d": max(ds) if ds else None,
        "mean_abs_d": round(sum(ds) / len(ds), 6) if ds else None,
        "max_drift": max(drift) if drift else None,  # cascades excluded
        "flat_cascades": sum(p.get("flat_cascade", False)
                             for p in per_policy.values()),
        "policies": per_policy,
    }


def run_audit(out: str, traces: Optional[Iterable[str]] = None,
              top_champions: int = 3, log=print) -> List[dict]:
    """Audit every trace (default: all shipped pod CSVs), appending one
    JSONL row per trace to ``out``. Returns the rows."""
    from fks_tpu.data import TraceParser

    traces = list(traces) if traces else TraceParser().get_available_pod_files()
    sources = panel_sources(top_champions)
    log(f"panel: {list(sources)}")
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    rows = []
    for pod_file in traces:
        t0 = time.time()
        try:
            row = audit_trace(pod_file, sources, {})
        except Exception as e:  # noqa: BLE001 — a bad trace must not end
            row = {"trace": pod_file, "error": f"{type(e).__name__}: {e}"}
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        with open(out, "a") as f:
            f.write(json.dumps({"ts": round(time.time(), 1), **row}) + "\n")
        log(f"{pod_file}: max|d|={row.get('max_abs_d')} "
            f"({row['wall_s']}s)")
    return rows


def format_audit_table(rows: Sequence[dict]) -> str:
    """The audit summary table (worst trace first)."""
    if not rows:
        return "(no traces audited)"
    width = max(len(r["trace"]) for r in rows)
    lines = [f"{'trace':<{width}}  {'pods':>6}  {'events':>7}  "
             f"{'max|d|':>8}  {'drift':>8}  {'cascades':>8}"]
    for r in sorted(rows, key=lambda r: -(r.get("max_abs_d") or 0)):
        if "error" in r:
            lines.append(f"{r['trace']:<{width}}  ERROR {r['error']}")
        else:
            lines.append(f"{r['trace']:<{width}}  {r['num_pods']:>6}  "
                         f"{r['max_events_processed']:>7}  "
                         f"{r['max_abs_d']:>8}  {r['max_drift']:>8}  "
                         f"{r['flat_cascades']:>8}")
    return "\n".join(lines)


def audit_main(argv=None) -> int:
    """CLI entry shared with ``tools/divergence_audit.py``."""
    import argparse

    ap = argparse.ArgumentParser(
        description="per-trace flat-vs-exact divergence audit")
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarks", "results", "divergence_audit.jsonl"))
    ap.add_argument("--traces", default="",
                    help="comma-separated pod CSVs (default: all)")
    ap.add_argument("--top-champions", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    traces = args.traces.split(",") if args.traces else None
    rows = run_audit(args.out, traces, args.top_champions,
                     log=lambda m: print(m, file=sys.stderr))
    print(format_audit_table(rows))
    return 0
