"""Run-dir report: summary table + fitness sparkline from the JSONL alone.

``cli report <run-dir>`` renders what a finished (or still-running, or
crashed — the JSONL is append-only and flushed per record) run did, with
no in-process state: meta.json for identity, metrics.jsonl for the
evolution ledger / bench stages, events.jsonl for spans, compile
telemetry, and device/mesh snapshots.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

SPARK_BARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float]) -> str:
    """Unicode sparkline; constant series render mid-height."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi <= lo:
        return SPARK_BARS[3] * len(values)
    scale = (len(SPARK_BARS) - 1) / (hi - lo)
    return "".join(SPARK_BARS[int(round((v - lo) * scale))] for v in values)


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL file line-by-line; raises ValueError naming the line
    on a corrupt record (a flight recorder flushes whole lines, so a
    partial trailing line means a crashed writer — tolerated only there)."""
    rows: List[Dict[str, Any]] = []
    with open(path) as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines, 1):
        if not line.strip():
            continue
        try:
            rows.append(json.loads(line))
        except json.JSONDecodeError:
            if i == len(lines):  # torn final write from a killed run
                continue
            raise ValueError(f"{path}:{i}: unparseable JSONL line") from None
    return rows


def load_run(run_dir: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]],
                                    List[Dict[str, Any]]]:
    """(meta, events, metrics) for a run directory; missing JSONL files
    read as empty (a run may die before its first event), but a missing
    meta.json means this is not a run directory and raises."""
    with open(os.path.join(run_dir, "meta.json")) as f:
        meta = json.load(f)
    events = metrics = []
    ep = os.path.join(run_dir, "events.jsonl")
    mp = os.path.join(run_dir, "metrics.jsonl")
    if os.path.exists(ep):
        events = read_jsonl(ep)
    if os.path.exists(mp):
        metrics = read_jsonl(mp)
    return meta, events, metrics


def _fmt_table(rows: List[Dict[str, Any]], cols: List[str]) -> List[str]:
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    head = "  ".join(c.rjust(widths[c]) for c in cols)
    out = [head, "-" * len(head)]
    for r in rows:
        out.append("  ".join(str(r.get(c, "")).rjust(widths[c])
                             for c in cols))
    return out


def _num(v: Any, nd: int = 4) -> Any:
    return round(v, nd) if isinstance(v, float) else v


def _generation_section(metrics: List[Dict[str, Any]]) -> List[str]:
    gens = [m for m in metrics if m.get("kind") == "generation"]
    if not gens:
        return []
    rows = [{
        "gen": g.get("generation"),
        "best": _num(g.get("best_score", 0.0)),
        "median": _num(g.get("median_score", 0.0)),
        "p10": _num(g.get("p10_score", 0.0)),
        "new": g.get("new_candidates", 0),
        "acc": g.get("accepted", 0),
        "dup": g.get("rejected_similar", 0),
        "sbx": g.get("sandbox_failed", 0),
        "tpl": g.get("transpile_failed", 0),
        "rsf": g.get("rescore_fallbacks", 0),
        "llm_s": _num(g.get("llm_seconds", 0.0), 2),
        "eval_s": _num(g.get("eval_seconds", 0.0), 2),
        "ev/s": _num(g.get("evals_per_sec", 0.0), 1),
        "segs": g.get("vm_segments", 0),
    } for g in gens]
    best = [float(g.get("best_score", 0.0)) for g in gens]
    lines = [f"generations: {len(gens)}  "
             "(dup=dup-suppressed sbx=sandbox-fail tpl=transpile-fail "
             "rsf=rescore-fallback segs=vm-segments)"]
    lines += _fmt_table(rows, ["gen", "best", "median", "p10", "new", "acc",
                               "dup", "sbx", "tpl", "rsf", "llm_s", "eval_s",
                               "ev/s", "segs"])
    lines.append(f"fitness best {best[0]:.4f} -> {best[-1]:.4f}  "
                 f"{sparkline(best)}")
    return lines


def _compile_section(events: List[Dict[str, Any]]) -> List[str]:
    compiles = [e for e in events if e.get("kind") == "compile"]
    if not compiles:
        return []
    by_key: Dict[str, List[float]] = {}
    for e in compiles:
        by_key.setdefault(e.get("key", "?"), []).append(
            float(e.get("seconds", 0.0)))
    lines = [f"compile events: {len(compiles)}"]
    for key in sorted(by_key):
        durs = by_key[key]
        lines.append(f"  {key.split('/')[-1]}: {len(durs)}x "
                     f"{sum(durs):.3f}s total")
    return lines


def _span_section(events: List[Dict[str, Any]]) -> List[str]:
    # trace_span rows are spans that additionally carry causal ids
    # (fks_tpu.obs.trace_ctx) — aggregate both kinds under one table
    spans = [e for e in events if e.get("kind") in ("span", "trace_span")]
    if not spans:
        return []
    agg: Dict[str, Dict[str, float]] = {}
    traces = set()
    for s in spans:
        a = agg.setdefault(s.get("path", s.get("label", "?")),
                           {"count": 0, "seconds": 0.0})
        a["count"] += 1
        a["seconds"] += float(s.get("seconds", 0.0))
        if s.get("trace_id"):
            traces.add(s["trace_id"])
    head = "spans (by path, total wall):"
    if traces:
        head = (f"spans (by path, total wall; {len(traces)} traces — "
                "'fks_tpu spans DIR' for waterfalls):")
    lines = [head]
    for path, a in sorted(agg.items(), key=lambda kv: -kv[1]["seconds"]):
        lines.append(f"  {path}: {int(a['count'])}x {a['seconds']:.3f}s")
    return lines


def _infra_section(events: List[Dict[str, Any]]) -> List[str]:
    lines = []
    devices = [e for e in events if e.get("kind") == "device"]
    if devices:
        plats: Dict[str, int] = {}
        for d in devices:
            plats[d.get("platform", "?")] = plats.get(
                d.get("platform", "?"), 0) + 1
        desc = ", ".join(f"{n}x {p}" for p, n in sorted(plats.items()))
        mem = [d for d in devices
               if isinstance(d.get("memory_stats"), dict)]
        if mem:
            used = sum(m["memory_stats"].get("bytes_in_use", 0) for m in mem)
            desc += f"; {used / 2**20:.0f} MiB in use across {len(mem)}"
        lines.append(f"devices: {desc}")
    for e in events:
        if e.get("kind") == "mesh":
            waste = e.get("pad_waste_fraction")
            lines.append(
                f"mesh: {e.get('shards')} shards {e.get('shape')}"
                + (f", pad waste {100 * waste:.1f}%"
                   f" ({e.get('pad_lanes')}/{e.get('padded_count')} lanes)"
                   if waste is not None else ""))
    return lines


def _budget_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Eval-budget rung ladder (fks_tpu.funsearch.budget): one row per
    rung per generation — who entered, who survived to the next rung,
    device wall per rung — plus the total pruned-candidate count."""
    rungs = [m for m in metrics if m.get("kind") == "budget_rung"]
    if not rungs:
        return []
    rows = [{
        "gen": r.get("generation"),
        "rung": r.get("rung"),
        "entered": r.get("entered"),
        "survived": r.get("survived"),
        "dev_s": _num(float(r.get("device_seconds", 0.0)), 3),
        "segs": r.get("segments", 0),
        "lanes": r.get("lanes", ""),
    } for r in rungs]
    pruned = sum(int(r.get("entered", 0)) - int(r.get("survived", 0))
                 for r in rungs)
    lines = [f"budget rungs: {len(rungs)} recorded, {pruned} candidates "
             "pruned before the full suite"]
    lines += _fmt_table(rows, ["gen", "rung", "entered", "survived",
                               "dev_s", "segs", "lanes"])
    return lines


def _device_profile_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Device-time attribution table (fks_tpu.obs.profiler): stages
    aggregated by name and ranked by wall share, each split into compile
    vs dispatch+compute, with occupancy-discounted utilization where the
    launch shape was annotated; the ``__total__`` record (when the run
    emitted a summary) heads the section with the attributed-vs-idle
    verdict."""
    profs = [m for m in metrics if m.get("kind") == "device_profile"]
    if not profs:
        return []
    totals = [m for m in profs if m.get("stage") == "__total__"]
    stages = [m for m in profs
              if m.get("stage") != "__total__" and not m.get("depth", 0)]
    agg: Dict[str, Dict[str, float]] = {}
    for m in stages:
        a = agg.setdefault(m.get("stage", "?"), {
            "count": 0, "wall": 0.0, "compile": 0.0, "compute": 0.0,
            "compiles": 0, "util": None})
        a["count"] += 1
        a["wall"] += float(m.get("wall_seconds", 0.0))
        a["compile"] += float(m.get("compile_seconds", 0.0))
        a["compute"] += float(m.get("compute_seconds", 0.0))
        a["compiles"] += int(m.get("compile_count", 0))
        if m.get("utilization_pct") is not None:
            a["util"] = max(a["util"] or 0.0, float(m["utilization_pct"]))
    total_wall = sum(a["wall"] for a in agg.values())
    lines = ["device-time attribution (obs.profiler):"]
    for t in totals[-1:]:
        lines.append(
            f"  attributed {100 * float(t.get('attributed_fraction', 0)):.1f}%"
            f" of {_num(float(t.get('measured_wall_seconds', 0.0)), 3)}s wall"
            f" ({100 * float(t.get('idle_fraction', 0)):.1f}% idle, "
            f"compile {_num(float(t.get('compile_seconds', 0.0)), 3)}s)")
    rows = [{
        "stage": name,
        "n": int(a["count"]),
        "wall_s": _num(a["wall"], 3),
        "%wall": _num(100 * a["wall"] / total_wall, 1) if total_wall else 0,
        "compile_s": _num(a["compile"], 3),
        "compute_s": _num(a["compute"], 3),
        "compiles": int(a["compiles"]),
        "util%": "" if a["util"] is None else _num(a["util"], 1),
    } for name, a in sorted(agg.items(), key=lambda kv: -kv[1]["wall"])]
    if rows:
        lines += _fmt_table(rows, ["stage", "n", "wall_s", "%wall",
                                   "compile_s", "compute_s", "compiles",
                                   "util%"])
    return lines


def _slo_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Latest burn rate per SLO (fks_tpu.obs.history.slo_burn): burn > 1
    means the error budget is being consumed faster than allowed."""
    burns = [m for m in metrics if m.get("kind") == "slo_burn"]
    if not burns:
        return []
    latest: Dict[str, Dict[str, Any]] = {}
    for b in burns:
        latest[str(b.get("slo", "?"))] = b
    lines = ["SLO burn rates:"]
    for name in sorted(latest):
        b = latest[name]
        rate = float(b.get("burn_rate", 0.0))
        verdict = "VIOLATING" if rate > 1.0 else "ok"
        lines.append(
            f"  {name}: burn {rate:.2f}x (observed "
            f"{_num(float(b.get('observed', 0.0)), 3)} vs target "
            f"{_num(float(b.get('target', 0.0)), 3)}) {verdict}")
    return lines


def _memory_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Memory observability (fks_tpu.obs.memory): the footprint ladder —
    every compiled executable's predicted HBM claim from
    ``memory_analysis``, latest record per (component, exe), ranked
    largest-first — plus the per-mesh-layout roll-up, the watermark
    sampler's latest host/device high-water view, and the leak
    sentinel's verdict per fenced hot loop."""
    fps = [m for m in metrics if m.get("kind") == "memory_footprint"]
    wms = [m for m in metrics if m.get("kind") == "memory_watermark"]
    leaks = [m for m in metrics if m.get("kind") == "leak_check"]
    if not (fps or wms or leaks):
        return []
    lines = ["memory (obs.memory):"]
    if fps:
        latest: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for m in fps:
            latest[(str(m.get("component", "?")),
                    str(m.get("exe_key", "?")))] = m
        def total(m: Dict[str, Any]) -> int:
            return int(m.get("total_bytes",
                             sum(int(m.get(k, 0)) for k in
                                 ("temp_bytes", "argument_bytes",
                                  "output_bytes",
                                  "generated_code_bytes"))))
        ranked = sorted(latest.items(), key=lambda kv: -total(kv[1]))
        rows = [{
            "component": c,
            "exe": e,
            "temp_KiB": _num(int(m.get("temp_bytes", 0)) / 2**10, 1),
            "args_KiB": _num(int(m.get("argument_bytes", 0)) / 2**10, 1),
            "out_KiB": _num(int(m.get("output_bytes", 0)) / 2**10, 1),
            "code_KiB": _num(
                int(m.get("generated_code_bytes", 0)) / 2**10, 1),
            "total_KiB": _num(total(m) / 2**10, 1),
        } for (c, e), m in ranked]
        lines.append(f"  footprint ladder ({len(rows)} executables, "
                     "largest first):")
        lines += ["  " + ln for ln in _fmt_table(
            rows, ["component", "exe", "temp_KiB", "args_KiB", "out_KiB",
                   "code_KiB", "total_KiB"])]
        from fks_tpu.obs.memory import rollup  # deferred, like exporter
        for a in rollup([m for _, m in ranked]):
            layout = a["mesh_layout"] or "unsharded"
            lines.append(
                f"  {a['component']} [{layout}]: {a['executables']} "
                f"executables, predicted "
                f"{a['predicted_hbm_bytes'] / 2**20:.2f} MiB HBM, "
                f"peak temp {a['peak_temp_bytes'] / 2**10:.1f} KiB")
    if wms:
        rss = [int(m.get("host_rss_kb", 0)) for m in wms]
        lines.append(f"  watermarks: {len(wms)} samples, host RSS peak "
                     f"{max(rss) / 1024:.0f} MiB")
        last = wms[-1]
        dev_rows = [{
            "dev": d.get("id", "?"),
            "platform": d.get("platform", "?"),
            "in_use_MiB": ("" if "bytes_in_use" not in d else
                           _num(int(d["bytes_in_use"]) / 2**20, 2)),
            "peak_MiB": ("" if "peak_bytes_in_use" not in d else
                         _num(int(d["peak_bytes_in_use"]) / 2**20, 2)),
            "limit_MiB": ("" if "bytes_limit" not in d else
                          _num(int(d["bytes_limit"]) / 2**20, 0)),
            "delta_KiB": ("" if "delta_bytes" not in d else
                          _num(int(d["delta_bytes"]) / 2**10, 1)),
        } for d in (last.get("devices") or []) if isinstance(d, dict)]
        if dev_rows:
            lines += ["  " + ln for ln in _fmt_table(
                dev_rows, ["dev", "platform", "in_use_MiB", "peak_MiB",
                           "limit_MiB", "delta_KiB"])]
    if leaks:
        latest_leak: Dict[str, Dict[str, Any]] = {}
        for m in leaks:
            latest_leak[str(m.get("loop", "?"))] = m
        for loop in sorted(latest_leak):
            m = latest_leak[loop]
            verdict = "ok" if m.get("ok") else "LEAK"
            lines.append(
                f"  leak sentinel {loop}: {verdict} — drift "
                f"{m.get('drift_count', 0)} arrays / "
                f"{m.get('drift_bytes', 0)} bytes over "
                f"{m.get('iterations', 0)} iterations")
    return lines


def _layout_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Layout observability (fks_tpu.obs.layout): the per-layout cost
    roll-up — one row per (workload_key, mesh_layout, layout_key) with
    pad waste, lane-step occupancy, cost-analysis bytes and the
    predicted HBM claim joined from the run's footprint records — plus
    the explorer's probe table and best-vs-default verdict when the run
    swept layouts."""
    rows = [m for m in metrics if m.get("kind") == "layout_ledger"]
    probes = [m for m in metrics if m.get("kind") == "layout_probe"]
    if not (rows or probes):
        return []
    lines = ["layouts (obs.layout):"]
    if rows:
        from fks_tpu.obs.layout import rollup_layouts  # deferred
        fps = [m for m in metrics if m.get("kind") == "memory_footprint"]
        aggs = rollup_layouts(rows, footprints=fps)
        tab = [{
            "workload": a["workload_key"] or "-",
            "mesh": a["mesh_layout"] or "unsharded",
            "layout": a["layout_key"],
            "rows": a["rows"],
            "pad_waste": _num(a["pad_waste_fraction_max"], 4),
            "occupancy": _num(a["occupancy"], 4),
            "hbm_MiB": ("" if "predicted_hbm_bytes" not in a else
                        _num(a["predicted_hbm_bytes"] / 2**20, 2)),
            "steady_s": ("" if "steady_seconds" not in a else
                         _num(a["steady_seconds"], 4)),
        } for a in aggs]
        lines.append(f"  ledger roll-up ({len(aggs)} layouts):")
        lines += ["  " + ln for ln in _fmt_table(
            tab, ["workload", "mesh", "layout", "rows", "pad_waste",
                  "occupancy", "hbm_MiB", "steady_s"])]
    if probes:
        tab = [{
            "mesh": p.get("mesh_shape", "?"),
            "layout": p.get("layout_key", "?"),
            "steady_s": _num(float(p.get("steady_seconds", 0.0)), 6),
            "compile_s": _num(float(p.get("first_call_seconds", 0.0)), 2),
            "pad_waste": _num(float(p.get("pad_waste_fraction", 0.0)), 4),
            "parity": _num(float(p.get("parity_max_abs", 0.0)), 8),
        } for p in probes]
        lines.append(f"  explorer probes ({len(probes)}):")
        lines += ["  " + ln for ln in _fmt_table(
            tab, ["mesh", "layout", "steady_s", "compile_s", "pad_waste",
                  "parity"])]
        best = min(probes,
                   key=lambda p: float(p.get("steady_seconds", 0.0)))
        lines.append(f"  best measured: {best.get('mesh_shape')} "
                     f"{best.get('layout_key')} at "
                     f"{float(best.get('steady_seconds', 0.0)):.6f}s "
                     "steady (single-process CPU meshes time-slice one "
                     "host; ranks are relative)")
    return lines


def _tenant_section(metrics: List[Dict[str, Any]]) -> List[str]:
    """Per-tenant accounting (fks_tpu.obs.workload): latest tenant_stats
    row per tenant — request/shed/expired/degraded counters, EWMA and
    tail latency, goodput, SLO burn — plus the Jain fairness index over
    per-tenant goodput, the latest workload-mix window, and the last
    loadgen summary when the run drove synthetic load."""
    stats = [m for m in metrics if m.get("kind") == "tenant_stats"]
    mixes = [m for m in metrics if m.get("kind") == "workload_mix"]
    lgs = [m for m in metrics if m.get("kind") == "loadgen_summary"]
    if not (stats or mixes or lgs):
        return []
    lines = ["tenants (obs.workload):"]
    if stats:
        latest: Dict[str, Dict[str, Any]] = {}
        for m in stats:
            latest[str(m.get("tenant", "?"))] = m
        rows = [{
            "tenant": t,
            "req": m.get("requests", 0),
            "shed": m.get("shed", 0),
            "exp": m.get("expired", 0),
            "deg": m.get("degraded", 0),
            "ewma_ms": _num(float(m.get("ewma_ms", 0.0)), 2),
            "p99_ms": _num(float(m.get("p99_ms", 0.0)), 2),
            "qps": _num(float(m.get("goodput_qps", 0.0)), 2),
            "burn": _num(float(m.get("burn_rate", 0.0)), 2),
        } for t, m in sorted(latest.items())]
        lines += _fmt_table(rows, ["tenant", "req", "shed", "exp", "deg",
                                   "ewma_ms", "p99_ms", "qps", "burn"])
        fair = float(next(iter(sorted(latest.items())))[1]
                     .get("fairness_index", 1.0))
        verdict = "ok" if fair >= 0.8 else "UNFAIR"
        lines.append(f"  fairness index (Jain, goodput): "
                     f"{fair:.4f} {verdict}")
        violators = [t for t, m in sorted(latest.items())
                     if float(m.get("burn_rate", 0.0)) > 1.0]
        if violators:
            lines.append("  SLO burn > 1x: " + ", ".join(violators))
    if mixes:
        m = mixes[-1]
        classes = m.get("classes") or {}
        top = sorted(classes.items(), key=lambda kv: -kv[1])[:5]
        lines.append(
            f"  workload mix: {m.get('distinct', 0)} classes over last "
            f"{m.get('window', 0)} requests — "
            + ", ".join(f"{c}={n}" for c, n in top))
    for lg in lgs[-1:]:
        lines.append(
            f"  loadgen [{lg.get('mode', '?')}]: "
            f"{lg.get('requests', 0)} requests, "
            f"{_num(float(lg.get('loadgen_qps', 0.0)), 2)} qps, "
            f"p99 {_num(float(lg.get('loadgen_p99_ms', 0.0)), 2)}ms, "
            f"shed {100 * float(lg.get('loadgen_shed_rate', 0.0)):.1f}%, "
            f"fairness "
            f"{_num(float(lg.get('loadgen_fairness_index', 1.0)), 4)}")
    return lines


def _portfolio_section(metrics: List[Dict[str, Any]],
                       events: List[Dict[str, Any]]) -> List[str]:
    """Portfolio serving (fks_tpu.portfolio): routed-request counts per
    slot and per rule over the whole run, plus every slot promotion —
    which slot, what it cost, and whether the transpile overlapped the
    shadow window."""
    routes = [m for m in metrics if m.get("kind") == "portfolio_route"]
    swaps = [e for e in events if e.get("kind") == "slot_swap"]
    if not (routes or swaps):
        return []
    lines = ["portfolio (fks_tpu.portfolio):"]
    if routes:
        by_slot: Dict[str, int] = {}
        by_reason: Dict[str, int] = {}
        for m in routes:
            by_slot[str(m.get("slot", "?"))] = \
                by_slot.get(str(m.get("slot", "?")), 0) + 1
            by_reason[str(m.get("reason", "?"))] = \
                by_reason.get(str(m.get("reason", "?")), 0) + 1
        mix = ", ".join(f"slot {s}={n}" for s, n in sorted(
            by_slot.items(), key=lambda kv: kv[0]))
        rules = ", ".join(f"{r}={n}" for r, n in sorted(
            by_reason.items(), key=lambda kv: -kv[1]))
        lines.append(f"  {len(routes)} routed requests — {mix}")
        lines.append(f"  routing rules: {rules}")
    if swaps:
        lines.append(f"  slot promotions: {len(swaps)}")
        for e in swaps[-5:]:
            overlap = (" (transpile overlapped)"
                       if e.get("transpile_overlapped") else "")
            lines.append(
                f"    slot {e.get('slot', '?')} <- "
                f"{e.get('champion', '?')}: "
                f"swap {_num(float(e.get('swap_ms', 0.0)), 2)}ms, "
                f"h2d {e.get('h2d_bytes', 0)}B{overlap}")
    return lines


def _bench_section(metrics: List[Dict[str, Any]]) -> List[str]:
    stages = [m for m in metrics if m.get("kind") == "bench_stage"]
    lines = []
    for s in stages:
        parts = [f"bench stage {s.get('stage', '?')}:"]
        for k in ("evals_per_sec", "code_evals_per_sec", "compile_seconds",
                  "first_call_seconds", "steady_state_seconds",
                  "cost_flops", "cost_bytes_accessed", "budget_speedup",
                  "budget_champion_match", "device_seconds_full",
                  "device_seconds_pruned"):
            if k in s:
                parts.append(f"{k}={_num(float(s[k]), 3)}")
        lines.append(" ".join(parts))
    return lines


def _trace_diff_lines(events: List[Dict[str, Any]]) -> List[str]:
    """Header summary of recorded engine trace-diffs: total count, how
    many diverged, and the earliest divergent step per engine pair."""
    diffs = [e for e in events if e.get("kind") == "trace_diff"]
    if not diffs:
        return []
    divergent = [d for d in diffs if d.get("divergent")]
    lines = [f"trace diffs: {len(diffs)} recorded, "
             f"{len(divergent)} divergent"]
    earliest: Dict[str, int] = {}
    for d in divergent:
        pair = " vs ".join(d.get("engines", ["?", "?"]))
        step = (d.get("first_divergence") or {}).get("step")
        if step is None:
            continue
        if pair not in earliest or step < earliest[pair]:
            earliest[pair] = step
    for pair in sorted(earliest):
        lines.append(f"  {pair}: first divergent step {earliest[pair]}")
    return lines


def render_report(run_dir: str) -> str:
    """The full run summary (see module docstring)."""
    meta, events, metrics = load_run(run_dir)
    head = (f"run {meta.get('run_id', '?')}"
            f" [{meta.get('command', meta.get('metric', '?'))}]"
            f" — status {meta.get('status', '?')}")
    if "wall_seconds" in meta:
        head += f", {meta['wall_seconds']}s"
    # liveness verdict from the heartbeat: a run that claims to be
    # running but whose heartbeat is older than 2x its own cadence is
    # STALE, 10x (or heartbeat-less) is DEAD (fks_tpu.obs.exporter)
    from fks_tpu.obs.exporter import run_health  # deferred: exporter
    health = run_health(run_dir, meta=meta, metrics=metrics)  # imports us
    if health["state"] not in ("FINISHED",):
        age = ("no heartbeat" if health["age"] is None
               else f"heartbeat {health['age']:.0f}s old")
        head += (f" — {health['state']} ({age}, "
                 f"cadence ~{health['cadence']:.0f}s)")
    lines = [head, f"started {meta.get('started', '?')}  dir {run_dir}"]
    for key in ("argv", "best_score", "workload"):
        if key in meta:
            lines.append(f"{key}: {meta[key]}")
    lines.extend(_trace_diff_lines(events))
    for section in (_infra_section(events), _generation_section(metrics),
                    _budget_section(metrics), _bench_section(metrics),
                    _device_profile_section(metrics), _slo_section(metrics),
                    _tenant_section(metrics),
                    _portfolio_section(metrics, events),
                    _memory_section(metrics), _layout_section(metrics),
                    _compile_section(events),
                    _span_section(events)):
        if section:
            lines.append("")
            lines.extend(section)
    if not events and not metrics:
        lines += ["", "(no events or metrics recorded)"]
    return "\n".join(lines)
