"""fks_tpu.obs — the flight recorder: run directories, spans, compile/
device telemetry, the per-generation evolution ledger, and the
watchdog / export / gating layer built on top of them.

Every ROADMAP evidence gap is an observability gap; this package records
what a run actually did, into a run directory that ``cli report`` renders
back without any in-process state (fks_tpu.obs.report). The disabled path
is a shared NullRecorder — zero filesystem writes, no conditionals in
jitted code.

- ``recorder``  — FlightRecorder/NullRecorder + the process-wide active
                  recorder (``get_recorder``/``recording``)
- ``spans``     — nested wall-clock scopes mirrored into xprof
                  (generalizes ``utils.profiling.timed``)
- ``trace_ctx`` — causal trace contexts (trace_id/span_id/parent_id)
                  propagated explicitly across thread boundaries, plus
                  waterfall/critical-path reconstruction (``cli spans``)
- ``telemetry`` — jax.monitoring compile listener, device memory_stats,
                  mesh/pad-waste snapshots
- ``ledger``    — per-generation evolution records
- ``report``    — run-dir summary rendering (``cli report``)
- ``watchdog``  — numeric guards (re-exported from sim.guards), host
                  reporting, the online parity sentinel, and the offline
                  divergence audit (``cli``/tools entry points)
- ``tracing``   — decision-trace extraction + first-divergence
                  localization across engines (``cli trace-diff``)
- ``exporter``  — OpenMetrics text export + heartbeat liveness
                  (``cli export-metrics`` / ``cli watch``)
- ``compare``   — cross-run regression gating (``cli compare``,
                  ``bench.py --gate``)
- ``profiler``  — per-stage device-time attribution: wall/compile/
                  compute split + occupancy (``device_profile`` metrics)
- ``history``   — cross-run index, trend/regression flagging, auto
                  baselines, SLO burn rates (``cli trends``)
- ``memory``    — executable-footprint ledger, watermark sampler, leak
                  sentinel + drills (``cli mem``, ``fks_mem_*`` gauges)
- ``layout``    — declarative LayoutSpec for the three batchable axes,
                  the per-layout cost ledger, and the measured layout
                  explorer (``cli layout``, ``fks_layout_*`` gauges)
- ``workload``  — query fingerprinting, per-tenant accounting with SLO
                  burn + fairness, and the multi-tenant load generator
                  (``cli loadgen`` / ``bench --stage loadgen``,
                  ``fks_tenant_*`` gauges)
"""
from fks_tpu.obs.compare import (
    DEFAULT_THRESHOLDS, Threshold, compare_runs, extract_metrics,
    format_comparison, has_regression, parse_threshold_overrides,
)
from fks_tpu.obs.exporter import (
    health_line, run_health, to_openmetrics, watch,
)
from fks_tpu.obs.history import (
    RunHistory, SLOConfig, record_slo_burn, resolve_auto_baseline, slo_burn,
)
from fks_tpu.obs.layout import (
    LAYOUT_AXES, LAYOUT_COMPONENTS, LayoutLedger, LayoutSpec, default_spec,
    explore_layouts, parse_layout_key, record_layout, rollup_layouts,
    tag_layout, valid_layouts,
)
from fks_tpu.obs.ledger import EvolutionLedger
from fks_tpu.obs.memory import (
    LEAK_LOOPS, MEMORY_COMPONENTS, NULL_SAMPLER, FootprintLedger,
    LeakSentinel, WatermarkSampler, footprint_of, leak_fence,
    live_array_stats, record_footprint, rollup, run_drill,
)
from fks_tpu.obs.profiler import (
    NULL_PROFILER, StageProfiler, profile_launch,
)
from fks_tpu.obs.recorder import (
    NULL, FlightRecorder, NullRecorder, get_recorder, recording,
)
from fks_tpu.obs.report import render_report, sparkline
from fks_tpu.obs.spans import span, span_path
from fks_tpu.obs import trace_ctx
from fks_tpu.obs.trace_ctx import (
    TraceContext, activate_trace, critical_path, current_trace, emit_span,
    new_trace, render_waterfall,
)
from fks_tpu.obs.tracing import (
    align_traces, candidate_trace_diff, extract_trace, format_diff,
    trace_diff,
)
from fks_tpu.obs.telemetry import (
    CompileWatcher, device_snapshot, mesh_snapshot, normalize_memory_stats,
    record_devices, record_mesh, watch_compiles,
)
from fks_tpu.obs.watchdog import (
    FLAG_INF, FLAG_NAN, FLAG_RANGE, ParitySentinel, check_result,
    combined_flags, describe_flags,
)
from fks_tpu.obs.workload import (
    DEFAULT_TENANT, LOADGEN_MODES, QueryFingerprinter, TenantAccountant,
    TenantLoad, default_make_pods, http_client, jain_fairness,
    parse_tenant_spec, run_loadgen, service_client, tenant_of,
)

__all__ = [
    "DEFAULT_TENANT", "DEFAULT_THRESHOLDS", "FLAG_INF", "FLAG_NAN",
    "FLAG_RANGE", "LAYOUT_AXES", "LAYOUT_COMPONENTS", "LEAK_LOOPS",
    "LOADGEN_MODES", "MEMORY_COMPONENTS",
    "NULL", "NULL_PROFILER", "NULL_SAMPLER", "CompileWatcher",
    "EvolutionLedger", "FlightRecorder", "FootprintLedger", "LayoutLedger",
    "LayoutSpec", "LeakSentinel",
    "NullRecorder", "ParitySentinel", "QueryFingerprinter", "RunHistory",
    "SLOConfig", "StageProfiler", "TenantAccountant", "TenantLoad",
    "Threshold", "WatermarkSampler", "align_traces", "candidate_trace_diff",
    "check_result", "combined_flags", "compare_runs", "default_make_pods",
    "default_spec", "describe_flags", "device_snapshot", "explore_layouts",
    "extract_metrics",
    "extract_trace", "footprint_of", "format_comparison", "format_diff",
    "get_recorder", "has_regression", "health_line", "http_client",
    "jain_fairness", "leak_fence", "live_array_stats", "mesh_snapshot",
    "normalize_memory_stats", "parse_layout_key", "parse_tenant_spec",
    "parse_threshold_overrides", "profile_launch", "record_devices",
    "record_footprint", "record_layout", "record_mesh", "record_slo_burn",
    "recording",
    "render_report", "resolve_auto_baseline", "rollup", "rollup_layouts",
    "run_drill",
    "run_health", "run_loadgen", "service_client", "slo_burn", "span",
    "span_path", "sparkline", "tag_layout", "tenant_of", "to_openmetrics",
    "trace_diff", "valid_layouts",
    "watch", "watch_compiles",
    "TraceContext", "activate_trace", "critical_path", "current_trace",
    "emit_span", "new_trace", "render_waterfall", "trace_ctx",
]
