"""fks_tpu.obs — the flight recorder: run directories, spans, compile/
device telemetry, and the per-generation evolution ledger.

Every ROADMAP evidence gap is an observability gap; this package records
what a run actually did, into a run directory that ``cli report`` renders
back without any in-process state (fks_tpu.obs.report). The disabled path
is a shared NullRecorder — zero filesystem writes, no conditionals in
jitted code.

- ``recorder``  — FlightRecorder/NullRecorder + the process-wide active
                  recorder (``get_recorder``/``recording``)
- ``spans``     — nested wall-clock scopes mirrored into xprof
                  (generalizes ``utils.profiling.timed``)
- ``telemetry`` — jax.monitoring compile listener, device memory_stats,
                  mesh/pad-waste snapshots
- ``ledger``    — per-generation evolution records
- ``report``    — run-dir summary rendering (``cli report``)
"""
from fks_tpu.obs.ledger import EvolutionLedger
from fks_tpu.obs.recorder import (
    NULL, FlightRecorder, NullRecorder, get_recorder, recording,
)
from fks_tpu.obs.report import render_report, sparkline
from fks_tpu.obs.spans import span, span_path
from fks_tpu.obs.telemetry import (
    CompileWatcher, device_snapshot, mesh_snapshot, record_devices,
    record_mesh, watch_compiles,
)

__all__ = [
    "NULL", "CompileWatcher", "EvolutionLedger", "FlightRecorder",
    "NullRecorder", "device_snapshot", "get_recorder", "mesh_snapshot",
    "record_devices", "record_mesh", "recording", "render_report", "span",
    "span_path", "sparkline", "watch_compiles",
]
