"""Spans: nested wall-clock scopes that line up with xprof traces.

``span(label)`` generalizes ``fks_tpu.utils.profiling.timed`` (it yields
the same ``Timing`` object, with the same ``t.sync(...)`` device-blocking
contract) and adds three things:

- **nesting**: a thread-local label stack gives every span a ``path``
  (``"evolve/gen/evaluate"``) and a ``depth``, so the recorder's span
  events reconstruct the call tree without an in-process profiler;
- **xprof mirroring**: each span enters ``jax.profiler.TraceAnnotation``
  (host-side trace event) and ``jax.named_scope`` (names any ops traced
  inside it), so when a run is captured with ``device_trace``/xprof, the
  host spans line up with the device timeline under the same labels;
- **flight-recorder events**: on exit (clock stopped AFTER the synced
  value materializes) the active recorder gets one ``kind="span"`` event
  with label/path/depth/seconds plus caller fields.

- **causal linkage**: when a ``trace_ctx`` context is active on the
  thread (a serve request, an evolve generation, a promotion attempt),
  the event is emitted as ``kind="trace_span"`` carrying trace_id /
  span_id / parent_id, and a child context is active for the span body —
  so nested spans (and anything they hand to another thread) chain to
  this one. No active context: the pre-trace ``kind="span"`` event,
  bit-for-bit.

With the NullRecorder active and no profiler attached, a span costs two
perf_counter reads, two cheap context entries, one thread-local read and
one no-op method call — nothing touches the filesystem and nothing is
added to jitted code.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, Optional

import jax

from fks_tpu.utils import profiling
from fks_tpu.obs import trace_ctx
from fks_tpu.obs.recorder import get_recorder

_nesting = threading.local()


def span_path() -> str:
    """The current thread's open-span path ("" outside any span)."""
    return "/".join(getattr(_nesting, "stack", []))


@contextlib.contextmanager
def span(label: str, sync: Any = None, recorder=None,
         **fields) -> Iterator[profiling.Timing]:
    """A nested, recorded, xprof-mirrored timing scope (see module
    docstring). Yields the ``Timing``; register device values with
    ``t.sync(...)`` exactly as with ``profiling.timed``. Extra keyword
    fields ride along on the recorded span event."""
    rec = recorder if recorder is not None else get_recorder()
    stack = getattr(_nesting, "stack", None)
    if stack is None:
        stack = _nesting.stack = []
    path = "/".join(stack + [label])
    depth = len(stack)
    stack.append(label)
    timing: Optional[profiling.Timing] = None
    # causal chain: an active trace context turns this span into a
    # trace_span child and re-parents anything opened inside the body
    parent = trace_ctx.current() if rec.enabled else None
    child = trace_ctx.child_of(parent) if parent is not None else None

    def _emit(t: profiling.Timing) -> None:
        if child is not None:
            rec.event("trace_span", label=label, path=path, depth=depth,
                      seconds=round(t.seconds, 6),
                      trace_id=child.trace_id, span_id=child.span_id,
                      parent_id=parent.span_id, **fields)
        else:
            rec.event("span", label=label, path=path, depth=depth,
                      seconds=round(t.seconds, 6), **fields)

    try:
        with contextlib.ExitStack() as ctx:
            # xprof mirroring is best-effort: a backend without profiler
            # support must not break the timing/recording contract
            try:
                ctx.enter_context(jax.profiler.TraceAnnotation(label))
                ctx.enter_context(jax.named_scope(label))
            except Exception:  # pragma: no cover - profiler-less backend
                pass
            if child is not None:
                ctx.enter_context(trace_ctx.activate(child))
            with profiling.timed(label, sync=sync, on_exit=_emit) as timing:
                yield timing
    finally:
        stack.pop()
