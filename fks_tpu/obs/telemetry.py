"""Compile/device telemetry: jax.monitoring listener + snapshot helpers.

Population-based JAX stacks attribute their throughput claims to
separating compile time from steady-state device time (PAPERS.md: evosax,
arxiv 2212.04180; Fast PBRL, arxiv 2206.08888). This module captures that
split from the host side, with zero instrumentation inside jitted code:

- ``CompileWatcher``: a ``jax.monitoring`` duration listener that records
  every jit compilation event (key, duration, running count) — the
  ``/jax/core/compile/*`` family: jaxpr trace, MLIR lowering, backend
  compile. Each event is appended to the active flight recorder as a
  ``kind="compile"`` event and accumulated in-process for summaries.
- ``device_snapshot``/``record_devices``: per-device identity plus
  ``memory_stats()`` (None on backends that don't report, e.g. CPU).
- ``mesh_snapshot``/``record_mesh``: mesh metadata — axis names/shape,
  shard count, and the pad-lane waste fraction from
  ``parallel.mesh.pad_stats`` (how many lanes of each launch are padding
  duplicates rather than real candidates).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional

import jax

from fks_tpu.obs.recorder import get_recorder

#: the jax.monitoring event-key family emitted per jit compilation
COMPILE_PREFIX = "/jax/core/compile"
#: the key measuring the actual XLA backend compile (vs trace/lowering)
BACKEND_COMPILE = "backend_compile_duration"


class CompileWatcher:
    """Capture every jit compilation's (key, duration) while installed.

    ``jax.monitoring`` listeners are global and additive; uninstall uses
    the private-but-stable ``_unregister_event_duration_listener_by_
    callback`` when available and otherwise leaves an inert callback
    behind (the ``_installed`` gate makes it a no-op — never clear ALL
    listeners, other subsystems may have their own).

    Usable as a context manager::

        with CompileWatcher(recorder) as w:
            ...  # any jit compiles in here are captured
        w.backend_compile_count, w.backend_compile_seconds
    """

    def __init__(self, recorder=None, prefix: str = COMPILE_PREFIX):
        self.recorder = recorder if recorder is not None else get_recorder()
        self.prefix = prefix
        self.events: List[tuple] = []  # (key, seconds)
        self._lock = threading.Lock()
        self._installed = False

    # the listener signature is (key, duration, **metadata) on this jax
    def _listen(self, key: str, seconds: float, **kwargs) -> None:
        if not self._installed or not key.startswith(self.prefix):
            return
        with self._lock:
            self.events.append((key, float(seconds)))
        self.recorder.event("compile", key=key, seconds=float(seconds))

    def install(self) -> "CompileWatcher":
        if not self._installed:
            self._installed = True
            jax.monitoring.register_event_duration_secs_listener(self._listen)
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        self._installed = False  # gate first: inert even if unregister fails
        try:
            from jax._src import monitoring as _monitoring
            _monitoring._unregister_event_duration_listener_by_callback(
                self._listen)
        except Exception:  # pragma: no cover - private API moved
            pass

    def __enter__(self) -> "CompileWatcher":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ----- summaries

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per event key: {"count", "total_seconds"}."""
        with self._lock:
            events = list(self.events)
        out: Dict[str, Dict[str, float]] = {}
        for key, secs in events:
            s = out.setdefault(key, {"count": 0, "total_seconds": 0.0})
            s["count"] += 1
            s["total_seconds"] += secs
        for s in out.values():
            s["total_seconds"] = round(s["total_seconds"], 6)
        return out

    @property
    def backend_compile_count(self) -> int:
        """XLA backend compiles observed (one per compiled program)."""
        with self._lock:
            return sum(1 for k, _ in self.events
                       if k.endswith(BACKEND_COMPILE))

    @property
    def backend_compile_seconds(self) -> float:
        """Total XLA backend compile time observed."""
        with self._lock:
            return sum(s for k, s in self.events
                       if k.endswith(BACKEND_COMPILE))


def watch_compiles(recorder=None):
    """A ``CompileWatcher`` context for ``recorder`` — or a null context
    when recording is disabled, so the no-run-dir path doesn't pay a
    per-compile listener callback."""
    rec = recorder if recorder is not None else get_recorder()
    if not rec.enabled:
        return contextlib.nullcontext(None)
    return CompileWatcher(rec)


# --------------------------------------------------------- snapshots

#: canonical memory_stats keys -> the per-backend spellings observed in
#: the wild (TPU/GPU PJRT report bytes_in_use/peak_bytes_in_use; some
#: stacks spell the pool limit bytes_limit vs bytes_reservable_limit)
_MEMORY_STAT_ALIASES = (
    ("bytes_in_use", ("bytes_in_use", "bytes_used", "used_bytes")),
    ("peak_bytes_in_use", ("peak_bytes_in_use", "peak_bytes",
                           "max_bytes_in_use", "largest_alloc_size")),
    ("bytes_limit", ("bytes_limit", "bytes_reservable_limit",
                     "pool_bytes", "limit_bytes")),
)


def normalize_memory_stats(raw: Any) -> Optional[Dict[str, int]]:
    """Canonicalize a backend's ``Device.memory_stats()`` dict to the
    closed ``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``
    subset every downstream reader (report tables, ``fks_mem_*`` gauges,
    the watermark sampler) keys on. Backends that don't report — CPU
    returns None, some raise — normalize to None; partial dicts keep
    whichever canonical keys they can answer, so a reader never KeyErrors
    on a backend-specific spelling."""
    if not isinstance(raw, dict) or not raw:
        return None
    out: Dict[str, int] = {}
    for canon, spellings in _MEMORY_STAT_ALIASES:
        for k in spellings:
            v = raw.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[canon] = int(v)
                break
    return out or None


def device_snapshot() -> List[Dict[str, Any]]:
    """Per-device identity + normalized ``memory_stats()`` (None where
    the backend doesn't report — CPU — rather than raising; key spellings
    canonicalized by ``normalize_memory_stats``)."""
    out = []
    for d in jax.devices():
        try:
            mem = d.memory_stats()
        except Exception:  # pragma: no cover - backend without the API
            mem = None
        out.append({
            "id": d.id,
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", ""),
            "process_index": getattr(d, "process_index", 0),
            "memory_stats": normalize_memory_stats(mem),
        })
    return out


def record_devices(recorder=None) -> List[Dict[str, Any]]:
    """Write one ``kind="device"`` event per visible device."""
    rec = recorder if recorder is not None else get_recorder()
    snap = device_snapshot() if rec.enabled else []
    for d in snap:
        rec.event("device", **d)
    return snap


def mesh_snapshot(mesh, real_count: Optional[int] = None) -> Dict[str, Any]:
    """Mesh metadata: axes/shape/device count/shard count, plus the
    pad-lane waste fraction when the caller's real candidate count is
    known (``pad_population`` pads to a shard multiple; the waste fraction
    is the share of launched lanes that are padding duplicates)."""
    from fks_tpu.parallel.mesh import num_shards, pad_stats

    info: Dict[str, Any] = {
        "axis_names": list(mesh.axis_names),
        "shape": {str(k): int(v) for k, v in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "shards": num_shards(mesh),
    }
    if real_count is not None:
        info.update(pad_stats(real_count, mesh))
    return info


def record_mesh(mesh, real_count: Optional[int] = None,
                recorder=None) -> Dict[str, Any]:
    """Write one ``kind="mesh"`` event describing the mesh."""
    rec = recorder if recorder is not None else get_recorder()
    if not rec.enabled:
        return {}
    snap = mesh_snapshot(mesh, real_count)
    rec.event("mesh", **snap)
    return snap
