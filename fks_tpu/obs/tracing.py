"""Decision-trace extraction, alignment, and first-divergence localization.

The engines' ``SimConfig.decision_trace`` instrument (fks_tpu.sim.types
``TraceBuffer``) logs one row per processed event inside the jitted step:
event kind, pod, chosen node, winning score + second-best margin, pending
count, and post-step free aggregates. This module is the host-side half:

- ``extract_trace``  — TraceBuffer / SimResult -> list of row dicts
- ``align_traces``   — first divergent row between two extracted traces
- ``replay``         — re-run one engine with tracing forced on
- ``trace_diff``     — replay two (engine, policy) specs on the same
                       workload, align, record ``decision_trace`` +
                       ``trace_diff`` events into the run dir
- ``format_diff``    — human-readable table for ``cli trace-diff``
- ``candidate_trace_diff`` — the ParitySentinel hook: localize WHERE a
                       drifting candidate's search-tier evaluation first
                       departs from the exact/jit reference

Why step alignment instead of final-fitness comparison: the parity
sentinel and ``tools/divergence_audit`` say THAT two engines drifted;
replaying with traces says WHICH scheduling decision diverged first —
any later divergence is downstream snowball (the flat engine's documented
retry-rule delta works exactly like this), so only the first row is
root cause.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fks_tpu.obs.recorder import get_recorder
from fks_tpu.sim.engine import SimConfig
from fks_tpu.sim.types import TRACE_KIND_NAMES, TraceBuffer

#: row fields compared exactly / within score_tol by align_traces
_EXACT_FIELDS = ("kind", "pod", "node", "pending",
                 "free_cpu", "free_mem", "free_gpu", "free_gpu_milli")
_SCORE_FIELDS = ("score", "margin")


def extract_trace(result_or_buffer) -> List[Dict[str, Any]]:
    """Written rows of a decision trace as a list of plain dicts (one per
    processed event, in step order). Accepts a ``SimResult`` (or any object
    with a ``.trace``) or a ``TraceBuffer`` directly."""
    buf = getattr(result_or_buffer, "trace", result_or_buffer)
    if buf is None:
        raise ValueError(
            "no decision trace recorded — run with SimConfig(decision_trace"
            "=True) (the fused kernel does not support tracing)")
    if not isinstance(buf, TraceBuffer):
        buf = TraceBuffer(*buf)
    data = np.asarray(buf.data)
    scores = np.asarray(buf.scores)
    if data.ndim != 2:
        raise ValueError(
            f"batched trace (data shape {data.shape}); index one lane first")
    count = int(np.asarray(buf.count))
    rows = []
    for i in range(min(count, data.shape[0])):
        d = data[i]
        rows.append({
            "step": i,
            "kind": TRACE_KIND_NAMES[int(d[TraceBuffer.COL_KIND])],
            "pod": int(d[TraceBuffer.COL_POD]),
            "node": int(d[TraceBuffer.COL_NODE]),
            "pending": int(d[TraceBuffer.COL_PENDING]),
            "free_cpu": int(d[TraceBuffer.COL_FREE_CPU]),
            "free_mem": int(d[TraceBuffer.COL_FREE_MEM]),
            "free_gpu": int(d[TraceBuffer.COL_FREE_GPU]),
            "free_gpu_milli": int(d[TraceBuffer.COL_FREE_GPU_MILLI]),
            "score": float(scores[i, 0]),
            "margin": float(scores[i, 1]),
        })
    return rows


def align_traces(a: Sequence[Dict[str, Any]], b: Sequence[Dict[str, Any]],
                 score_tol: float = 1e-5) -> Optional[Dict[str, Any]]:
    """First divergent step between two extracted traces, or None when they
    agree. Integer fields compare exactly; score/margin within
    ``score_tol``. A strict-prefix match diverges at the first missing row
    (field "length", the shorter side's row None)."""
    for i in range(min(len(a), len(b))):
        ra, rb = a[i], b[i]
        for field in _EXACT_FIELDS:
            if ra[field] != rb[field]:
                return {"step": i, "field": field, "a": ra, "b": rb}
        for field in _SCORE_FIELDS:
            if abs(ra[field] - rb[field]) > score_tol:
                return {"step": i, "field": field, "a": ra, "b": rb}
    if len(a) != len(b):
        i = min(len(a), len(b))
        return {"step": i, "field": "length",
                "a": a[i] if i < len(a) else None,
                "b": b[i] if i < len(b) else None}
    return None


def replay(workload, engine: str, param_policy, params,
           cfg: SimConfig = SimConfig()):
    """Re-run ``engine`` ("exact" | "flat") on ``workload`` with the
    decision trace forced on; returns the SimResult (``.trace`` set)."""
    import jax

    from fks_tpu.sim import get_engine

    cfg = dataclasses.replace(cfg, decision_trace=True)
    mod = get_engine(engine)  # rejects "fused" with an explanation
    run = jax.jit(mod.make_param_run_fn(workload, param_policy, cfg))
    return run(params, mod.initial_state(workload, cfg))


def trace_diff(workload, specs, cfg: Optional[SimConfig] = None,
               score_tol: float = 1e-5, recorder=None, label: str = "",
               max_trace_events: int = 64) -> Dict[str, Any]:
    """Replay exactly two ``(name, engine, param_policy, params)`` specs on
    the same workload, align their decision logs, and return the
    ``trace_diff`` record (also written to the active run dir, alongside
    one bounded ``decision_trace`` event per engine)."""
    if len(specs) != 2:
        raise ValueError(f"trace_diff compares exactly 2 specs, got {len(specs)}")
    if cfg is None:
        # cond_policy: replays are single-lane, where skipping the policy
        # on deletes is both the fast path and the sentinel's config
        cfg = SimConfig(cond_policy=True)
    rec = recorder if recorder is not None else get_recorder()
    names, traces, scores = [], [], {}
    for name, engine, param_policy, params in specs:
        res = replay(workload, engine, param_policy, params, cfg)
        rows = extract_trace(res)
        names.append(name)
        traces.append(rows)
        scores[name] = float(np.asarray(res.policy_score))
        rec.event("decision_trace", engine=name, label=label,
                  steps=len(rows), events=rows[:max_trace_events])
    div = align_traces(traces[0], traces[1], score_tol=score_tol)
    record = {
        "engines": names,
        "label": label,
        "steps": {names[0]: len(traces[0]), names[1]: len(traces[1])},
        "scores": scores,
        "score_tol": score_tol,
        "divergent": div is not None,
        "first_divergence": div,
    }
    rec.event("trace_diff", **record)
    return record


def format_diff(record: Dict[str, Any]) -> str:
    """Human-readable rendering of a ``trace_diff`` record."""
    na, nb = record["engines"]
    lines = [f"trace-diff: {na} vs {nb}"
             + (f"  [{record['label']}]" if record.get("label") else "")]
    for n in (na, nb):
        lines.append(f"  {n}: {record['steps'][n]} steps, "
                     f"fitness {record['scores'][n]:.6f}")
    div = record.get("first_divergence")
    if div is None:
        steps = record["steps"][na]
        lines.append(f"  no divergence ({steps} steps compared)")
        return "\n".join(lines)
    lines.append(f"  FIRST DIVERGENCE at step {div['step']} "
                 f"(field: {div['field']})")
    hdr = f"    {'engine':<24} {'kind':<7} {'pod':>4} {'node':>4} " \
          f"{'score':>12} {'margin':>12} {'pending':>7}"
    lines.append(hdr)
    for n, row in ((na, div.get("a")), (nb, div.get("b"))):
        if row is None:
            lines.append(f"    {n:<24} <trace ended>")
            continue
        lines.append(
            f"    {n:<24} {row['kind']:<7} {row['pod']:>4} {row['node']:>4} "
            f"{row['score']:>12.6f} {row['margin']:>12.6f} "
            f"{row['pending']:>7}")
    return "\n".join(lines)


def policy_params(workload, policy_name: str = "", code: str = "",
                  capacity: int = 512) -> Tuple[Any, Any]:
    """(param_policy, params) for ``cli trace-diff``: candidate source
    ``code`` runs on the funsearch VM; otherwise ``policy_name`` picks a
    zoo policy (params None)."""
    if code:
        from fks_tpu.funsearch import vm
        return vm.score, vm.compile_for_workload(code, workload,
                                                 capacity=capacity)
    from fks_tpu.models import zoo
    if policy_name not in zoo.ZOO:
        raise ValueError(f"unknown policy {policy_name!r}; "
                         f"available: {', '.join(sorted(zoo.ZOO))}")
    pol = zoo.ZOO[policy_name]()
    return (lambda _p, pod, nodes: pol(pod, nodes)), None


def candidate_trace_diff(evaluator, code: str, recorder=None,
                         score_tol: float = 1e-5,
                         label: str = "") -> Dict[str, Any]:
    """Trace-diff a candidate's SEARCH-tier evaluation (the evaluator's
    engine + VM program when eligible) against the exact/jit reference —
    the same two numbers the ParitySentinel compares, so the returned
    first divergence is the root-cause step of a parity alert."""
    from fks_tpu.funsearch import transpiler, vm

    wl = evaluator.workload
    cfg = dataclasses.replace(evaluator.cfg, cond_policy=True)
    engine = evaluator.engine if evaluator.engine in ("exact", "flat") else "flat"
    policy = transpiler.transpile(code)

    def jit_policy(_p, pod, nodes):
        return policy(pod, nodes)

    search_policy, search_params, search_tier = jit_policy, None, "jit"
    if getattr(evaluator, "use_vm", True):
        try:
            search_params = vm.compile_for_workload(code, wl)
            search_policy, search_tier = vm.score, "vm"
        except Exception:  # noqa: BLE001 — VM-ineligible -> jit tier
            pass
    specs = [
        (f"search:{engine}/{search_tier}", engine, search_policy, search_params),
        ("reference:exact/jit", "exact", jit_policy, None),
    ]
    return trace_diff(wl, specs, cfg=cfg, score_tol=score_tol,
                      recorder=recorder, label=label)
