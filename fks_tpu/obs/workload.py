"""Workload observability: query fingerprints, tenant accounting, loadgen.

The serve stack's metrics were tenant-blind: ``serve_request`` rows
carried latency and bucket shape but nothing about WHO sent the query or
WHAT KIND of work it was, and every published qps number came from a
serial in-process loop. This module is the measurement half of the
multi-tenant roadmap item, landed before any routing/shedding policy so
that work is gated from day one:

- ``QueryFingerprinter``: a deterministic content/shape signature per
  query — pod-count bucket, per-pod resource-mix decade histogram (the
  pre-flight ``analysis.candidate._bucket`` idiom: sign + magnitude
  decade, so 120 and 160 cluster while 120 and 12000 split), and the
  snapshot-trigger-table content hash (the ``blake2b`` idiom the serve
  engine's device ktable cache uses). Classes are stable across
  processes and pod orderings, so live traffic clusters into workload
  classes and a windowed ``workload_mix`` metric records the
  distribution.
- ``TenantAccountant``: per-tenant request/shed/expiry/degraded
  counters, EWMA service time, per-tenant SLO burn through the existing
  ``SLOConfig``/``slo_burn`` math (obs.history), and a Jain's fairness
  index over per-tenant goodput — recorded as one ``tenant_stats``
  metric per tenant, exported as ``fks_tenant_*`` gauges, rendered as a
  table by ``cli report`` and live lines by ``cli watch``.
- ``run_loadgen``: a sustained multi-tenant arrival driver (open-loop
  Poisson rates and closed-loop worker counts per tenant) over any
  ``send(query) -> outcome`` client — in-process ``service_client`` or
  the concurrent-HTTP ``http_client`` — summarized into the four
  compare-gated keys ``loadgen_qps`` / ``loadgen_p99_ms`` /
  ``loadgen_shed_rate`` / ``loadgen_fairness_index`` and recorded as a
  ``loadgen_summary`` metric.

Disabled path discipline: the service holds ``accountant=None`` /
``fingerprinter=None`` by default — no object, no lock, no per-request
cost (the NullRecorder rule applied to accounting).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import random
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

from fks_tpu.obs.history import SLOConfig, slo_burn

#: queries that name no tenant all account to one bucket — the
#: single-tenant deployments that existed before this module
DEFAULT_TENANT = "default"

#: loadgen arrival modes (closed vocabulary — pinned by
#: tools/check_jsonl_schema.py against its own copy)
LOADGEN_MODES = ("open", "closed", "mixed")


def tenant_of(query: Dict[str, Any]) -> str:
    """The tenant a request accounts to: its ``tenant`` field, else
    ``DEFAULT_TENANT``. Always a str — accounting keys must never be
    unhashable or collide across JSON round trips."""
    t = query.get("tenant") if isinstance(query, dict) else None
    return str(t) if t else DEFAULT_TENANT


# ------------------------------------------------------------ fingerprints


def _decade(v: float) -> str:
    """Sign + magnitude-decade token (``analysis.candidate._bucket``):
    "0" for zero, else "+eK"/"-eK" — the resolution at which resource
    requests cluster into classes without hashing exact values."""
    v = float(v)
    if v == 0:
        return "0"
    mag = abs(v)
    dec = 0 if mag <= 1.0 else int(math.floor(math.log10(mag))) + 1
    return f"{'+' if v > 0 else '-'}e{dec}"


def _pow2_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


class QueryFingerprinter:
    """Deterministic workload-class signatures + a windowed class mix.

    ``classify(pods)`` is pure and ORDER-INDEPENDENT: the signature is
    (pod-count power-of-two bucket, sorted resource-mix histogram,
    snapshot-trigger-table hash), digested with ``blake2b`` — the same
    query permuted, re-serialized, or classified in another process
    lands in the same class. ``observe`` classifies AND counts;
    ``record_mix`` emits the windowed ``workload_mix`` metric."""

    def __init__(self, *, snapshot_interval: float = 0.05,
                 max_steps_per_pod: int = 8, window: int = 256):
        self.snapshot_interval = float(snapshot_interval)
        self.max_steps_per_pod = int(max_steps_per_pod)
        self.window = max(1, int(window))
        self._counts: Dict[str, int] = {}
        self._seen = 0
        self._lock = threading.Lock()

    def _ktable_digest(self, n_pods: int) -> str:
        """Content hash of the snapshot trigger table this query would
        ship (the serve upload's third tensor): sized from the REAL pod
        count exactly as ``batcher._query_ktable`` sizes it, hashed with
        the engine's device-cache ``blake2b`` idiom."""
        from fks_tpu.sim.evaluator import (
            max_snapshot_count, snapshot_trigger_table,
        )

        tbl = snapshot_trigger_table(
            n_pods,
            max_snapshot_count(self.max_steps_per_pod * n_pods, n_pods,
                               self.snapshot_interval),
            self.snapshot_interval)
        import numpy as np
        return hashlib.blake2b(np.asarray(tbl, np.int32).tobytes(),
                               digest_size=8).hexdigest()

    def classify(self, pods: Sequence[Dict[str, Any]]) -> str:
        """Pod list -> class label ``p{bucket}:{digest}`` (stable across
        processes, pod orderings, and dict key orders)."""
        n = len(pods)
        bucket = _pow2_bucket(max(1, n))
        mix: Dict[str, int] = {}
        for p in pods:
            tok = "/".join((
                _decade(p.get("cpu_milli", 0)),
                _decade(p.get("memory_mib", 0)),
                _decade(p.get("gpu_milli", 0)),
                _decade(p.get("duration_time", 0)),
            ))
            mix[tok] = mix.get(tok, 0) + 1
        canon = json.dumps(
            [bucket, sorted(mix.items()), self._ktable_digest(n)],
            separators=(",", ":"))
        digest = hashlib.blake2b(canon.encode(), digest_size=6).hexdigest()
        return f"p{bucket}:{digest}"

    def observe(self, pods: Sequence[Dict[str, Any]]) -> str:
        cls = self.classify(pods)
        with self._lock:
            self._counts[cls] = self._counts.get(cls, 0) + 1
            self._seen += 1
        return cls

    def mix(self) -> Dict[str, int]:
        """Class -> count for the current window (insertion order by
        first sighting; copy, safe to mutate)."""
        with self._lock:
            return dict(self._counts)

    def record_mix(self, recorder, *, reset: bool = True) -> dict:
        """Emit the windowed ``workload_mix`` metric and (by default)
        start a fresh window. Returns the record (empty window -> {})."""
        with self._lock:
            if not self._seen:
                return {}
            classes = dict(self._counts)
            seen = self._seen
            if reset:
                self._counts = {}
                self._seen = 0
        rec = {"window": seen, "distinct": len(classes),
               "classes": classes}
        if recorder is not None:
            recorder.metric("workload_mix", **rec)
        return rec


# ------------------------------------------------------------- accounting


def jain_fairness(values: Sequence[float]) -> float:
    """Jain's fairness index ``(sum x)^2 / (n * sum x^2)`` over
    per-tenant goodput: 1.0 = perfectly even, 1/n = one tenant has it
    all. Empty or all-zero inputs read as fair (1.0) — an idle service
    is not unfair."""
    vals = [float(v) for v in values]
    n = len(vals)
    total = sum(vals)
    if n == 0 or total == 0:
        return 1.0
    return (total * total) / (n * sum(v * v for v in vals))


class _TenantSlot:
    __slots__ = ("requests", "shed", "expired", "degraded", "ewma_ms",
                 "latencies_ms")

    def __init__(self):
        self.requests = 0
        self.shed = 0
        self.expired = 0
        self.degraded = 0
        self.ewma_ms = 0.0
        self.latencies_ms: List[float] = []


class TenantAccountant:
    """Per-tenant serve accounting with SLO burn and fairness.

    One slot per tenant: completed/shed/expired/degraded counts, an EWMA
    of service time (``alpha`` — recent traffic dominates), and the
    latency tail for percentile + burn math. ``record`` emits one
    ``tenant_stats`` metric per tenant; every row carries the GLOBAL
    ``fairness_index`` (Jain over per-tenant goodput) so any single row
    answers "is the service being fair right now". Thread-safe: sheds
    land from submitter threads (HTTP handlers), completions from the
    batcher thread."""

    def __init__(self, *, slo: Optional[SLOConfig] = None,
                 alpha: float = 0.2, max_latencies: int = 4096):
        self.slo = slo if slo is not None else SLOConfig()
        self.alpha = float(alpha)
        self.max_latencies = max(16, int(max_latencies))
        self._slots: Dict[str, _TenantSlot] = {}
        self._lock = threading.Lock()
        self._t_first: Optional[float] = None
        self._t_last: float = 0.0

    def _slot(self, tenant: str) -> _TenantSlot:
        s = self._slots.get(tenant)
        if s is None:
            s = self._slots[tenant] = _TenantSlot()
        return s

    def note_request(self, tenant: str, latency_ms: float, *,
                     degraded: bool = False) -> None:
        now = time.perf_counter()
        with self._lock:
            s = self._slot(tenant)
            s.requests += 1
            if degraded:
                s.degraded += 1
            s.ewma_ms = (latency_ms if s.requests == 1 else
                         self.alpha * latency_ms
                         + (1.0 - self.alpha) * s.ewma_ms)
            s.latencies_ms.append(float(latency_ms))
            if len(s.latencies_ms) > self.max_latencies:
                del s.latencies_ms[: len(s.latencies_ms) // 2]
            if self._t_first is None:
                self._t_first = now
            self._t_last = now

    def note_shed(self, tenant: str) -> None:
        with self._lock:
            self._slot(tenant).shed += 1

    def note_expired(self, tenant: str) -> None:
        with self._lock:
            self._slot(tenant).expired += 1

    def ewma_service_s(self, tenant: str) -> Optional[float]:
        """This tenant's EWMA service time in SECONDS, or None while the
        tenant is cold — the per-tenant Retry-After source the admission
        controller plugs in (``AdmissionController.service_time_for``)."""
        with self._lock:
            s = self._slots.get(tenant)
            if s is None or not s.requests:
                return None
            return s.ewma_ms / 1e3

    def _elapsed(self) -> float:
        return (self._t_last - self._t_first) \
            if self._t_first is not None else 0.0

    def fairness_index(self) -> float:
        with self._lock:
            return jain_fairness([s.requests
                                  for s in self._slots.values()])

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant snapshot: counters, EWMA/percentile latencies,
        goodput qps over the accountant's own observation window, and
        the p99 SLO burn rate (0.0 when no SLO is set)."""
        elapsed = self._elapsed()
        fair = self.fairness_index()
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            items = [(t, s, list(s.latencies_ms))
                     for t, s in sorted(self._slots.items())]
        for tenant, s, lat in items:
            srt = sorted(lat)
            n = len(srt)
            burn = 0.0
            if self.slo.p99_ms and n:
                recs = slo_burn(SLOConfig(p99_ms=self.slo.p99_ms,
                                          error_budget=self.slo.error_budget),
                                lat, elapsed)
                burn = recs[0]["burn_rate"] if recs else 0.0
            out[tenant] = {
                "tenant": tenant,
                "requests": s.requests,
                "shed": s.shed,
                "expired": s.expired,
                "degraded": s.degraded,
                "ewma_ms": round(s.ewma_ms, 3),
                "p50_ms": round(srt[n // 2], 3) if n else 0.0,
                "p99_ms": round(srt[min(n - 1, int(0.99 * n))], 3)
                if n else 0.0,
                "goodput_qps": round(s.requests / elapsed, 2)
                if elapsed > 0 else 0.0,
                "burn_rate": burn,
                "fairness_index": round(fair, 4),
            }
        return out

    def record(self, recorder) -> Dict[str, Dict[str, Any]]:
        """One ``tenant_stats`` metric per tenant onto ``recorder``;
        returns the snapshot."""
        stats = self.stats()
        if recorder is not None:
            for row in stats.values():
                recorder.metric("tenant_stats", **row)
        return stats


# ---------------------------------------------------------------- loadgen


@dataclasses.dataclass(frozen=True)
class TenantLoad:
    """One tenant's arrival process. ``closed``: ``concurrency`` workers
    each submit-wait-repeat (throughput-seeking, self-clocking).
    ``open``: Poisson arrivals at ``rate_qps`` regardless of response
    times (latency-honest under overload — the arrival rate does not
    slow down because the server did)."""

    tenant: str
    mode: str = "closed"
    concurrency: int = 1
    rate_qps: float = 0.0
    pods_per_query: int = 2

    def __post_init__(self):
        if self.mode not in ("open", "closed"):
            raise ValueError(f"mode must be open|closed, got {self.mode!r}")
        if self.mode == "open" and self.rate_qps <= 0:
            raise ValueError("open-loop tenant needs rate_qps > 0")
        if self.mode == "closed" and self.concurrency < 1:
            raise ValueError("closed-loop tenant needs concurrency >= 1")


def parse_tenant_spec(spec: str) -> List[TenantLoad]:
    """``"a:closed:2,b:open:25"`` -> TenantLoads (third field: workers
    for closed, qps for open; optional fourth: pods per query)."""
    plan: List[TenantLoad] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 3:
            raise ValueError(
                f"tenant spec {part!r} needs name:mode:rate_or_workers")
        name, mode, amount = bits[0], bits[1], float(bits[2])
        pods = int(bits[3]) if len(bits) > 3 else 2
        if mode == "open":
            plan.append(TenantLoad(name, "open", rate_qps=amount,
                                   pods_per_query=pods))
        else:
            plan.append(TenantLoad(name, mode, concurrency=int(amount),
                                   pods_per_query=pods))
    if not plan:
        raise ValueError(f"empty tenant spec {spec!r}")
    return plan


def default_make_pods(load: TenantLoad, i: int) -> List[dict]:
    """Deterministic per-request pod lists: resources vary with the
    request ordinal so fingerprint classes differ across tenants but
    repeat runs are bit-identical."""
    return [{"cpu_milli": 10 + (i * 7 + j * 13) % 60,
             "memory_mib": 50 + 11 * j,
             "creation_time": j, "duration_time": 40}
            for j in range(load.pods_per_query)]


def service_client(service) -> Callable[[dict], dict]:
    """In-process client: ``submit().result()`` with shed/expiry mapped
    to outcomes (no socket — the accounting-overhead measurement path)."""
    from fks_tpu.resilience.deadline import ResilienceError

    def send(query: dict) -> dict:
        try:
            service.submit(query).result(timeout=60)
            return {"outcome": "ok"}
        except ResilienceError as e:
            return {"outcome": "shed", "reason": e.reason}
        except Exception as e:  # noqa: BLE001 — loadgen counts, not raises
            return {"outcome": "error", "reason": str(e)}
    return send


def http_client(port: int, *, host: str = "127.0.0.1",
                timeout_s: float = 30.0) -> Callable[[dict], dict]:
    """HTTP client against the serve front: POST /query, 503 -> shed
    (Retry-After honored as data, not by waiting), other non-200 ->
    error. One connection per request — loadgen measures the service,
    not a keep-alive pool."""
    import urllib.error
    import urllib.request

    url = f"http://{host}:{port}/query"

    def send(query: dict) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(query).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                resp.read()
                return {"outcome": "ok"}
        except urllib.error.HTTPError as e:
            e.read()
            if e.code == 503:
                return {"outcome": "shed",
                        "retry_after": e.headers.get("Retry-After")}
            return {"outcome": "error", "status": e.code}
        except Exception as e:  # noqa: BLE001 — loadgen counts, not raises
            return {"outcome": "error", "reason": str(e)}
    return send


def run_loadgen(send: Callable[[dict], dict], plan: Sequence[TenantLoad],
                duration_s: float, *, seed: int = 0,
                make_pods: Callable[[TenantLoad, int], List[dict]] = None,
                recorder=None) -> dict:
    """Drive the arrival plan against ``send`` for ``duration_s`` and
    summarize into the gated loadgen vocabulary.

    Closed-loop tenants run ``concurrency`` synchronous worker threads;
    open-loop tenants run one seeded-Poisson dispatcher firing each
    arrival on its own thread (arrivals never wait on responses — the
    open-loop contract; a shed answer is an outcome, not an error).
    Returns the summary dict and records it as ``loadgen_summary``."""
    make_pods = make_pods or default_make_pods
    results: List[tuple] = []  # (tenant, outcome, latency_ms)
    lock = threading.Lock()
    t_start = time.perf_counter()
    t_end = t_start + float(duration_s)

    def fire(load: TenantLoad, i: int) -> None:
        q = {"id": f"{load.tenant}-{i:05d}", "tenant": load.tenant,
             "pods": make_pods(load, i)}
        t0 = time.perf_counter()
        out = send(q)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with lock:
            results.append((load.tenant, out.get("outcome", "error"),
                            dt_ms))

    threads: List[threading.Thread] = []
    arrival_threads: List[threading.Thread] = []

    def closed_worker(load: TenantLoad, w: int) -> None:
        i = w
        while time.perf_counter() < t_end:
            fire(load, i)
            i += load.concurrency

    def open_dispatcher(load: TenantLoad) -> None:
        rng = random.Random(seed ^ zlib.crc32(load.tenant.encode()))
        i = 0
        next_t = time.perf_counter()
        while True:
            next_t += rng.expovariate(load.rate_qps)
            if next_t >= t_end:
                return
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=fire, args=(load, i), daemon=True)
            t.start()
            arrival_threads.append(t)
            i += 1

    for load in plan:
        if load.mode == "closed":
            for w in range(load.concurrency):
                threads.append(threading.Thread(
                    target=closed_worker, args=(load, w), daemon=True))
        else:
            threads.append(threading.Thread(
                target=open_dispatcher, args=(load,), daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for t in arrival_threads:  # open-loop stragglers finish their answer
        t.join(timeout=60)
    elapsed = time.perf_counter() - t_start

    modes = {load.mode for load in plan}
    mode = modes.pop() if len(modes) == 1 else "mixed"
    ok_lat = sorted(dt for _, outcome, dt in results if outcome == "ok")
    n_ok = len(ok_lat)
    n_shed = sum(1 for _, o, _ in results if o == "shed")
    n_err = sum(1 for _, o, _ in results if o == "error")
    per_tenant: Dict[str, Dict[str, Any]] = {}
    for load in plan:
        rows = [(o, dt) for t, o, dt in results if t == load.tenant]
        lat = sorted(dt for o, dt in rows if o == "ok")
        k = len(lat)
        per_tenant[load.tenant] = {
            "mode": load.mode,
            "sent": len(rows),
            "ok": k,
            "shed": sum(1 for o, _ in rows if o == "shed"),
            "errors": sum(1 for o, _ in rows if o == "error"),
            "p50_ms": round(lat[k // 2], 3) if k else 0.0,
            "p99_ms": round(lat[min(k - 1, int(0.99 * k))], 3) if k
            else 0.0,
            "goodput_qps": round(k / elapsed, 2) if elapsed > 0 else 0.0,
        }
    summary = {
        "mode": mode,
        "tenant_count": len(plan),
        "duration_s": round(elapsed, 3),
        "requests": len(results),
        "completed": n_ok,
        "shed": n_shed,
        "errors": n_err,
        "loadgen_qps": round(n_ok / elapsed, 2) if elapsed > 0 else 0.0,
        "loadgen_p50_ms": round(ok_lat[n_ok // 2], 3) if n_ok else 0.0,
        "loadgen_p99_ms": round(ok_lat[min(n_ok - 1, int(0.99 * n_ok))], 3)
        if n_ok else 0.0,
        "loadgen_shed_rate": round(n_shed / len(results), 4)
        if results else 0.0,
        "loadgen_fairness_index": round(jain_fairness(
            [v["ok"] for v in per_tenant.values()]), 4),
        "tenants": per_tenant,
    }
    if recorder is not None:
        recorder.metric("loadgen_summary", **summary)
    return summary
