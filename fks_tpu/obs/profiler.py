"""Device-time attribution: per-stage wall / compile / compute split.

The flight recorder's spans say how long a scope took; this module says
where the time WENT. A ``StageProfiler`` owns a ``CompileWatcher``
(fks_tpu.obs.telemetry) and carves a run into named stages — codegen /
sandbox+preflight / transpile / device-eval / rank / ledger for the
evolution loop, per-bucket compile and steady for serving — each fenced
with explicit ``block_until_ready`` so a stage's wall clock includes the
device work it dispatched, not just the Python that enqueued it. Per
stage it reports:

- ``wall_seconds``: fenced wall time of the scope;
- ``compile_seconds`` / ``compile_count``: the XLA backend-compile share,
  read as a before/after delta off the compile watcher (host-side
  ``jax.monitoring`` telemetry — zero instrumentation in jitted code);
- ``compute_seconds``: the dispatch+compute remainder;
- occupancy, when the caller annotates the launch shape: pad-lane waste
  from ``parallel.mesh.pad_stats`` plus the scenario and trace-segment
  batch axes fold into ``utilization_pct`` — the share of launched
  lane-time spent on real candidates actually computing — and an
  attached XLA ``cost_analysis`` FLOP count yields ``est_flops_per_sec``.

Each stage lands as one ``device_profile`` metric on the active flight
recorder; ``summary()`` aggregates by stage name and reports the
attributed fraction of a measured wall interval (the ≥95% acceptance
bar) with the rest called idle. ``cli report`` renders the aggregate as
an attribution table.

The module follows the repo's Python-static-flag convention: a disabled
profiler (``NULL_PROFILER``, or ``StageProfiler(enabled=False)``) is
pure host-side no-op scaffolding — it never touches tracing, so any
program lowered inside a stage is bit-identical with the profiler on or
off (pinned as ``flat_step/profiled`` in the jaxpr manifest).
"""
from __future__ import annotations

import contextlib
import time
from typing import Any, Dict, Iterator, List, Optional

import jax

from fks_tpu.obs.recorder import get_recorder
from fks_tpu.obs.telemetry import CompileWatcher


class StageHandle:
    """What an enabled ``stage(...)`` scope yields: annotate launch-shape
    fields onto the stage record, fence device values into its clock."""

    __slots__ = ("fields", "record")

    def __init__(self, **fields) -> None:
        self.fields: Dict[str, Any] = dict(fields)
        self.record: Optional[Dict[str, Any]] = None  # set at stage exit

    def annotate(self, **fields) -> None:
        """Attach occupancy/cost fields (e.g. ``parallel.mesh.pad_stats``
        output, ``cost_flops``) to the stage's device_profile record."""
        self.fields.update(fields)

    def sync(self, value: Any) -> Any:
        """Block until ``value`` is device-ready, so the dispatched work
        lands inside this stage's wall clock. Returns ``value``."""
        jax.block_until_ready(value)
        return value


class _NullHandle:
    """The disabled handle: annotate drops fields, sync is identity (the
    unprofiled path must not grow extra device fences)."""

    __slots__ = ()
    record = None

    def annotate(self, **fields) -> None:
        pass

    def sync(self, value: Any) -> Any:
        return value


_NULL_HANDLE = _NullHandle()


class StageProfiler:
    """Attribute wall time to named pipeline stages (module docstring).

    ``enabled=False`` is the Python-static off path: ``stage()`` yields a
    shared no-op handle and records nothing — same code shape for
    callers, zero filesystem writes, zero effect on lowering. The
    ``recorder`` (default: the process-wide active flight recorder)
    receives one ``device_profile`` metric per completed stage; in-memory
    ``records`` accumulate regardless, so recorder-less tools
    (tools/profile_step.py) can read the attribution directly.
    """

    def __init__(self, enabled: bool = True, scope: str = "evolve",
                 recorder=None, watcher: Optional[CompileWatcher] = None,
                 sampler=None):
        self.enabled = bool(enabled)
        self.scope = scope
        self.recorder = recorder if recorder is not None else get_recorder()
        # optional memory watermark hook (fks_tpu.obs.memory
        # .WatermarkSampler): one sample per completed stage, so the
        # watermark table attributes RSS/device bytes to pipeline stages.
        # None (default) and a disabled sampler are both exact no-ops —
        # the profiled/mem_sampled jaxpr pins stay bit-identical.
        self.sampler = sampler
        self.records: List[Dict[str, Any]] = []
        self._depth = 0
        self._segments = 0
        self._t_start = time.perf_counter()
        self.watcher: Optional[CompileWatcher] = None
        self._own_watcher = False
        if self.enabled:
            if watcher is None:
                # NullRecorder-backed watcher: compile deltas accumulate
                # in-process without requiring an open run dir
                watcher = CompileWatcher(recorder=self.recorder).install()
                self._own_watcher = True
            self.watcher = watcher

    def close(self) -> None:
        """Uninstall the owned compile listener (borrowed watchers are the
        caller's to manage)."""
        if self._own_watcher and self.watcher is not None:
            self.watcher.uninstall()

    def __enter__(self) -> "StageProfiler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----- stages

    @contextlib.contextmanager
    def stage(self, name: str, **fields) -> Iterator[Any]:
        """A named attribution scope. Nested stages record with their
        ``depth``; only depth-0 stages count toward the summary totals
        (an inner stage's wall is already inside its parent's)."""
        if not self.enabled:
            yield _NULL_HANDLE
            return
        handle = StageHandle(**fields)
        depth = self._depth
        self._depth += 1
        seg0 = self._segments
        c_s0 = self.watcher.backend_compile_seconds
        c_n0 = self.watcher.backend_compile_count
        t0 = time.perf_counter()
        try:
            yield handle
        finally:
            wall = time.perf_counter() - t0
            self._depth -= 1
            compile_s = self.watcher.backend_compile_seconds - c_s0
            compile_n = self.watcher.backend_compile_count - c_n0
            segs = self._segments - seg0
            rec: Dict[str, Any] = {
                "scope": self.scope, "stage": name, "depth": depth,
                "wall_seconds": round(wall, 6),
                "compile_seconds": round(min(compile_s, wall), 6),
                "compile_count": int(compile_n),
                "compute_seconds": round(max(0.0, wall - compile_s), 6),
            }
            if segs:
                rec["segments"] = int(segs)
            rec.update(handle.fields)
            _finish_utilization(rec)
            handle.record = rec
            self.records.append(rec)
            self.recorder.metric("device_profile", dict(rec))
            if self.sampler is not None:
                self.sampler.sample(stage=name)

    def segment_tick(self, n: int = 1) -> None:
        """Count a dispatched trace segment against the open stage (wired
        as the segmented runner's ``on_segment`` host callback)."""
        self._segments += int(n)

    # ----- summaries

    def summary(self, measured_wall: Optional[float] = None,
                emit: bool = False) -> Dict[str, Any]:
        """Aggregate depth-0 stages by name (wall/compile/compute sums,
        occurrence counts, per-stage share of the attributed total) and
        judge coverage against ``measured_wall`` (default: time since
        construction): ``attributed_fraction`` is the ≥95% acceptance
        number, the remainder is ``idle_fraction``. ``emit=True``
        additionally lands the aggregate as a ``stage="__total__"``
        device_profile metric."""
        top = [r for r in self.records if r.get("depth", 0) == 0]
        by: Dict[str, Dict[str, Any]] = {}
        for r in top:
            a = by.setdefault(r["stage"], {
                "stage": r["stage"], "count": 0, "wall_seconds": 0.0,
                "compile_seconds": 0.0, "compute_seconds": 0.0,
                "compile_count": 0, "segments": 0})
            a["count"] += 1
            a["wall_seconds"] += float(r["wall_seconds"])
            a["compile_seconds"] += float(r["compile_seconds"])
            a["compute_seconds"] += float(r["compute_seconds"])
            a["compile_count"] += int(r["compile_count"])
            a["segments"] += int(r.get("segments", 0))
            if "utilization_pct" in r:
                a["_uw"] = a.get("_uw", 0.0) + float(r["wall_seconds"])
                a["_us"] = a.get("_us", 0.0) + (
                    float(r["utilization_pct"]) * float(r["wall_seconds"]))
        total = sum(a["wall_seconds"] for a in by.values())
        stages = sorted(by.values(), key=lambda a: -a["wall_seconds"])
        for a in stages:
            a["pct_of_attributed"] = round(
                100.0 * a["wall_seconds"] / total, 2) if total else 0.0
            for k in ("wall_seconds", "compile_seconds", "compute_seconds"):
                a[k] = round(a[k], 6)
            uw, us = a.pop("_uw", 0.0), a.pop("_us", 0.0)
            if uw:  # wall-weighted mean of the annotated occurrences
                a["utilization_pct"] = round(us / uw, 2)
        if measured_wall is None:
            measured_wall = time.perf_counter() - self._t_start
        frac = total / measured_wall if measured_wall > 0 else 0.0
        out = {
            "scope": self.scope,
            "stages": stages,
            "wall_seconds": round(total, 6),
            "measured_wall_seconds": round(measured_wall, 6),
            "attributed_fraction": round(min(frac, 1.0), 4),
            "idle_fraction": round(max(0.0, 1.0 - frac), 4),
            "compile_seconds": round(
                sum(a["compile_seconds"] for a in stages), 6),
            "segments": int(self._segments),
        }
        if emit and self.enabled:
            self.recorder.metric(
                "device_profile", stage="__total__", scope=self.scope,
                wall_seconds=out["wall_seconds"],
                measured_wall_seconds=out["measured_wall_seconds"],
                attributed_fraction=out["attributed_fraction"],
                idle_fraction=out["idle_fraction"],
                compile_seconds=out["compile_seconds"],
                segments=out["segments"])
        return out


def _finish_utilization(rec: Dict[str, Any]) -> None:
    """Fold annotated occupancy/cost fields into derived numbers: pad-lane
    waste (and the scenario/segment axes, already multiplicative in lane
    count) discounts the compute share of the stage wall into
    ``utilization_pct``; an attached static FLOP count prices the compute
    seconds into ``est_flops_per_sec``."""
    wall = float(rec.get("wall_seconds", 0.0))
    waste = rec.get("pad_waste_fraction")
    if waste is not None and wall > 0:
        occ = max(0.0, 1.0 - float(waste))
        rec["occupancy"] = round(occ, 4)
        rec["utilization_pct"] = round(
            100.0 * occ * float(rec["compute_seconds"]) / wall, 2)
    flops = rec.get("cost_flops")
    if flops and float(rec.get("compute_seconds", 0.0)) > 0:
        rec["est_flops_per_sec"] = round(
            float(flops) / float(rec["compute_seconds"]), 1)


#: shared disabled profiler — instrumented paths default to this, so
#: profiling never needs an ``if profiler:`` guard (same pattern as
#: ``obs.recorder.NULL``)
NULL_PROFILER = StageProfiler(enabled=False, scope="null")


def profile_launch(fn, *args, name: str = "launch",
                   profiler: Optional[StageProfiler] = None,
                   reps: int = 1, **fields):
    """Warmup-then-measure attribution for one jitted launch — the shared
    code path behind tools/profile_step.py and bench.py's throughput
    stages. The first call runs in a ``{name}:compile`` stage (its
    compile split read off the watcher), then ``reps`` fenced calls in a
    ``{name}:steady`` stage. Returns ``(result, record)`` where record
    carries first/compile/best-steady seconds plus the two stage
    records."""
    prof = profiler if profiler is not None else NULL_PROFILER
    with prof.stage(f"{name}:compile", **fields) as hc:
        out = hc.sync(fn(*args))
    best = None
    with prof.stage(f"{name}:steady", reps=int(reps), **fields) as hs:
        for _ in range(max(1, int(reps))):
            t0 = time.perf_counter()
            out = hs.sync(fn(*args))
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
    record = {
        "name": name,
        "reps": int(reps),
        "best_seconds": best,
    }
    if hc.record is not None:  # enabled profiler: fold in the compile split
        record.update(
            first_call_seconds=hc.record["wall_seconds"],
            compile_seconds=hc.record["compile_seconds"],
            compile_count=hc.record["compile_count"],
            steady_total_seconds=hs.record["wall_seconds"])
    return out, record
