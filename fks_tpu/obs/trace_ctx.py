"""Causal trace contexts: Dapper-style trace/span ids across threads.

``obs.span`` records *thread-local nested* timings — the moment work
crosses a Future, the batcher's worker thread, or the promotion ledger,
causality is lost. This module adds the missing identity layer:

- ``TraceContext`` — an immutable (trace_id, span_id) pair. The span_id
  is the id of the context's OWN span (the parent of anything started
  under it). ``new_trace`` preallocates the root span id, so the root's
  id is stable from submit time even though the root ``trace_span``
  event is only written when the request finishes (children can be
  emitted before their parent's event exists; reconstruction sorts it
  out).
- explicit propagation: ``activate(ctx)`` binds the context to the
  current thread; producers (the serve batcher, the promotion
  controller, the evolve loop) attach the context OBJECT to queued
  items/Futures and re-activate it on the consuming thread — there is
  no ambient cross-thread magic to get wrong.
- ``emit`` — one ``trace_span`` event (trace_id/span_id/parent_id/path/
  seconds) into a recorder. ``obs.span`` calls it automatically when a
  context is active; code with better timing information (the batcher's
  queue-wait split) calls it directly.

The null path stays allocation-light: with no recorder, no context is
ever created, and ``current()`` is a single thread-local read.

Reconstruction (the ``cli spans`` viewer and the run_full_suite trace
gate) lives here too: group ``trace_span`` events by trace id, build
the parent/child tree, render per-request latency waterfalls, and
compute the critical path of an evolve generation (device-idle vs
LLM-idle seconds — the numbers the async-island ROADMAP item needs).
"""
from __future__ import annotations

import threading
import uuid
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = [
    "TraceContext", "new_trace", "new_span_id", "current", "activate",
    "child_of", "emit", "trace_spans", "traces_by_id", "build_tree",
    "render_waterfall", "critical_path", "waterfall_complete",
    "SERVE_ROOT", "SERVE_COMPONENTS", "activate_trace", "current_trace",
    "emit_span",
]

#: canonical serve-request span paths (the waterfall vocabulary)
SERVE_ROOT = "serve/request"
SERVE_COMPONENTS = ("queue_wait", "batch_wait", "pack_h2d", "dispatch",
                    "scatter_back")


class TraceContext:
    """One (trace_id, span_id) hop of a causal chain. Immutable by
    convention; cheap enough to attach to every queued request."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id!r}, {self.span_id!r})"


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def new_trace(prefix: str = "req") -> TraceContext:
    """Fresh trace with the ROOT span id preallocated — children created
    before the root event is written still get a resolvable parent_id."""
    return TraceContext(f"{prefix}-{uuid.uuid4().hex[:16]}", new_span_id())


def child_of(ctx: TraceContext) -> TraceContext:
    return TraceContext(ctx.trace_id, new_span_id())


_local = threading.local()


def current() -> Optional[TraceContext]:
    """The thread's active context, or None. One attribute read — safe
    on the recorder-off hot path."""
    return getattr(_local, "ctx", None)


@contextmanager
def activate(ctx: Optional[TraceContext]):
    """Bind ``ctx`` as the thread's active context for the block
    (no-op when ctx is None, so call sites need no branch)."""
    if ctx is None:
        yield None
        return
    prev = getattr(_local, "ctx", None)
    _local.ctx = ctx
    try:
        yield ctx
    finally:
        _local.ctx = prev


def emit(recorder, path: str, seconds: float, *,
         ctx: Optional[TraceContext] = None,
         span_id: Optional[str] = None,
         parent_id: Optional[str] = None,
         root: bool = False, **fields) -> Optional[str]:
    """Write one ``trace_span`` event. ``ctx`` defaults to the thread's
    active context; with neither, this is a no-op (returns None).

    ``root=True`` reuses the context's preallocated span id as this
    span's OWN id with a null parent — the request/generation root.
    Otherwise a fresh span id is minted with ``parent_id`` defaulting to
    the context's span id."""
    ctx = ctx if ctx is not None else current()
    if ctx is None or not getattr(recorder, "enabled", False):
        return None
    if root:
        sid, pid = ctx.span_id, None
    else:
        sid = span_id or new_span_id()
        pid = parent_id if parent_id is not None else ctx.span_id
    recorder.event("trace_span", trace_id=ctx.trace_id, span_id=sid,
                   parent_id=pid, path=path,
                   seconds=round(float(seconds), 6), **fields)
    return sid


# --------------------------------------------------------- reconstruction

def trace_spans(events) -> List[dict]:
    """The ``trace_span`` rows of an event stream (list of dicts, e.g.
    from ``obs.report.load_run``)."""
    return [e for e in events if e.get("kind") == "trace_span"]


def traces_by_id(spans) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for s in spans:
        out.setdefault(s.get("trace_id", "?"), []).append(s)
    return out


def build_tree(spans) -> List[dict]:
    """Parent/child tree of one trace's spans: returns the roots, each a
    ``{"span": row, "children": [...]}`` node. Spans whose parent_id
    does not resolve (torn trail) surface as extra roots rather than
    vanishing."""
    by_id = {s["span_id"]: {"span": s, "children": []} for s in spans}
    roots = []
    for s in spans:
        node = by_id[s["span_id"]]
        pid = s.get("parent_id")
        if pid and pid in by_id and pid != s["span_id"]:
            by_id[pid]["children"].append(node)
        else:
            roots.append(node)
    for node in by_id.values():
        node["children"].sort(key=_start)
    roots.sort(key=_start)
    return roots


def _start(node) -> float:
    s = node["span"]
    return float(s.get("ts", 0.0)) - float(s.get("seconds", 0.0))


def render_waterfall(spans, width: int = 36) -> str:
    """Text waterfall of one trace: indent shows causality, the bar
    shows when inside the trace's wall the span ran (event ``ts`` is the
    span END; start = ts - seconds)."""
    if not spans:
        return "(no spans)"
    roots = build_tree(spans)
    t0 = min(_start(n) for n in _walk(roots))
    t1 = max(float(n["span"].get("ts", 0.0)) for n in _walk(roots))
    wall = max(t1 - t0, 1e-9)
    lines = [f"trace {spans[0].get('trace_id', '?')}  "
             f"wall {wall * 1e3:.2f} ms  ({len(spans)} spans)"]
    name_w = max(len(_label(n, d)) for n, d in _walk_depth(roots))
    for node, depth in _walk_depth(roots):
        s = node["span"]
        sec = float(s.get("seconds", 0.0))
        lo = int(round((_start(node) - t0) / wall * width))
        hi = int(round((_start(node) - t0 + sec) / wall * width))
        lo = min(max(lo, 0), width - 1)
        hi = min(max(hi, lo + 1), width)
        bar = " " * lo + "#" * (hi - lo) + " " * (width - hi)
        lines.append(f"  {_label(node, depth):<{name_w}}  "
                     f"{sec * 1e3:9.3f} ms  |{bar}|")
    return "\n".join(lines)


def _label(node, depth) -> str:
    return "  " * depth + str(node["span"].get("path", "?"))


def _walk(roots):
    for node in roots:
        yield node
        yield from _walk(node["children"])


def _walk_depth(roots, depth: int = 0):
    for node in roots:
        yield node, depth
        yield from _walk_depth(node["children"], depth + 1)


def critical_path(spans) -> dict:
    """Critical-path summary of one trace (an evolve generation or a
    serve request): root wall, per-child attribution, the bounding
    stage, and the attributed fraction. For generation traces the
    device/LLM idle split is read off the stage vocabulary: the device
    idles while the LLM drafts (``llm``), the LLM idles during
    everything else."""
    roots = [n for n in build_tree(spans) if not n["span"].get("parent_id")]
    if not roots:
        return {"ok": False, "reason": "no root span"}
    root = max(roots, key=lambda n: float(n["span"].get("seconds", 0.0)))
    wall = float(root["span"].get("seconds", 0.0))
    stages = {}
    for child in root["children"]:
        p = str(child["span"].get("path", "?")).rpartition("/")[2]
        stages[p] = stages.get(p, 0.0) + float(
            child["span"].get("seconds", 0.0))
    attributed = sum(stages.values())
    bounding = max(stages, key=stages.get) if stages else ""
    llm_s = stages.get("llm", 0.0)
    return {
        "ok": True,
        "trace_id": root["span"].get("trace_id"),
        "path": root["span"].get("path"),
        "wall_seconds": round(wall, 6),
        "stages": {k: round(v, 6) for k, v in sorted(stages.items())},
        "attributed_seconds": round(attributed, 6),
        "attributed_fraction": round(attributed / wall, 4) if wall else 0.0,
        "bounding_stage": bounding,
        "device_idle_seconds": round(llm_s, 6),
        "llm_idle_seconds": round(max(attributed - llm_s, 0.0), 6),
    }


def waterfall_complete(spans, require=SERVE_COMPONENTS) -> bool:
    """True when one trace's spans form a complete serve waterfall:
    exactly one resolvable root, every parent link resolves, and every
    required component path appears under it."""
    if not spans:
        return False
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans if not s.get("parent_id")]
    if len(roots) != 1:
        return False
    for s in spans:
        pid = s.get("parent_id")
        if pid and pid not in ids:
            return False
    leaves = {str(s.get("path", "")).rpartition("/")[2] for s in spans}
    return all(c in leaves for c in require)


# unambiguous names for the ``fks_tpu.obs`` namespace re-export
activate_trace = activate
current_trace = current
emit_span = emit
