"""The evolution ledger: one record per generation, streamed as written.

Threaded through ``FunSearch.evolve_generation``: the controller calls
``begin_generation()`` before the LLM stage and ``commit(stats)`` after
truncation. Each committed record is the full ``GenerationStats``
(fitness best/median/p10, admit/reject breakdown — dup-suppressed,
sandbox-fail, transpile-fail, rescore-fallback — LLM latency, eval wall
time) plus evaluator counter DELTAS for the generation:

- ``programs_compiled`` — unique XLA programs built (jit-tier candidates);
- ``vm_candidates``     — candidates served by the VM tier (no compile);
- ``vm_batches``        — batched one-launch-per-generation VM launches;
- ``vm_segments``       — host-loop segment dispatches from the segmented
                          (sharded or single-device) batched path;
- ``preflight_rejections``   — candidates the static pre-flight analyzer
                          (fks_tpu.analysis) rejected before sandbox/
                          transpile/compile spent anything on them;
- ``fingerprint_duplicates`` — candidates collapsed onto a batch sibling
                          by the normalized-AST fingerprint;
- ``evals_per_sec``     — generation eval throughput (new candidates over
                          eval wall seconds).

Records land in the run directory's ``metrics.jsonl`` (``kind=
"generation"``) and each commit refreshes the heartbeat file, so an
external watcher sees per-generation liveness. With the NullRecorder the
ledger is pure no-op arithmetic — zero filesystem writes.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from fks_tpu.obs.recorder import get_recorder

#: CodeEvaluator counters snapshotted per generation (missing attributes
#: read as 0, so the ledger also accepts reduced evaluator stand-ins)
EVALUATOR_COUNTERS = {
    "compile_count": "programs_compiled",
    "vm_count": "vm_candidates",
    "vm_batch_count": "vm_batches",
    "segments_dispatched": "vm_segments",
    "preflight_rejected": "preflight_rejections",
    "preflight_duplicates": "fingerprint_duplicates",
}


class EvolutionLedger:
    """Per-generation record builder bound to one recorder + evaluator."""

    def __init__(self, recorder=None, evaluator: Any = None):
        self.recorder = recorder if recorder is not None else get_recorder()
        self.evaluator = evaluator
        self._base: Dict[str, int] = self._counters()

    def _counters(self) -> Dict[str, int]:
        if self.evaluator is None:
            return {k: 0 for k in EVALUATOR_COUNTERS}
        return {k: int(getattr(self.evaluator, k, 0))
                for k in EVALUATOR_COUNTERS}

    def begin_generation(self) -> None:
        """Snapshot evaluator counters; deltas are computed at commit."""
        self._base = self._counters()

    def generation_record(self, stats) -> Dict[str, Any]:
        """The full ledger row for ``stats`` (a ``GenerationStats``): the
        dataclass fields verbatim — the ledger and the return value agree
        by construction — plus evaluator counter deltas and throughput."""
        rec: Dict[str, Any] = dataclasses.asdict(stats)
        now = self._counters()
        for counter, name in EVALUATOR_COUNTERS.items():
            rec[name] = now[counter] - self._base.get(counter, 0)
        if stats.eval_seconds > 0:
            rec["evals_per_sec"] = round(
                stats.new_candidates / stats.eval_seconds, 3)
        return rec

    def commit(self, stats) -> Dict[str, Any]:
        """Write the generation record (``metrics.jsonl``) and refresh the
        heartbeat. Returns the record (callers may also stream it to
        ``--metrics``)."""
        rec = self.generation_record(stats)
        self.recorder.metric("generation", rec)
        self.recorder.heartbeat()
        return rec
