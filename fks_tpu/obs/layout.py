"""Layout observability (fks_tpu.obs.layout).

The ROADMAP's parallelism-layout autotuner needs a priced search space
before it can search: the mapping of the three batchable axes —
candidates, scenarios, trace segments — onto the mesh was hard-coded
inside ``parallel/mesh.py``, and the pad-waste / collective / occupancy
costs of that choice were computed (``pad_stats``/``occupancy_stats``)
but never attributed to a NAMED layout, persisted, or compared. This
module makes layout a first-class, observable dimension:

- ``LayoutSpec`` — a declarative, canonicalizable spec naming which
  axes shard, which vmap, and the segment size (the FKS_VM_SEG_STEPS
  contract). The default spec reproduces the historical hard-coded
  behavior bit-identically (jaxpr-pinned in
  ``tests/fixtures/jaxpr_pins.json``, same discipline as the memory
  sampler's ``flat_step/mem_sampled`` pin).
- ``LayoutLedger``/``record_layout`` — a bounded process-wide ledger of
  per-layout cost rows: pad-waste per axis, occupancy, XLA
  ``cost_analysis`` bytes (collective bytes when the backend exposes a
  per-collective breakdown; total bytes-accessed otherwise), recorded
  both at wiring time AND at eval time so remainder-padding changes
  from dynamic population sizes show up. Identical repeat rows dedupe
  by layout key — steady-state traffic costs one row, not one per call.
- ``rollup_layouts`` — aggregation per (workload_key, mesh_layout,
  layout_key), joining predicted HBM from the PR-17 footprint ledger
  (``obs.memory.LEDGER``) by mesh layout.
- ``explore_layouts`` — enumerate the valid (candidate_shards x
  scenario_shards) factorizations of a device mesh for a given
  (population x suite) shape, run each through one warm probe, emit
  ``layout_probe`` metrics, and persist the best measured layout per
  (workload_key, device_count) into ``RunHistory`` as a prior for the
  future autotuner.

Surfaces: ``cli layout`` (ledger table / ``--explore``),
``fks_layout_*`` OpenMetrics gauges, a layout section in ``cli
report``, and ``bench.py --stage layout`` whose best-vs-default ratio
and pad-waste fraction are compare-gated keys.

Import discipline: module level is stdlib + the recorder only; jax and
``parallel.mesh`` load lazily inside functions (``parallel.mesh``
imports this module the same way — no cycle at import time).
"""
from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from fks_tpu.obs.recorder import get_recorder

#: the three batchable axes a layout maps (closed vocabulary — mirrored
#: stdlib-only in tools/check_jsonl_schema.py; keep in sync)
LAYOUT_AXES = ("candidates", "scenarios", "segments")

#: components that may file layout rows (closed vocabulary, mirrored in
#: tools/check_jsonl_schema.py; keep in sync)
LAYOUT_COMPONENTS = ("eval", "code_eval", "gen_step", "suite_eval",
                     "serve", "vm_serve", "portfolio_serve", "probe",
                     "bench")

_KEY_RE = re.compile(
    r"^shard\[(?P<shard>[a-z_,]*)\]\|vmap\[(?P<vmap>[a-z_,]*)\]"
    r"\|seg=(?P<seg>\d+)$")


@dataclasses.dataclass(frozen=True)
class LayoutSpec:
    """Declarative layout: which axes shard over the mesh, which vmap
    inside each shard, and the host-loop segment size.

    Rules (validated at construction):

    - every axis comes from the closed ``LAYOUT_AXES`` vocabulary;
    - ``candidates`` always shards (it is the problem's primary parallel
      dimension) and sharded axes stay locally vmapped inside their
      shard, so ``shard`` is a subset of ``vmap``;
    - ``segments`` never shards or vmaps — it is the segmented runner's
      HOST loop, carried here only as ``seg_steps`` (the
      FKS_VM_SEG_STEPS contract; 0 = single dispatch).
    """

    shard: Tuple[str, ...] = ("candidates",)
    vmap: Tuple[str, ...] = ("candidates",)
    seg_steps: int = 0

    def __post_init__(self):
        for field, axes in (("shard", self.shard), ("vmap", self.vmap)):
            if not isinstance(axes, tuple):
                object.__setattr__(self, field, tuple(axes))
                axes = getattr(self, field)
            for a in axes:
                if a not in LAYOUT_AXES:
                    raise ValueError(
                        f"unknown layout axis {a!r} in {field}; choose "
                        f"from {LAYOUT_AXES}")
            if len(set(axes)) != len(axes):
                raise ValueError(f"duplicate axes in {field}: {axes}")
            if "segments" in axes:
                raise ValueError(
                    "'segments' is the segmented runner's host loop — it "
                    f"cannot {field}; express it via seg_steps")
        if "candidates" not in self.shard:
            raise ValueError("'candidates' must shard (it is the only "
                             "always-parallel axis)")
        missing = set(self.shard) - set(self.vmap)
        if missing:
            raise ValueError(
                f"sharded axes must stay locally vmapped inside their "
                f"shard; {sorted(missing)} missing from vmap")
        if self.seg_steps < 0:
            raise ValueError(f"seg_steps {self.seg_steps} < 0")

    @property
    def key(self) -> str:
        """Canonical key (round-trips through ``parse_layout_key``).
        Axis ORDER is canonicalized to ``LAYOUT_AXES`` order, so two
        specs naming the same axes in different orders share a key."""
        shard = ",".join(a for a in LAYOUT_AXES if a in self.shard)
        vmap = ",".join(a for a in LAYOUT_AXES if a in self.vmap)
        return f"shard[{shard}]|vmap[{vmap}]|seg={self.seg_steps}"

    def describe(self) -> dict:
        return {"layout_key": self.key, "shard": list(self.shard),
                "vmap": list(self.vmap), "seg_steps": self.seg_steps}


def default_spec(*, scenarios: bool = False, seg_steps: int = 0
                 ) -> LayoutSpec:
    """The spec the hard-coded behavior always used: candidates shard
    over the mesh's pop axes, everything batchable vmaps inside the
    shard, segments stay a host loop. ``scenarios=True`` is the suite
    entry point's default (candidates x scenarios vmap lanes)."""
    vmap = ("candidates", "scenarios") if scenarios else ("candidates",)
    return LayoutSpec(shard=("candidates",), vmap=vmap,
                      seg_steps=int(seg_steps))


def parse_layout_key(key: str) -> LayoutSpec:
    """Inverse of ``LayoutSpec.key`` (raises ValueError on malformed or
    out-of-vocabulary keys — the schema checker's contract)."""
    m = _KEY_RE.match(key)
    if not m:
        raise ValueError(f"malformed layout key {key!r}")
    split = lambda s: tuple(a for a in s.split(",") if a)  # noqa: E731
    return LayoutSpec(shard=split(m.group("shard")),
                      vmap=split(m.group("vmap")),
                      seg_steps=int(m.group("seg")))


def tag_layout(fn, layout_key: str):
    """Best-effort tag of a compiled/compilable callable with its layout
    key (``_fks_layout_key``). Transform-returned callables may reject
    attribute assignment; the ledger row is the durable record, the tag
    is a convenience for downstream components (serve engines read it
    back into their footprint records)."""
    try:
        fn._fks_layout_key = layout_key
    except (AttributeError, TypeError):
        pass
    return fn


# ---------------------------------------------------------------- ledger


class LayoutLedger:
    """Bounded, thread-safe, process-wide ledger of layout cost rows.

    ``add`` dedupes: a row identical to the LAST row filed under the
    same (component, layout_key, mesh_layout, workload_key) is dropped —
    steady-state eval loops re-recording unchanged pad stats cost one
    row, while a CHANGED population size (different padding) lands a
    fresh row."""

    def __init__(self, cap: int = 512):
        self.cap = int(cap)
        self._rows: List[Dict[str, Any]] = []
        self._last: Dict[Tuple, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def add(self, rec: Dict[str, Any]) -> bool:
        key = (rec.get("component"), rec.get("layout_key"),
               rec.get("mesh_layout"), rec.get("workload_key"))
        with self._lock:
            if self._last.get(key) == rec:
                return False
            self._last[key] = dict(rec)
            self._rows.append(dict(rec))
            if len(self._rows) > self.cap:
                del self._rows[: len(self._rows) - self.cap]
        return True

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._rows]

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._last.clear()


#: the process-wide layout ledger (mirrors obs.memory.LEDGER)
LEDGER = LayoutLedger()


def cost_stats_of(compiled) -> dict:
    """Best-effort XLA ``cost_analysis`` summary for a compiled
    executable: ``cost_flops``/``cost_bytes_accessed`` totals plus
    ``collective_bytes`` when the backend publishes a per-collective
    breakdown (TPU backends do; CPU reports totals only, so the field
    is honestly absent there). ``{}`` when the analysis is unavailable
    — never an error."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — estimates are best-effort
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out = {}
    for key, name in (("flops", "cost_flops"),
                      ("bytes accessed", "cost_bytes_accessed")):
        v = cost.get(key)
        if v is not None:
            out[name] = float(v)
    coll = sum(float(v) for k, v in cost.items()
               if isinstance(v, (int, float)) and "collective" in k)
    if coll:
        out["collective_bytes"] = coll
    return out


def record_layout(component: str, spec, *, mesh=None, workload_key: str = "",
                  real_count: Optional[int] = None, scenarios: int = 1,
                  segments: int = 1, compiled=None, recorder=None,
                  **fields) -> Optional[dict]:
    """File one ``layout_ledger`` row in the process ledger and the
    active recorder. ``spec`` is a ``LayoutSpec`` or a canonical key
    string. With ``real_count`` and a mesh, pad/occupancy stats over the
    mesh's candidate shards are folded in (the EVAL-TIME path — call it
    per launch; the ledger dedupes identical repeats). ``compiled`` adds
    the executable's ``cost_analysis`` bytes. Returns the row, or None
    when it deduped away."""
    if component not in LAYOUT_COMPONENTS:
        raise ValueError(f"unknown layout component {component!r}; "
                         f"choose from {LAYOUT_COMPONENTS}")
    if isinstance(spec, str):
        spec = parse_layout_key(spec)
    from fks_tpu.obs.memory import mesh_layout_label
    rec: Dict[str, Any] = {
        "component": component,
        "layout_key": spec.key,
        "mesh_layout": mesh_layout_label(mesh),
        "workload_key": workload_key,
        "axes": [a for a in LAYOUT_AXES if a in spec.shard],
        "seg_steps": spec.seg_steps,
    }
    if real_count is not None:
        rec["real_count"] = int(real_count)
        if mesh is not None:
            from fks_tpu.parallel.mesh import num_shards, occupancy_stats
            rec.update(occupancy_stats(int(real_count), num_shards(mesh),
                                       scenarios=scenarios,
                                       segments=segments))
    if compiled is not None:
        rec.update(cost_stats_of(compiled))
    rec.update(fields)
    if not LEDGER.add(rec):
        return None
    r = recorder if recorder is not None else get_recorder()
    r.metric("layout_ledger", dict(rec))
    return rec


def rollup_layouts(records: Optional[Sequence[dict]] = None,
                   footprints: Optional[Sequence[dict]] = None
                   ) -> List[dict]:
    """Aggregate ledger rows per (workload_key, mesh_layout, layout_key):
    row counts, the latest pad/occupancy accounting, worst pad-waste
    seen, summed lane-step occupancy, best-effort cost bytes, and the
    predicted HBM claim joined from the footprint ledger
    (``obs.memory.LEDGER``) by mesh layout — the largest executable
    filed under that layout's mesh shape."""
    if records is None:
        records = LEDGER.records()
    if footprints is None:
        from fks_tpu.obs.memory import LEDGER as MEM_LEDGER
        footprints = MEM_LEDGER.records()
    hbm: Dict[str, int] = {}
    for f in footprints:
        ml = f.get("mesh_layout", "")
        hbm[ml] = max(hbm.get(ml, 0), int(f.get("total_bytes", 0)))
    groups: Dict[Tuple[str, str, str], List[dict]] = {}
    for r in records:
        k = (r.get("workload_key", ""), r.get("mesh_layout", ""),
             r.get("layout_key", ""))
        groups.setdefault(k, []).append(r)
    out = []
    for (wk, ml, lk), rows in sorted(groups.items()):
        launched = sum(int(r.get("launched_lane_steps", 0)) for r in rows)
        real = sum(int(r.get("real_lane_steps", 0)) for r in rows)
        padded = [r for r in rows if "pad_waste_fraction" in r]
        agg: Dict[str, Any] = {
            "workload_key": wk, "mesh_layout": ml, "layout_key": lk,
            "rows": len(rows),
            "components": sorted({r.get("component", "") for r in rows}),
            "pad_waste_fraction_max": max(
                (float(r["pad_waste_fraction"]) for r in padded),
                default=0.0),
            "occupancy": (real / launched) if launched else 1.0,
        }
        if padded:
            last = padded[-1]
            agg["real_count"] = int(last.get("real_count", 0))
            agg["padded_count"] = int(last.get("padded_count", 0))
        costs = [r for r in rows if "cost_bytes_accessed" in r]
        if costs:
            agg["cost_bytes_accessed"] = max(
                float(r["cost_bytes_accessed"]) for r in costs)
        colls = [r for r in rows if "collective_bytes" in r]
        if colls:
            agg["collective_bytes"] = max(
                float(r["collective_bytes"]) for r in colls)
        if ml in hbm:
            agg["predicted_hbm_bytes"] = hbm[ml]
        steadies = [float(r["steady_seconds"]) for r in rows
                    if "steady_seconds" in r]
        if steadies:
            agg["steady_seconds"] = min(steadies)
        compiles = [float(r["compile_seconds"]) for r in rows
                    if "compile_seconds" in r]
        if compiles:
            agg["compile_seconds"] = max(compiles)
        out.append(agg)
    return out


# -------------------------------------------------------------- explorer


def valid_layouts(num_devices: int, scenarios: int) -> List[dict]:
    """Enumerate the valid (candidate_shards x scenario_shards)
    factorizations of ``num_devices`` for a suite of ``scenarios``:
    every factor pair c*s == num_devices with s dividing the scenario
    count (the scenario axis shards without padding — scenario suites
    are small and authored, unlike populations, so remainder-padding
    them would silently skew the robust aggregate). s=1 is the default
    layout and always valid; ordering is s ascending, so the default
    comes first."""
    num_devices = int(num_devices)
    scenarios = int(scenarios)
    if num_devices < 1:
        raise ValueError(f"num_devices {num_devices} < 1")
    out = []
    for s in range(1, num_devices + 1):
        if num_devices % s:
            continue
        if s > 1 and (scenarios < s or scenarios % s):
            continue
        spec = (default_spec(scenarios=True) if s == 1 else
                LayoutSpec(shard=("candidates", "scenarios"),
                           vmap=("candidates", "scenarios")))
        out.append({"candidate_shards": num_devices // s,
                    "scenario_shards": s,
                    "mesh_shape": f"{num_devices // s}x{s}",
                    "spec": spec})
    return out


def explore_layouts(suite, *, devices=None, population: int = 64,
                    cfg=None, rc=None, elite_k: int = 8,
                    engine: str = "exact", recorder=None, history=None,
                    workload_key: str = "", reps: int = 2,
                    dominance_margin: float = 0.05) -> dict:
    """Measure every valid layout of (population x suite) over the given
    devices: one wiring + one cold call (compile) + ``reps`` warm calls
    per layout, a ``layout_probe`` metric each, parity of the robust
    scores against the default layout, and a summary with the two
    compare-gated keys (``layout_best_over_default``,
    ``layout_pad_waste_frac``). With ``history`` (a ``RunHistory``) the
    best measured layout persists per (workload_key, device_count) for
    read-back as a prior. Single-process CPU dryrun meshes time-slice
    one host, so steady-seconds deltas there rank layouts relatively;
    absolute speedups need real devices (PROFILE.md round 22)."""
    import jax
    import numpy as np

    from fks_tpu.models import parametric
    from fks_tpu.parallel.mesh import (
        layout_mesh, num_shards, pad_population, pad_stats,
    )
    from fks_tpu.scenarios.robust import RobustConfig, make_sharded_suite_eval
    from fks_tpu.sim.engine import SimConfig

    cfg = cfg if cfg is not None else SimConfig()
    rc = rc if rc is not None else RobustConfig()
    devices = list(devices) if devices is not None else list(jax.devices())
    ndev = len(devices)
    scn = len(suite)
    rec = recorder if recorder is not None else get_recorder()
    candidates = valid_layouts(ndev, scn)
    params = parametric.init_population(jax.random.PRNGKey(0),
                                        int(population))
    probes: List[dict] = []
    default_probe: Optional[dict] = None
    default_robust: Optional[Any] = None
    for lay in candidates:
        spec = lay["spec"]
        mesh = layout_mesh(devices, lay["scenario_shards"])
        ev = make_sharded_suite_eval(suite, mesh, cfg=cfg, rc=rc,
                                     elite_k=elite_k, engine=engine,
                                     layout=spec)
        padded, real = pad_population(params, num_shards(mesh))
        t0 = time.perf_counter()
        out = ev(padded, real)
        jax.block_until_ready(out)
        first = time.perf_counter() - t0
        steady = float("inf")
        for _ in range(max(1, int(reps))):
            t0 = time.perf_counter()
            out = ev(padded, real)
            jax.block_until_ready(out)
            steady = min(steady, time.perf_counter() - t0)
        robust = np.asarray(out[0])[:real]
        ps = pad_stats(real, num_shards(mesh))
        if default_probe is None:  # s=1 enumerates first
            parity = 0.0
            default_robust = robust
        else:
            parity = float(np.max(np.abs(robust - default_robust)))
        probe = {
            "layout_key": spec.key,
            "mesh_shape": lay["mesh_shape"],
            "workload_key": workload_key,
            "axes": [a for a in LAYOUT_AXES if a in spec.shard],
            "candidates": int(real),
            "scenarios": scn,
            "candidate_shards": lay["candidate_shards"],
            "scenario_shards": lay["scenario_shards"],
            "first_call_seconds": round(first, 4),
            "steady_seconds": round(steady, 6),
            "pad_waste_fraction": ps["pad_waste_fraction"],
            "parity_max_abs": parity,
        }
        rec.metric("layout_probe", dict(probe))
        record_layout("probe", spec, mesh=mesh, workload_key=workload_key,
                      real_count=real, scenarios=scn, recorder=rec,
                      steady_seconds=probe["steady_seconds"],
                      compile_seconds=round(max(0.0, first - steady), 4))
        probes.append(probe)
        if default_probe is None:
            default_probe = probe
    best = min(probes, key=lambda p: p["steady_seconds"])
    ratio = (default_probe["steady_seconds"] / best["steady_seconds"]
             if best["steady_seconds"] else 1.0)
    summary = {
        "workload_key": workload_key,
        "devices": ndev,
        "candidates": int(default_probe["candidates"]),
        "scenarios": scn,
        "layouts_probed": len(probes),
        "default_layout_key": default_probe["layout_key"],
        "best_layout_key": best["layout_key"],
        "best_mesh_shape": best["mesh_shape"],
        "default_steady_seconds": default_probe["steady_seconds"],
        "best_steady_seconds": best["steady_seconds"],
        "layout_best_over_default": round(ratio, 4),
        "layout_pad_waste_frac": best["pad_waste_fraction"],
        "parity_max_abs": max(p["parity_max_abs"] for p in probes),
        "default_dominated": (best["layout_key"]
                              != default_probe["layout_key"]
                              and ratio > 1.0 + dominance_margin),
        "probes": probes,
    }
    if history is not None:
        history.record_layout_prior(
            workload_key, str(ndev), best["layout_key"],
            {"mesh_shape": best["mesh_shape"],
             "steady_seconds": best["steady_seconds"],
             "layout_best_over_default": summary["layout_best_over_default"],
             "layout_pad_waste_frac": summary["layout_pad_waste_frac"]})
    return summary
