"""Memory observability: footprint ledger, watermark sampler, leak sentinel.

The simulator's real scaling wall is HBM, not FLOPs — yet until this
module the repo had no memory accounting: ``memory_stats()`` was an
opaque blob, ``memory_analysis()`` a discarded bench log line, and
nothing said whether 1,000 champion hot-swaps leak device buffers.
Three pillars, all host-side (zero effect on lowered programs — the
Python-static-flag convention, pinned as ``flat_step/mem_sampled``):

- **Executable-footprint ledger** — ``record_footprint`` captures a
  compiled program's ``memory_analysis()`` (temp / argument / output /
  generated-code bytes) as one ``memory_footprint`` metric per
  executable, tagged with its component (serve AOT ladder, VM capacity
  bucket, evolve tier, bench probe) and mesh layout. ``rollup``
  aggregates the ledger per (component, mesh_layout) into predicted-HBM
  totals, so ``parallel.mesh`` layouts become comparable by bytes
  before a single batch runs — the layout-autotuner's cost signal.
- **Watermark sampler** — ``WatermarkSampler`` records per-device
  ``memory_stats()`` watermarks (normalized keys, deltas vs the start
  fence), host RSS via ``resource.getrusage``, and optional
  ``tracemalloc`` top-N host attribution, as ``memory_watermark``
  metrics — interval-driven from a background thread, or per
  StageProfiler stage via the profiler's ``sampler=`` hook. Off by
  default; the disabled sampler is a shared no-op.
- **Leak sentinel** — ``LeakSentinel`` fences ``jax.live_arrays()``
  count/bytes around N iterations of a hot loop (serve batches, VM
  ``swap_program``, promotion cycles, evolve generations) and records a
  ``leak_check`` verdict against a drift tolerance. Two deterministic
  drills (``vm_swap_leak``, ``snapshot_cache_bound``) back the
  ``memory_gate`` in tools/run_full_suite.py.

Read back by ``cli mem`` (footprint ladder + watermark table), the
``cli report`` memory section, and the ``fks_mem_*`` OpenMetrics gauges.
"""
from __future__ import annotations

import contextlib
import gc
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from fks_tpu.obs.recorder import get_recorder
from fks_tpu.obs.telemetry import normalize_memory_stats

#: closed vocabulary for memory_footprint.component — which tier compiled
#: the executable (duplicated stdlib-only in tools/check_jsonl_schema.py;
#: tests pin the two copies against each other)
MEMORY_COMPONENTS = ("serve_aot", "serve_vm", "evolve", "bench")

#: closed vocabulary for leak_check.loop — which hot loop was fenced
LEAK_LOOPS = ("serve_batch", "vm_swap", "promotion", "evolve_generation",
              "drill")

#: canonical footprint byte keys, in ladder-rendering order
FOOTPRINT_KEYS = ("temp_bytes", "argument_bytes", "output_bytes",
                  "generated_code_bytes")

#: memory_analysis() attribute -> canonical ledger key
_ANALYSIS_ATTRS = (
    ("temp_size_in_bytes", "temp_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
)


# ------------------------------------------------------ footprint ledger


def footprint_of(compiled: Any) -> Optional[Dict[str, int]]:
    """The canonical byte footprint of a ``jax`` ``Compiled`` executable
    (or anything exposing ``memory_analysis()``): temp / argument /
    output / generated-code bytes plus their ``total_bytes`` sum — the
    executable's predicted steady-state HBM claim. None when the backend
    cannot price the program (the caller records nothing rather than a
    row of zeros)."""
    ma = getattr(compiled, "memory_analysis", None)
    if ma is None:
        return None
    try:
        stats = ma() if callable(ma) else ma
    except Exception:
        return None
    if stats is None:
        return None
    out: Dict[str, int] = {}
    for attr, key in _ANALYSIS_ATTRS:
        v = getattr(stats, attr, None) if not isinstance(stats, dict) \
            else stats.get(key, stats.get(attr))
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = int(v)
    if not any(k in out for k in FOOTPRINT_KEYS):
        return None
    for k in FOOTPRINT_KEYS:
        out.setdefault(k, 0)
    out["total_bytes"] = sum(out[k] for k in FOOTPRINT_KEYS)
    return out


def mesh_layout_label(mesh: Any) -> str:
    """A mesh's layout as a stable comparison key: ``"pop=4,scn=2"``
    from its axis shape (empty for single-device / no mesh)."""
    if mesh is None:
        return ""
    try:
        shape = mesh.shape
        return ",".join(f"{k}={int(v)}" for k, v in shape.items())
    except Exception:
        return ""


class FootprintLedger:
    """Bounded in-process ledger of recorded executable footprints —
    the roll-up source when no run dir is open. Thread-safe appends
    (serve compiles happen under batcher threads)."""

    def __init__(self, cap: int = 512):
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []

    def add(self, record: Dict[str, Any]) -> None:
        with self._lock:
            self._records.append(dict(record))
            if len(self._records) > self.cap:
                del self._records[: len(self._records) - self.cap]

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


#: the process-wide ledger every ``record_footprint`` lands in (alongside
#: the active flight recorder, when one is enabled)
LEDGER = FootprintLedger()


def record_footprint(component: str, exe_key: Any, compiled: Any = None, *,
                     footprint: Optional[Dict[str, int]] = None,
                     mesh: Any = None, recorder=None,
                     **fields) -> Optional[Dict[str, Any]]:
    """One ``memory_footprint`` record for a compiled executable: the
    ``footprint_of`` bytes tagged with ``component`` (closed vocabulary),
    a stable ``exe_key`` (e.g. ``"lanes=2,pods=8"``), and the mesh
    layout. Lands in the in-process ``LEDGER`` and, when recording, on
    the flight recorder. Returns the record, or None when the backend
    cannot price the program — callers never branch on it."""
    if component not in MEMORY_COMPONENTS:
        raise ValueError(f"unknown memory component {component!r} "
                         f"(expect one of {sorted(MEMORY_COMPONENTS)})")
    fp = footprint if footprint is not None else footprint_of(compiled)
    if fp is None:
        return None
    rec: Dict[str, Any] = {
        "component": component,
        "exe_key": str(exe_key),
        "mesh_layout": mesh_layout_label(mesh),
        **fp,
        **fields,
    }
    LEDGER.add(rec)
    r = recorder if recorder is not None else get_recorder()
    r.metric("memory_footprint", dict(rec))
    return rec


def rollup(records: Optional[List[Dict[str, Any]]] = None
           ) -> List[Dict[str, Any]]:
    """Per-(component, mesh_layout) aggregate over footprint records
    (default: the process ledger): executable count, per-key byte sums,
    the ``predicted_hbm_bytes`` total, and the single largest
    executable's temp claim — what makes two mesh layouts comparable by
    predicted HBM before either runs. Sorted largest-first."""
    recs = LEDGER.records() if records is None else records
    by: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for r in recs:
        key = (str(r.get("component", "")), str(r.get("mesh_layout", "")))
        a = by.setdefault(key, {
            "component": key[0], "mesh_layout": key[1], "executables": 0,
            "predicted_hbm_bytes": 0, "peak_temp_bytes": 0,
            **{k: 0 for k in FOOTPRINT_KEYS}})
        a["executables"] += 1
        for k in FOOTPRINT_KEYS:
            a[k] += int(r.get(k, 0))
        total = int(r.get("total_bytes",
                          sum(int(r.get(k, 0)) for k in FOOTPRINT_KEYS)))
        a["predicted_hbm_bytes"] += total
        a["peak_temp_bytes"] = max(a["peak_temp_bytes"],
                                   int(r.get("temp_bytes", 0)))
    return sorted(by.values(), key=lambda a: -a["predicted_hbm_bytes"])


# ----------------------------------------------------- watermark sampler


def host_rss_kb() -> int:
    """Peak resident set size of this process in KB (``ru_maxrss`` is KB
    on Linux, bytes on macOS — normalized to KB). 0 where the resource
    module is unavailable."""
    try:
        import resource
        import sys
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss // 1024) if sys.platform == "darwin" else int(rss)
    except Exception:  # pragma: no cover - platform without getrusage
        return 0


def _device_watermarks(base: Dict[int, Dict[str, int]]
                       ) -> List[Dict[str, Any]]:
    """Per-device normalized memory stats plus the delta vs the sampler's
    start fence. Non-reporting backends (CPU) contribute identity-only
    rows — present, so the table says 'this backend does not report'."""
    import jax

    out: List[Dict[str, Any]] = []
    for d in jax.devices():
        try:
            stats = normalize_memory_stats(d.memory_stats())
        except Exception:
            stats = None
        row: Dict[str, Any] = {"id": int(d.id), "platform": d.platform}
        if stats:
            row.update(stats)
            b = base.get(int(d.id), {})
            if "bytes_in_use" in stats and "bytes_in_use" in b:
                row["delta_bytes"] = (stats["bytes_in_use"]
                                      - b["bytes_in_use"])
        out.append(row)
    return out


class WatermarkSampler:
    """Low-overhead memory watermark recorder (module docstring).

    ``enabled=False`` (the default construction for instrumented paths)
    is the Python-static off path: ``start``/``stop``/``sample`` are
    no-ops, no thread exists, nothing is recorded — and because the
    sampler never touches tracing, programs lowered while a sampler runs
    are bit-identical (``flat_step/mem_sampled`` pin). Enabled, each
    ``sample(stage=...)`` lands one ``memory_watermark`` metric: host
    RSS, per-device normalized watermarks with deltas vs the start
    fence, and — when ``tracemalloc`` tracing is active or
    ``trace_host=True`` started it — the top-N allocation sites.

    ``interval_s > 0`` + ``start()`` runs a daemon thread sampling on
    that cadence (``stage="interval"``); ``sample`` stays callable
    inline (the StageProfiler ``sampler=`` hook calls it per stage).
    """

    def __init__(self, enabled: bool = True, interval_s: float = 0.0,
                 top_n: int = 5, trace_host: bool = False, recorder=None,
                 cap: int = 1024):
        self.enabled = bool(enabled)
        self.interval_s = float(interval_s)
        self.top_n = int(top_n)
        self.trace_host = bool(trace_host)
        self.recorder = recorder if recorder is not None else get_recorder()
        self.cap = int(cap)
        self.samples: List[Dict[str, Any]] = []
        self._base_rss_kb = 0
        self._base_dev: Dict[int, Dict[str, int]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._own_tracemalloc = False

    # ----- lifecycle

    def start(self) -> "WatermarkSampler":
        """Fence the baselines (RSS + per-device bytes_in_use) and, with
        an interval, launch the daemon sampling thread."""
        if not self.enabled:
            return self
        import jax

        self._base_rss_kb = host_rss_kb()
        self._base_dev = {}
        for d in jax.devices():
            try:
                stats = normalize_memory_stats(d.memory_stats())
            except Exception:
                stats = None
            if stats:
                self._base_dev[int(d.id)] = stats
        if self.trace_host:
            import tracemalloc
            if not tracemalloc.is_tracing():
                tracemalloc.start()
                self._own_tracemalloc = True
        if self.interval_s > 0 and self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="fks-mem-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=max(1.0, 2 * self.interval_s))
            self._thread = None
        if self._own_tracemalloc:
            import tracemalloc
            tracemalloc.stop()
            self._own_tracemalloc = False

    def __enter__(self) -> "WatermarkSampler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sample(stage="interval")

    # ----- sampling

    def _top_allocs(self) -> List[Dict[str, Any]]:
        import tracemalloc

        if not tracemalloc.is_tracing() or self.top_n <= 0:
            return []
        try:
            stats = tracemalloc.take_snapshot().statistics("lineno")
        except Exception:  # pragma: no cover - snapshot raced a stop()
            return []
        return [{"site": f"{s.traceback[0].filename}:"
                         f"{s.traceback[0].lineno}",
                 "kb": round(s.size / 1024.0, 1), "count": int(s.count)}
                for s in stats[: self.top_n]]

    def sample(self, stage: str = "") -> Dict[str, Any]:
        """One ``memory_watermark`` record for the current instant (empty
        dict when disabled — the no-op contract instrumented paths rely
        on)."""
        if not self.enabled:
            return {}
        rss = host_rss_kb()
        rec: Dict[str, Any] = {
            "stage": stage or "manual",
            "host_rss_kb": rss,
            "host_rss_delta_kb": rss - self._base_rss_kb,
            "devices": _device_watermarks(self._base_dev),
        }
        top = self._top_allocs()
        if top:
            rec["top_allocs"] = top
        self.samples.append(rec)
        if len(self.samples) > self.cap:
            del self.samples[: len(self.samples) - self.cap]
        self.recorder.metric("memory_watermark", dict(rec))
        return rec


#: shared disabled sampler — instrumented paths default to this, so
#: watermark sampling never needs an ``if sampler:`` guard (the
#: ``NULL_PROFILER`` pattern)
NULL_SAMPLER = WatermarkSampler(enabled=False)


# -------------------------------------------------------- leak sentinel


def live_array_stats() -> Dict[str, int]:
    """Count and total bytes of every live ``jax.Array`` in the process —
    the leak sentinel's fence reading. Arrays deleted mid-walk are
    skipped rather than raising."""
    import jax

    count = 0
    total = 0
    for a in jax.live_arrays():
        try:
            nb = int(a.nbytes)
        except Exception:
            continue
        count += 1
        total += nb
    return {"count": count, "bytes": total}


class LeakSentinel:
    """Fence ``live_arrays()`` around N iterations of a hot loop and
    record the drift verdict.

    Usage: ``fence()`` before the loop (after warmup — caches and
    constants allocated on first use are residency, not leaks), run the
    loop, then ``check(iterations)``: one ``leak_check`` metric with the
    count/byte drift and ``ok`` judged against the tolerances (default:
    ZERO net growth — the steady-state contract of donated batch buffers
    and content-hash caches). Both fences ``gc.collect()`` first so
    Python-side garbage holding device buffers can't masquerade as a
    device leak."""

    def __init__(self, loop: str, tolerance_count: int = 0,
                 tolerance_bytes: int = 0, recorder=None):
        if loop not in LEAK_LOOPS:
            raise ValueError(f"unknown leak loop {loop!r} "
                             f"(expect one of {sorted(LEAK_LOOPS)})")
        self.loop = loop
        self.tolerance_count = int(tolerance_count)
        self.tolerance_bytes = int(tolerance_bytes)
        self.recorder = recorder if recorder is not None else get_recorder()
        self.baseline: Optional[Dict[str, int]] = None
        self.result: Optional[Dict[str, Any]] = None

    def fence(self) -> Dict[str, int]:
        gc.collect()
        self.baseline = live_array_stats()
        return self.baseline

    def check(self, iterations: int) -> Dict[str, Any]:
        if self.baseline is None:
            raise RuntimeError("fence() before check()")
        gc.collect()
        now = live_array_stats()
        drift_count = now["count"] - self.baseline["count"]
        drift_bytes = now["bytes"] - self.baseline["bytes"]
        rec = {
            "loop": self.loop,
            "iterations": int(iterations),
            "drift_count": int(drift_count),
            "drift_bytes": int(drift_bytes),
            "baseline_count": self.baseline["count"],
            "baseline_bytes": self.baseline["bytes"],
            "ok": (drift_count <= self.tolerance_count
                   and drift_bytes <= self.tolerance_bytes),
        }
        self.result = rec
        self.recorder.metric("leak_check", dict(rec))
        return rec


@contextlib.contextmanager
def leak_fence(loop: str, iterations: int, tolerance_count: int = 0,
               tolerance_bytes: int = 0,
               recorder=None) -> Iterator[LeakSentinel]:
    """``with leak_fence("vm_swap", 50) as s: ...`` — fence on entry,
    check on clean exit; the verdict is ``s.result`` (never raises on
    drift: gating is the caller's call)."""
    s = LeakSentinel(loop, tolerance_count=tolerance_count,
                     tolerance_bytes=tolerance_bytes, recorder=recorder)
    s.fence()
    try:
        yield s
    finally:
        s.check(iterations)


# --------------------------------------------------------------- drills


def _drill_workload():
    """The test_vm_serve recipe: 8 nodes x 16 pods, deterministic."""
    from fks_tpu.data.synthetic import synthetic_workload

    return synthetic_workload(8, 16, seed=0)


def _drill_envelope():
    from fks_tpu.serve.artifact import ShapeEnvelope

    return ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2,
                         max_gpu_milli=1000)


def _drill_queries(n: int, pods: int = 3) -> List[List[dict]]:
    return [[{"cpu_milli": 10 + 7 * i + j, "memory_mib": 50 + 11 * j,
              "creation_time": j, "duration_time": 40}
             for j in range(pods)] for i in range(n)]


def drill_vm_swap_leak(swaps: int = 50, batches: int = 200,
                       recorder=None) -> Dict[str, Any]:
    """The ISSUE-17 gated drill: ``swaps`` consecutive ``swap_program``
    promotions alternating two champions, interleaved with ``batches``
    served batches, must show ZERO net ``live_arrays()`` growth — every
    swap frees the displaced program tables, every batch's buffers are
    donated or cache-hits. Warmup (one full swap cycle + a served batch
    per champion) happens BEFORE the fence: first-use constants and the
    snapshot-table cache are residency, not leaks."""
    from fks_tpu.funsearch import template
    from fks_tpu.serve.artifact import ChampionSpec
    from fks_tpu.serve.vm_engine import VMServeEngine

    champs = [
        ChampionSpec(code=template.fill_template("score = 1000"),
                     score=0.4, source="<drill-a>"),
        ChampionSpec(code=template.fill_template(
            "score = 1000 + (node.cpu_milli_left - pod.cpu_milli) "
            "/ max(1, node.cpu_milli_total)"), score=0.9,
            source="<drill-b>"),
    ]
    eng = VMServeEngine(champs[0], _drill_workload(),
                        envelope=_drill_envelope(), engine="flat")
    queries = _drill_queries(2)
    # warmup: compile the bucket, populate the snapshot cache, touch both
    # champions' first-use paths
    for c in (champs[1], champs[0]):
        eng.swap_program(c)
        eng.answer_batch(queries)
    sent = LeakSentinel("vm_swap", recorder=recorder)
    sent.fence()
    b = 0
    for i in range(int(swaps)):
        eng.swap_program(champs[(i + 1) % 2])
        while b * swaps < (i + 1) * batches:  # interleave evenly
            eng.answer_batch(queries)
            b += 1
    while b < int(batches):
        eng.answer_batch(queries)
        b += 1
    rec = sent.check(int(swaps) + b)
    return {"ok": bool(rec["ok"]), "drill": "vm_swap_leak",
            "swaps": int(swaps), "batches": b, **rec}


def drill_snapshot_cache_bound(max_bytes: int = 0,
                               recorder=None) -> Dict[str, Any]:
    """The PR-14 snapshot-table LRU must respect a configured BYTE
    ceiling, not just an entry count: stream distinct-content queries
    (each a cache miss) through an engine whose cache is capped at ~2
    tables' bytes and verify the resident total never exceeds the cap,
    eviction actually happened, and a re-sent recent query still hits."""
    from fks_tpu.funsearch import template
    from fks_tpu.serve.artifact import ChampionSpec, ServeEngine

    champ = ChampionSpec(code=template.fill_template("score = 1000"),
                         score=0.4, source="<drill>")
    probe = ServeEngine(champ, _drill_workload(),
                        envelope=_drill_envelope(), engine="flat")
    # distinct real pod counts -> distinct snapshot-trigger tables (the
    # table content is a function of the query's pod count, so counts
    # 1..8 inside the one pod bucket give 8 distinct cache entries)
    distinct = [[{"cpu_milli": 10 + j, "memory_mib": 50 + j,
                  "creation_time": j, "duration_time": 40}
                 for j in range(n)] for n in range(1, 9)]
    probe.answer_batch(distinct[:1])
    one_table = max(probe.snapshot_cache_bytes, 1)
    cap = int(max_bytes) or 2 * one_table
    eng = ServeEngine(champ, _drill_workload(), envelope=_drill_envelope(),
                      engine="flat", snapshot_cache_max_bytes=cap)
    over = 0
    for q in distinct:
        eng.answer_batch([q])
        if eng.snapshot_cache_bytes > cap:
            over += 1
    stats = eng.snapshot_cache_stats()
    hits0 = stats["hits"]
    eng.answer_batch([distinct[-1]])  # most recent survivor must hit
    stats = eng.snapshot_cache_stats()
    evicted = stats["misses"] > stats["entries"]
    rehit = stats["hits"] > hits0
    ok = over == 0 and evicted and rehit
    rec = {"ok": ok, "drill": "snapshot_cache_bound",
           "cap_bytes": cap, "over_cap_observations": over,
           "evicted": evicted, "recent_rehit": rehit, **stats}
    r = recorder if recorder is not None else get_recorder()
    r.metric("leak_check", loop="drill",
             iterations=len(distinct), drift_count=over,
             drift_bytes=max(0, stats["bytes"] - cap), ok=ok)
    return rec


#: drill name -> callable returning {"ok": bool, ...} — the ``cli mem
#: --drill`` / run_full_suite ``memory_gate`` dispatch table
DRILLS: Dict[str, Callable[..., Dict[str, Any]]] = {
    "vm_swap_leak": drill_vm_swap_leak,
    "snapshot_cache_bound": drill_snapshot_cache_bound,
}


def run_drill(name: str, **kw) -> Dict[str, Any]:
    """Run one named memory drill; raises ``KeyError`` on unknown names
    (the cli surfaces the legal set)."""
    if name not in DRILLS:
        raise KeyError(f"unknown memory drill {name!r} "
                       f"(expect one of {sorted(DRILLS)})")
    t0 = time.perf_counter()
    out = DRILLS[name](**kw)
    out["seconds"] = round(time.perf_counter() - t0, 3)
    return out
