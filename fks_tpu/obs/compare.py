"""Cross-run regression gating: diff two runs, exit nonzero on regression.

``cli compare BASELINE CANDIDATE`` (and ``bench.py --gate``) accept
either flight-recorder run DIRECTORIES or bench JSONL FILES (the
one-line headline contract or a ``round*_tpu.jsonl`` session log), pull
a common metric vocabulary out of each, and judge the candidate against
the baseline with per-metric thresholds:

- throughput (``evals_per_sec``/``code_evals_per_sec``): a RELATIVE drop
  beyond the tolerance (default 10%) is a regression — comfortably under
  the issue's 20% injected-regression bar while riding out rep noise;
- ``compile_seconds``: relative growth beyond 25% (compile time is the
  noisiest surface measured — persistent-cache hits halve it);
- fitness (``best_score``/``median_score``) and ``parity_max_drift``:
  ABSOLUTE drift beyond 1e-5 — the engines are deterministic, so any
  real movement is a code change, not noise;
- ``watchdog_violations``/``alerts``: ANY increase is a regression.

A metric present in only one run is reported but never fails the gate
(bench files don't carry fitness; evolve runs don't carry headline
throughput). Verdict rows come back structured for tests and rendered
as a table for humans.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Threshold:
    """How one metric is judged. ``higher_is_better`` sets the regression
    direction; ``rel`` is a relative tolerance on the bad-direction move,
    ``abs_tol`` an absolute one (either alone, or both — the move must
    exceed BOTH to regress, so abs_tol doubles as a noise floor)."""

    higher_is_better: bool = True
    rel: Optional[float] = None
    abs_tol: Optional[float] = None


#: the default gate (see module docstring for rationale)
DEFAULT_THRESHOLDS: Dict[str, Threshold] = {
    "evals_per_sec": Threshold(higher_is_better=True, rel=0.10),
    "code_evals_per_sec": Threshold(higher_is_better=True, rel=0.10),
    "compile_seconds": Threshold(higher_is_better=False, rel=0.25,
                                 abs_tol=0.5),
    "best_score": Threshold(higher_is_better=True, abs_tol=1e-5),
    "median_score": Threshold(higher_is_better=True, abs_tol=1e-5),
    "parity_max_drift": Threshold(higher_is_better=False, abs_tol=1e-5),
    "watchdog_violations": Threshold(higher_is_better=False, abs_tol=0.0),
    "alerts": Threshold(higher_is_better=False, abs_tol=0.0),
    # eval-budget allocation (bench stage_budget): pruned-vs-full device
    # seconds per generation must not regress by more than 10%, and the
    # pruned run's champion must keep matching the full run's (0/1 flag)
    "budget_speedup": Threshold(higher_is_better=True, rel=0.10),
    "budget_champion_match": Threshold(higher_is_better=True, abs_tol=0.0),
    # large-cluster scale tier (bench stage_scale1k): 1k-node x 100k-pod
    # completion throughput on the flat engine must not drop >10%
    "scale1k_events_per_sec": Threshold(higher_is_better=True, rel=0.10),
    # champion serving (bench stage_serve): warm tail latency must not
    # inflate more than 25% (with a 2 ms noise floor — CPU timer jitter
    # at millisecond scale), and batched throughput must not drop >10%
    "serve_p99_ms": Threshold(higher_is_better=False, rel=0.25, abs_tol=2.0),
    "serve_qps": Threshold(higher_is_better=True, rel=0.10),
    # mesh-sharded serving (bench stage_serve --devices): global
    # throughput across the device mesh must not drop >10%, and the
    # per-query upload volume (post-packing, snapshot-cache-discounted)
    # must not regress — growth means packing broke or the cache stopped
    # hitting (64-byte floor absorbs padding jitter at tiny shapes)
    "serve_sharded_qps": Threshold(higher_is_better=True, rel=0.10),
    "serve_h2d_bytes_per_query": Threshold(higher_is_better=False,
                                           rel=0.0, abs_tol=64.0),
    # causal tracing (bench stage_serve): per-request trace emission must
    # stay within noise of the untraced service path — more than a
    # 2-point absolute jump in overhead means the null/hot path grew a
    # real cost (the value is already a percentage, so abs only)
    "trace_overhead_pct": Threshold(higher_is_better=False, abs_tol=2.0),
    # VM-native promotion (bench stage_promote): the zero-rebuild swap
    # must stay a swap — transpile + pack + H2D only. Latency gets the
    # serve_p99_ms treatment (25% rel with a 2 ms CPU-jitter floor);
    # the swap's device traffic must not regress at all beyond a
    # 64-byte padding-jitter floor — growth means program packing broke
    "promotion_swap_ms": Threshold(higher_is_better=False, rel=0.25,
                                   abs_tol=2.0),
    "vm_swap_h2d_bytes": Threshold(higher_is_better=False,
                                   rel=0.0, abs_tol=64.0),
    # memory budgets (obs.memory / bench stages): the run's peak
    # predicted device bytes and the largest executable's XLA scratch
    # claim must not grow — one 4 KiB page of absolute floor absorbs
    # buffer-assignment jitter at tiny CPU shapes, any real growth gates
    "peak_device_bytes": Threshold(higher_is_better=False, rel=0.0,
                                   abs_tol=4096.0),
    "exe_temp_bytes": Threshold(higher_is_better=False, rel=0.0,
                                abs_tol=4096.0),
    # static pre-flight (bench stage_preflight): the fraction of the
    # candidate stream rejected before sandbox/transpile must not drop
    # more than 5 points — a drop means the analyzer stopped catching a
    # junk class it used to catch (absolute: the rate is already a ratio)
    "preflight_reject_rate": Threshold(higher_is_better=True, abs_tol=0.05),
    # sustained multi-tenant load (bench stage_loadgen): throughput and
    # the Jain fairness index over per-tenant goodput must not drop,
    # tail latency and shed rate must not grow. qps/p99 get the serve
    # treatment; shed rate and fairness are already ratios, so absolute
    # tolerances (2 points of shed, 5 points of fairness) absorb
    # scheduling jitter in short deterministic runs
    "loadgen_qps": Threshold(higher_is_better=True, rel=0.10),
    "loadgen_p99_ms": Threshold(higher_is_better=False, rel=0.25,
                                abs_tol=2.0),
    "loadgen_shed_rate": Threshold(higher_is_better=False, abs_tol=0.02),
    "loadgen_fairness_index": Threshold(higher_is_better=True,
                                        abs_tol=0.05),
    # portfolio serving (bench stage_portfolio): routed multi-champion
    # throughput through the shared slot-vmapped executable must not
    # drop >10%, and the mid-traffic slot promotion must stay a table
    # upload — same latency treatment as the single-slot swap (25% rel
    # with a 2 ms CPU-jitter floor)
    "portfolio_qps": Threshold(higher_is_better=True, rel=0.10),
    "portfolio_slot_swap_ms": Threshold(higher_is_better=False, rel=0.25,
                                        abs_tol=2.0),
    # layout explorer (bench stage_layout): best-measured-over-default
    # steady ratio must not drop more than 10 points (a drop means the
    # default layout got relatively worse, or the explorer stopped
    # finding the better layout it used to find), and the best layout's
    # padded-lane waste must not grow more than 5 points — both are
    # already ratios, so absolute tolerances absorb single-host
    # time-slicing jitter on the dryrun mesh
    "layout_best_over_default": Threshold(higher_is_better=True,
                                          abs_tol=0.10),
    "layout_pad_waste_frac": Threshold(higher_is_better=False,
                                       abs_tol=0.05),
}


def _num(v: Any) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def _from_run_dir(run_dir: str) -> Dict[str, float]:
    from fks_tpu.obs.report import load_run

    _meta, events, metrics = load_run(run_dir)
    out: Dict[str, float] = {}
    gens = [m for m in metrics if m.get("kind") == "generation"]
    if gens:
        bests = [v for v in (_num(g.get("best_score")) for g in gens)
                 if v is not None]
        if bests:
            out["best_score"] = max(bests)
        med = _num(gens[-1].get("median_score"))
        if med is not None:
            out["median_score"] = med
        eps = [v for v in (_num(g.get("evals_per_sec")) for g in gens)
               if v is not None]
        if eps:
            out["evals_per_sec"] = max(eps)
    for m in metrics:
        if m.get("kind") != "bench_stage":
            continue
        for key in ("evals_per_sec", "code_evals_per_sec",
                    "budget_speedup", "budget_champion_match",
                    "scale1k_events_per_sec", "serve_qps",
                    "serve_sharded_qps", "preflight_reject_rate",
                    "loadgen_qps", "loadgen_fairness_index",
                    "portfolio_qps", "layout_best_over_default"):
            v = _num(m.get(key))
            if v is not None:
                out[key] = max(out.get(key, 0.0), v)
        # latency/upload volume/trace cost: best (lowest) observation,
        # mirroring serve_qps's max
        for key in ("serve_p99_ms", "serve_h2d_bytes_per_query",
                    "trace_overhead_pct", "promotion_swap_ms",
                    "vm_swap_h2d_bytes", "loadgen_p99_ms",
                    "loadgen_shed_rate", "portfolio_slot_swap_ms",
                    "layout_pad_waste_frac"):
            v = _num(m.get(key))
            if v is not None:
                out[key] = min(out.get(key, v), v)
        # memory budgets: WORST (highest) observation — a peak metric's
        # whole point is the high-water mark, so the gate judges the
        # largest claim any stage recorded
        for key in ("peak_device_bytes", "exe_temp_bytes"):
            v = _num(m.get(key))
            if v is not None:
                out[key] = max(out.get(key, 0.0), v)
        v = _num(m.get("compile_seconds"))
        if v is not None:
            out["compile_seconds"] = out.get("compile_seconds", 0.0) + v
    drifts = [v for v in (_num(m.get("max_drift")) for m in metrics
                          if m.get("kind") == "parity") if v is not None]
    if drifts:
        out["parity_max_drift"] = max(drifts)
    if "compile_seconds" not in out:
        compile_s = sum(float(e.get("seconds", 0.0)) for e in events
                        if e.get("kind") == "compile")
        if compile_s:
            out["compile_seconds"] = compile_s
    out["watchdog_violations"] = float(sum(
        1 for e in events if e.get("kind") == "watchdog"))
    out["alerts"] = float(sum(1 for e in events if e.get("kind") == "alert"))
    return out


def _from_jsonl(path: str, allow_stale: bool = False) -> Dict[str, float]:
    """Best metrics out of a bench JSONL: the headline contract line maps
    ``value`` (unit evals/s) onto ``evals_per_sec``; session-log rows
    (``{"ok", "stage", "result": {...}}``) contribute their result dict;
    a 0.0-with-``banked_from`` fallback line contributes NOTHING to the
    headline throughput (nothing was measured that run). A STALE headline
    (``stale_from_run`` marker: a failed probe carrying the last healthy
    historical value, fks_tpu.obs.history) counts only when
    ``allow_stale`` — as a BASELINE denominator it is real evidence, as a
    candidate it would mask the very failure it records."""
    out: Dict[str, float] = {}

    def take(rec: Dict[str, Any], stale: bool = False) -> None:
        for key in ("evals_per_sec", "code_evals_per_sec",
                    "compile_seconds", "best_score", "median_score",
                    "parity_max_drift", "budget_speedup",
                    "budget_champion_match", "scale1k_events_per_sec",
                    "serve_p99_ms", "serve_qps", "serve_sharded_qps",
                    "serve_h2d_bytes_per_query", "preflight_reject_rate",
                    "trace_overhead_pct", "promotion_swap_ms",
                    "vm_swap_h2d_bytes", "peak_device_bytes",
                    "exe_temp_bytes", "loadgen_qps", "loadgen_p99_ms",
                    "loadgen_shed_rate", "loadgen_fairness_index",
                    "portfolio_qps", "portfolio_slot_swap_ms",
                    "layout_best_over_default", "layout_pad_waste_frac"):
            v = _num(rec.get(key))
            if v is None:
                continue
            # memory budgets on a STALE fallback line are carried-forward
            # donor evidence, not a live measurement — the same baseline-
            # only asymmetry as the stale headline (take() runs on every
            # record, so the guard must live here, not at the call site)
            if (stale and not allow_stale
                    and key in ("peak_device_bytes", "exe_temp_bytes")):
                continue
            if key in ("compile_seconds", "serve_p99_ms",
                       "serve_h2d_bytes_per_query", "trace_overhead_pct",
                       "promotion_swap_ms", "vm_swap_h2d_bytes",
                       "loadgen_p99_ms", "loadgen_shed_rate",
                       "portfolio_slot_swap_ms"):
                out[key] = min(out.get(key, v), v)
            elif key in ("peak_device_bytes", "exe_temp_bytes"):
                # peak metrics: the high-water mark across records
                out[key] = max(out.get(key, 0.0), v)
            else:
                out[key] = max(out.get(key, v), v)

    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # prose/torn lines ride along in bench logs
            if not isinstance(rec, dict):
                continue
            if rec.get("unit") == "evals/s" and "value" in rec:
                v = _num(rec["value"])
                # the fallback contract: value 0.0 means "not measured";
                # stale (carried-forward) values count for baselines only
                if v and (allow_stale or "stale_from_run" not in rec):
                    out["evals_per_sec"] = max(
                        out.get("evals_per_sec", 0.0), v)
            stale = "stale_from_run" in rec
            take(rec, stale=stale)
            if isinstance(rec.get("result"), dict):
                take(rec["result"], stale=stale)
    return out


def extract_metrics(path: str, allow_stale: bool = False) -> Dict[str, float]:
    """The comparator's metric vocabulary for a run dir or a JSONL file.
    ``allow_stale`` admits carried-forward bench headlines (baseline
    side only — see ``_from_jsonl``)."""
    if os.path.isdir(path):
        return _from_run_dir(path)
    return _from_jsonl(path, allow_stale=allow_stale)


def _judge(name: str, a: float, b: float, th: Threshold) -> str:
    """OK / REGRESSION / IMPROVED for candidate ``b`` vs baseline ``a``."""
    delta = b - a if th.higher_is_better else a - b  # >0 = better
    if delta >= 0:
        return "IMPROVED" if delta > 0 else "OK"
    worse = -delta
    over_abs = th.abs_tol is None or worse > th.abs_tol
    over_rel = th.rel is None or (abs(a) > 0 and worse / abs(a) > th.rel)
    if th.abs_tol is None and th.rel is None:
        return "OK"  # informational metric, never gates
    # when both bounds are set the move must exceed both (abs = noise floor)
    return "REGRESSION" if over_abs and over_rel else "OK"


def compare_runs(baseline: str, candidate: str,
                 thresholds: Optional[Dict[str, Threshold]] = None,
                 ) -> List[Dict[str, Any]]:
    """Verdict rows for candidate vs baseline; a row per metric seen in
    either: ``{"metric", "baseline", "candidate", "status"}`` with status
    OK / IMPROVED / REGRESSION / BASELINE-ONLY / CANDIDATE-ONLY."""
    thresholds = thresholds if thresholds is not None else DEFAULT_THRESHOLDS
    # stale asymmetry: a carried-forward headline is a legitimate
    # DENOMINATOR (the last healthy measurement) but never a legitimate
    # candidate (it would hide the failed probe it stands in for)
    a = extract_metrics(baseline, allow_stale=True)
    b = extract_metrics(candidate)
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(a) | set(b), key=lambda n: (
            n not in thresholds, n)):
        av, bv = a.get(name), b.get(name)
        if av is None or bv is None:
            status = "BASELINE-ONLY" if bv is None else "CANDIDATE-ONLY"
        elif name not in thresholds:
            status = "OK"
        else:
            status = _judge(name, av, bv, thresholds[name])
        rows.append({"metric": name, "baseline": av, "candidate": bv,
                     "status": status})
    return rows


def has_regression(rows: List[Dict[str, Any]]) -> bool:
    return any(r["status"] == "REGRESSION" for r in rows)


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v == int(v) and abs(v) < 1e9:
        return str(int(v))
    return f"{v:.6g}"


def format_comparison(rows: List[Dict[str, Any]], baseline: str,
                      candidate: str) -> str:
    """Human-readable verdict table + one-line summary."""
    lines = [f"baseline:  {baseline}", f"candidate: {candidate}", ""]
    w = max((len(r["metric"]) for r in rows), default=6)
    lines.append(f"{'metric':<{w}}  {'baseline':>12}  {'candidate':>12}  "
                 "verdict")
    for r in rows:
        lines.append(f"{r['metric']:<{w}}  {_fmt(r['baseline']):>12}  "
                     f"{_fmt(r['candidate']):>12}  {r['status']}")
    n_reg = sum(1 for r in rows if r["status"] == "REGRESSION")
    lines.append("")
    lines.append("REGRESSION: "
                 + ", ".join(r["metric"] for r in rows
                             if r["status"] == "REGRESSION")
                 if n_reg else "no regressions")
    return "\n".join(lines)


def parse_threshold_overrides(spec: str) -> Dict[str, Threshold]:
    """``--threshold metric=rel:0.2`` / ``metric=abs:1e-4`` overrides,
    comma-separated, on top of the defaults."""
    out = dict(DEFAULT_THRESHOLDS)
    for item in (s for s in spec.split(",") if s.strip()):
        name, _, bound = item.partition("=")
        kind, _, val = bound.partition(":")
        name = name.strip()
        base = out.get(name, Threshold())
        if kind.strip() == "rel":
            out[name] = dataclasses.replace(base, rel=float(val),
                                            abs_tol=None)
        elif kind.strip() == "abs":
            out[name] = dataclasses.replace(base, abs_tol=float(val),
                                            rel=None)
        else:
            raise ValueError(
                f"bad threshold {item!r} (want metric=rel:X or metric=abs:X)")
    return out
