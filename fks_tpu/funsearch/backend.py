"""Fitness backend for code candidates: transpile -> jit -> evaluate.

TPU-native replacement for the reference's subprocess fitness fan-out
(reference: funsearch/funsearch_integration.py:30-64 ``evaluate_policy_
standalone`` + 535-562 ProcessPoolExecutor): instead of forking a process
per candidate that re-parses the trace CSVs and runs the Python event loop,
each unique candidate is transpiled once into a vectorized policy, jitted
against the device-resident workload, and executed on-chip. The trace is
parsed once for the life of the backend; repeated/near-identical candidates
hit an AST-keyed compile cache (SURVEY.md §7: dedup doubles as compile-cache
key).

Failure semantics follow the reference's subprocess path: any failure —
validation, transpile, or execution — maps to fitness 0.0 and the candidate
stays in the pool's view (reference: funsearch_integration.py:63-64;
SURVEY.md §2 fine print 8).

Three throughput tiers:
- VM candidates (default): the candidate's jaxpr is lowered to a register
  program (fks_tpu.funsearch.vm) and interpreted by ONE engine program
  compiled once per evaluator — a fresh candidate costs a device run, not
  an XLA compile; with ``mesh=`` (a >1-device population mesh) the stacked
  generation is SHARDED over the mesh via
  fks_tpu.parallel.mesh.make_sharded_code_eval, each device interpreting
  its shard of the batch;
- jit candidates (fallback): one compiled program per unique AST, for the
  rare candidate outside the VM vocabulary;
- parametric candidates: one program TOTAL for the whole population
  (fks_tpu.parallel.population / .mesh) — the fast path the evolution
  controller uses for weight-vector mutation between LLM rounds.
"""
from __future__ import annotations

import concurrent.futures
import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

import dataclasses as _dc

from fks_tpu import obs
from fks_tpu.data.entities import Workload
from fks_tpu.funsearch import transpiler, vm
from fks_tpu.sim.engine import SimConfig
from fks_tpu.sim.types import SimResult
from fks_tpu.utils.segments import validate_seg_steps


@dataclasses.dataclass
class EvalRecord:
    """One candidate's evaluation outcome."""

    code: str
    score: float
    error: Optional[str] = None  # why fitness is 0, when it is
    result: Optional[SimResult] = None
    # scenario-suite evaluations only: the per-scenario fitness vector the
    # composite ``score`` was folded from, and the fold that produced it
    scenario_scores: Optional[List[float]] = None
    aggregation: Optional[str] = None
    # budget-allocated evaluations (fks_tpu.funsearch.budget): the rung
    # this record's fidelity comes from — 0 = pruned at the probe rung
    # (score is the capped probe aggregate), 1 = survived to the full
    # suite; None on unbudgeted evaluations
    budget_rung: Optional[int] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class CodeEvaluator:
    """Evaluate candidate source strings against one workload.

    The compile cache maps canonical AST keys to jitted run functions, so a
    re-submitted (or whitespace-variant) candidate costs one device launch,
    not a retrace. XLA's own jit cache adds a second layer keyed on the
    traced computation.
    """

    VM_CAPACITY = 512  # op budget; longer programs use the jit tier

    def __init__(self, workload: Workload, cfg: SimConfig = SimConfig(),
                 max_workers: Optional[int] = None, use_vm: bool = True,
                 engine: str = "exact", vm_batch: Optional[bool] = None,
                 mesh=None, suite=None, robust=None, budget=None,
                 preflight: bool = True, fp_dedup: bool = True,
                 profiler=None):
        from fks_tpu.sim import get_engine

        self.workload = workload
        # Device-time attribution (fks_tpu.obs.profiler): when an enabled
        # StageProfiler is passed, evaluate() fences and attributes its
        # sandbox+preflight / transpile / device-eval stages; the default
        # NULL_PROFILER keeps every stage a no-op with no fences.
        self.profiler = (profiler if profiler is not None
                         else obs.NULL_PROFILER)
        self.cfg = cfg
        self.engine = engine
        self._mod = get_engine(engine)
        # Scenario-suite mode (fks_tpu.scenarios): with ``suite`` (a
        # materialized ScenarioSuite over this workload) every candidate is
        # evaluated on ALL scenarios in one vmapped program and scored by
        # the composite robust aggregate; EvalRecords carry the
        # per-scenario breakdown. The jitted fused kernel has no fault
        # vocabulary (sim/fused.py rejects fault workloads), so suite mode
        # requires the exact or flat engine.
        self.suite = suite
        self.robust = robust
        # Eval-budget allocation (fks_tpu.funsearch.budget): with an
        # enabled BudgetConfig the batched VM tier spends its device
        # budget in rungs — the whole generation on a cheap probe, only
        # the surviving 1/eta fraction on the full suite.
        self.budget = budget if (budget is not None
                                 and budget.enabled) else None
        self.last_budget_stats: List[dict] = []  # per-rung, last evaluate()
        if self.budget is not None and engine == "fused":
            raise ValueError(
                "budget-pruned rungs (fks_tpu.funsearch.budget) are not "
                "supported in the fused kernel (probe scoring and fault "
                "suites have no Pallas lowering); run budget-allocated "
                "suite evaluation with engine='exact' or 'flat'")
        if self.budget is not None and suite is None:
            raise ValueError(
                "budget allocation prunes between a probe suite and the "
                "full suite, so it requires suite mode; set "
                "EvolutionConfig.scenario_suite (cli evolve --suite)")
        if suite is not None:
            if engine == "fused":
                raise ValueError(
                    "scenario suites are not supported on the fused "
                    "engine (fault events have no Pallas lowering); use "
                    "engine='exact' or 'flat'")
            if robust is None:
                from fks_tpu.scenarios.robust import RobustConfig
                self.robust = RobustConfig()
        self.state0 = self._mod.initial_state(workload, cfg)
        self._cache: Dict[str, object] = {}
        self._lock = threading.Lock()
        self.compile_count = 0  # observability: unique programs built
        self.vm_count = 0  # candidates served by the VM tier (no compile)
        # Static pre-flight (fks_tpu.analysis.candidate): reject candidates
        # the transpiler provably cannot lower BEFORE sandbox/transpile/
        # compile spend anything on them, and collapse normalized-AST
        # fingerprint duplicates within a batch onto one representative.
        # Both paths emit ``candidate_rejected`` ledger events with a
        # machine-readable taxonomy.
        self.preflight = preflight
        self.fp_dedup = fp_dedup
        self.preflight_rejected = 0  # counters: ledger reads deltas
        self.preflight_duplicates = 0
        # observability: host-loop segment dispatches from the segmented
        # batched runners (fks_tpu.obs ledger reads per-generation deltas)
        self.segments_dispatched = 0
        self.last_eval_stats: Dict[str, int] = {}  # most recent evaluate()
        self.max_workers = max_workers or min(8, os.cpu_count() or 1)
        self.use_vm = use_vm
        self._vm_run = None  # lazily built shared engine program
        self._vm_pop_run = None  # lazily built POPULATION engine program
        self._vm_mesh_run = None  # lazily built SHARDED population program
        self._budget_eval = None  # lazily built rung ladder (budget mode)
        self.vm_batch_count = 0  # observability: batched VM launches
        # Mesh-sharded batched tier: with a >1-device mesh each device
        # interprets its shard of the stacked generation
        # (parallel.mesh.make_sharded_code_eval) — the jit/parametric
        # tiers and single-device behavior are unchanged.
        self.mesh = mesh
        from fks_tpu.parallel.mesh import num_shards
        self._n_shards = num_shards(mesh) if mesh is not None else 1
        # Batched VM evaluation: under vmap the interpreter's lax.switch
        # over a per-lane opcode executes ALL ~40 branches and selects.
        # On TPU each branch is one elementwise vreg op — noise next to
        # the engine step — so a generation as ONE launch wins; on a CPU
        # host the same 40x op fan-out runs serially and loses badly to
        # the sequential unbatched VM tier. Auto: batch iff the default
        # backend is an accelerator — or a multi-device mesh was passed,
        # which only the batched tier can use.
        if vm_batch is None:
            vm_batch = (jax.default_backend() != "cpu"
                        or self._n_shards > 1
                        # the budget rung ladder IS a batched-tier
                        # construct (one stacked launch per rung); with
                        # an enabled budget the pruning win dominates the
                        # CPU switch-fan-out loss, so batch there too
                        or self.budget is not None)
        self.vm_batch = vm_batch
        # Bounded device-call length for the batched tier (flat engine
        # only): the axon TPU tunnel kills single device executions over
        # ~60 s (bench.py protocol), and a full-trace batched-VM launch
        # can exceed that regardless of population size. 0 disables.
        seg = os.environ.get("FKS_VM_SEG_STEPS")
        if seg is not None:
            self.vm_seg_steps = validate_seg_steps(
                seg, source="FKS_VM_SEG_STEPS")
        else:
            self.vm_seg_steps = (
                4096 if jax.default_backend() == "tpu" else 0)
        # double-buffered segment handoff (flat.make_segmented_population
        # _run): dispatch segment i+1 before syncing segment i's all-done
        # flag, so the device never stalls on the host round-trip. On by
        # default (results are pinned identical); FKS_VM_DOUBLE_BUFFER=0
        # restores the classic sync-per-segment loop for debugging.
        self.vm_double_buffer = (
            os.environ.get("FKS_VM_DOUBLE_BUFFER", "1") not in ("0", ""))

    # ----- VM tier: one engine program, candidates as data

    def _vm_runner(self):
        if self._vm_run is None:
            if self.suite is not None:
                # one candidate x all scenarios in one vmapped program;
                # cond_policy stays off — under the trace vmap a lax.cond
                # runs both branches anyway
                from fks_tpu.scenarios.robust import make_suite_eval
                ev = make_suite_eval(self.suite, vm.score, self.cfg,
                                     engine=self.engine)
                self._vm_run = lambda prog, _s: ev(prog)
            else:
                # the VM interpreter is expensive per event; skip it on
                # deletions (cond_policy) — this tier runs unbatched, where
                # lax.cond executes one branch
                cfg = _dc.replace(self.cfg, cond_policy=True)
                self._vm_run = jax.jit(
                    self._mod.make_param_run_fn(self.workload, vm.score, cfg))
        return self._vm_run

    def _try_vm(self, code: str) -> Optional[SimResult]:
        """SimResult via the shared interpreter program, or None when the
        candidate is outside the VM vocabulary (caller jits it instead)."""
        c = self.workload.cluster
        try:
            prog = vm.compile_policy(code, c.n_padded, c.g_padded,
                                     capacity=self.VM_CAPACITY)
        except vm.VMUnsupported:
            return None
        with self._lock:
            self.vm_count += 1
        return self._vm_runner()(prog, self.state0)

    # ----- batched VM tier: a GENERATION as one device program

    def _count_segment(self):
        """Host-loop segment-dispatch callback from the segmented batched
        runners (runs between device calls, never inside them)."""
        with self._lock:
            self.segments_dispatched += 1
        self.profiler.segment_tick()

    def _vm_pop_runner(self):
        if self._vm_pop_run is None:
            if self.suite is not None:
                # candidates x scenarios [C, T] from one program; the
                # segmented runners have no trace-batched variant, so
                # suite mode always takes the single-dispatch path
                from fks_tpu.scenarios.robust import make_suite_eval
                ev = make_suite_eval(self.suite, vm.score_static, self.cfg,
                                     population=True, engine=self.engine)
                self._vm_pop_run = lambda progs, _s: ev(progs)
                return self._vm_pop_run
            # population semantics per SimConfig.cond_policy docs: under
            # vmap a cond runs both branches, so keep cond_policy off and
            # let the self-masking step skip nothing — the batch amortizes
            if (self.vm_seg_steps > 0
                    and hasattr(self._mod, "make_segmented_population_run")):
                # manages its own inner jits; results identical to the
                # unsegmented runner (tests/test_flat_engine.py)
                self._vm_pop_run = self._mod.make_segmented_population_run(
                    self.workload, vm.score_static, self.cfg,
                    seg_steps=self.vm_seg_steps,
                    on_segment=self._count_segment,
                    double_buffer=self.vm_double_buffer)
            else:
                self._vm_pop_run = jax.jit(
                    self._mod.make_population_run_fn(
                        self.workload, vm.score_static, self.cfg))
        return self._vm_pop_run

    def _vm_mesh_runner(self):
        if self._vm_mesh_run is None:
            from fks_tpu.parallel.mesh import make_sharded_code_eval
            self._vm_mesh_run = make_sharded_code_eval(
                self.workload, self.mesh, cfg=self.cfg, elite_k=1,
                engine=self.engine, seg_steps=self.vm_seg_steps,
                on_segment=self._count_segment)
        return self._vm_mesh_run

    def _maybe_record_vm_footprint(self, run, stacked, pop: int) -> None:
        """Evolve-tier footprint ledger entry: price this bucket's
        population runner once per (pop, capacity) bucket — only while a
        flight recorder is on (the AOT lower is not free, so the silent
        path pays nothing) and only for runners that expose ``.lower``
        (the plain jitted path; segmented/mesh runners manage their own
        inner jits and stay unpriced)."""
        from fks_tpu.obs.recorder import get_recorder
        rec = get_recorder()
        if not rec.enabled or getattr(run, "lower", None) is None:
            return
        cap = int(stacked.opcode.shape[-1])
        key = (pop, cap)
        done = getattr(self, "_footprinted_buckets", None)
        if done is None:
            done = self._footprinted_buckets = set()
        if key in done:
            return
        done.add(key)
        try:
            from fks_tpu.obs.layout import default_spec
            from fks_tpu.obs.memory import record_footprint
            compiled = run.lower(stacked, self.state0).compile()
            record_footprint("evolve", f"pop={pop},cap={cap}", compiled,
                             mesh=self.mesh, recorder=rec,
                             engine=self.engine,
                             layout_key=getattr(run, "_fks_layout_key",
                                                default_spec().key))
        except Exception:  # noqa: BLE001 — pricing is best-effort
            pass

    def _run_vm_batch(self, progs: List[vm.VMProgram]) -> List[SimResult]:
        """Evaluate stacked VM candidates in ONE device launch — sharded
        over the mesh when one with >1 device was passed.

        Shapes are bucketed (capacity to the stack's power-of-two, the
        population axis to the next power of two rounded to the shard
        count, padded by repeating the last program) so the jitted
        population runner retraces only per bucket, never per generation.
        Replaces the reference's one-subprocess-per-candidate fan-out
        (funsearch_integration.py:535-562) with one XLA program.
        """
        from fks_tpu.obs import span

        pop = vm.bucket_lanes(len(progs), self._n_shards)
        padded = list(progs) + [progs[-1]] * (pop - len(progs))
        stacked = vm.stack_programs(padded)
        # footprint the bucket's runner BEFORE the span: the once-per-
        # bucket AOT lower must not land on the vm_batch device clock
        # (same branch condition as the dispatch below)
        if not (self._n_shards > 1 and self.suite is None):
            self._maybe_record_vm_footprint(self._vm_pop_runner(),
                                            stacked, pop)
        # the span's clock covers the device work AND the one transfer:
        # device_get materializes the whole generation, so no extra sync
        with span("vm_batch", candidates=len(progs), lanes=pop,
                  shards=self._n_shards):
            if self._n_shards > 1 and self.suite is None:
                # each device interprets pop/shards lanes; the elite
                # outputs are discarded here (the evolution loop ranks on
                # the host, where admission/dedup live). Suite mode skips
                # this tier: make_sharded_code_eval has no scenario axis —
                # the [C, T] population runner serves the batch instead
                # (mesh-sharded SUITE evaluation lives at the parametric
                # tier, fks_tpu.scenarios.robust.make_sharded_suite_eval).
                result, _, _ = self._vm_mesh_runner()(stacked, len(progs))
            else:
                result = self._vm_pop_runner()(stacked, self.state0)
            # ONE device->host transfer for the whole generation: slicing
            # lazy device arrays would cost ~3 tiny syncs/lane in _record
            result = jax.device_get(result)
        with self._lock:
            self.vm_batch_count += 1
            self.vm_count += len(progs)
        return [jax.tree_util.tree_map(lambda x, i=i: x[i], result)
                for i in range(len(progs))]

    # ----- budgeted batched tier: probe rung -> survivors -> full rung

    def _budget_ladder(self):
        """The lazily built rung ladder (fks_tpu.funsearch.budget). The
        full rung reuses THIS evaluator's population suite program, so
        budget mode adds one compiled program (the probe), not two."""
        if self._budget_eval is None:
            from fks_tpu.funsearch.budget import BudgetedSuiteEval
            self._budget_eval = BudgetedSuiteEval(
                self.workload, self.cfg, self.budget, self.robust,
                full_runner=lambda stacked: self._vm_pop_runner()(
                    stacked, self.state0),
                engine=self.engine, n_shards=self._n_shards,
                segment_counter=lambda: self.segments_dispatched)
        return self._budget_eval

    def _budget_active(self, n: int) -> bool:
        """Budget pruning engages only when it would actually prune: an
        enabled schedule, suite mode, and a batch big enough that the
        survivor count is a strict subset."""
        return (self.budget is not None and self.suite is not None
                and n >= 2 and self.budget.survivors(n) < n)

    def _run_vm_batch_budget(self, progs: List[vm.VMProgram],
                             codes: List[str]) -> List[EvalRecord]:
        """Budgeted generation evaluation: every rung is one device
        launch on a bucketed static shape (fks_tpu.funsearch.budget).
        Survivors get full-fidelity suite records (budget_rung=1); the
        pruned keep their probe aggregate capped below the worst
        survivor's full score (budget_rung=0), so pruning can demote but
        never promote — the generation champion is always a survivor,
        and ParitySentinel.check_champion audits the rest."""
        outcome = self._budget_ladder().run(progs)
        with self._lock:
            self.vm_batch_count += len(outcome.rungs)
            self.vm_count += len(progs)
        records: List[Optional[EvalRecord]] = [None] * len(progs)
        floor = None
        for i in outcome.survivor_indices:
            rec = self._record_suite(codes[i], outcome.results[i])
            rec.budget_rung = 1
            records[i] = rec
            floor = rec.score if floor is None else min(floor, rec.score)
        for i, pruned in enumerate(outcome.pruned):
            if pruned:
                records[i] = self._record_pruned(
                    codes[i], outcome.results[i],
                    outcome.probe_scores[i], floor or 0.0)
        self.last_budget_stats = [r.asdict() for r in outcome.rungs]
        return records

    def _record_pruned(self, code: str, result: SimResult,
                       probe_score: float, floor: float) -> EvalRecord:
        """Probe-rung record for a pruned candidate. Truncation is the
        probe's DESIGN (probe_steps stops the run early), so unlike
        ``_record_suite`` an all-truncated probe is not an error — only
        an all-scenarios failure is. The score is the probe robust
        aggregate capped at the worst survivor's full-suite score: probe
        fitness is biased high (partial-prefix scoring ignores the
        unassigned-pods gate), and an uncapped probe score could crown a
        pruned dud over a fully-evaluated survivor."""
        per = np.asarray(result.policy_score, np.float64)
        breakdown = [float(x) for x in per]
        agg = self.robust.aggregation
        if bool(np.asarray(result.failed).all()):
            return EvalRecord(code, 0.0, "gpu allocation aborted "
                              "(all scenarios)", result, breakdown, agg,
                              budget_rung=0)
        return EvalRecord(code, float(min(probe_score, floor)), None,
                          result, breakdown, agg, budget_rung=0)

    def _record(self, code: str, result: SimResult) -> EvalRecord:
        if self.suite is not None:
            return self._record_suite(code, result)
        if bool(result.failed):
            return EvalRecord(code, 0.0, "gpu allocation aborted", result)
        if bool(result.truncated):
            return EvalRecord(code, 0.0, "event budget exceeded", result)
        return EvalRecord(code, float(result.policy_score), None, result)

    def _record_suite(self, code: str, result: SimResult) -> EvalRecord:
        """Suite-mode record: result leaves carry the scenario axis [T].
        A scenario that fails scores 0 THERE (finalize already gates the
        fitness) and drags the aggregate — reference failure semantics
        applied per scenario; the candidate only errors out when every
        scenario failed."""
        from fks_tpu.scenarios.robust import aggregate

        per = np.asarray(result.policy_score, np.float64)
        breakdown = [float(x) for x in per]
        agg = self.robust.aggregation
        failed = np.asarray(result.failed)
        truncated = np.asarray(result.truncated)
        if bool(failed.all()):
            return EvalRecord(code, 0.0, "gpu allocation aborted "
                              "(all scenarios)", result, breakdown, agg)
        if bool((failed | truncated).all()):
            return EvalRecord(code, 0.0, "event budget exceeded "
                              "(all scenarios)", result, breakdown, agg)
        score = float(aggregate(per, self.robust))
        return EvalRecord(code, score, None, result, breakdown, agg)

    def _compiled(self, code: str):
        key = transpiler.canonical_key(code)
        with self._lock:
            fn = self._cache.get(key)
        if fn is None:
            # transpile + trace OUTSIDE the lock: XLA compilation is native
            # code (GIL released), so distinct candidates compile in
            # parallel across evaluate()'s thread pool
            policy = transpiler.transpile(code)
            if self.suite is not None:
                from fks_tpu.scenarios.robust import make_suite_eval
                ev = make_suite_eval(
                    self.suite,
                    lambda _p, pod, nodes: policy(pod, nodes),
                    self.cfg, engine=self.engine)
                fn = lambda _s: ev(None)  # noqa: E731 — state0-call shape
            else:
                fn = jax.jit(
                    self._mod.make_run_fn(self.workload, policy, self.cfg))
            with self._lock:
                if key in self._cache:  # lost the race: reuse the winner
                    fn = self._cache[key]
                else:
                    self._cache[key] = fn
                    self.compile_count += 1
        return fn

    def evaluate_one(self, code: str, *,
                     try_vm: Optional[bool] = None) -> EvalRecord:
        """Reference semantics: exceptions -> score 0 with the reason kept
        (the reference loses the reason; we keep it for observability).
        ``try_vm=False`` skips the VM attempt (used by ``evaluate`` for
        candidates already known to be outside the VM vocabulary)."""
        try:
            result: Optional[SimResult] = None
            if self.use_vm if try_vm is None else try_vm:
                result = self._try_vm(code)
            if result is None:
                run = self._compiled(code)
                result = run(self.state0)
            return self._record(code, result)
        except transpiler.TranspileError as e:
            return EvalRecord(code, 0.0, f"transpile: {e}")
        except Exception as e:  # noqa: BLE001 — candidate code is untrusted
            return EvalRecord(code, 0.0, f"runtime: {e}")

    def evaluate(self, codes: Sequence[str]) -> List[EvalRecord]:
        """Evaluate a batch; duplicate sources are computed once.

        VM-vocabulary candidates (the common case) are lowered to register
        programs on the host, STACKED, and evaluated as ONE device launch
        (`_run_vm_batch`) — a generation of LLM candidates costs one
        population-engine execution, zero per-candidate XLA compiles. The
        rare candidate outside the VM vocabulary fans out over a thread
        pool to the per-code jit tier, whose XLA compiles (native code, GIL
        released) overlap each other. Result order — and therefore
        population admission order — matches the input order regardless of
        completion order.
        """
        seg0 = self.segments_dispatched
        vm0 = self.vm_count
        pf_rejected = 0
        fp_dupes = 0
        works: List[int] = []  # static per-node work bounds (accepted)
        fps: Dict[str, Optional[str]] = {}  # canonical key -> fingerprint
        keyed: List[Optional[str]] = []
        errors: Dict[int, EvalRecord] = {}
        analysis = None
        unique: Dict[str, str] = {}
        alias: Dict[str, str] = {}
        with self.profiler.stage("sandbox+preflight",
                                 candidates=len(codes)) as hp:
            if self.preflight or self.fp_dedup:
                # lazy: fks_tpu.analysis pulls funsearch tables, and
                # funsearch/__init__ imports this module first
                from fks_tpu import analysis
            g_padded = self.workload.cluster.g_padded
            for i, code in enumerate(codes):
                rep = None
                if analysis is not None:
                    rep = analysis.preflight_check(code)
                    if self.preflight and not rep.ok:
                        # statically doomed: never reaches sandbox.validate,
                        # transpile, or any compile tier (pinned by tests)
                        keyed.append(None)
                        errors[i] = EvalRecord(
                            code, 0.0,
                            f"preflight: {rep.taxonomy}: {rep.reason}")
                        obs.get_recorder().event(
                            "candidate_rejected", taxonomy=rep.taxonomy,
                            stage="preflight", reason=rep.reason[:200])
                        pf_rejected += 1
                        continue
                    if rep.ok and rep.cost is not None:
                        works.append(rep.cost.work(g_padded))
                try:
                    key = transpiler.canonical_key(code)
                except SyntaxError as e:
                    keyed.append(None)
                    errors[i] = EvalRecord(code, 0.0, f"syntax: {e}")
                    continue
                keyed.append(key)
                if rep is not None and key not in fps:
                    fps[key] = rep.fingerprint
            for key, code in zip(keyed, codes):
                if key is not None and key not in unique:
                    unique[key] = code

            # normalized-AST near-duplicate suppression (within this
            # batch): fingerprint-colliding sources collapse onto one
            # representative — one sandbox/transpile/compile/eval instead
            # of k — and every echo still receives the representative's
            # full EvalRecord
            if self.fp_dedup:
                by_fp: Dict[str, str] = {}
                for key in list(unique):
                    fp = fps.get(key)
                    if fp is None:
                        continue
                    owner = by_fp.setdefault(fp, key)
                    if owner != key:
                        alias[key] = owner
                        del unique[key]
                        fp_dupes += 1
                        obs.get_recorder().event(
                            "candidate_rejected",
                            taxonomy="duplicate_fingerprint",
                            stage="fp_dedup", reason=f"fingerprint {fp}")
            hp.annotate(rejected=pf_rejected, duplicates=fp_dupes,
                        unique=len(unique))

        memo: Dict[str, EvalRecord] = {}
        vm_progs: Dict[str, vm.VMProgram] = {}
        jit_only: Dict[str, str] = {}  # known outside the VM vocabulary
        general: Dict[str, str] = {}  # default tier choice (VM then jit)
        c = self.workload.cluster
        with self.profiler.stage("transpile") as ht:
            if self.use_vm and self.vm_batch and len(unique) > 1:
                for key, code in unique.items():
                    try:
                        prog = vm.compile_policy(code, c.n_padded,
                                                 c.g_padded)
                        if prog.capacity > self.VM_CAPACITY:
                            raise vm.VMUnsupported(
                                f"program too long: capacity "
                                f"{prog.capacity}")
                        vm_progs[key] = prog
                    except vm.VMUnsupported:
                        jit_only[key] = code
                    except transpiler.TranspileError as e:
                        memo[key] = EvalRecord(code, 0.0, f"transpile: {e}")
                    except Exception as e:  # noqa: BLE001 — untrusted code
                        memo[key] = EvalRecord(code, 0.0, f"runtime: {e}")
                if len(vm_progs) == 1:  # a population program for one lane
                    (key,) = vm_progs  # isn't worth it: unbatched VM tier
                    general[key] = unique[key]
                    vm_progs = {}
            else:
                general = dict(unique)
            ht.annotate(vm_lanes=len(vm_progs),
                        jit_fallback=len(jit_only) + len(general))

        batch_served = 0
        self.last_budget_stats = []
        with self.profiler.stage("device-eval") as hd:
            if vm_progs:
                vm_keys = list(vm_progs)
                try:
                    if self._budget_active(len(vm_keys)):
                        recs = self._run_vm_batch_budget(
                            [vm_progs[k] for k in vm_keys],
                            [unique[k] for k in vm_keys])
                        for key, rec in zip(vm_keys, recs):
                            memo[key] = rec
                    else:
                        results = self._run_vm_batch(
                            [vm_progs[k] for k in vm_keys])
                        for key, res in zip(vm_keys, results):
                            memo[key] = self._record(unique[key], res)
                    batch_served = len(vm_keys)
                except Exception as e:  # noqa: BLE001 — batch failed:
                    # per-candidate fallback still produces scores, but say
                    # WHY the one-launch-per-generation path is not engaging
                    from fks_tpu.utils import get_logger
                    get_logger("fks_tpu.funsearch.backend").warning(
                        "batched VM launch failed (%s: %s); falling back "
                        "to per-candidate evaluation", type(e).__name__, e)
                    for key in vm_keys:
                        general.setdefault(key, unique[key])

            if jit_only or general:
                with concurrent.futures.ThreadPoolExecutor(
                        max_workers=self.max_workers) as ex:
                    futs = {key: ex.submit(self.evaluate_one, code,
                                           try_vm=False)
                            for key, code in jit_only.items()}
                    futs.update({key: ex.submit(self.evaluate_one, code)
                                 for key, code in general.items()})
                    for key, f in futs.items():
                        memo[key] = f.result()

            # occupancy over the three batch axes (padded lanes x
            # scenarios x trace segments): only the batched tier pads
            # lanes; the threadpool fallback launches real work only
            if batch_served:
                from fks_tpu.parallel.mesh import occupancy_stats
                hd.annotate(lanes=batch_served, **occupancy_stats(
                    batch_served, self._n_shards,
                    scenarios=len(self.suite) if self.suite else 1,
                    segments=max(1, self.segments_dispatched - seg0)))
            else:
                hd.annotate(lanes=len(jit_only) + len(general),
                            pad_waste_fraction=0.0)

        # observability: how this batch was served, for the evolution
        # ledger / flight recorder (host bookkeeping only — no device work)
        self.preflight_rejected += pf_rejected
        self.preflight_duplicates += fp_dupes
        self.last_eval_stats = {
            "candidates": len(codes),
            "unique": len(unique),
            "syntax_failed": len(errors) - pf_rejected,
            "preflight_rejected": pf_rejected,
            "fingerprint_duplicates": fp_dupes,
            "mean_static_work": (round(sum(works) / len(works), 1)
                                 if works else 0),
            "vm_batch_lanes": batch_served,
            "fallback_lanes": len(jit_only) + len(general),
            "segments": self.segments_dispatched - seg0,
            "budget_pruned": sum(r["entered"] - r["survived"]
                                 for r in self.last_budget_stats),
            # fraction of the batch's unique candidates served by the
            # VM tier — the live estimate of how much of the population
            # the zero-rebuild serve fast path can carry
            "vm_coverage": round((self.vm_count - vm0)
                                 / max(1, len(unique)), 4),
        }

        out = []
        for i, (key, code) in enumerate(zip(keyed, codes)):
            if key is None:
                out.append(errors[i])
            else:
                r = memo[alias.get(key, key)]
                out.append(EvalRecord(code, r.score, r.error, r.result,
                                      r.scenario_scores, r.aggregation,
                                      r.budget_rung))
        return out

    def scores(self, codes: Sequence[str]) -> np.ndarray:
        return np.asarray([r.score for r in self.evaluate(codes)], np.float64)
