"""Fitness backend for code candidates: transpile -> jit -> evaluate.

TPU-native replacement for the reference's subprocess fitness fan-out
(reference: funsearch/funsearch_integration.py:30-64 ``evaluate_policy_
standalone`` + 535-562 ProcessPoolExecutor): instead of forking a process
per candidate that re-parses the trace CSVs and runs the Python event loop,
each unique candidate is transpiled once into a vectorized policy, jitted
against the device-resident workload, and executed on-chip. The trace is
parsed once for the life of the backend; repeated/near-identical candidates
hit an AST-keyed compile cache (SURVEY.md §7: dedup doubles as compile-cache
key).

Failure semantics follow the reference's subprocess path: any failure —
validation, transpile, or execution — maps to fitness 0.0 and the candidate
stays in the pool's view (reference: funsearch_integration.py:63-64;
SURVEY.md §2 fine print 8).

Two throughput tiers:
- code candidates: one compiled program per unique AST (this module);
- parametric candidates: one program TOTAL for the whole population
  (fks_tpu.parallel.population / .mesh) — the fast path the evolution
  controller uses for weight-vector mutation between LLM rounds.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from fks_tpu.data.entities import Workload
from fks_tpu.funsearch import transpiler
from fks_tpu.sim.engine import SimConfig, initial_state, make_run_fn
from fks_tpu.sim.types import SimResult


@dataclasses.dataclass
class EvalRecord:
    """One candidate's evaluation outcome."""

    code: str
    score: float
    error: Optional[str] = None  # why fitness is 0, when it is
    result: Optional[SimResult] = None

    @property
    def ok(self) -> bool:
        return self.error is None


class CodeEvaluator:
    """Evaluate candidate source strings against one workload.

    The compile cache maps canonical AST keys to jitted run functions, so a
    re-submitted (or whitespace-variant) candidate costs one device launch,
    not a retrace. XLA's own jit cache adds a second layer keyed on the
    traced computation.
    """

    def __init__(self, workload: Workload, cfg: SimConfig = SimConfig()):
        self.workload = workload
        self.cfg = cfg
        self.state0 = initial_state(workload, cfg)
        self._cache: Dict[str, object] = {}
        self.compile_count = 0  # observability: unique programs built

    def _compiled(self, code: str):
        key = transpiler.canonical_key(code)
        fn = self._cache.get(key)
        if fn is None:
            policy = transpiler.transpile(code)
            fn = jax.jit(make_run_fn(self.workload, policy, self.cfg))
            self._cache[key] = fn
            self.compile_count += 1
        return fn

    def evaluate_one(self, code: str) -> EvalRecord:
        """Reference semantics: exceptions -> score 0 with the reason kept
        (the reference loses the reason; we keep it for observability)."""
        try:
            run = self._compiled(code)
            result: SimResult = run(self.state0)
            score = float(result.policy_score)
            if bool(result.failed):
                return EvalRecord(code, 0.0, "gpu allocation aborted", result)
            if bool(result.truncated):
                return EvalRecord(code, 0.0, "event budget exceeded", result)
            return EvalRecord(code, score, None, result)
        except transpiler.TranspileError as e:
            return EvalRecord(code, 0.0, f"transpile: {e}")
        except Exception as e:  # noqa: BLE001 — candidate code is untrusted
            return EvalRecord(code, 0.0, f"runtime: {e}")

    def evaluate(self, codes: Sequence[str]) -> List[EvalRecord]:
        """Evaluate a batch; duplicate sources are computed once."""
        memo: Dict[str, EvalRecord] = {}
        out = []
        for code in codes:
            try:
                key = transpiler.canonical_key(code)
            except SyntaxError as e:
                out.append(EvalRecord(code, 0.0, f"syntax: {e}"))
                continue
            if key not in memo:
                memo[key] = self.evaluate_one(code)
            r = memo[key]
            out.append(EvalRecord(code, r.score, r.error, r.result))
        return out

    def scores(self, codes: Sequence[str]) -> np.ndarray:
        return np.asarray([r.score for r in self.evaluate(codes)], np.float64)
