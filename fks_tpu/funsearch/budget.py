"""Successive-halving eval-budget allocation over scenario suites.

Suite mode spends the full ``default8`` x full-trace budget on every
candidate in every generation, including obvious duds that a 3-scenario
smoke pass or a truncated trace prefix already ranks at the bottom. This
layer sits between candidate generation and
``fks_tpu.scenarios.robust.make_suite_eval`` and spends the budget in
rungs (successive halving; PAPERS.md: "Speeding up Policy Simulation in
Supply Chain RL" cuts simulated work per candidate, "Fast Population-
Based RL on a Single Machine" compiles heterogeneous per-member budgets
into one vectorized program):

- **rung 0 (probe)**: the WHOLE generation is scored on a cheap probe —
  the ``probe_suite`` (default ``smoke3``) and/or a truncated trace
  prefix (``probe_steps`` caps the event budget; the engines' step-budget
  early exit is the same machinery the segmented runner's cond uses, so
  a probe run simply stops after ``probe_steps`` events and reports
  ``truncated=True``). The probe scores under ``SimConfig.probe_score``:
  fitness is the utilization integral over the consumed prefix instead
  of the full-run gate that zeroes truncated runs.
- **rung 1 (full)**: only the top ``1/eta`` fraction by probe robust
  score advances to the full suite + full trace + the configured robust
  aggregation (CVaR included). Pruned candidates keep their probe score,
  capped below the worst survivor's full-suite score, so a pruned dud
  can never out-rank a fully-evaluated survivor.

Every rung is ONE vmapped device call with a static shape: lane counts
are bucketed to powers of two (``vm.bucket_lanes``) and survivor sets
are re-padded onto the bucket via ``parallel.mesh.pad_population``
(replicating the last survivor's slice), so each rung compiles once per
(bucket-size, probe-shape) pair — never per generation.

Correctness is gated by ``fks_tpu.obs.watchdog.ParitySentinel.
check_champion``: pruning may never change which candidate wins a
generation, only how cheaply — the sentinel rescoring the pruned
candidates through the unpruned exact reference alerts (CLI exit 3) if
any pruned candidate would have beaten the pruned run's champion.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

SCHEDULES = ("none", "halving")


@dataclasses.dataclass(frozen=True)
class BudgetConfig:
    """Static eval-budget knobs (EvolutionConfig.budget_* / cli evolve
    --budget)."""

    schedule: str = "none"  # "none" = full suite for everyone (pre-budget)
    eta: int = 2  # survivor fraction denominator: keep ceil(n/eta)
    probe_suite: str = "smoke3"  # rung-0 suite name (scenarios.SUITE_SPECS)
    probe_steps: int = 0  # rung-0 event budget; 0 = full trace on the probe
    min_survivors: int = 1  # never prune below this many full evaluations

    def __post_init__(self):
        if self.schedule not in SCHEDULES:
            raise ValueError(
                f"unknown budget schedule {self.schedule!r}; "
                f"one of {', '.join(SCHEDULES)}")
        if self.eta < 2:
            raise ValueError(f"eta must be >= 2 (got {self.eta}): "
                             "eta=1 advances everyone — use schedule='none'")
        if self.probe_steps < 0:
            raise ValueError(
                f"probe_steps must be >= 0 (0 = full trace on the probe), "
                f"got {self.probe_steps}")
        if self.min_survivors < 1:
            raise ValueError(
                f"min_survivors must be >= 1, got {self.min_survivors}")

    @property
    def enabled(self) -> bool:
        return self.schedule != "none"

    def survivors(self, n: int) -> int:
        """How many of ``n`` candidates advance to the full rung."""
        return min(n, max(self.min_survivors, -(-n // self.eta)))

    def describe(self) -> dict:
        return {"schedule": self.schedule, "eta": self.eta,
                "probe_suite": self.probe_suite,
                "probe_steps": self.probe_steps,
                "min_survivors": self.min_survivors}


@dataclasses.dataclass
class RungStats:
    """Per-rung accounting for the ledger / OpenMetrics ``budget_rung``
    records: who entered, who survived, what the rung cost on device."""

    rung: int
    entered: int
    survived: int
    device_seconds: float
    segments: int = 0
    lanes: int = 0  # padded lane count actually launched

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class BudgetOutcome:
    """One generation's budgeted evaluation: per-candidate results in
    input order (full-suite results for survivors, probe results for the
    pruned), plus the bookkeeping the records/ledger need."""

    results: List[object]  # SimResult slices, one per input candidate
    pruned: List[bool]
    probe_scores: List[float]  # rung-0 robust aggregate, every candidate
    survivor_indices: List[int]
    rungs: List[RungStats]


def probe_sim_config(cfg, budget: BudgetConfig):
    """The rung-0 SimConfig: probe scoring on (partial-prefix fitness
    instead of the zero-on-truncation gate) and, when ``probe_steps`` is
    set, the event budget capped at the prefix length."""
    fields = {"probe_score": True}
    if budget.probe_steps > 0:
        fields["max_steps"] = budget.probe_steps
    return dataclasses.replace(cfg, **fields)


class BudgetedSuiteEval:
    """The rung ladder over the batched VM suite tier (see module
    docstring). Owns the probe-rung runner; the full-suite runner is
    INJECTED (``full_runner``) so the full rung shares the one compiled
    population program the unbudgeted path uses — turning the budget on
    adds exactly one extra compiled program (the probe), not a second
    full-suite program.
    """

    def __init__(self, workload, cfg, budget: BudgetConfig, robust,
                 full_runner: Callable, engine: str = "exact",
                 n_shards: int = 1,
                 segment_counter: Optional[Callable[[], int]] = None):
        from fks_tpu.scenarios import get_suite

        self.budget = budget
        self.robust = robust
        self.engine = engine
        self.n_shards = n_shards
        self._full_runner = full_runner
        self._segment_counter = segment_counter or (lambda: 0)
        self._probe_suite = get_suite(budget.probe_suite, workload)
        self._probe_cfg = probe_sim_config(cfg, budget)
        self._probe_run = None  # lazily built probe population program

    def _probe_runner(self):
        if self._probe_run is None:
            from fks_tpu.funsearch import vm
            from fks_tpu.scenarios.robust import make_suite_eval
            self._probe_run = make_suite_eval(
                self._probe_suite, vm.score_static, self._probe_cfg,
                population=True, engine=self.engine)
        return self._probe_run

    def _launch(self, rung: int, progs, bucket: int, entered: int,
                runner: Callable):
        """Pad a stacked program batch onto its lane bucket and run the
        rung as one device call; returns (host result, RungStats)."""
        from fks_tpu.obs import span
        from fks_tpu.parallel.mesh import pad_population

        padded, _ = pad_population(progs, bucket)
        seg0 = self._segment_counter()
        with span("budget_rung", rung=rung, entered=entered,
                  lanes=bucket) as t:
            result = jax.device_get(runner(padded))
        return result, RungStats(
            rung=rung, entered=entered, survived=entered,
            device_seconds=round(t.seconds, 6),
            segments=self._segment_counter() - seg0, lanes=bucket)

    def run(self, progs: Sequence) -> BudgetOutcome:
        """Evaluate lowered VM programs through the rung ladder."""
        from fks_tpu.scenarios.robust import aggregate
        from fks_tpu.funsearch import vm

        n = len(progs)
        k = self.budget.survivors(n)
        stacked = vm.stack_programs(list(progs))
        cap = stacked.opcode.shape[-1]

        # rung 0: the whole generation on the cheap probe
        res0, r0 = self._launch(
            0, stacked, vm.bucket_lanes(n, self.n_shards), n,
            self._probe_runner())
        per0 = np.asarray(res0.policy_score, np.float64)[:n]
        probe_scores = np.asarray(aggregate(per0, self.robust), np.float64)
        r0.survived = k

        # survivor selection: top-k by probe robust score, stable under
        # ties (argsort of the negated scores preserves input order), kept
        # in input order so result slicing stays positional
        order = np.argsort(-probe_scores, kind="stable")
        keep = sorted(int(i) for i in order[:k])

        # rung 1: survivors re-stacked at the SAME capacity (shape-stable
        # across generations) and re-padded onto the survivor bucket
        stacked1 = vm.stack_programs([progs[i] for i in keep], capacity=cap)
        res1, r1 = self._launch(
            1, stacked1, vm.bucket_lanes(k, self.n_shards), k,
            self._full_runner)

        slot = {cand: pos for pos, cand in enumerate(keep)}
        tm = jax.tree_util.tree_map
        results = [
            tm(lambda x, j=slot[i]: x[j], res1) if i in slot
            else tm(lambda x, j=i: x[j], res0)
            for i in range(n)
        ]
        return BudgetOutcome(
            results=results,
            pruned=[i not in slot for i in range(n)],
            probe_scores=[float(s) for s in probe_scores],
            survivor_indices=keep,
            rungs=[r0, r1])
