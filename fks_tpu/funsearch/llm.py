"""LLM code generation: OpenAI-compatible client + hermetic fake backend.

Counterpart of the reference generator (reference:
funsearch/safe_execution.py:273-328 ``LLMCodeGenerator`` — an OpenAI-SDK
chat.completions call against OpenRouter, template fill, validate, None on
any failure) and its thread-pool fan-out (reference:
funsearch/funsearch_integration.py:461-525). Codegen is host-side I/O and
stays off the device exactly as the reference keeps it outside its hot path
(SURVEY.md §3.2); concurrency is a ThreadPoolExecutor because the work is
network-bound.

The ``FakeLLM`` backend closes a testability gap called out in SURVEY.md §4:
the reference has no fake LLM, so its evolution loop is untestable without a
live API key. Here the fake draws deterministic mutations from a small
grammar of scoring ideas, seeded per call, so evolution tests are hermetic
and reproducible.
"""
from __future__ import annotations

import concurrent.futures
import random
import threading
from typing import Callable, List, Optional, Protocol, Sequence, Tuple

from fks_tpu.funsearch import sandbox, template, transpiler

Parent = Tuple[str, float]  # (candidate source, fitness)


def _retry_after_seconds(headers) -> Optional[float]:
    """Parse a ``Retry-After`` response header: either delta-seconds or
    an HTTP-date (RFC 9110 §10.2.3). None when absent or unparsable —
    the caller falls back to its own backoff ladder."""
    value = headers.get("Retry-After") if headers is not None else None
    if not value:
        return None
    value = value.strip()
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    import email.utils  # noqa: PLC0415 — keep module imports jax-light
    import time

    try:
        when = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if when is None:
        return None
    return max(0.0, when.timestamp() - time.time())


class TextBackend(Protocol):
    """Something that turns a prompt into a raw logic block."""

    def complete(self, prompt: str) -> str: ...


class OpenAIBackend:
    """OpenAI-compatible chat/completions client, self-contained over
    stdlib HTTP (reference: safe_execution.py:283-303 does the same call
    through the ``openai`` SDK against OpenRouter).

    Dropping the SDK is deliberate: the request is one POST with a JSON
    body and the response is one JSON object — a dependency-free client
    keeps the framework runnable (and this path hermetically testable,
    tests/test_llm_stub.py) in images without the SDK. Unlike the
    reference, timeout and retry policy are explicit: the SDK's 600 s
    default timeout stalls a whole generation's thread-pool slot on one
    hung request.

    Wire behavior: POST ``{base_url}/chat/completions`` with
    ``{model, messages, max_tokens, temperature}`` and a Bearer key;
    transient failures (connect/read errors, HTTP 429/5xx) retry up to
    ``max_retries`` times with linear backoff; anything else raises —
    ``CandidateGenerator.generate`` maps every raise to None, matching the
    reference's None-on-any-failure contract (safe_execution.py:315-317).
    """

    def __init__(self, api_key: str, base_url: str, model: str,
                 max_tokens: int = 500, temperature: float = 0.7,
                 timeout: float = 60.0, max_retries: int = 2,
                 deadline: Optional[float] = None):
        self.api_key = api_key
        self.base_url = base_url.rstrip("/")
        self.model = model
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.timeout = timeout
        self.max_retries = max_retries
        # ``timeout`` is PER ATTEMPT; the overall bound on one complete()
        # call is this deadline, enforced across retries + backoff so one
        # hung endpoint holds a generation thread-pool slot for at most
        # this long (default: the old worst case, attempts x timeout + the
        # backoff sum, now explicit instead of implied)
        self.deadline = deadline if deadline is not None else (
            (max_retries + 1) * timeout
            + sum(0.5 * (a + 1) for a in range(max_retries)))

    def complete(self, prompt: str) -> str:
        import json  # noqa: PLC0415 — keep module imports jax-light
        import time
        import urllib.error
        import urllib.request

        body = json.dumps({
            "model": self.model,
            "messages": [{"role": "user", "content": prompt}],
            "max_tokens": self.max_tokens,
            "temperature": self.temperature,
        }).encode()
        req = urllib.request.Request(
            f"{self.base_url}/chat/completions", data=body,
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {self.api_key}"})
        last: Exception = TimeoutError(
            f"deadline ({self.deadline:g}s) exhausted before any attempt")
        t_end = time.monotonic() + self.deadline
        retry_after: Optional[float] = None
        for attempt in range(self.max_retries + 1):
            remaining = t_end - time.monotonic()
            if remaining <= 0:
                break  # overall deadline exhausted mid-retry
            try:
                with urllib.request.urlopen(
                        req, timeout=min(self.timeout, remaining)) as r:
                    # chunked read with deadline checks: urlopen's timeout
                    # is per-socket-operation, so a drip-feeding endpoint
                    # resets it with every byte. read1 issues at most ONE
                    # underlying recv (read(n) would loop recvs until n
                    # bytes arrive, deferring the check indefinitely), so
                    # t_end is re-checked per recv and the overall bound
                    # is ~deadline + one socket timeout.
                    chunks = []
                    while True:
                        if time.monotonic() >= t_end:
                            raise TimeoutError(
                                "deadline exhausted mid-response")
                        chunk = r.read1(65536)
                        if not chunk:
                            break
                        chunks.append(chunk)
                    resp = json.loads(b"".join(chunks).decode())
                return (resp["choices"][0]["message"]["content"] or "").strip()
            except urllib.error.HTTPError as e:
                last = e
                if e.code not in (429, 500, 502, 503, 504):
                    raise
                # rate-limit / overload responses usually say when to come
                # back; honoring it beats hammering a throttling endpoint
                # with the fixed-ladder backoff
                if e.code in (429, 503):
                    retry_after = _retry_after_seconds(e.headers)
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                last = e
            if attempt < self.max_retries:
                delay = (retry_after if retry_after is not None
                         else 0.5 * (attempt + 1))
                # always capped by the overall deadline: a server asking
                # for an hour gets whatever budget is actually left
                time.sleep(min(delay, max(0.0, t_end - time.monotonic())))
            retry_after = None
        raise last


class FakeLLM:
    """Deterministic offline "LLM": emits logic blocks from a grammar of
    scheduling heuristics (packing pressure, fragmentation avoidance,
    balance, GPU tightness), occasionally emitting junk to exercise the
    validate/reject path the way real LLM output does."""

    _TERMS = (
        "(node.cpu_milli_left - pod.cpu_milli) / max(1, node.cpu_milli_total)",
        "(node.memory_mib_left - pod.memory_mib) / max(1, node.memory_mib_total)",
        "(node.gpu_left - pod.num_gpu) / max(1, len(node.gpus))",
        "node.cpu_milli_left / max(1, node.cpu_milli_total)",
        "node.memory_mib_left / max(1, node.memory_mib_total)",
        "sum(gpu.gpu_milli_left for gpu in node.gpus) / max(1, 1000 * len(node.gpus))",
        "sum(1 for gpu in node.gpus if gpu.gpu_milli_left >= pod.gpu_milli)"
        " / max(1, len(node.gpus))",
    )
    _JUNK = (
        "score = untrusted_helper(pod)",
        "import os\n    score = 1",
        "while node.gpu_left > 0:\n        score = 1",
    )

    def __init__(self, seed: int = 0, junk_rate: float = 0.1):
        self._rng = random.Random(seed)
        self._junk_rate = junk_rate
        self._lock = threading.Lock()

    def getstate(self):
        """Serializable generator state (checkpointed by the evolution
        driver so hermetic runs resume bit-identically)."""
        kind, internal, gauss = self._rng.getstate()
        return [kind, list(internal), gauss]

    def setstate(self, obj) -> None:
        kind, internal, gauss = obj
        self._rng.setstate((kind, tuple(internal), gauss))

    def complete(self, prompt: str) -> str:  # noqa: ARG002 — prompt unused
        with self._lock:
            rng = self._rng
            if rng.random() < self._junk_rate:
                return rng.choice(self._JUNK)
            n = rng.randint(1, 3)
            terms = rng.sample(self._TERMS, n)
            coeffs = [round(rng.uniform(-2.0, 2.0), 3) for _ in terms]
            expr = " + ".join(f"({c}) * ({t})" for c, t in zip(coeffs, terms))
            lines = [f"score = 10000 * (1.0 + {expr})"]
            if rng.random() < 0.5:
                lines.append("if pod.num_gpu > 0:")
                lines.append(f"        score = score * {round(rng.uniform(0.8, 1.2), 3)}")
            return "\n    ".join(lines)


class CandidateGenerator:
    """Backend + template + validation = candidate factory (reference:
    safe_execution.py:283-317 ``generate_policy``): returns a full validated
    candidate source, or None on any failure."""

    def __init__(self, backend: TextBackend, smoke: bool = True):
        self.backend = backend
        self.smoke = smoke

    def generate(self, parents: Sequence[Parent], feedback: str = "") -> Optional[str]:
        try:
            logic = self.backend.complete(template.build_prompt(parents, feedback))
        except Exception:  # noqa: BLE001 — network/API errors -> skip
            return None
        if not logic:
            return None
        code = template.fill_template(_strip_fences(logic))
        if not sandbox.validate(code):
            return None
        try:
            transpiler.transpile(code)  # TPU-tightened third stage
        except transpiler.TranspileError:
            return None
        if self.smoke and sandbox.smoke_test(code) is not None:
            return None
        return code


def _strip_fences(text: str) -> str:
    """Real LLMs wrap output in ``` fences despite instructions; unwrap."""
    t = text.strip()
    if t.startswith("```"):
        lines = t.splitlines()
        lines = lines[1:]
        if lines and lines[-1].strip().startswith("```"):
            lines = lines[:-1]
        t = "\n".join(lines).strip()
    return t


def generate_many(gen: CandidateGenerator, n: int,
                  sample_parents: Callable[[], Sequence[Parent]],
                  feedback: str = "", max_workers: int = 8) -> List[str]:
    """Thread-pool fan-out of n generation attempts (reference:
    funsearch_integration.py:512-525); failures are dropped, so the result
    may be shorter than n."""
    out: List[str] = []
    with concurrent.futures.ThreadPoolExecutor(max_workers=max_workers) as ex:
        futs = [ex.submit(gen.generate, sample_parents(), feedback)
                for _ in range(n)]
        # collect in submission order (not as_completed): result order — and
        # therefore population order and dedup outcomes — stays deterministic
        for f in futs:
            code = f.result()
            if code is not None:
                out.append(code)
    return out
