"""FunSearch evolution layer: sandbox, transpiler, codegen, controller.

TPU-native counterpart of the reference ``funsearch/`` package
(reference: funsearch/safe_execution.py + funsearch/funsearch_integration.py).
"""
from fks_tpu.funsearch.backend import CodeEvaluator, EvalRecord
from fks_tpu.funsearch.budget import (
    BudgetConfig, BudgetedSuiteEval, probe_sim_config,
)
from fks_tpu.funsearch.device_evolution import (
    DeviceGenStats, ParametricEvolution,
)
from fks_tpu.funsearch.evolution import (
    EvolutionConfig, FunSearch, GenerationStats, LLMSettings, run,
)
from fks_tpu.funsearch.llm import (
    CandidateGenerator, FakeLLM, OpenAIBackend, generate_many,
)
from fks_tpu.funsearch.sandbox import (
    ScalarGPU, ScalarNode, ScalarPod, execute_scalar, smoke_test, validate,
)
from fks_tpu.funsearch.template import build_prompt, fill_template, seed_policies
from fks_tpu.funsearch.transpiler import TranspileError, canonical_key, transpile

__all__ = [
    "BudgetConfig", "BudgetedSuiteEval",
    "CandidateGenerator", "CodeEvaluator", "DeviceGenStats", "EvalRecord",
    "EvolutionConfig", "probe_sim_config",
    "FakeLLM", "FunSearch", "GenerationStats", "LLMSettings", "OpenAIBackend",
    "ParametricEvolution",
    "ScalarGPU", "ScalarNode", "ScalarPod", "TranspileError", "build_prompt",
    "canonical_key", "execute_scalar", "fill_template", "generate_many",
    "run", "seed_policies", "smoke_test", "transpile", "validate",
]
