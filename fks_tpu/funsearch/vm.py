"""Candidate policies as DATA: a jaxpr->bytecode compiler + on-device VM.

Why: every LLM candidate is new code, and jitting the simulation engine per
candidate costs seconds of XLA compile (the engine dominates: ~7 s on this
container's CPU, far more on TPU) for milliseconds of run. The reference
sidesteps this because CPython "compiles" instantly (reference:
funsearch/funsearch_integration.py:67-101 compiles candidates with exec());
a TPU-native framework needs a different shape: compile the engine ONCE
with the policy as an interpreted register program, so a fresh candidate is
a few arrays uploaded to the device, not a recompilation.

Pipeline:
  candidate source
    -> transpiler.transpile (validation + vectorization, unchanged)
    -> jax.make_jaxpr on the padded (N, G) view shapes
    -> this module lowers the (inlined) jaxpr to a register program:
       every value lives as an f32[N, G] register (scalars and [N] values
       broadcast across G), each op writes one fresh register, reductions
       over the GPU axis re-broadcast their result
    -> ``VMProgram`` pytree of int32/float32 arrays, padded to a bucket size
       so ONE compiled engine serves every candidate of that bucket.

Execution (`score`): ``fori_loop`` over live ops, each a ``lax.switch``
over a deliberately minimal 33-opcode table on [N, G] values (scalar
literals load from a pooled register block, not op slots; boolean and
sign ops are canonicalized into arithmetic at lowering — see the
CONST_POOL / opcode-table comments below for the vmap rationale). Numeric model: everything runs at the
AMBIENT float precision — f64 when x64 is on (CPU tests / golden parity,
where the transpiler also computes floats in f64, matching the reference's
CPython binary64), f32 otherwise (TPU, where the jit tier is f32 too).
Keeping the two tiers at the same precision is what makes VM scores
integer-exact against the transpiled policy: a trunc after an f32 division
can land one short of the f64 result right at integer boundaries. Bools
are 0/1; integer ops are exact below the mantissa (trace resources are
≤ ~1e6). Integer division/remainder use C-style truncation exactly like
lax.

Candidates using constructs outside the lowerable vocabulary raise
``VMUnsupported`` — the caller falls back to the per-candidate jit tier
(fks_tpu.funsearch.backend), so coverage is a throughput optimization, not
a correctness gate.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from fks_tpu.funsearch import transpiler
from fks_tpu.sim.types import NodeView, PodView

def _ambient_float():
    """f64 under x64 (what the transpiled jit tier computes floats in
    there), else f32. Evaluated at trace time, not import time."""
    return jax.dtypes.canonicalize_dtype(np.float64)

# --------------------------------------------------------------- input plan

# register ids 0..N_INPUTS-1 hold the broadcast policy inputs, in this order
_POD_FIELDS = ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
               "creation_time", "duration_time")
_NODE_SCALARS = ("cpu_milli_left", "cpu_milli_total", "memory_mib_left",
                 "memory_mib_total", "gpu_left", "num_gpus")
_NODE_GRIDS = ("gpu_milli_left", "gpu_milli_total", "gpu_mem_total")
N_INPUTS = len(_POD_FIELDS) + len(_NODE_SCALARS) + len(_NODE_GRIDS) + 2

# Constant pool: scalar literals live in a fixed block of registers right
# after the inputs, filled host-side from ``VMProgram.consts`` — NOT in op
# slots. Two wins, both sized for the vmapped population path where every
# branch in the switch table runs for every slot: constants stop consuming
# slot iterations, and the CONST branch leaves the table entirely. The
# pool size is FIXED so register numbering is identical across programs
# (stacked programs must agree on the layout); overflow -> VMUnsupported
# -> the jit tier.
CONST_POOL = 32

# opcodes (order is the lax.switch branch table in `_branches`). The table
# is deliberately MINIMAL: under vmap (population-batched evaluation) the
# switch index is per-lane data, so XLA executes EVERY branch per op slot
# and selects — each table entry costs [N, G] work per slot whether or not
# any program uses it. Ops with an exactness-safe expansion are therefore
# canonicalized at lowering instead of tabled: AND->MUL, OR->MAX (0/1
# domain), NOT->1-x, NEG->x*(-1) (sign-exact for -0.0, unlike 0-x),
# SQUARE->x*x, integer_pow->POW against a pooled constant, and constants
# load from the pool.
(OP_NOP, OP_ADD, OP_SUB, OP_MUL, OP_DIV, OP_MAX, OP_MIN,
 OP_GE, OP_GT, OP_LT, OP_LE, OP_EQ, OP_NE,
 OP_SEL, OP_TRUNC, OP_FLOOR, OP_CEIL, OP_ABS, OP_SIGN,
 OP_ISFIN, OP_REM, OP_POW, OP_EXP, OP_LOG, OP_SQRT,
 OP_SIN, OP_COS, OP_TAN, OP_COL, OP_RSUM_G, OP_RMAX_G, OP_RMIN_G,
 OP_SETCOL) = range(33)


class VMUnsupported(Exception):
    """Candidate uses a construct outside the VM vocabulary."""


class VMProgram(NamedTuple):
    """One lowered candidate. Pure data — a pytree of arrays the compiled
    engine takes as an argument (and can be stacked/batched)."""

    opcode: jax.Array  # i32[O]
    a: jax.Array  # i32[O] operand register
    b: jax.Array  # i32[O]
    c: jax.Array  # i32[O]
    imm: jax.Array  # f32[O] immediate (COL/SETCOL column index)
    consts: jax.Array  # f32[CONST_POOL] pooled scalar literals
    n_ops: jax.Array  # i32[] live op count (fori bound; padding never runs)
    out_reg: jax.Array  # i32[]

    @property
    def capacity(self) -> int:
        return self.opcode.shape[0]


# ---------------------------------------------------------------- compiler


class _Lowerer:
    def __init__(self, n: int, g: int):
        self.n, self.g = n, g
        self.ops: List[Tuple[int, int, int, int, float]] = []
        self.consts: List[float] = []  # pool values, register N_INPUTS + i
        self.reg_of: Dict[Any, int] = {}  # jaxpr Var id -> register
        self.const_reg: Dict[float, int] = {}
        self.cse: Dict[Tuple, int] = {}  # value numbering (all ops pure)
        # concatenate provenance: reg -> list of piece regs (for fold-away
        # of the stack+reduce pattern the transpiler's gpu loops emit)
        self.pieces: Dict[int, List[int]] = {}

    # -- emission

    def emit(self, op: int, a: int = 0, b: int = 0, c: int = 0,
             imm: float = 0.0) -> int:
        key = (op, a, b, c, float(imm))
        if op != OP_NOP:  # NOPs are concat placeholders with identity
            r = self.cse.get(key)
            if r is not None:
                return r
        self.ops.append((op, a, b, c, float(imm)))
        r = N_INPUTS + CONST_POOL + len(self.ops) - 1
        if op != OP_NOP:
            self.cse[key] = r
        return r

    def const(self, v: float) -> int:
        import math

        v = float(v)
        # key includes the sign bit: -0.0 == 0.0 in Python, but the pool
        # value is THE source of the literal and 1/-0 != 1/+0 — collapsing
        # them would break sign-exactness vs the jit tier
        key = (v, math.copysign(1.0, v))
        r = self.const_reg.get(key)
        if r is None:
            if len(self.consts) >= CONST_POOL:
                raise VMUnsupported(
                    f"more than {CONST_POOL} distinct constants")
            self.consts.append(v)
            r = N_INPUTS + len(self.consts) - 1
            self.const_reg[key] = r
        return r

    # -- operand resolution

    def reg(self, atom) -> int:
        from jax.extend.core import Literal

        if isinstance(atom, Literal):
            val = np.asarray(atom.val)
            if val.ndim == 0:
                return self.const(float(val))
            raise VMUnsupported(f"array literal of shape {val.shape}")
        r = self.reg_of.get(id(atom))
        if r is None:
            raise VMUnsupported(f"unbound variable {atom}")
        if r in self.pieces:
            # a stacked-pieces placeholder holds piece 0's value, not the
            # concatenation; only the reduce fold may consume it
            raise VMUnsupported("concatenate consumed by non-reduce op")
        return r

    def reg_any(self, atom) -> int:
        """Operand lookup that lets stacked-pieces placeholders through —
        used at call boundaries (nested jit) so a concatenate can reach the
        reduce inside the callee; any real consumer still goes via reg()."""
        r = self.reg_of.get(id(atom))
        if r is not None:
            return r
        return self.reg(atom)

    def bind(self, var, reg: int) -> None:
        self.reg_of[id(var)] = reg

    # -- lowering

    def lower_closed(self, closed, in_regs: Sequence[int]) -> List[int]:
        jaxpr = closed.jaxpr
        if len(jaxpr.invars) != len(in_regs):
            raise VMUnsupported("arity mismatch in nested jaxpr")
        for var, reg in zip(jaxpr.invars, in_regs):
            self.bind(var, reg)
        for var, val in zip(jaxpr.constvars, closed.consts):
            arr = np.asarray(val)
            if arr.ndim == 0:
                self.bind(var, self.const(float(arr)))
            else:
                raise VMUnsupported(f"array constant of shape {arr.shape}")
        for eqn in jaxpr.eqns:
            self.eqn(eqn)
        return [self.reg_any(v) for v in jaxpr.outvars]

    def eqn(self, eqn) -> None:
        name = eqn.primitive.name
        handler = getattr(self, f"_p_{name}", None)
        if handler is None:
            raise VMUnsupported(f"primitive {name}")
        handler(eqn)

    # -- helpers

    def _unary(self, eqn, op):
        self.bind(eqn.outvars[0], self.emit(op, self.reg(eqn.invars[0])))

    def _binary(self, eqn, op):
        a, b = (self.reg(v) for v in eqn.invars)
        self.bind(eqn.outvars[0], self.emit(op, a, b))

    @staticmethod
    def _is_int(var) -> bool:
        return jnp.issubdtype(var.aval.dtype, jnp.integer)

    # -- structural primitives

    def _p_pjit(self, eqn):
        outs = self.lower_closed(eqn.params["jaxpr"],
                                 [self.reg_any(v) for v in eqn.invars])
        for var, reg in zip(eqn.outvars, outs):
            self.bind(var, reg)

    _p_closed_call = _p_pjit
    _p_jit = _p_pjit  # jax>=0.7 names the inlineable call primitive "jit"

    def _p_custom_jvp_call(self, eqn):
        outs = self.lower_closed(eqn.params["call_jaxpr"],
                                 [self.reg_any(v) for v in eqn.invars])
        for var, reg in zip(eqn.outvars, outs):
            self.bind(var, reg)

    def _p_broadcast_in_dim(self, eqn):
        # storage is already fully broadcast [N, G]; pure aliasing
        self.bind(eqn.outvars[0], self.reg(eqn.invars[0]))

    def _p_squeeze(self, eqn):
        self.bind(eqn.outvars[0], self.reg(eqn.invars[0]))

    def _p_reshape(self, eqn):
        # reshapes between (), [1], [N], [N,1], [1,N] views of the same
        # broadcast value are aliases; anything that reorders data is not
        src = tuple(d for d in eqn.invars[0].aval.shape if d != 1)
        dst = tuple(d for d in eqn.outvars[0].aval.shape if d != 1)
        if src != dst:
            raise VMUnsupported(
                f"reshape {eqn.invars[0].aval.shape} -> "
                f"{eqn.outvars[0].aval.shape}")
        self.bind(eqn.outvars[0], self.reg(eqn.invars[0]))

    def _p_convert_element_type(self, eqn):
        src_f = not self._is_int(eqn.invars[0]) and \
            eqn.invars[0].aval.dtype != jnp.bool_
        dst_i = self._is_int(eqn.outvars[0])
        r = self.reg(eqn.invars[0])
        if src_f and dst_i:
            r = self.emit(OP_TRUNC, r)  # f->i casts truncate toward zero
        self.bind(eqn.outvars[0], r)

    def _p_stop_gradient(self, eqn):
        self.bind(eqn.outvars[0], self.reg(eqn.invars[0]))

    def _p_slice(self, eqn):
        aval = eqn.invars[0].aval
        start = eqn.params["start_indices"]
        limit = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or (1,) * len(start)
        if any(s != 1 for s in strides):
            raise VMUnsupported("strided slice")
        shape = aval.shape
        if len(shape) == 2 and shape == (self.n, self.g) and \
                start[0] == 0 and limit[0] == self.n and \
                limit[1] - start[1] == 1:
            # gpu column pick: [N, G][:, g:g+1] (transpiler's per-GPU loop)
            r = self.emit(OP_COL, self.reg(eqn.invars[0]), imm=start[1])
            self.bind(eqn.outvars[0], r)
            return
        if all(s == 0 for s in start) and tuple(limit) == tuple(shape):
            self.bind(eqn.outvars[0], self.reg(eqn.invars[0]))  # full slice
            return
        raise VMUnsupported(f"slice {shape} [{start}:{limit}]")

    def _p_concatenate(self, eqn):
        out_shape = eqn.outvars[0].aval.shape
        dim = eqn.params["dimension"]
        if (len(out_shape) == 2 and out_shape == (self.n, self.g)
                and dim == 1
                and all(v.aval.shape[1] == 1 for v in eqn.invars)):
            # the transpiler's per-GPU generators stack G column values
            # [N,1] into an [N,G] grid — build a REAL grid register so any
            # consumer (select_n masking, reductions, arithmetic) works
            acc = self.const(0.0)
            for col, v in enumerate(eqn.invars):
                acc = self.emit(OP_SETCOL, acc, self.reg(v), imm=col)
            self.bind(eqn.outvars[0], acc)
            return
        if len(out_shape) == 1:
            # 1-D stack (e.g. min/max over a scalar generator): keep piece
            # provenance; only a reduce may consume it, as a pairwise fold
            piece_regs = [self.reg(v) for v in eqn.invars]
            r = self.emit(OP_NOP, piece_regs[0])  # placeholder: piece 0
            self.pieces[r] = piece_regs
            self.bind(eqn.outvars[0], r)
            return
        raise VMUnsupported(
            f"concatenate -> {out_shape} along axis {dim}")

    # -- arithmetic

    def _p_add(self, eqn):
        self._binary(eqn, OP_ADD)

    def _p_sub(self, eqn):
        self._binary(eqn, OP_SUB)

    def _p_mul(self, eqn):
        self._binary(eqn, OP_MUL)

    def _p_div(self, eqn):
        a, b = (self.reg(v) for v in eqn.invars)
        r = self.emit(OP_DIV, a, b)
        if self._is_int(eqn.outvars[0]):
            r = self.emit(OP_TRUNC, r)  # lax int div truncates toward zero
        self.bind(eqn.outvars[0], r)

    def _p_rem(self, eqn):
        self._binary(eqn, OP_REM)

    def _p_max(self, eqn):
        self._binary(eqn, OP_MAX)

    def _p_min(self, eqn):
        self._binary(eqn, OP_MIN)

    def _p_pow(self, eqn):
        self._binary(eqn, OP_POW)

    def _p_integer_pow(self, eqn):
        y = eqn.params["y"]
        r = self.reg(eqn.invars[0])
        if y == 2:
            self.bind(eqn.outvars[0], self.emit(OP_MUL, r, r))  # x*x exact
        else:
            # jnp.power(x, float(y)) — what the removed IPOW branch ran
            self.bind(eqn.outvars[0],
                      self.emit(OP_POW, r, self.const(float(y))))

    def _p_neg(self, eqn):
        # x * -1, NOT 0 - x: sub flips the sign of +0.0 (0 - 0 = +0 where
        # -(+0) = -0), and 1/-0 != 1/+0 — the mul form is sign-exact
        self.bind(eqn.outvars[0],
                  self.emit(OP_MUL, self.reg(eqn.invars[0]),
                            self.const(-1.0)))

    def _p_abs(self, eqn):
        self._unary(eqn, OP_ABS)

    def _p_sign(self, eqn):
        self._unary(eqn, OP_SIGN)

    def _p_floor(self, eqn):
        self._unary(eqn, OP_FLOOR)

    def _p_ceil(self, eqn):
        self._unary(eqn, OP_CEIL)

    def _p_round(self, eqn):
        raise VMUnsupported("round")  # rounding-mode sensitive; keep exact

    def _p_exp(self, eqn):
        self._unary(eqn, OP_EXP)

    def _p_log(self, eqn):
        self._unary(eqn, OP_LOG)

    def _p_sqrt(self, eqn):
        self._unary(eqn, OP_SQRT)

    def _p_sin(self, eqn):
        self._unary(eqn, OP_SIN)

    def _p_cos(self, eqn):
        self._unary(eqn, OP_COS)

    def _p_tan(self, eqn):
        self._unary(eqn, OP_TAN)

    def _p_is_finite(self, eqn):
        self._unary(eqn, OP_ISFIN)

    # -- logic / comparison (bools are 0/1 f32, so the boolean ops are
    # plain arithmetic — no dedicated table branches)

    def _p_and(self, eqn):
        self._binary(eqn, OP_MUL)

    def _p_or(self, eqn):
        self._binary(eqn, OP_MAX)

    def _p_xor(self, eqn):
        self._binary(eqn, OP_NE)  # 0/1 xor == ne

    def _p_not(self, eqn):
        self.bind(eqn.outvars[0],
                  self.emit(OP_SUB, self.const(1.0),
                            self.reg(eqn.invars[0])))

    def _p_ge(self, eqn):
        self._binary(eqn, OP_GE)

    def _p_gt(self, eqn):
        self._binary(eqn, OP_GT)

    def _p_lt(self, eqn):
        self._binary(eqn, OP_LT)

    def _p_le(self, eqn):
        self._binary(eqn, OP_LE)

    def _p_eq(self, eqn):
        self._binary(eqn, OP_EQ)

    def _p_ne(self, eqn):
        self._binary(eqn, OP_NE)

    def _p_select_n(self, eqn):
        pred, x0, x1 = (self.reg(v) for v in eqn.invars)
        # select_n picks cases[pred]: pred==0 -> x0, pred==1 -> x1
        self.bind(eqn.outvars[0], self.emit(OP_SEL, pred, x0, x1))

    # -- reductions (GPU axis or stacked-pieces folds)

    def _reduce(self, eqn, op_grid, fold_op):
        (src,) = eqn.invars
        r = self.reg_of.get(id(src))  # direct lookup: pieces allowed here
        if r is None:
            r = self.reg(src)
        axes = tuple(eqn.params["axes"])
        shape = src.aval.shape
        if r in self.pieces:
            # transpiler's per-GPU generator: stack pieces then reduce over
            # the stacked axis -> fold the pieces pairwise instead
            if len(axes) != 1:
                raise VMUnsupported("multi-axis reduce of stacked pieces")
            regs = self.pieces[r]
            acc = regs[0]
            for p in regs[1:]:
                acc = self.emit(fold_op, acc, p)
            self.bind(eqn.outvars[0], acc)
            return
        if shape == (self.n, self.g) and axes == (1,):
            self.bind(eqn.outvars[0], self.emit(op_grid, r))
            return
        raise VMUnsupported(f"reduce over axes {axes} of {shape}")

    def _p_reduce_sum(self, eqn):
        self._reduce(eqn, OP_RSUM_G, OP_ADD)

    def _p_reduce_max(self, eqn):
        self._reduce(eqn, OP_RMAX_G, OP_MAX)

    def _p_reduce_min(self, eqn):
        self._reduce(eqn, OP_RMIN_G, OP_MIN)

    def _p_reduce_and(self, eqn):
        self._reduce(eqn, OP_RMIN_G, OP_MUL)  # 0/1 and == mul

    def _p_reduce_or(self, eqn):
        self._reduce(eqn, OP_RMAX_G, OP_MAX)


def _dummy_views(n: int, g: int) -> Tuple[PodView, NodeView]:
    i = jnp.zeros((), jnp.int32)
    vn = jnp.zeros(n, jnp.int32)
    vg = jnp.zeros((n, g), jnp.int32)
    return (PodView(i, i, i, i, i, i),
            NodeView(vn, vn, vn, vn, vn, vn, vg, vg, vg,
                     jnp.ones((n, g), bool), jnp.ones(n, bool)))


def compile_policy(code: str, n: int, g: int,
                   capacity: Optional[int] = None) -> VMProgram:
    """Lower candidate source to a VMProgram for padded shapes (n, g).

    Raises TranspileError (invalid candidate) or VMUnsupported (valid but
    outside the VM vocabulary -> caller uses the jit tier).
    """
    policy = transpiler.transpile(code)
    pod, nodes = _dummy_views(n, g)
    closed = jax.make_jaxpr(policy)(pod, nodes)

    lo = _Lowerer(n, g)
    flat_in = [*range(N_INPUTS)]
    # jaxpr invars = flattened (PodView, NodeView) leaves, in pytree order,
    # which matches the register input plan (both are field order)
    outs = lo.lower_closed(closed, flat_in)
    out_reg = outs[0]

    n_ops = len(lo.ops)
    cap = capacity or max(64, 1 << (n_ops - 1).bit_length())
    if n_ops > cap:
        raise VMUnsupported(f"program too long: {n_ops} ops > {cap}")
    arr = np.zeros((5, cap), np.float64)
    for k, (op, a, b, c, imm) in enumerate(lo.ops):
        arr[:, k] = (op, a, b, c, imm)
    pool = np.zeros(CONST_POOL, np.float64)
    pool[: len(lo.consts)] = lo.consts
    return VMProgram(
        opcode=jnp.asarray(arr[0], jnp.int32),
        a=jnp.asarray(arr[1], jnp.int32),
        b=jnp.asarray(arr[2], jnp.int32),
        c=jnp.asarray(arr[3], jnp.int32),
        imm=jnp.asarray(arr[4], _ambient_float()),
        consts=jnp.asarray(pool, _ambient_float()),
        n_ops=jnp.asarray(n_ops, jnp.int32),
        out_reg=jnp.asarray(out_reg, jnp.int32),
    )


def compile_for_workload(code: str, workload, capacity: int = 512) -> VMProgram:
    """``compile_policy`` with (n, g) taken from a parsed workload's padded
    cluster shape — the replay / trace-diff entry point
    (fks_tpu.obs.tracing), where the caller holds a Workload, not shapes."""
    c = workload.cluster
    return compile_policy(code, c.n_padded, c.g_padded, capacity=capacity)


# ---------------------------------------------------------------- executor


def _inputs(pod: PodView, nodes: NodeView) -> jax.Array:
    """[N_INPUTS, N, G] ambient-float broadcast input registers."""
    n, g = nodes.gpu_mask.shape
    F = _ambient_float()

    def full(x):
        return jnp.full((n, g), jnp.asarray(x, F))

    def cols(x):
        return jnp.broadcast_to(jnp.asarray(x, F)[:, None], (n, g))

    rows = [full(getattr(pod, f)) for f in _POD_FIELDS]
    rows += [cols(getattr(nodes, f)) for f in _NODE_SCALARS]
    rows += [jnp.asarray(getattr(nodes, f), F) for f in _NODE_GRIDS]
    rows += [jnp.asarray(nodes.gpu_mask, F), cols(nodes.node_mask)]
    return jnp.stack(rows)


def _branches(n: int, g: int):
    F = _ambient_float()

    def red(fn):
        def go(va, vb, vc, im):
            return jnp.broadcast_to(fn(va, axis=1, keepdims=True), (n, g))
        return go

    def col(va, vb, vc, im):
        c = jnp.clip(im.astype(jnp.int32), 0, g - 1)
        return jnp.broadcast_to(
            lax.dynamic_slice_in_dim(va, c, 1, axis=1), (n, g))

    return [
        lambda va, vb, vc, im: va,  # NOP (value = operand a)
        lambda va, vb, vc, im: va + vb,
        lambda va, vb, vc, im: va - vb,
        lambda va, vb, vc, im: va * vb,
        lambda va, vb, vc, im: va / vb,
        lambda va, vb, vc, im: jnp.maximum(va, vb),
        lambda va, vb, vc, im: jnp.minimum(va, vb),
        lambda va, vb, vc, im: (va >= vb).astype(F),
        lambda va, vb, vc, im: (va > vb).astype(F),
        lambda va, vb, vc, im: (va < vb).astype(F),
        lambda va, vb, vc, im: (va <= vb).astype(F),
        lambda va, vb, vc, im: (va == vb).astype(F),
        lambda va, vb, vc, im: (va != vb).astype(F),
        lambda va, vb, vc, im: jnp.where(va > 0.5, vc, vb),  # SEL
        lambda va, vb, vc, im: jnp.trunc(va),
        lambda va, vb, vc, im: jnp.floor(va),
        lambda va, vb, vc, im: jnp.ceil(va),
        lambda va, vb, vc, im: jnp.abs(va),
        lambda va, vb, vc, im: jnp.sign(va),
        lambda va, vb, vc, im: jnp.isfinite(va).astype(F),
        lambda va, vb, vc, im: jnp.fmod(va, vb),  # REM (trunc-signed)
        lambda va, vb, vc, im: jnp.power(va, vb),
        lambda va, vb, vc, im: jnp.exp(va),
        lambda va, vb, vc, im: jnp.log(va),
        lambda va, vb, vc, im: jnp.sqrt(va),
        lambda va, vb, vc, im: jnp.sin(va),
        lambda va, vb, vc, im: jnp.cos(va),
        lambda va, vb, vc, im: jnp.tan(va),
        col,  # COL
        red(jnp.sum),  # RSUM_G
        red(jnp.max),  # RMAX_G
        red(jnp.min),  # RMIN_G
        lambda va, vb, vc, im: jnp.where(  # SETCOL: va with column im := vb
            jnp.arange(g)[None, :] == im.astype(jnp.int32), vb, va),
    ]


def _execute(prog: VMProgram, pod: PodView, nodes: NodeView,
             bound) -> jax.Array:
    n, g = nodes.gpu_mask.shape
    branches = _branches(n, g)
    inp = _inputs(pod, nodes)
    cap = prog.capacity
    pool = jnp.broadcast_to(
        prog.consts.astype(_ambient_float())[:, None, None],
        (prog.consts.shape[0], n, g))
    regs = jnp.concatenate(
        [inp, pool, jnp.zeros((cap, n, g), _ambient_float())])
    op_base = N_INPUTS + prog.consts.shape[0]

    def body(k, regs):
        res = lax.switch(
            prog.opcode[k], branches,
            regs[prog.a[k]], regs[prog.b[k]], regs[prog.c[k]], prog.imm[k])
        return lax.dynamic_update_index_in_dim(regs, res, op_base + k, 0)

    regs = lax.fori_loop(0, bound, body, regs)
    out = regs[prog.out_reg][:, 0]
    # Non-finite values (a candidate dividing by zero, log of a negative)
    # would hit the int cast below with implementation-defined results;
    # mask them to 0 — the engines' "refuse placement" sentinel — so a
    # pathological candidate degrades deterministically. Identity for
    # finite values, which the cast assumes are integral.
    out = jnp.where(jnp.isfinite(out), out, jnp.zeros_like(out))
    # the policy's jaxpr already ends in an int cast; values are integral
    return out.astype(jnp.int32)


def score(prog: VMProgram, pod: PodView, nodes: NodeView) -> jax.Array:
    """Execute a lowered candidate -> i32 scores over the node axis.

    The signature matches ``ParamPolicyFn`` with the program as the
    parameter pytree, so every engine runner (plain, population, trace
    batch, mesh) accepts VM candidates unchanged.
    """
    return _execute(prog, pod, nodes, prog.n_ops)


def score_static(prog: VMProgram, pod: PodView, nodes: NodeView) -> jax.Array:
    """`score` with a STATIC trip count (the padded capacity) — the
    population-batched variant.

    Under ``vmap`` the per-candidate ``n_ops`` is a batched loop bound, so
    ``fori_loop`` would lower to a while_loop whose every iteration selects
    the full [N_INPUTS+CONST_POOL+cap, N, G] register file per lane to
    freeze finished lanes — far more HBM traffic than the ops themselves. Padding slots are
    OP_NOPs (they copy register 0 into a fresh register the output never
    reads), so running every lane to the static capacity is semantically
    free and keeps the loop bound unbatched. Stack candidates with
    ``stack_programs`` (which right-sizes the shared capacity) and pass this
    as the ``param_policy`` of ``make_population_run_fn``.
    """
    return _execute(prog, pod, nodes, prog.capacity)


def capacity_bucket(n_ops: int) -> int:
    """Program-capacity bucket for ``n_ops`` live ops: the smallest power
    of two covering it, floored at 64 (``compile_policy``'s own default
    ladder). The serve tier keys its compiled programs on this bucket —
    every champion padding to the same rung shares ONE executable, so a
    hot-swap is a table upload, never a recompile."""
    return max(64, 1 << max(0, int(n_ops) - 1).bit_length())


def pad_capacity(prog: VMProgram, capacity: int) -> VMProgram:
    """Re-pad a program's op arrays to ``capacity`` (NOP fill)."""
    n_live = int(prog.n_ops)
    if n_live > capacity:
        raise VMUnsupported(f"program too long: {n_live} ops > {capacity}")
    cur = prog.capacity
    if cur == capacity:
        return prog
    if cur < capacity:
        pad = capacity - cur

        def ext(x, fill):
            return jnp.concatenate(
                [x, jnp.full((pad,), fill, x.dtype)])

        return prog._replace(
            opcode=ext(prog.opcode, OP_NOP), a=ext(prog.a, 0),
            b=ext(prog.b, 0), c=ext(prog.c, 0), imm=ext(prog.imm, 0.0))
    return prog._replace(
        opcode=prog.opcode[:capacity], a=prog.a[:capacity],
        b=prog.b[:capacity], c=prog.c[:capacity], imm=prog.imm[:capacity])


def stack_programs(progs: Sequence[VMProgram],
                   capacity: Optional[int] = None) -> VMProgram:
    """Stack lowered candidates into ONE batched ``VMProgram`` pytree.

    The shared capacity defaults to the smallest power of two covering the
    longest member (min 32) so one compiled population-engine program
    serves every batch of that bucket. This is the data half of the
    population-batched code-candidate path: the reference evaluates a
    generation by forking a subprocess per candidate (reference:
    funsearch/funsearch_integration.py:535-562); here a generation is one
    stacked pytree handed to one XLA program.
    """
    if not progs:
        raise ValueError("stack_programs needs at least one program")
    longest = max(int(p.n_ops) for p in progs)
    cap = capacity or max(32, 1 << max(0, (longest - 1)).bit_length())
    padded = [pad_capacity(p, cap) for p in progs]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *padded)


def select_slot(stacked: VMProgram, slot) -> VMProgram:
    """One member of a ``stack_programs`` pytree by (possibly traced) slot
    index — the portfolio serve tier's per-lane dispatch primitive.

    Under ``vmap`` with the stacked program broadcast (``in_axes=None``)
    and ``slot`` batched per lane, this lowers to one gather per table, so
    a single executable answers a batch that MIXES champions: each lane
    reads its own opcode/operand rows out of the resident slot tables.
    The selected program's ``capacity`` stays shape-derived (static under
    tracing); ``n_ops``/``out_reg`` become traced scalars, which
    ``score_static`` never uses as loop bounds."""
    return jax.tree_util.tree_map(lambda x: x[slot], stacked)


def bucket_lanes(n: int, multiple: int = 1) -> int:
    """Lane count for a batch of ``n`` programs: the next power of two
    (so the jitted population runner retraces per BUCKET, never per
    generation), rounded up to a multiple of ``multiple`` — the mesh
    shard count, so a stacked batch divides evenly over the population
    shards. For power-of-two shard counts (every real topology) the
    round-up is absorbed by the bucket and the bucket set is unchanged.
    """
    pop = max(1, 1 << (max(1, n) - 1).bit_length())
    return -(-pop // multiple) * multiple


def lower_fake_candidates(n: int, g: int, need: int, *, capacity: int = 256,
                          seed: int = 7, max_tries_factor: int = 12):
    """Generate + lower ``need`` FakeLLM candidates to VM programs.

    The shared measurement protocol for code-candidate throughput (bench.py
    ``codetput`` stage and the TPU session's ``vmbatch`` stage use the same
    candidate source so their numbers stay apples-to-apples): deterministic
    FakeLLM completions, template-filled, lowered via ``compile_policy``;
    junk/too-long candidates are skipped. Returns ``(progs, lower_seconds)``
    — per-candidate host lowering times ride along for the lowering-cost
    metric. The attempt loop is bounded by ``max_tries_factor * need``, so
    a degenerate generator cannot spin forever; callers must check
    ``len(progs)`` against ``need``.
    """
    import time as _time

    from fks_tpu.funsearch import llm, template

    fake = llm.FakeLLM(seed=seed, junk_rate=0.0)
    progs: List[VMProgram] = []
    lower_s: List[float] = []
    for _ in range(max_tries_factor * need):
        if len(progs) >= need:
            break
        code = template.fill_template(fake.complete("x"))
        t0 = _time.perf_counter()
        try:
            prog = compile_policy(code, n, g, capacity=capacity)
        except Exception:  # noqa: BLE001 — outside the VM vocabulary
            continue
        lower_s.append(_time.perf_counter() - t0)
        progs.append(prog)
    return progs, lower_s
