"""Restricted-Python -> vectorized JAX policy compiler.

This is the TPU-native answer to the reference's sandboxed interpretation of
evolved code: where the reference ``exec``s candidate source and calls the
resulting scalar ``priority_function(pod, node)`` once per node per event
(reference: funsearch/funsearch_integration.py:67-101,
funsearch/safe_execution.py:126-168), here the SAME source is compiled once
into a jit-traceable ``PolicyFn`` that scores ALL nodes in one fused vector
program — so evolved candidates run inside the device event loop at zoo-policy
speed, with no Python in the hot path.

Lowering rules (SURVEY.md §7 "dynamic policy code on device"):
- every value is (broadcastable to) an array over the node axis N;
- ``if``/``elif``/``else`` -> both branches execute, assignments blend under
  the branch predicate (``jnp.where``) — classic predication;
- ``return`` -> a per-lane ``returned`` mask + first-return-wins value blend;
- ``for gpu in node.gpus`` -> a static unrolled loop over the padded GPU
  axis G, body masked by ``gpu_mask[:, g]`` (real-GPU lanes only);
- ``a and b`` / ``a or b`` keep Python value semantics
  (``where(truthy(a), b, a)`` / ``where(truthy(a), a, b)``);
- ``int(x)`` truncates toward zero like Python; ``//``/``%`` follow Python
  sign semantics (numpy matches for these);
- the final result is truncated to int32 — the engine's score contract.

Divergence from the reference, by design: arithmetic faults (division by
zero, log of a negative) do not raise — lanes whose score comes out
non-finite score 0 (refuse) instead of aborting the whole candidate. The
reference maps such candidates to fitness 0 via the exception path
(funsearch_integration.py:63-64); here they merely refuse the affected
nodes. The prompt instructs guarded division, and differential tests only
use guarded candidates.
"""
from __future__ import annotations

import ast
import math
from typing import Any, Dict, Optional

import jax.numpy as jnp

from fks_tpu.funsearch import sandbox
from fks_tpu.sim.types import NodeView, PodView, PolicyFn


class TranspileError(ValueError):
    """Candidate uses syntax outside the JAX-lowerable subset."""


# ------------------------------------------------------------ object model

class _Pod:
    """Scalar pod fields (broadcast over N by jnp)."""

    FIELDS = ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
              "creation_time", "duration_time")

    def __init__(self, pod: PodView):
        self._pod = pod

    def attr(self, name: str):
        if name not in self.FIELDS:
            raise TranspileError(f"unknown pod attribute {name!r}")
        return getattr(self._pod, name)


class _GpuList:
    """``node.gpus`` — iteration yields one padded-GPU column at a time."""

    def __init__(self, nodes: NodeView):
        self.nodes = nodes

    @property
    def count(self):
        return self.nodes.num_gpus  # i32[N] == len(node.gpus) per node

    @property
    def padded(self) -> int:
        return self.nodes.gpu_mask.shape[1]


class _Gpu:
    """One column g of the per-GPU arrays. ``memory_mib_left`` maps to the
    static total: the reference never allocates GPU memory
    (SURVEY.md §2 fine print 11)."""

    def __init__(self, nodes: NodeView, g: int):
        self.nodes, self.g = nodes, g

    def attr(self, name: str):
        n, g = self.nodes, self.g
        if name == "gpu_milli_left":
            return n.gpu_milli_left[:, g]
        if name == "gpu_milli_total":
            return n.gpu_milli_total[:, g]
        if name in ("memory_mib_left", "memory_mib_total"):
            return n.gpu_mem_total[:, g]
        raise TranspileError(f"unknown gpu attribute {name!r}")


class _SortedVals:
    """``sorted(expr for gpu in node.gpus [if cond])`` — per-node ascending
    values over the padded GPU axis. Masked-out slots sort to the tail via
    a dtype-max sentinel; ``count[N]`` is the per-node live length, so
    indexing can reproduce Python's IndexError as lane poison (the
    reference maps the raised IndexError to candidate fitness 0,
    funsearch_integration.py:63-64; here only the offending lanes refuse).
    """

    def __init__(self, vals, sel):
        vals = jnp.asarray(vals)
        if jnp.issubdtype(vals.dtype, jnp.integer):
            big = jnp.iinfo(vals.dtype).max
        else:
            big = jnp.asarray(jnp.inf, vals.dtype)
        self.vals = jnp.sort(jnp.where(sel, vals, big), axis=1)
        self.count = jnp.sum(sel, axis=1).astype(jnp.int32)

    def index(self, k: int, mask, interp):
        gp = self.vals.shape[1]
        if k >= 0:
            interp.poison = interp.poison | (mask & (self.count <= k))
            return self.vals[:, min(k, gp - 1)]
        interp.poison = interp.poison | (mask & (self.count < -k))
        idx = jnp.clip(self.count + k, 0, gp - 1)
        return jnp.take_along_axis(self.vals, idx[:, None], axis=1)[:, 0]


class _Node:
    FIELDS = ("cpu_milli_left", "cpu_milli_total", "memory_mib_left",
              "memory_mib_total", "gpu_left")

    def __init__(self, nodes: NodeView):
        self._nodes = nodes
        self.gpus = _GpuList(nodes)

    def attr(self, name: str):
        if name == "gpus":
            return self.gpus
        if name not in self.FIELDS:
            raise TranspileError(f"unknown node attribute {name!r}")
        return getattr(self._nodes, name)


def _to_inexact(v):
    """Float coercion matching the reference's numeric model: CPython
    computes ``/`` and ``math.*`` in binary64 regardless of operand types
    (reference: funsearch/safe_execution.py math whitelist), so integral
    operands are promoted to the ambient float — f64 under x64 (tests,
    golden parity), f32 otherwise (TPU). Without this, JAX's
    ``to_inexact_dtype`` picks f32 for int32 operands and f64 for int64
    ones even under x64, so the SAME candidate mixes precisions depending
    on which entity field fed the expression — and the VM tier
    (fks_tpu.funsearch.vm), which runs a single-dtype register model,
    cannot reproduce the mix."""
    a = jnp.asarray(v)
    if jnp.issubdtype(a.dtype, jnp.inexact):
        return a
    return a.astype(jnp.float64 if _x64() else jnp.float32)


def _mathfn(fn):
    def go(*args):
        return fn(*(_to_inexact(a) for a in args))
    return go


_MATH_FNS = {
    "sqrt": _mathfn(jnp.sqrt), "log": _mathfn(jnp.log),
    "exp": _mathfn(jnp.exp), "pow": _mathfn(jnp.power),
    "sin": _mathfn(jnp.sin), "cos": _mathfn(jnp.cos),
    "tan": _mathfn(jnp.tan),
}


def _truthy(v):
    if isinstance(v, bool):
        return v
    a = jnp.asarray(v)
    return a if a.dtype == jnp.bool_ else a != 0


def _int_trunc(v):
    """Python int(): truncate toward zero. Non-finite inputs (where Python
    raises OverflowError/ValueError and the reference maps the candidate to
    fitness 0) become 0 — the lane refuses (module docstring divergence)."""
    a = jnp.asarray(v)
    if jnp.issubdtype(a.dtype, jnp.integer):
        return a
    if a.dtype == bool:
        return a.astype(jnp.int32)
    return jnp.where(jnp.isfinite(a), jnp.trunc(a), 0).astype(jnp.int32)


def _where(mask, new, old):
    return jnp.where(mask, new, old)


class _Interp:
    """Vectorized symbolic executor over the function AST.

    ``mask`` threading: each block executes under an "active lanes" bool[N];
    assignments and returns only take effect on active lanes. ``returned``
    is global (a return deactivates the lane for the rest of the function,
    including subsequent loop iterations).
    """

    MAX_UNROLL = 64  # static range() loops larger than this are rejected

    def __init__(self, pod: PodView, nodes: NodeView):
        self.n = nodes.node_mask.shape[0]
        self.env: Dict[str, Any] = {
            "pod": _Pod(pod), "node": _Node(nodes), "math": "math",
        }
        self.nodes = nodes
        self.returned = jnp.zeros(self.n, bool)
        self.retval = jnp.zeros(self.n, jnp.int32)
        # lanes where Python would have raised (int() of a non-finite,
        # min()/max() of an empty generator, read of a variable the taken
        # path never assigned); they refuse at the end instead of aborting
        # the whole candidate
        self.poison = jnp.zeros(self.n, bool)
        # per-variable "assigned on this lane" masks; absent = all lanes
        self.defined: Dict[str, Any] = {}
        # syntactic conditional-nesting depth: 0 = function top level, where
        # a statement executes on every lane that hasn't returned (masks
        # become tracers after the first data-dependent return, so
        # "unconditional" must be tracked syntactically, not by value)
        self.cond_depth = 0

    # ----- statements

    def run_block(self, stmts, mask):
        for st in stmts:
            self.run_stmt(st, mask & ~self.returned)

    def run_stmt(self, st, mask):
        if isinstance(st, ast.Assign):
            if len(st.targets) != 1 or not isinstance(st.targets[0], ast.Name):
                raise TranspileError("only simple `name = expr` assignment")
            self.assign(st.targets[0].id, self.eval(st.value, mask), mask)
        elif isinstance(st, ast.AugAssign):
            if not isinstance(st.target, ast.Name):
                raise TranspileError("only simple augmented assignment")
            cur = self.load(st.target.id, mask)
            val = self.binop(st.op, cur, self.eval(st.value, mask))
            self.assign(st.target.id, val, mask)
        elif isinstance(st, ast.If):
            cond = _truthy(self.eval(st.test, mask))
            self.cond_depth += 1
            try:
                self.run_block(st.body, mask & cond)
                if st.orelse:
                    self.run_block(st.orelse, mask & ~cond)
            finally:
                self.cond_depth -= 1
        elif isinstance(st, ast.Return):
            if st.value is None:
                raise TranspileError("bare return not allowed")
            val = self.eval(st.value, mask)
            active = mask & ~self.returned
            self.retval = _where(active, val, self.retval)
            self.returned = self.returned | active
        elif isinstance(st, ast.For):
            self.run_for(st, mask)
        elif isinstance(st, ast.Expr):
            if isinstance(st.value, ast.Constant):  # docstring
                return
            raise TranspileError("expression statements have no effect")
        elif isinstance(st, ast.Pass):
            return
        else:
            raise TranspileError(f"unsupported statement {type(st).__name__}")

    def run_for(self, st, mask):
        if st.orelse:
            raise TranspileError("for/else not supported")
        it = self.eval_iter(st.iter, mask)
        if isinstance(it, _GpuList):
            if not isinstance(st.target, ast.Name):
                raise TranspileError("gpu loop target must be a name")
            self.cond_depth += 1  # bodies run under a per-lane gpu mask
            try:
                for g in range(it.padded):
                    gmask = mask & self.nodes.gpu_mask[:, g] & ~self.returned
                    self.env[st.target.id] = _Gpu(self.nodes, g)
                    self.run_block(st.body, gmask)
            finally:
                self.cond_depth -= 1
            self.env.pop(st.target.id, None)
        elif isinstance(it, _EnumGpus):
            if not (isinstance(st.target, ast.Tuple)
                    and len(st.target.elts) == 2
                    and all(isinstance(e, ast.Name) for e in st.target.elts)):
                raise TranspileError("enumerate target must be `i, gpu`")
            iname, gname = (e.id for e in st.target.elts)
            self.cond_depth += 1
            try:
                for g in range(it.gpus.padded):
                    gmask = mask & self.nodes.gpu_mask[:, g] & ~self.returned
                    self.env[iname] = g
                    self.env[gname] = _Gpu(self.nodes, g)
                    self.run_block(st.body, gmask)
            finally:
                self.cond_depth -= 1
            self.env.pop(iname, None)
            self.env.pop(gname, None)
        elif isinstance(it, range):
            if not isinstance(st.target, ast.Name):
                raise TranspileError("range loop target must be a name")
            if len(it) > self.MAX_UNROLL:
                raise TranspileError(f"range loop longer than {self.MAX_UNROLL}")
            for i in it:
                self.env[st.target.id] = i
                self.run_block(st.body, mask & ~self.returned)
            self.env.pop(st.target.id, None)
        else:
            raise TranspileError(
                "only `for gpu in node.gpus`, enumerate(node.gpus), or "
                "constant range() loops are supported")

    def eval_iter(self, node, mask):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
            if node.func.id == "range":
                args = [self.eval(a, mask) for a in node.args]
                _check_arity("range", len(args))
                if not all(isinstance(a, int) for a in args):
                    raise TranspileError("range() bounds must be static ints")
                return range(*args)
            if node.func.id == "enumerate":
                _check_arity("enumerate", len(node.args))
                inner = self.eval(node.args[0], mask)
                if isinstance(inner, _GpuList):
                    return _EnumGpus(inner)
                raise TranspileError("enumerate() only over node.gpus")
        return self.eval(node, mask)

    # ----- environment

    def assign(self, name: str, val, mask):
        if name in ("pod", "node", "math"):
            raise TranspileError(f"cannot rebind {name!r}")
        if isinstance(val, (_Pod, _Node, _Gpu, _GpuList, _EnumGpus)):
            raise TranspileError("cannot store entity objects in variables")
        active = mask & ~self.returned
        all_active = _statically_true(active)
        if isinstance(self.env.get(name), _SortedVals) \
                and not isinstance(val, _SortedVals):
            # overwriting a list with a scalar/array: plain rebinding is
            # fine when the statement executes on every lane that hasn't
            # returned (returned lanes can never read the name again);
            # a branch-local overwrite would need lane-wise blending of a
            # list with a scalar, which has no meaning
            if self.cond_depth != 0:
                raise TranspileError(
                    "cannot conditionally overwrite a sorted() list")
            self.env[name] = val
            self.defined.pop(name, None)
            return
        if isinstance(val, _SortedVals):
            # the object holds data for EVERY lane, so a masked first
            # assignment just records which lanes may legally read it
            # (others poison on read, like any conditionally-bound name);
            # lane-wise BLENDING of two different lists is meaningless
            if name in self.env and not all_active:
                raise TranspileError(
                    "cannot conditionally reassign a sorted() list")
            self.env[name] = val
            if name in self.defined:
                self.defined[name] = self.defined[name] | active
            elif not all_active:
                self.defined[name] = active
            return
        if name in self.env:
            old = self.env[name]
            if isinstance(old, (int, float)) and isinstance(val, (int, float)) \
                    and all_active:
                self.env[name] = val  # stay scalar on unconditional paths
            else:
                self.env[name] = _where(active, val, old)
            if name in self.defined:
                self.defined[name] = self.defined[name] | active
        else:
            if isinstance(val, (int, float)) and all_active:
                self.env[name] = val
            else:
                # first assignment under a condition: untaken lanes hold a
                # placeholder 0 and are poisoned if they ever READ it
                # (Python raises UnboundLocalError there -> candidate
                # fitness 0 in the reference; here the lane refuses)
                self.env[name] = _where(active, val, 0)
                if not all_active:
                    self.defined[name] = active

    def load(self, name: str, mask=None):
        if name not in self.env:
            raise TranspileError(f"undefined variable {name!r}")
        if mask is not None and name in self.defined:
            self.poison = self.poison | (mask & ~self.defined[name])
        return self.env[name]

    # ----- expressions

    def eval(self, node, mask):
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool) or isinstance(node.value, (int, float)):
                return node.value
            raise TranspileError(f"unsupported constant {node.value!r}")
        if isinstance(node, ast.Name):
            return self.load(node.id, mask)
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value, mask)
            if isinstance(base, _Pod) or isinstance(base, _Node) \
                    or isinstance(base, _Gpu):
                return base.attr(node.attr)
            raise TranspileError(
                f"attribute access on non-entity value: .{node.attr}")
        if isinstance(node, ast.BinOp):
            return self.binop(node.op, self.eval(node.left, mask),
                              self.eval(node.right, mask))
        if isinstance(node, ast.UnaryOp):
            v = self.eval(node.operand, mask)
            if isinstance(node.op, ast.USub):
                return -v if isinstance(v, (int, float)) else jnp.negative(v)
            if isinstance(node.op, ast.UAdd):
                return v
            if isinstance(node.op, ast.Not):
                t = _truthy(v)
                return (not t) if isinstance(t, bool) else jnp.logical_not(t)
            raise TranspileError("unsupported unary operator")
        if isinstance(node, ast.BoolOp):
            # later operands evaluate under the lanes where Python would
            # actually reach them (short-circuit narrowing), so side effects
            # (poison) in an unreached operand can't leak
            out = self.eval(node.values[0], mask)
            reach = mask
            for v in node.values[1:]:
                t = _truthy(out)
                if isinstance(t, bool):
                    if isinstance(node.op, ast.And):
                        out = self.eval(v, reach) if t else out
                    else:
                        out = out if t else self.eval(v, reach)
                elif isinstance(node.op, ast.And):
                    reach = reach & t
                    out = _where(t, self.eval(v, reach), out)
                else:
                    reach = reach & ~t
                    out = _where(t, out, self.eval(v, reach))
            return out
        if isinstance(node, ast.Compare):
            left = self.eval(node.left, mask)
            result = None
            reach = mask
            for op, rhs_node in zip(node.ops, node.comparators):
                rhs = self.eval(rhs_node, reach)
                c = self.compare(op, left, rhs)
                result = c if result is None else jnp.logical_and(result, c)
                if not isinstance(result, bool):
                    reach = reach & result  # chained comparisons short-circuit
                left = rhs
            return result
        if isinstance(node, ast.IfExp):
            cond = _truthy(self.eval(node.test, mask))
            if isinstance(cond, bool):
                return self.eval(node.body if cond else node.orelse, mask)
            a = self.eval(node.body, mask & cond)
            b = self.eval(node.orelse, mask & ~cond)
            return _where(cond, a, b)
        if isinstance(node, ast.Call):
            return self.call(node, mask)
        if isinstance(node, ast.Subscript):
            return self.subscript(node, mask)
        raise TranspileError(f"unsupported expression {type(node).__name__}")

    def subscript(self, node, mask):
        base = self.eval(node.value, mask)
        idx = node.slice
        k: Optional[int] = None
        if isinstance(idx, ast.Constant) and isinstance(idx.value, int) \
                and not isinstance(idx.value, bool):
            k = idx.value
        elif isinstance(idx, ast.UnaryOp) and isinstance(idx.op, ast.USub) \
                and isinstance(idx.operand, ast.Constant) \
                and isinstance(idx.operand.value, int):
            k = -idx.operand.value
        if k is None:
            raise TranspileError("subscripts must use a static integer index")
        if isinstance(base, _SortedVals):
            return base.index(k, mask, self)
        if isinstance(base, _GpuList):
            # node.gpus[k]: out-of-range lanes poison (Python IndexError)
            if k < 0:
                raise TranspileError("negative gpu index not supported")
            if k >= base.padded:
                self.poison = self.poison | mask
                return _Gpu(self.nodes, 0)
            self.poison = self.poison | (mask & ~self.nodes.gpu_mask[:, k])
            return _Gpu(self.nodes, k)
        raise TranspileError("subscript of unsupported value")

    def binop(self, op, a, b):
        both_py = isinstance(a, (int, float)) and isinstance(b, (int, float))
        if isinstance(op, ast.Add):
            return a + b
        if isinstance(op, ast.Sub):
            return a - b
        if isinstance(op, ast.Mult):
            return a * b
        if isinstance(op, ast.Div):
            if both_py:
                return a / b if b != 0 else math.inf  # lowered to refuse later
            return _to_inexact(a) / _to_inexact(b)
        if isinstance(op, ast.FloorDiv):
            if both_py:
                return a // b if b != 0 else math.inf
            return jnp.floor_divide(jnp.asarray(a), jnp.asarray(b))
        if isinstance(op, ast.Mod):
            if both_py:
                return a % b if b != 0 else math.inf
            return jnp.mod(jnp.asarray(a), jnp.asarray(b))
        if isinstance(op, ast.Pow):
            if both_py:
                try:
                    return a ** b
                except (OverflowError, ZeroDivisionError):
                    return math.inf
            return jnp.power(a, b)
        raise TranspileError("unsupported binary operator")

    def compare(self, op, a, b):
        if isinstance(op, ast.Eq):
            return jnp.equal(a, b) if not _is_py(a, b) else a == b
        if isinstance(op, ast.NotEq):
            return jnp.not_equal(a, b) if not _is_py(a, b) else a != b
        if isinstance(op, ast.Lt):
            return jnp.less(a, b) if not _is_py(a, b) else a < b
        if isinstance(op, ast.LtE):
            return jnp.less_equal(a, b) if not _is_py(a, b) else a <= b
        if isinstance(op, ast.Gt):
            return jnp.greater(a, b) if not _is_py(a, b) else a > b
        if isinstance(op, ast.GtE):
            return jnp.greater_equal(a, b) if not _is_py(a, b) else a >= b
        raise TranspileError("unsupported comparison")

    def call(self, node, mask):
        if node.keywords:
            raise TranspileError("keyword arguments not supported")
        f = node.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "math" \
                    and f.attr in _MATH_FNS:
                args = [self.eval(a, mask) for a in node.args]
                _check_arity(f"math.{f.attr}", len(args))
                return _MATH_FNS[f.attr](*args)
            raise TranspileError("only math.<fn> attribute calls allowed")
        if not isinstance(f, ast.Name):
            raise TranspileError("computed call targets not allowed")
        name = f.id

        # reductions over a generator comprehension
        if name in ("sum", "min", "max") and len(node.args) == 1 \
                and isinstance(node.args[0], ast.GeneratorExp):
            return self.reduce_genexp(name, node.args[0], mask)
        if name == "sorted":
            if len(node.args) == 1 \
                    and isinstance(node.args[0], ast.GeneratorExp):
                return _SortedVals(*self.genexp_grid(node.args[0], mask))
            raise TranspileError("sorted() only over a generator")

        args = [self.eval(a, mask) for a in node.args]
        _check_arity(name, len(args))
        if name == "abs":
            (a,) = args
            return abs(a) if isinstance(a, (int, float)) else jnp.abs(a)
        if name in ("min", "max"):
            if len(args) < 2:
                raise TranspileError(f"{name}() needs 2+ args or a generator")
            fn = jnp.minimum if name == "min" else jnp.maximum
            py = min if name == "min" else max
            out = args[0]
            for a in args[1:]:
                out = py(out, a) if _is_py(out, a) else fn(out, a)
            return out
        if name == "len":
            (a,) = args
            if isinstance(a, (_GpuList, _SortedVals)):
                return a.count
            raise TranspileError("len() only of node.gpus or sorted(...)")
        if name == "int":
            (a,) = args
            if isinstance(a, (int, float)):
                if not math.isfinite(a):
                    self.poison = self.poison | mask
                    return 0
                return int(a)
            arr = jnp.asarray(a)
            if jnp.issubdtype(arr.dtype, jnp.floating):
                self.poison = self.poison | (mask & ~jnp.isfinite(arr))
            return _int_trunc(a)
        if name == "float":
            (a,) = args
            return float(a) if isinstance(a, (int, float)) \
                else jnp.asarray(a).astype(jnp.float64 if _x64() else jnp.float32)
        if name == "bool":
            (a,) = args
            return _truthy(a)
        if name == "round":
            args2 = args if len(args) == 2 else (args[0],)
            if all(isinstance(a, (int, float)) for a in args2):
                return round(*args2)
            if len(args2) == 2:
                if not isinstance(args2[1], int):
                    raise TranspileError("round() digits must be static")
                s = 10 ** args2[1]
                return jnp.round(jnp.asarray(args2[0]) * s) / s
            return jnp.round(jnp.asarray(args2[0]))
        if name == "sum":
            raise TranspileError("sum() only over a generator")
        raise TranspileError(f"call to unsupported function {name!r}")

    def genexp_grid(self, gen, mask):
        """Evaluate ``(expr for gpu in node.gpus [if cond])`` into
        ``(vals[N, Gp], sel[N, Gp])`` over the padded GPU axis."""
        if len(gen.generators) != 1:
            raise TranspileError("single-clause generators only")
        comp = gen.generators[0]
        if comp.is_async:
            raise TranspileError("async generators not allowed")
        it = self.eval_iter(comp.iter, mask)
        if not isinstance(it, _GpuList):
            raise TranspileError("generators only over node.gpus")
        if not isinstance(comp.target, ast.Name):
            raise TranspileError("generator target must be a name")
        tname = comp.target.id
        saved = self.env.get(tname)
        cols, conds = [], []
        for g in range(it.padded):
            self.env[tname] = _Gpu(self.nodes, g)
            sel = self.nodes.gpu_mask[:, g]
            for if_ in comp.ifs:
                sel = sel & _truthy(self.eval(if_, mask))
            cols.append(jnp.asarray(self.eval(gen.elt, mask)))
            conds.append(sel)
        if saved is None:
            self.env.pop(tname, None)
        else:
            self.env[tname] = saved
        vals = jnp.stack([jnp.broadcast_to(c, (self.n,)) for c in cols], axis=1)
        sel = jnp.stack(conds, axis=1)
        return vals, sel

    def reduce_genexp(self, name, gen, mask):
        """``sum/min/max(expr for gpu in node.gpus [if cond])`` -> masked
        reduction over the padded GPU axis."""
        vals, sel = self.genexp_grid(gen, mask)
        if name == "sum":
            return jnp.sum(jnp.where(sel, vals, 0), axis=1)
        # Python min()/max() of an empty iterable raises (-> reference maps
        # the candidate to fitness 0); lanes whose generator selects nothing
        # are poisoned so the identity sentinel can never leak as a score
        self.poison = self.poison | (mask & ~jnp.any(sel, axis=1))
        if jnp.issubdtype(vals.dtype, jnp.integer):
            info = jnp.iinfo(vals.dtype)
            big = info.max if name == "min" else info.min
        else:
            big = jnp.inf if name == "min" else -jnp.inf
        out = jnp.where(sel, vals, jnp.asarray(big, vals.dtype))
        return jnp.min(out, axis=1) if name == "min" else jnp.max(out, axis=1)


class _EnumGpus:
    def __init__(self, gpus: _GpuList):
        self.gpus = gpus


#: name -> (min_args, max_args) for whitelisted calls; malformed arity must
#: reject the candidate (TranspileError), not crash the evolution loop
_ARITY = {
    "abs": (1, 1), "len": (1, 1), "int": (1, 1), "float": (1, 1),
    "bool": (1, 1), "round": (1, 2), "min": (2, None), "max": (2, None),
    "range": (1, 3), "enumerate": (1, 1),
    "math.sqrt": (1, 1), "math.log": (1, 1), "math.exp": (1, 1),
    "math.pow": (2, 2), "math.sin": (1, 1), "math.cos": (1, 1),
    "math.tan": (1, 1),
}


def _check_arity(name: str, n: int) -> None:
    lo, hi = _ARITY.get(name, (0, None))
    if n < lo or (hi is not None and n > hi):
        raise TranspileError(f"{name}() called with {n} argument(s)")


def _is_py(*vals):
    return all(isinstance(v, (int, float, bool)) for v in vals)


def _statically_true(mask) -> bool:
    """True iff ``mask`` is a compile-time constant that is all-True (safe
    under jit: tracers — data-dependent masks — report False)."""
    import jax
    if isinstance(mask, jax.core.Tracer):
        return False
    try:
        return bool(jnp.all(mask))
    except Exception:
        return False


def _x64() -> bool:
    return jnp.zeros(0).dtype == jnp.float64


# --------------------------------------------------------------- public API

def canonical_key(code: str) -> str:
    """Compile-cache key: the AST dump, insensitive to comments/whitespace
    (SURVEY.md §7: dedup doubles as compile-cache key)."""
    return ast.dump(ast.parse(code))


def transpile(code: str, entry_point: str = "priority_function") -> PolicyFn:
    """Validate + compile candidate source into a vectorized PolicyFn.

    Raises ``TranspileError`` for code outside the lowerable subset (this is
    the TPU-tightened third validation stage, SURVEY.md §2 fine print 10).
    """
    r = sandbox.validate(code, entry_point)
    if not r:
        raise TranspileError(f"validation failed: {r.reason}")
    tree = ast.parse(code)
    fn = next(n for n in tree.body if isinstance(n, ast.FunctionDef))
    body = fn.body

    def policy(pod: PodView, nodes: NodeView):
        interp = _Interp(pod, nodes)
        interp.run_block(body, jnp.ones(interp.n, bool))
        val = interp.retval
        # lanes that never returned, or whose arithmetic went non-finite,
        # refuse (see module docstring divergence note)
        vf = jnp.asarray(val)
        if not jnp.issubdtype(vf.dtype, jnp.integer):
            finite = jnp.isfinite(vf)
            vf = jnp.where(finite, vf, 0)
        out = _int_trunc(vf).astype(jnp.int32)
        return jnp.where(interp.returned & ~interp.poison, out, 0)

    _dry_trace(policy)
    return policy


def _dry_trace(policy: PolicyFn) -> None:
    """Abstractly evaluate the lowered policy on tiny dummy views so subset
    violations (unsupported calls, oversized unrolls, unknown attributes)
    surface at transpile time, not at first simulation."""
    import jax

    n, g = 2, 2
    i = jnp.zeros((), jnp.int32)
    pod = PodView(i, i, i, i, i, i)
    vn = jnp.zeros(n, jnp.int32)
    vg = jnp.zeros((n, g), jnp.int32)
    nodes = NodeView(vn, vn, vn, vn, vn, vn, vg, vg, vg,
                     jnp.ones((n, g), bool), jnp.ones(n, bool))
    jax.eval_shape(policy, pod, nodes)
