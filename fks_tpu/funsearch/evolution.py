"""The FunSearch evolution controller.

TPU-native re-design of the reference driver (reference:
funsearch/funsearch_integration.py:124-604 ``SimpleFunSearch``): identical
population semantics — descending sort, top-``elite_size`` elites, at most
``min(8, population_size - elite_size)`` new candidates per generation,
difflib near-duplicate suppression against equal-or-better incumbents,
truncation to ``population_size``, early stop on threshold — but the fitness
stage is the on-device backend (one compiled XLA program per unique
candidate, trace parsed once) instead of a subprocess pool that re-parses
CSVs per candidate.

Additions over the reference, called for by SURVEY.md §5:
- full checkpoint/resume (population + RNG state + generation), which the
  reference lacks entirely (its champion JSONs are write-only);
- a hermetic fake-LLM mode so the loop is testable without network;
- per-generation metrics records for observability.
"""
from __future__ import annotations

import contextlib
import dataclasses
import difflib
import functools
import hashlib
import json
import os
import random
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax

from fks_tpu import obs
from fks_tpu.obs import trace_ctx
from fks_tpu.funsearch import llm as llm_mod
from fks_tpu.funsearch import template
from fks_tpu.funsearch.backend import CodeEvaluator, EvalRecord
from fks_tpu.resilience.wal import GenerationWAL
from fks_tpu.sim.engine import SimConfig


# ------------------------------------------------------------------ config

@dataclasses.dataclass
class LLMSettings:
    """Reference ``openrouter`` block (configs/llm_config.json:2-8)."""

    api_key: str = ""
    base_url: str = "https://openrouter.ai/api/v1"
    model: str = "deepseek/deepseek-chat-v3-0324"
    max_tokens: int = 500
    temperature: float = 0.7
    # reachable from llm_config.json (unlike the reference, which rides the
    # SDK's 600 s default and retries): one hung request must not stall a
    # generation's thread-pool slot for 10 minutes
    timeout: float = 60.0
    max_retries: int = 2


@dataclasses.dataclass
class EvolutionConfig:
    """Reference ``funsearch`` block defaults (configs/llm_config.json:19-25;
    ``similarity_threshold`` default 0.85 per funsearch_integration.py:156)."""

    population_size: int = 20
    generations: int = 5
    early_stop_threshold: float = 0.6
    elite_size: int = 5
    max_workers: int = 8
    similarity_threshold: float = 0.85
    candidates_per_generation: int = 8  # reference cap: min(8, pop - elite)
    seed: int = 0
    # device-resident parametric rounds interleaved between LLM rounds
    # (0 = off): each generation additionally advances this many compiled
    # weight-evolution steps on the mesh and admits the rendered champion
    # through the normal code path (fks_tpu.funsearch.device_evolution)
    parametric_rounds: int = 0
    parametric_pop: int = 32
    parametric_noise: float = 0.05
    # parity sentinel (fks_tpu.obs.watchdog.ParitySentinel): re-score this
    # many sampled population members per generation through the exact
    # reference evaluator on the JIT tier and alert when |Δfitness|
    # exceeds parity_tol (0 = off). NOTE: the default tol assumes an
    # exact-engine search; flat-engine runs need a tol above the trace's
    # measured divergence bound (tools/divergence_audit.py).
    parity_sample: int = 0
    parity_tol: float = 1e-5
    # scenario-suite robust fitness (fks_tpu.scenarios): name a registered
    # suite ("" = off, single-trace fitness as before) and candidates are
    # scored by the composite robust aggregate over every scenario —
    # fault-injected variants included — evaluated in one vmapped call
    scenario_suite: str = ""
    robust_aggregation: str = "mean"  # mean | min | cvar
    robust_cvar_alpha: float = 0.25
    # successive-halving eval-budget allocation (fks_tpu.funsearch.budget;
    # requires a scenario_suite): score the whole generation on a cheap
    # probe rung — the probe_suite and/or a probe_steps-truncated trace
    # prefix — and advance only the top 1/budget_eta fraction to the full
    # suite. "none" = full-fidelity evaluation for every candidate.
    budget_schedule: str = "none"  # none | halving
    budget_eta: int = 2
    probe_suite: str = "smoke3"
    probe_steps: int = 0  # probe event budget; 0 = full trace on the probe
    # LLM-outage circuit breaker: after this many CONSECUTIVE generations
    # where every LLM call failed (zero candidates drafted), stop the run
    # with an ``llm_outage`` ledger event instead of spinning through the
    # remaining generation budget on an endpoint that is down (0 = spin)
    llm_outage_generations: int = 3

    llm: LLMSettings = dataclasses.field(default_factory=LLMSettings)

    @classmethod
    def from_json(cls, path: str) -> "EvolutionConfig":
        """Load the reference's config file format
        (reference: funsearch_integration.py:127-141)."""
        with open(path) as f:
            raw = json.load(f)
        fs = raw.get("funsearch", {})
        lm = raw.get("openrouter", {})
        return cls(
            population_size=fs.get("population_size", 20),
            generations=fs.get("generations", 5),
            early_stop_threshold=fs.get("early_stop_threshold", 0.6),
            elite_size=fs.get("elite_size", 5),
            max_workers=fs.get("max_workers", 8),
            similarity_threshold=fs.get("similarity_threshold", 0.85),
            parametric_rounds=fs.get("parametric_rounds", 0),
            parametric_pop=fs.get("parametric_pop", 32),
            parametric_noise=fs.get("parametric_noise", 0.05),
            parity_sample=fs.get("parity_sample", 0),
            parity_tol=fs.get("parity_tol", 1e-5),
            scenario_suite=fs.get("scenario_suite", ""),
            robust_aggregation=fs.get("robust_aggregation", "mean"),
            robust_cvar_alpha=fs.get("robust_cvar_alpha", 0.25),
            budget_schedule=fs.get("budget_schedule", "none"),
            budget_eta=fs.get("budget_eta", 2),
            probe_suite=fs.get("probe_suite", "smoke3"),
            probe_steps=fs.get("probe_steps", 0),
            llm_outage_generations=fs.get("llm_outage_generations", 3),
            llm=LLMSettings(
                api_key=lm.get("api_key", ""),
                base_url=lm.get("base_url", LLMSettings.base_url),
                model=lm.get("model", LLMSettings.model),
                max_tokens=lm.get("max_tokens", 500),
                temperature=lm.get("temperature", 0.7),
                timeout=lm.get("timeout", LLMSettings.timeout),
                max_retries=lm.get("max_retries", LLMSettings.max_retries),
            ),
        )


Member = Tuple[str, float]  # (candidate source, fitness)


@dataclasses.dataclass
class GenerationStats:
    generation: int
    best_score: float
    mean_score: float
    new_candidates: int
    accepted: int
    rejected_similar: int  # dup-suppressed (difflib near-duplicate)
    eval_seconds: float
    compile_count: int
    # fitness distribution over the post-truncation population (best /
    # median / p10 is the trio population-based stacks track per
    # generation; PAPERS.md: evosax, Fast PBRL)
    median_score: float = 0.0
    p10_score: float = 0.0
    # reject/failure breakdown the loop already observes (EvalRecord
    # errors + exact-rescore fallbacks) — previously dropped on the floor
    sandbox_failed: int = 0  # candidate raised during sandboxed execution
    transpile_failed: int = 0  # syntax / transpile rejection
    rescore_fallbacks: int = 0  # exact rescore failed -> search fitness
    llm_seconds: float = 0.0  # wall time of the LLM candidate stage
    # numerics watchdog: OR of SimResult.numeric_flags across this
    # generation's evaluations (0 unless SimConfig.watchdog is on), and
    # the parity sentinel's per-generation verdict (0 checks unless
    # EvolutionConfig.parity_sample > 0)
    watchdog_flags: int = 0
    parity_checked: int = 0
    parity_max_drift: float = 0.0
    parity_alerts: int = 0
    # scenario-suite searches: which suite/aggregation scored this
    # generation, and the champion's per-scenario breakdown (empty lists /
    # "" on single-trace runs — the pre-scenario schema unchanged)
    scenario_suite: str = ""
    robust_aggregation: str = ""
    best_scenario_scores: List[float] = dataclasses.field(
        default_factory=list)
    # eval-budget allocation (fks_tpu.funsearch.budget): how many LLM
    # candidates the probe rung pruned away from the full suite this
    # generation, and the total device wall across all rungs (the
    # per-rung breakdown rides kind="budget_rung" metric records; 0/0.0
    # on unbudgeted runs — the pre-budget schema unchanged)
    budget_pruned: int = 0
    budget_device_seconds: float = 0.0
    # fraction of this generation's unique candidates that lowered to
    # the VM register tier (backend.last_eval_stats) — the population's
    # eligibility for the zero-rebuild VM serve fast path (0.0 on
    # evaluators without the stat — the pre-VM-serve schema unchanged)
    vm_coverage: float = 0.0


def _percentile(sorted_desc: Sequence[float], q: float) -> float:
    """Nearest-rank quantile (q in [0, 1], from the BOTTOM) of an already
    descending-sorted score list; 0.0 on empty."""
    if not sorted_desc:
        return 0.0
    idx = min(len(sorted_desc) - 1,
              max(0, int(round((1.0 - q) * (len(sorted_desc) - 1)))))
    return float(sorted_desc[idx])


def _code_sha(code: str) -> str:
    """Content address of a candidate's source — the key that links an
    evolve-generation candidate span to the promotion attempt serving
    it (fks_tpu.pipeline.controller stamps the same hash)."""
    return hashlib.sha1(code.encode()).hexdigest()[:12]


def _failure_counts(records) -> Tuple[int, int]:
    """(sandbox_failed, transpile_failed) breakdown of a generation's
    EvalRecords. Transpile-fail covers the static rejections ("syntax:",
    "transpile:", and the pre-flight analyzer's "preflight:" verdicts —
    fks_tpu.analysis rejects are transpile failures caught early);
    sandbox-fail covers everything that failed while actually running —
    candidate exceptions ("runtime:") and simulated aborts (gpu
    allocation aborted / event budget exceeded). Failed candidates still
    enter selection at score 0 (reference semantics); these counters are
    observational only."""
    sandbox = transpile = 0
    for r in records:
        if r.error is None:
            continue
        if r.error.startswith(("syntax", "transpile", "preflight")):
            transpile += 1
        else:
            sandbox += 1
    return sandbox, transpile


@functools.lru_cache(maxsize=4096)
def analysis_fingerprint(code: str) -> Optional[str]:
    """Memoized normalized-AST fingerprint (fks_tpu.analysis). Incumbents
    are fingerprinted once per process, not once per similarity check."""
    from fks_tpu.analysis import fingerprint
    return fingerprint(code)


# ------------------------------------------------------------------ driver

class FunSearch:
    """Population manager + generation loop (reference semantics throughout;
    see module docstring)."""

    def __init__(self, evaluator: CodeEvaluator,
                 config: EvolutionConfig = EvolutionConfig(),
                 backend: Optional[llm_mod.TextBackend] = None,
                 log: Callable[[str], None] = print,
                 on_generation: Optional[
                     Callable[["GenerationStats"], None]] = None,
                 recorder: Optional[obs.NullRecorder] = None,
                 profiler=None):
        self.cfg = config
        self.evaluator = evaluator
        # device-time attribution (fks_tpu.obs.profiler): defaults to the
        # evaluator's profiler so one StageProfiler wired through the
        # evaluator attributes the whole loop — codegen / rank / ledger
        # here, sandbox+preflight / transpile / device-eval in the backend
        self.profiler = (profiler if profiler is not None
                         else evaluator.profiler)
        self.rng = random.Random(config.seed)
        self.log = log
        # flight recorder: explicit > process-wide active (cli --run-dir
        # installs one via obs.recording); defaults to the NullRecorder,
        # under which the ledger performs zero filesystem writes
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self.ledger = obs.EvolutionLedger(self.recorder, evaluator)
        # the parity sentinel is a no-op unless parity_sample > 0; its
        # lifetime ``alerts`` counter feeds the CLI's nonzero-exit policy
        self.sentinel = obs.ParitySentinel(
            evaluator, sample=config.parity_sample, tol=config.parity_tol,
            seed=config.seed, recorder=self.recorder)
        self.rescore_fallbacks = 0  # lifetime count; per-gen delta in stats
        if backend is None:
            if config.llm.api_key:
                backend = llm_mod.OpenAIBackend(
                    config.llm.api_key, config.llm.base_url, config.llm.model,
                    config.llm.max_tokens, config.llm.temperature,
                    timeout=config.llm.timeout,
                    max_retries=config.llm.max_retries)
            else:
                backend = llm_mod.FakeLLM(seed=config.seed)
        self.generator = llm_mod.CandidateGenerator(backend)
        self.on_generation = on_generation
        self.population: List[Member] = []
        self.generation = 0
        self.best: Optional[Member] = None
        self.history: List[GenerationStats] = []
        # LLM-outage circuit breaker: consecutive all-calls-failed
        # generations; run_evolution() trips after
        # cfg.llm_outage_generations of them and sets ``llm_outage``
        # (the CLI maps it to a distinct exit code)
        self.llm_failures = 0
        self.llm_outage = False
        # lazily built device-resident parametric searcher; its weight
        # population persists on device across generations (its state is
        # NOT checkpointed — rendered champions persist via the code
        # population instead)
        self._device_evo = None
        # fast-engine searches (flat/fused) report fitness under relaxed
        # retry semantics, which is NOT comparable to the reference's
        # published numbers. Every NEW BEST and every persisted champion
        # is therefore re-scored through the exact reference-replica
        # engine; both numbers are kept. (Round-2 verdict: search-on-fast
        # + rescore-on-exact must be the built-in default, not a tools/
        # afterthought.)
        self._exact_eval: Optional[CodeEvaluator] = None
        self._exact_memo: dict = {}  # canonical AST key -> exact score
        self._scenario_memo: dict = {}  # key -> per-scenario exact scores
        self.best_exact: Optional[float] = None
        # generation WAL (fks_tpu.resilience.wal): when attached (run()'s
        # ``wal_path``), drafted codes and eval outcomes are durably
        # logged mid-generation and the loop checkpoints at EVERY
        # generation boundary — a kill mid-generation resumes without
        # re-spending LLM calls or device evals
        self.wal: Optional[GenerationWAL] = None
        self.checkpoint_path: Optional[str] = None
        self.wal_replayed_codes = 0  # lifetime resume accounting
        self.wal_replayed_evals = 0

    # ----- population mechanics (reference funsearch_integration.py:174-215)

    def initialize_population(self) -> None:
        """Seed from the baseline policies (reference seeds first-fit +
        best-fit, funsearch_integration.py:179-186) and evaluate them."""
        seeds = list(template.seed_policies().values())
        records = self.evaluator.evaluate(seeds)
        for r in records:
            if r.ok:  # in-process baseline eval skips failures
                self._admit(r.code, r.score)
        self._sort()
        if self.population:
            self.best = self.population[0]

    def _sort(self) -> None:
        """Descending by search fitness, then the head window re-ranked by
        EXACT fitness. Fast-engine scores drift from the exact engine by up
        to ~0.05 on the default trace (tools/divergence_audit.py) while
        published champion gaps are ~0.01, so a ranking taken raw from the
        fast engine would aim selection pressure inside the noise band.
        Re-ranking the top ``2*elite_size`` members by exact-engine fitness
        (memoized; ≤window extra exact runs per generation, usually just
        the new head entrants) makes elite selection and parent sampling
        exact-ranked, as the reference's single-engine sort trivially is
        (reference: funsearch_integration.py:494-496)."""
        self.population.sort(key=lambda m: m[1], reverse=True)
        if self.evaluator.engine == "exact" or self.cfg.elite_size <= 0:
            return
        window = min(len(self.population), 2 * self.cfg.elite_size)
        if window <= 1:
            return
        head = self.population[:window]
        # exact first, search fitness as the tie-break; a transiently
        # failed rescore falls back to the member's search fitness
        # (un-memoized), so an infrastructure hiccup cannot evict a true
        # champion from the head window
        head.sort(key=lambda m: (self._exact_score(m[0], m[1]), m[1]),
                  reverse=True)
        self.population[:window] = head

    def _is_too_similar(self, code: str, score: float) -> bool:
        """difflib ratio >= threshold against any incumbent with >= score
        => reject (reference: funsearch_integration.py:208-215). Compared on
        the evolved logic block, not the full source: every candidate shares
        the fixed template, which would dominate a full-string ratio."""
        logic = template.logic_of(code)
        # normalized-AST fast path (fks_tpu.analysis): an exact fingerprint
        # collision with any incumbent at >= score is a duplicate by
        # construction (alpha-renames and same-decade coefficient jitter
        # collide) — skip the quadratic difflib pass for it
        fp = analysis_fingerprint(code)
        for other_code, other_score in self.population:
            if other_score >= score:
                if fp is not None and fp == analysis_fingerprint(other_code):
                    return True
                ratio = difflib.SequenceMatcher(
                    None, logic, template.logic_of(other_code)).ratio()
                if ratio >= self.cfg.similarity_threshold:
                    return True
        return False

    def _exact_score(self, code: str, score: float) -> float:
        """Fitness under the exact reference-replica engine. Identity when
        the search engine already IS exact; otherwise one VM-tier (or
        cached-jit) run of fks_tpu.sim.engine, memoized per canonical AST
        so NEW-BEST logging and the save paths never re-simulate the same
        candidate. A transiently failed rescore falls back to ``score``
        (the member's search fitness, un-memoized, retried next call);
        only an unparseable candidate maps to 0.0 — the rule the
        reference applies to failed evaluations (reference:
        funsearch_integration.py:63-64)."""
        if self.evaluator.engine == "exact":
            return score
        from fks_tpu.funsearch import transpiler
        try:
            key = transpiler.canonical_key(code)
        except SyntaxError:
            return 0.0
        if key in self._exact_memo:
            return self._exact_memo[key]
        try:
            # pin rescoring to the host CPU: on a TPU session the exact
            # engine's per-event cost is ~10x the CPU's (PROFILE.md), the
            # rescore would compete with the search for the device, and
            # the axon tunnel's execution kill window could take it down
            # mid-run. The exact engine is integer/deterministic, so the
            # score is backend-independent.
            with self._exact_device():
                exact = self._exact_evaluator().evaluate_one(code).score
        except Exception as e:  # noqa: BLE001 — a transient infrastructure
            # failure (evaluate_one catches candidate failures, but
            # evaluator construction itself can raise) must never kill the
            # evolve loop mid-generation. Fall back to the member's SEARCH
            # fitness: ranking on (exact if ok else search, search) keeps a
            # true champion inside the elite window, where a 0.0 would
            # evict it — and the head window would then aim selection
            # pressure away from the best member for the rest of the run.
            # NOT memoized: the failure is transient; the next _sort
            # retries the exact rescore.
            self.rescore_fallbacks += 1
            self.log(f"  exact rescore failed ({type(e).__name__}: {e}); "
                     f"falling back to search fitness {score:.4f}")
            return score
        self._exact_memo[key] = exact
        return exact

    def _exact_evaluator(self) -> CodeEvaluator:
        """The lazily built exact rescoring evaluator. A scenario-suite
        search rescores on the SAME suite (the persisted robust score must
        be the exact-engine fold of the same scenarios the search ranked
        on, not a single-trace number)."""
        if self._exact_eval is None:
            self._exact_eval = CodeEvaluator(
                self.evaluator.workload, self.evaluator.cfg,
                engine="exact", suite=self.evaluator.suite,
                robust=self.evaluator.robust)
        return self._exact_eval

    def _scenario_breakdown(self, code: str) -> Optional[List[float]]:
        """Per-scenario EXACT-engine scores for a champion (None without a
        suite; memoized per canonical AST so champion saves and NEW-BEST
        stats never re-simulate the same candidate)."""
        if self.evaluator.suite is None:
            return None
        from fks_tpu.funsearch import transpiler
        try:
            key = transpiler.canonical_key(code)
        except SyntaxError:
            return None
        if key not in self._scenario_memo:
            try:
                if self.evaluator.engine == "exact":
                    rec = self.evaluator.evaluate_one(code)
                else:
                    with self._exact_device():
                        rec = self._exact_evaluator().evaluate_one(code)
            except Exception:  # noqa: BLE001 — transient infra failure:
                # skip the breakdown this time, retry on the next call
                return None
            self._scenario_memo[key] = rec.scenario_scores
        return self._scenario_memo[key]

    @staticmethod
    def _exact_device():
        """Context manager pinning exact rescoring to the host CPU backend
        (no-op when CPU is unavailable or already the default)."""
        try:
            dev = jax.devices("cpu")[0]
        except RuntimeError:
            return contextlib.nullcontext()
        return jax.default_device(dev)

    def _admit(self, code: str, score: float) -> None:
        self.population.append((code, score))
        if self.best is None or score > self.best[1]:
            self.best = (code, score)
            self.best_exact = self._exact_score(code, score)
            if self.evaluator.engine == "exact":
                self.log(f"  NEW BEST {score:.4f} (gen {self.generation})")
            else:
                self.log(f"  NEW BEST {score:.4f} "
                         f"[{self.evaluator.engine}] = {self.best_exact:.4f} "
                         f"[exact] (gen {self.generation})")

    def _sample_parents(self) -> Sequence[Member]:
        """<=2 random elites as prompt parents (reference:
        funsearch_integration.py:466)."""
        elites = self.population[: self.cfg.elite_size]
        k = min(2, len(elites))
        return self.rng.sample(elites, k) if k else []

    # ----- the generation loop (reference funsearch_integration.py:487-597)

    def evolve_generation(self) -> GenerationStats:
        self.generation += 1
        # one causal trace per generation (fks_tpu.obs.trace_ctx): the
        # llm/evaluate/rank/commit spans become children of a root
        # ``generation`` span, so ``cli spans --critical-path`` can read
        # the device-idle (LLM-bound) vs LLM-idle split straight off the
        # trail; per-candidate marker spans carry a content hash linking
        # this generation to any promotion attempt its champion wins
        gen_ctx = (trace_ctx.new_trace(prefix="gen")
                   if getattr(self.recorder, "enabled", False) else None)
        t_gen0 = time.perf_counter()
        with trace_ctx.activate(gen_ctx):
            stats = self._evolve_generation_body()
            trace_ctx.emit(self.recorder, "generation",
                           time.perf_counter() - t_gen0, ctx=gen_ctx,
                           root=True, generation=self.generation,
                           candidates=stats.new_candidates)
        return stats

    def _evolve_generation_body(self) -> GenerationStats:
        cfg = self.cfg
        with self.profiler.stage("codegen", generation=self.generation):
            self.ledger.begin_generation()
            fallbacks0 = self.rescore_fallbacks
            self._sort()
            n_new = min(cfg.candidates_per_generation,
                        max(0, cfg.population_size - cfg.elite_size))
            feedback = ""
            if self.best:
                feedback = (
                    f"best fitness so far {self.best[1]:.4f}; higher "
                    "utilization with less GPU fragmentation wins")
            cached_codes = (self.wal.pending_codes(self.generation)
                            if self.wal is not None else None)
            with obs.span("llm", generation=self.generation,
                          candidates=n_new) as lt:
                if cached_codes is not None:
                    # WAL replay: the drafted candidates survived the
                    # kill; burn the parent draws generate_many would
                    # have made (exactly n_new, at submit time) so the
                    # RNG trajectory matches the original attempt, and
                    # issue ZERO LLM calls
                    for _ in range(n_new):
                        self._sample_parents()
                    codes = list(cached_codes)
                    self.wal_replayed_codes += len(codes)
                else:
                    codes = llm_mod.generate_many(
                        self.generator, n_new, self._sample_parents,
                        feedback, cfg.max_workers)
                    if self.wal is not None:
                        self.wal.record_codes(self.generation, codes)
        llm_s = lt.seconds
        # outage tracking: a generation that ASKED for candidates and got
        # none back means every LLM call failed (generate() returns None
        # on any failure and generate_many drops them)
        if n_new > 0 and not codes:
            self.llm_failures += 1
        else:
            self.llm_failures = 0

        # plain wall time: evaluate() returns host floats (each candidate's
        # score is materialized inside), so there is nothing left to sync —
        # and its EvalRecord dataclasses are opaque to block_until_ready
        with obs.span("evaluate", generation=self.generation,
                      candidates=len(codes)) as t:
            records = self._evaluate_with_wal(codes, cached_codes)
            if getattr(self.recorder, "enabled", False):
                # content-addressed candidate markers: code_sha is the
                # key the promotion controller stamps on its attempts,
                # so ledger -> shadow -> swap links back to the evolve
                # generation that produced the champion
                for r in records:
                    trace_ctx.emit(
                        self.recorder, "evaluate/candidate", 0.0,
                        code_sha=_code_sha(r.code),
                        score=round(float(r.score), 6),
                        generation=self.generation)
        eval_s = t.seconds
        sandbox_failed, transpile_failed = _failure_counts(records)

        with self.profiler.stage("rank", generation=self.generation) as hr, \
                obs.span("rank", generation=self.generation):
            # eval-budget ledger: one budget_rung metric per rung (entered
            # / survived / device-seconds / segment count), then the
            # champion audit — pruning may never change who wins a
            # generation, only how cheaply, and a violated audit alerts
            # into the same exit-3 policy as fitness-drift parity alerts
            budget_rungs = list(
                getattr(self.evaluator, "last_budget_stats", []) or [])
            budget_alerts = 0
            for rung in budget_rungs:
                self.recorder.metric(
                    "budget_rung", generation=self.generation, **rung)
            if budget_rungs:
                budget_alerts = self.sentinel.check_champion(
                    self.generation, records)["alerts"]

            # numerics watchdog: one event per generation carrying the OR
            # of every evaluation's flag mask (always 0 when
            # SimConfig.watchdog is off — the guards are compiled out)
            wd_flags = 0
            for r in records:
                if r.result is not None:
                    wd_flags |= obs.combined_flags(
                        getattr(r.result, "numeric_flags", 0))
            if wd_flags:
                self.recorder.event(
                    "watchdog", flags=wd_flags,
                    kinds=obs.describe_flags(wd_flags),
                    generation=self.generation, candidates=len(records))

            accepted = rejected = 0
            for r in records:
                # subprocess-path semantics: failures carry score 0 and
                # still enter selection (SURVEY.md §2 fine print 8)
                if self._is_too_similar(r.code, r.score):
                    rejected += 1
                    continue
                self._admit(r.code, r.score)
                accepted += 1

            if cfg.parametric_rounds > 0:
                r = self._parametric_round()
                if r is not None:
                    if self._is_too_similar(r.code, r.score):
                        rejected += 1
                    else:
                        self._admit(r.code, r.score)
                        accepted += 1
            self._sort()
            del self.population[cfg.population_size:]

            # parity sentinel: sample the post-truncation population
            # (those are the members whose fitness selection actually
            # trusts)
            parity = self.sentinel.check(self.generation, self.population)
            hr.annotate(accepted=accepted, rejected_similar=rejected)

        with self.profiler.stage("ledger", generation=self.generation), \
                obs.span("commit", generation=self.generation):
            stats = self._commit_generation(
                codes, eval_s, llm_s, sandbox_failed, transpile_failed,
                fallbacks0, wd_flags, parity, budget_alerts, budget_rungs,
                accepted, rejected)
        if self.wal is not None:
            # checkpoint BEFORE the WAL commit: a kill between the two
            # leaves stale uncommitted records for THIS generation, which
            # the next resume (restored to this generation) never reads —
            # whereas commit-before-checkpoint would lose the generation
            if self.checkpoint_path:
                self.checkpoint(self.checkpoint_path)
            self.wal.commit(self.generation)
        return stats

    def _evaluate_with_wal(self, codes: List[str],
                           cached_codes) -> List[EvalRecord]:
        """Evaluate, replaying WAL-cached outcomes on resume: candidates
        whose eval already landed in the WAL are reconstructed (zero
        device work); only the fresh remainder runs, and each fresh
        outcome is durably logged before ranking sees it."""
        if self.wal is None:
            return self.evaluator.evaluate(codes)
        cached = self.wal.cached_evals(self.generation)
        keys = [GenerationWAL.code_key(c) for c in codes]
        fresh_idx = [i for i, k in enumerate(keys) if k not in cached]
        fresh = (self.evaluator.evaluate([codes[i] for i in fresh_idx])
                 if fresh_idx else [])
        by_idx = {}
        for i, r in zip(fresh_idx, fresh):
            by_idx[i] = r
            self.wal.record_eval(self.generation, r)
        records: List[EvalRecord] = []
        replayed = 0
        for i, code in enumerate(codes):
            if i in by_idx:
                records.append(by_idx[i])
            else:
                e = cached[keys[i]]
                records.append(EvalRecord(
                    code=code, score=e["score"], error=e["error"],
                    scenario_scores=e["scenario_scores"],
                    aggregation=e["aggregation"],
                    budget_rung=e["budget_rung"]))
                replayed += 1
        self.wal_replayed_evals += replayed
        if cached_codes is not None or replayed:
            self.recorder.event(
                "resume_wal", generation=self.generation,
                cached_codes=len(cached_codes or []), cached_evals=replayed,
                fresh_evals=len(fresh_idx))
        return records

    def _commit_generation(self, codes, eval_s, llm_s, sandbox_failed,
                           transpile_failed, fallbacks0, wd_flags, parity,
                           budget_alerts, budget_rungs, accepted,
                           rejected) -> GenerationStats:
        """Stats assembly + flight-recorder commit for one generation
        (the ``ledger`` profiler stage of ``evolve_generation``)."""
        # scenario-suite bookkeeping: the champion's per-scenario breakdown
        # rides the stats/ledger, and one robust_fitness metric per
        # generation lands in the flight-recorder trail
        suite = self.evaluator.suite
        best_breakdown: List[float] = []
        if suite is not None and self.best is not None:
            best_breakdown = self._scenario_breakdown(self.best[0]) or []
            self.recorder.metric(
                "robust_fitness", generation=self.generation,
                suite=suite.name, version=suite.version,
                aggregation=self.evaluator.robust.aggregation,
                scores=best_breakdown)

        scores = [s for _, s in self.population]  # descending post-_sort
        stats = GenerationStats(
            generation=self.generation,
            best_score=self.best[1] if self.best else 0.0,
            mean_score=sum(scores) / len(scores) if scores else 0.0,
            new_candidates=len(codes), accepted=accepted,
            rejected_similar=rejected, eval_seconds=eval_s,
            compile_count=self.evaluator.compile_count,
            median_score=_percentile(scores, 0.5),
            p10_score=_percentile(scores, 0.10),
            sandbox_failed=sandbox_failed,
            transpile_failed=transpile_failed,
            rescore_fallbacks=self.rescore_fallbacks - fallbacks0,
            llm_seconds=llm_s,
            watchdog_flags=wd_flags,
            parity_checked=parity["checked"],
            parity_max_drift=parity["max_drift"],
            parity_alerts=parity["alerts"] + budget_alerts,
            scenario_suite=suite.name if suite is not None else "",
            robust_aggregation=(self.evaluator.robust.aggregation
                                if suite is not None else ""),
            best_scenario_scores=best_breakdown,
            budget_pruned=sum(r["entered"] - r["survived"]
                              for r in budget_rungs),
            budget_device_seconds=round(sum(r["device_seconds"]
                                            for r in budget_rungs), 6),
            vm_coverage=float(getattr(self.evaluator, "last_eval_stats",
                                      {}).get("vm_coverage", 0.0)))
        self.history.append(stats)
        # ledger first: the flight-recorder trail must be complete even if a
        # user on_generation callback raises
        self.ledger.commit(stats)
        if self.on_generation is not None:
            # streamed per generation so an interrupted run still leaves a
            # complete metric trail (fks_tpu.utils.logging contract)
            self.on_generation(stats)
        self.log(
            f"gen {stats.generation}: best {stats.best_score:.4f} "
            f"mean {stats.mean_score:.4f} new {stats.new_candidates} "
            f"accepted {stats.accepted} (dup-rejected {stats.rejected_similar}) "
            f"eval {eval_s:.2f}s programs {stats.compile_count}")
        return stats

    def _parametric_round(self):
        """Advance the device-resident weight search and feed its champion
        back into the code population through the normal evaluation path
        (the rendered source is re-scored by the evaluator, so the
        admission comparison is apples-to-apples with LLM candidates)."""
        from fks_tpu.funsearch.device_evolution import ParametricEvolution

        if self._device_evo is None:
            self._device_evo = ParametricEvolution(
                self.evaluator.workload, pop_size=self.cfg.parametric_pop,
                noise=self.cfg.parametric_noise, cfg=self.evaluator.cfg,
                engine=self.evaluator.engine, seed=self.cfg.seed)
        st = self._device_evo.run(self.cfg.parametric_rounds)
        self.log(f"  parametric: gen {st.generation} best {st.best_score:.4f} "
                 f"mean {st.mean_score:.4f} (device-resident)")
        code = self._device_evo.best_code()
        rec = self.evaluator.evaluate([code])[0]
        return rec

    def run_evolution(self) -> Tuple[str, float]:
        """Full loop -> (best_code, best_score) (reference:
        funsearch_integration.py:574-597)."""
        if not self.population:
            # a named stage (not codegen) so the backend's nested eval
            # stages stay attributed to seeding, not the first generation
            with self.profiler.stage("seed"):
                self.initialize_population()
        while self.generation < self.cfg.generations:
            stats = self.evolve_generation()
            if stats.best_score >= self.cfg.early_stop_threshold:
                self.log(f"early stop: {stats.best_score:.4f} >= "
                         f"{self.cfg.early_stop_threshold}")
                break
            if (self.cfg.llm_outage_generations > 0
                    and self.llm_failures >= self.cfg.llm_outage_generations):
                # the endpoint is down, not flaky: stop burning the
                # generation budget on empty rounds. The caller's normal
                # shutdown path still checkpoints and saves champions.
                self.llm_outage = True
                self.recorder.event(
                    "llm_outage", generation=self.generation,
                    consecutive=self.llm_failures,
                    detail=f"every LLM call failed for {self.llm_failures} "
                           "consecutive generations; halting evolution")
                self.log(f"LLM OUTAGE: {self.llm_failures} consecutive "
                         "generations with zero drafted candidates; "
                         "checkpointing and stopping")
                break
        if self.best is None:
            return "", 0.0
        return self.best

    # ----- persistence (reference funsearch_integration.py:606-679) + resume

    def _champion_fields(self, code: str, score: float) -> dict:
        """The persisted ``score`` is ALWAYS exact-engine fitness — the only
        number comparable to the reference's published table. When the
        search ran on a fast engine, the raw search fitness and the engine
        name ride along as ``search_score``/``search_engine``."""
        exact = self._exact_score(code, score)
        fields = {"score": exact}
        if self.evaluator.engine != "exact":
            fields["search_score"] = score
            fields["search_engine"] = self.evaluator.engine
        suite = self.evaluator.suite
        if suite is not None:
            fields["scenario_suite"] = suite.name
            fields["suite_version"] = suite.version
            fields["aggregation"] = self.evaluator.robust.aggregation
            per = self._scenario_breakdown(code)
            if per is not None:
                fields["scenario_scores"] = dict(zip(suite.names, per))
        return fields

    def save_top_policies(self, directory: str, k: int = 5) -> str:
        """Champion JSON with rank/score/generation/code/timestamp schema
        (reference: funsearch_integration.py:635-679). Fast-engine
        searches take the top ``k`` by search fitness, then RANK the
        payload by exact-engine fitness — a consumer reading rank 1 gets
        the exact-engine best of the rescored set, and the listed scores
        are monotonic."""
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(directory, f"top_policies_{stamp}.json")
        self._sort()
        entries = [
            {**self._champion_fields(c, s), "generation": self.generation,
             "code": c, "timestamp": stamp}
            for c, s in self.population[:k]
        ]
        entries.sort(key=lambda e: e["score"], reverse=True)
        payload = [{"rank": i + 1, **e} for i, e in enumerate(entries)]
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)
        return path

    def save_best_policy(self, directory: str = "policies/discovered") -> str:
        """Single-champion JSON, reference schema {score, generation, code,
        timestamp} and filename pattern ``funsearch_<stamp>_score<s>.json``
        (reference: funsearch_integration.py:606-633). The score in both
        the filename and the payload is exact-engine fitness; for
        fast-engine searches the saved champion is the exact-engine best
        among the rescored top-5 (search order and exact order can
        disagree, and the persisted 'best' must honor the persisted
        metric)."""
        if self.best is None:
            raise ValueError("no best policy to save")
        self._sort()
        candidates = list(self.population[:5])
        if self.best not in candidates:
            candidates.append(self.best)
        code, score = max(
            candidates, key=lambda m: self._exact_score(m[0], m[1]))
        fields = self._champion_fields(code, score)
        os.makedirs(directory, exist_ok=True)
        stamp = time.strftime("%Y%m%d_%H%M%S")
        path = os.path.join(
            directory, f"funsearch_{stamp}_score{fields['score']:.4f}.json")
        with open(path, "w") as f:
            json.dump({**fields, "generation": self.generation,
                       "code": code,
                       "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S")},
                      f, indent=2)
        return path

    def checkpoint(self, path: str) -> None:
        """Mid-evolution state: population, best, generation, RNG — enough
        to resume bit-identically (absent from the reference; SURVEY.md §5
        flags it as required for long mesh jobs)."""
        state = {
            "version": 1,
            "generation": self.generation,
            "population": [{"code": c, "score": s} for c, s in self.population],
            "best": ({"code": self.best[0], "score": self.best[1]}
                     if self.best else None),
            "best_exact": self.best_exact,
            "rng_state": _encode_rng(self.rng.getstate()),
            "config": dataclasses.asdict(self.cfg),
        }
        backend = self.generator.backend
        if hasattr(backend, "getstate"):
            state["backend_state"] = backend.getstate()
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            # fsync BEFORE the atomic rename: without it a crash can
            # replace a good checkpoint with an empty/torn rename target
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    #: config fields that change what a fitness NUMBER means (or how the
    #: population evolves); resuming a checkpoint across a drift in any
    #: of them would silently mix incomparable scores in one population
    _DRIFT_KEYS = ("scenario_suite", "robust_aggregation",
                   "robust_cvar_alpha", "population_size")

    def restore(self, path: str) -> None:
        try:
            with open(path) as f:
                state = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"{path}: torn checkpoint (invalid JSON: {e}); delete it "
                "or restore from a backup — resuming from half a state "
                "would corrupt the population") from e
        if state.get("version") != 1:
            raise ValueError(f"unknown checkpoint version {state.get('version')}")
        stored = state.get("config") or {}
        current = dataclasses.asdict(self.cfg)
        drifted = [k for k in self._DRIFT_KEYS
                   if k in stored and stored[k] != current[k]]
        if drifted:
            diff = ", ".join(f"{k}: checkpoint={stored[k]!r} "
                             f"current={current[k]!r}" for k in drifted)
            raise ValueError(
                f"{path}: checkpoint config drift — resuming would mix "
                f"incomparable fitness scales ({diff}). Re-run with the "
                "checkpoint's config or start a fresh checkpoint.")
        self.generation = state["generation"]
        self.population = [(m["code"], m["score"]) for m in state["population"]]
        self.best = ((state["best"]["code"], state["best"]["score"])
                     if state["best"] else None)
        self.best_exact = state.get("best_exact")
        self.rng.setstate(_decode_rng(state["rng_state"]))
        backend = self.generator.backend
        if "backend_state" in state and hasattr(backend, "setstate"):
            backend.setstate(state["backend_state"])


def _encode_rng(state):
    """random.Random state contains a tuple-of-ints; make it JSON-stable."""
    kind, internal, gauss = state
    return [kind, list(internal), gauss]


def _decode_rng(obj):
    kind, internal, gauss = obj
    return (kind, tuple(internal), gauss)


# ------------------------------------------------------------- entry point

def run(workload, config: Optional[EvolutionConfig] = None,
        backend: Optional[llm_mod.TextBackend] = None,
        sim_config: SimConfig = SimConfig(),
        checkpoint_path: Optional[str] = None,
        wal_path: Optional[str] = None,
        out_dir: Optional[str] = None,
        engine: str = "exact",
        log: Callable[[str], None] = print,
        on_generation: Optional[Callable[[GenerationStats], None]] = None,
        recorder: Optional[obs.NullRecorder] = None,
        profile: bool = False,
        ) -> FunSearch:
    """Assemble evaluator + driver, optionally resuming from a checkpoint,
    and run to completion. Returns the driver for inspection.

    ``profile=True`` attributes the run's wall time per pipeline stage
    (fks_tpu.obs.profiler.StageProfiler): device_profile metrics into the
    recorder trail plus a summary on the returned driver's
    ``profiler.records``. Off is the default and compiles bit-identical
    programs (the NULL profiler adds no fences — pinned by cli lint).

    A KeyboardInterrupt mid-evolution still persists champions (top-K +
    single best into ``out_dir``, reference: funsearch_integration.py:
    698-702) and the checkpoint — a long device run killed at the terminal
    must never lose its discoveries."""
    config = config or EvolutionConfig()
    profiler = (obs.StageProfiler(scope="evolve", recorder=recorder)
                if profile else obs.NULL_PROFILER)
    suite = robust = budget = None
    if config.scenario_suite:
        from fks_tpu.scenarios import RobustConfig, get_suite
        suite = get_suite(config.scenario_suite, workload)
        robust = RobustConfig(aggregation=config.robust_aggregation,
                              cvar_alpha=config.robust_cvar_alpha)
        log(f"scenario suite {suite.name} v{suite.version}: "
            f"{len(suite)} scenarios, robust={robust.aggregation}")
    if config.budget_schedule != "none":
        from fks_tpu.funsearch.budget import BudgetConfig
        budget = BudgetConfig(schedule=config.budget_schedule,
                              eta=config.budget_eta,
                              probe_suite=config.probe_suite,
                              probe_steps=config.probe_steps)
        log(f"eval budget {budget.schedule}: probe {budget.probe_suite}"
            + (f" @{budget.probe_steps} events" if budget.probe_steps
               else "")
            + f", top 1/{budget.eta} advance to the full suite")
    with profiler.stage("setup", engine=engine):
        fs = FunSearch(CodeEvaluator(workload, sim_config, engine=engine,
                                     suite=suite, robust=robust,
                                     budget=budget, profiler=profiler),
                       config, backend, log,
                       on_generation=on_generation, recorder=recorder)
    if checkpoint_path and os.path.exists(checkpoint_path):
        fs.restore(checkpoint_path)
        log(f"resumed from {checkpoint_path} at generation {fs.generation}")
    if wal_path:
        # preemption-safe mode: WAL + checkpoint-every-generation, so the
        # pending window is exactly one generation and a kill -9
        # mid-generation resumes without re-buying its LLM/device spend
        fs.wal = GenerationWAL(wal_path)
        fs.checkpoint_path = checkpoint_path
        summ = fs.wal.summary()
        if summ["records"]:
            log(f"generation WAL {wal_path}: {summ['records']} records, "
                f"{len(summ['committed'])} committed generations"
                + (f", {summ['skipped_lines']} torn lines skipped"
                   if summ["skipped_lines"] else ""))
    fs.interrupted = False  # callers: champions already persisted when True
    try:
        fs.run_evolution()
    except KeyboardInterrupt:
        fs.interrupted = True
        log("evolution interrupted; saving champions")
        if fs.population and out_dir:
            log(f"top policies saved to {fs.save_top_policies(out_dir, k=5)}")
        if fs.best and out_dir:
            log(f"best policy saved to {fs.save_best_policy(out_dir)}")
        if checkpoint_path:
            fs.checkpoint(checkpoint_path)
        return fs
    finally:
        if profile:
            # the __total__ device_profile record: per-stage attribution
            # aggregate + the idle (unattributed) remainder of the run
            summ = profiler.summary(emit=True)
            log("device-time attribution: "
                f"{summ['attributed_fraction'] * 100:.1f}% of "
                f"{summ['measured_wall_seconds']:.2f}s wall attributed "
                f"({summ['compile_seconds']:.2f}s compile); see cli report")
            profiler.close()
    if checkpoint_path:
        fs.checkpoint(checkpoint_path)
    return fs
