"""Policy template + LLM prompt construction.

TPU-native counterpart of the reference template system (reference:
funsearch/safe_execution.py:171-270 ``PolicyTemplate``): the LLM fills only
the scoring logic inside a fixed ``priority_function(pod, node)`` skeleton
whose prologue performs the canonical feasibility gate and whose epilogue
clamps to ``max(1, int(score))`` — so a feasible node can never be refused
and an infeasible node always scores 0 (the engine's strict-argmax ``> 0``
gate depends on this, reference: simulator/main.py:104-111).

The schema documented to the LLM is the reference entity schema
(simulator/entities.py:4-43); the transpiler maps it onto the vectorized
``PodView``/``NodeView`` arrays. The prompt constraints differ from the
reference in ONE deliberate way (SURVEY.md §2 fine print 10): generated
logic must stay in the transpilable subset — straight-line math and
``if``/``else`` only — because it is compiled to a branchless masked-blend
XLA program, not interpreted per (pod, node) pair.
"""
from __future__ import annotations

from typing import Sequence, Tuple

LOGIC_PLACEHOLDER = "{evolved_logic}"

TEMPLATE = '''\
def priority_function(pod, node):
    """Score placing `pod` on `node`; higher is better, 0 refuses.

    Fields available (all integers):
      pod.cpu_milli      CPU request, thousandths of a core
      pod.memory_mib     memory request, MiB
      pod.num_gpu        number of whole GPUs required
      pod.gpu_milli      compute required on EACH requested GPU (0..1000)
      node.cpu_milli_left / node.cpu_milli_total
      node.memory_mib_left / node.memory_mib_total
      node.gpu_left      count of GPUs not yet assigned to any pod
      node.gpus          list of GPU objects on this node, each with
                         gpu.gpu_milli_left / gpu.gpu_milli_total
    """
    if pod.cpu_milli > node.cpu_milli_left:
        return 0
    if pod.memory_mib > node.memory_mib_left:
        return 0
    if pod.num_gpu > node.gpu_left:
        return 0
    if pod.num_gpu > 0:
        fitting_gpus = 0
        for gpu in node.gpus:
            if gpu.gpu_milli_left >= pod.gpu_milli:
                fitting_gpus = fitting_gpus + 1
        if fitting_gpus < pod.num_gpu:
            return 0

    score = 0.0

    {evolved_logic}

    return max(1, int(score))
'''


def fill_template(evolved_logic: str) -> str:
    """Insert the LLM-generated block at 4-space indentation (reference:
    safe_execution.py:267-270).

    The reference splices the stripped block verbatim, so continuation
    lines must already carry their own 4-space base indent (the prompt
    demands it). LLMs routinely emit the block at column 0 instead, which
    the verbatim splice turns into a SyntaxError and a wasted candidate —
    so when the verbatim fill does not parse, retry with every line after
    the first shifted to the template's 4-space base. Contract-compliant
    blocks are spliced byte-identically to the reference."""
    import ast

    logic = evolved_logic.strip()
    code = TEMPLATE.replace(LOGIC_PLACEHOLDER, logic)
    lines = logic.splitlines()
    if len(lines) == 1:
        return code
    try:
        ast.parse(code)
        return code
    except SyntaxError:
        pass
    shifted = "\n".join([lines[0]] + ["    " + l if l.strip() else l
                                      for l in lines[1:]])
    reindented = TEMPLATE.replace(LOGIC_PLACEHOLDER, shifted)
    try:
        ast.parse(reindented)
        return reindented
    except SyntaxError:
        return code  # let validation report the original form


_PREFIX, _SUFFIX = TEMPLATE.split(LOGIC_PLACEHOLDER)


def logic_of(code: str) -> str:
    """Extract the evolved block back out of a filled candidate; returns the
    whole source for non-template code. Used by near-duplicate suppression:
    comparing full candidates is meaningless when ~90% of every string is
    the shared template boilerplate (difflib ratio would exceed any sane
    threshold for ALL pairs)."""
    if code.startswith(_PREFIX) and code.endswith(_SUFFIX):
        return code[len(_PREFIX):len(code) - len(_SUFFIX)]
    return code


def _format_parents(parents: Sequence[Tuple[str, float]]) -> str:
    if not parents:
        return "(no prior policies yet)"
    out = []
    for i, (code, score) in enumerate(parents):
        out.append(f"--- parent {i + 1} (fitness {score:.4f}) ---\n{code}")
    return "\n".join(out)


def build_prompt(parents: Sequence[Tuple[str, float]],
                 feedback: str = "") -> str:
    """The codegen prompt (reference: safe_execution.py:227-254), with the
    TPU-subset constraints spelled out."""
    return f"""\
You are evolving the scoring logic of a Kubernetes pod-scheduling policy.
The policy decides which cluster node a pod is placed on: every node is
scored and the pod goes to the highest strictly-positive score.

You must produce ONLY the logic that replaces {LOGIC_PLACEHOLDER} in the
template below. Hard constraints:
- Assign the final value to the variable `score` (a number).
- Use only: + - * / // % ** abs() min() max() sum() int() float() round(),
  math.sqrt/log/exp/pow/sin/cos/tan, comparisons, and if/else statements.
- You may loop ONLY with `for gpu in node.gpus:` to aggregate per-GPU
  statistics; no other loops, no imports, no function definitions, no
  strings, no lists, no while, no lambda.
- Guard every division so the denominator cannot be zero
  (e.g. `/ max(1, x)`).
- Indent every line with 4 spaces (8 inside an if, 12 nested, ...), because
  your block is pasted inside the function body.
- Output the raw code block only: no backticks, no prose, no blank template.

Template your block is inserted into:
{TEMPLATE}

Prior policies, best first — improve on them rather than repeating them:
{_format_parents(parents)}

Performance feedback: {feedback or "(none)"}
"""


# ------------------------------------------------------------- seed logic

#: Seed logic blocks for population initialization — the spirit of the
#: reference's active baseline factories (reference:
#: funsearch/funsearch_integration.py:217-269 first-fit + best-fit seeds),
#: expressed in the template's evolved-logic slot.
SEED_LOGIC = {
    "first_fit": "score = 1000",
    "best_fit": (
        "cpu_after = (node.cpu_milli_left - pod.cpu_milli) / max(1, node.cpu_milli_total)\n"
        "    mem_after = (node.memory_mib_left - pod.memory_mib) / max(1, node.memory_mib_total)\n"
        "    gpu_after = (node.gpu_left - pod.num_gpu) / max(1, len(node.gpus))\n"
        "    score = (1.0 - (cpu_after * 0.33 + mem_after * 0.33 + gpu_after * 0.34)) * 10000"
    ),
}


def seed_policies() -> dict:
    """name -> full candidate source for the initial population."""
    return {name: fill_template(logic) for name, logic in SEED_LOGIC.items()}
