"""Device-resident parametric evolution: weights never leave the mesh.

The reference's evolution loop moves every candidate through the host on
every generation (ProcessPool pickling, reference: funsearch/
funsearch_integration.py:535-562). The parametric tier has no reason to:
the population weight matrix lives sharded over the mesh, and each
generation is ONE compiled program — sharded evaluation, ICI all-gather of
fitness, global top-k elite selection, mutation (fks_tpu.parallel.mesh.
make_sharded_generation_step). Only per-generation scores (a few floats)
cross to the host, for logging.

Two uses:
- standalone: ``ParametricEvolution.run(generations)`` — pure weight-space
  search at device speed;
- inside FunSearch (fks_tpu.funsearch.evolution): between LLM rounds, a
  persistent ParametricEvolution advances ``parametric_rounds`` device
  generations, then its best weight vector is RENDERED to candidate source
  (models.parametric.render_code) and fed through the normal sandbox ->
  transpile -> evaluate -> dedup admission path, cross-pollinating the
  code population — the integration backend.py's tier list promises.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.models import parametric
from fks_tpu.parallel import (
    make_sharded_generation_step, pad_population, population_mesh,
)
from fks_tpu.sim.engine import SimConfig


def _to_host(arr) -> np.ndarray:
    """Device array -> host numpy, gathering across processes when the
    mesh spans hosts (np.asarray alone raises on arrays that are not
    fully addressable)."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


@dataclasses.dataclass
class DeviceGenStats:
    generation: int
    best_score: float
    mean_score: float


class ParametricEvolution:
    """Persistent device-resident weight-space evolution over a mesh."""

    def __init__(self, workload, mesh=None, pop_size: int = 64,
                 elite_k: int = 4, noise: float = 0.05,
                 cfg: SimConfig = SimConfig(), engine: str = "exact",
                 seed: int = 0, init_noise: float = 0.1):
        self.mesh = mesh if mesh is not None else population_mesh()
        self.step = make_sharded_generation_step(
            workload, self.mesh, cfg=cfg, elite_k=elite_k, noise=noise,
            engine=engine)
        key = jax.random.PRNGKey(seed)
        self._key, sub = jax.random.split(key)
        params, self.real_count = pad_population(
            parametric.init_population(sub, pop_size, noise=init_noise),
            self.mesh)
        self.params = params  # device-resident across generations
        self.generation = 0
        self.history: List[DeviceGenStats] = []
        self.best_score = float("-inf")
        self._best_params = None

    def run(self, generations: int,
            on_generation: Optional[Callable[[DeviceGenStats], None]] = None,
            ) -> DeviceGenStats:
        """Advance ``generations`` device steps; params stay on device."""
        last = None
        for _ in range(generations):
            self._key, sub = jax.random.split(self._key)
            self.params, scores, elite_scores = self.step(
                self.params, sub, self.real_count)
            self.generation += 1
            # elites survive in the leading slots (mesh.gen_step layout),
            # so row 0 of the NEW population is the best of this round
            best = float(np.asarray(elite_scores)[0])
            if best > self.best_score:
                self.best_score = best
                self._best_params = self.params[0]
            real = np.asarray(scores)[: self.real_count]
            last = DeviceGenStats(self.generation, best, float(real.mean()))
            self.history.append(last)
            if on_generation is not None:
                on_generation(last)
        return last

    @property
    def best_params(self):
        if self._best_params is None:
            raise ValueError("run() has not advanced any generation yet")
        return self._best_params

    def best_code(self) -> str:
        """The champion weights rendered as reference-style source."""
        return parametric.render_code(_to_host(self.best_params))

    # ------------------------------------------------------------ resume
    # The code-candidate loop (fks_tpu.funsearch.evolution) checkpoints
    # population + RNG; long device-resident runs need the same (the
    # reference has no resume at all — SURVEY.md §5).

    def save_checkpoint(self, path: str) -> str:
        """Everything needed to continue deterministically: padded params,
        RNG key, champion, and history. Returns the file actually written
        (np.savez appends ``.npz`` when missing)."""
        if not path.endswith(".npz"):
            path += ".npz"
        hist = np.array([[h.generation, h.best_score, h.mean_score]
                         for h in self.history], np.float64).reshape(-1, 3)
        best = (_to_host(self._best_params) if self._best_params is not None
                else np.zeros(0, np.float32))
        if jax.process_index() == 0:  # one writer on shared filesystems
            np.savez(path, params=_to_host(self.params),
                     key=np.asarray(self._key), generation=self.generation,
                     best_score=self.best_score, best_params=best,
                     real_count=self.real_count, history=hist)
        return path

    def init_from_weights(self, weights, noise: float, seed: int = 0) -> None:
        """Seed the population around one weight vector: lane 0 holds it
        exactly, the rest are Gaussian perturbations at ``noise`` scale.
        Unlike ``restore_checkpoint`` (which demands an identical pop
        size), this lets a NEW population geometry continue from a saved
        champion. Preserves the mesh sharding and pad-lane masking
        (``real_count`` is untouched)."""
        from fks_tpu.parallel import shard_population

        champ = jnp.asarray(weights, self.params.dtype)
        if champ.shape != tuple(self.params.shape[1:]):
            raise ValueError(
                f"champion weight vector has shape {tuple(champ.shape)}; "
                f"this instance's parametric model expects "
                f"{tuple(self.params.shape[1:])}")
        key = jax.random.PRNGKey(seed)
        perturbed = champ[None, :] + noise * jax.random.normal(
            key, self.params.shape, self.params.dtype)
        self.params = shard_population(perturbed.at[0].set(champ), self.mesh)

    def restore_checkpoint(self, path: str) -> None:
        """Restore onto an instance built with the SAME workload/mesh/
        engine/pop_size; continuing reproduces the uninterrupted run
        exactly (same key-split sequence)."""
        from fks_tpu.parallel import shard_population

        if not path.endswith(".npz"):  # mirror save_checkpoint's normalize
            path += ".npz"
        with np.load(path) as d:
            if d["params"].shape != tuple(self.params.shape):
                raise ValueError(
                    f"checkpoint population shape {d['params'].shape} != "
                    f"this instance's {tuple(self.params.shape)}")
            # re-establish the mesh sharding (every process holds the full
            # array, so device_put builds the same global array everywhere)
            self.params = shard_population(jnp.asarray(d["params"]),
                                           self.mesh)
            self._key = jnp.asarray(d["key"])
            self.generation = int(d["generation"])
            self.best_score = float(d["best_score"])
            self._best_params = (jnp.asarray(d["best_params"])
                                 if d["best_params"].size else None)
            self.real_count = int(d["real_count"])
            self.history = [DeviceGenStats(int(g), float(b), float(m))
                            for g, b, m in d["history"]]
