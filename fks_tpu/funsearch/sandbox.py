"""Candidate-code validation + host-side scalar sandbox.

TPU-native re-design of the reference sandbox (reference:
funsearch/safe_execution.py:15-168 ``SafeExecutor``): the same two-stage
static validation — a lowercased-substring blacklist then an AST walk with a
call whitelist — but the contract is *tightened* for the TPU build
(SURVEY.md §2 fine print 10): accepted code must also transpile to a
JAX-traceable vectorized policy (fks_tpu.funsearch.transpiler), which is
where data-dependent Python control flow is lowered (if/else -> masked
blends) or rejected.

The scalar executor here serves two roles the reference's SafeExecutor
serves one of:
- a smoke test that candidate code runs at all on one (pod, node) pair
  before it is compiled for the device (reference: safe_execution.py:126-168,
  319-328);
- the *oracle* for transpiler differential tests: the transpiled vectorized
  policy must agree with this per-node scalar execution on every node
  (a hermetic correctness check the reference lacks).
"""
from __future__ import annotations

import ast
import contextlib
import dataclasses
import math
import operator
import signal
import threading
from typing import Any, Optional, Sequence

# ---------------------------------------------------------------- whitelists

#: Builtins visible to candidate code (reference: safe_execution.py:19-22).
SAFE_BUILTINS = (
    "abs", "min", "max", "sum", "len", "range", "enumerate", "int", "float",
    "bool", "str", "round", "sorted",
)
#: math functions (reference: safe_execution.py:24).
SAFE_MATH = ("sqrt", "log", "exp", "pow", "sin", "cos", "tan")
#: operator-module functions (reference: safe_execution.py:26-27).
SAFE_OPERATOR = ("add", "sub", "mul", "truediv", "mod")

#: Lowercased substrings that reject a candidate outright (reference:
#: safe_execution.py:29-33,73-79 — the reference checks 'import', '__', and
#: exec/eval-style escapes anywhere in the lowercased source).
FORBIDDEN_SUBSTRINGS = (
    "import", "__", "exec", "eval", "compile", "open(", "globals", "locals",
    "getattr", "setattr", "delattr", "vars(", "dir(", "input(", "breakpoint",
    "lambda", "yield", "while", "class ", "global ", "nonlocal ",
)

#: AST statement/expression node types candidate code may contain.
_ALLOWED_NODES = (
    ast.Module, ast.FunctionDef, ast.arguments, ast.arg,
    ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Pass,
    ast.Return, ast.If, ast.IfExp, ast.For, ast.Compare, ast.BoolOp,
    ast.BinOp, ast.UnaryOp, ast.Call, ast.Attribute, ast.Name, ast.Constant,
    # NB: ast.Index is never produced on py3.9+ and ast.Slice (a[1:2])
    # was dead weight — the transpiler rejects any non-static-int
    # subscript, so slice syntax is denied here, one stage earlier
    ast.Tuple, ast.List, ast.Subscript,
    ast.GeneratorExp, ast.comprehension, ast.keyword,
    ast.Load, ast.Store,
    ast.And, ast.Or, ast.Not,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.USub, ast.UAdd,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
)


@dataclasses.dataclass
class ValidationResult:
    ok: bool
    reason: str = ""

    def __bool__(self) -> bool:
        return self.ok


def validate_source_text(code: str) -> ValidationResult:
    """Stage 1: substring blacklist over the lowercased source
    (reference: safe_execution.py:73-79)."""
    low = code.lower()
    for bad in FORBIDDEN_SUBSTRINGS:
        if bad in low:
            return ValidationResult(False, f"forbidden construct: {bad!r}")
    return ValidationResult(True)


def validate_structure(code: str,
                       entry_point: str = "priority_function") -> ValidationResult:
    """Stage 2: AST walk (reference: safe_execution.py:38-64) — exactly one
    top-level function with the canonical (pod, node) signature, only
    whitelisted node types, only whitelisted calls, no dunder attributes."""
    try:
        tree = ast.parse(code)
    except SyntaxError as e:
        return ValidationResult(False, f"syntax error: {e}")

    funcs = [n for n in tree.body if isinstance(n, ast.FunctionDef)]
    if len(funcs) != 1 or funcs[0].name != entry_point:
        return ValidationResult(
            False, f"must define exactly one function {entry_point!r}")
    if [a.arg for a in funcs[0].args.args] != ["pod", "node"]:
        return ValidationResult(False, "signature must be (pod, node)")
    others = [n for n in tree.body if not isinstance(n, (ast.FunctionDef,))]
    if any(not (isinstance(n, ast.Expr)
                and isinstance(n.value, ast.Constant)) for n in others):
        return ValidationResult(False, "top level must be the function only")

    allowed_calls = set(SAFE_BUILTINS)
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            return ValidationResult(
                False, f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.FunctionDef) and node is not funcs[0]:
            return ValidationResult(False, "nested functions are not allowed")
        if isinstance(node, ast.Attribute):
            if node.attr.startswith("_"):
                return ValidationResult(
                    False, f"private attribute: {node.attr!r}")
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                if f.id not in allowed_calls:
                    return ValidationResult(
                        False, f"call to non-whitelisted {f.id!r}")
            elif isinstance(f, ast.Attribute):
                if not (isinstance(f.value, ast.Name) and f.value.id == "math"
                        and f.attr in SAFE_MATH):
                    return ValidationResult(
                        False, "only math.<whitelisted> attribute calls allowed")
            else:
                return ValidationResult(False, "computed call targets not allowed")
    return ValidationResult(True)


def validate(code: str, entry_point: str = "priority_function") -> ValidationResult:
    """Both static stages. The third, TPU-specific stage is
    ``transpiler.transpile`` itself (raises TranspileError)."""
    r = validate_source_text(code)
    if not r:
        return r
    return validate_structure(code, entry_point)


# ------------------------------------------------- scalar entities + executor

@dataclasses.dataclass
class ScalarGPU:
    """One GPU as candidate code sees it (reference: simulator/entities.py:4-10)."""
    gpu_milli_left: int
    gpu_milli_total: int
    memory_mib_left: int = 0
    memory_mib_total: int = 0


@dataclasses.dataclass
class ScalarNode:
    """One node as candidate code sees it (reference: simulator/entities.py:12-21)."""
    cpu_milli_left: int
    cpu_milli_total: int
    memory_mib_left: int
    memory_mib_total: int
    gpu_left: int
    gpus: Sequence[ScalarGPU] = ()


@dataclasses.dataclass
class ScalarPod:
    """The pod as candidate code sees it (reference: simulator/entities.py:29-43)."""
    cpu_milli: int
    memory_mib: int
    num_gpu: int
    gpu_milli: int
    creation_time: int = 0
    duration_time: int = 0


def safe_environment() -> dict:
    """Restricted globals for candidate execution (reference:
    safe_execution.py:98-124): whitelisted builtins + ``math`` facade +
    operator functions, nothing else."""
    env = {"__builtins__": {}}
    import builtins
    for name in SAFE_BUILTINS:
        env[name] = getattr(builtins, name)

    class _Math:
        pass

    m = _Math()
    for name in SAFE_MATH:
        setattr(m, name, getattr(math, name))
    env["math"] = m
    for name in SAFE_OPERATOR:
        env[name] = getattr(operator, name)
    return env


class PolicyRuntimeError(RuntimeError):
    """Candidate code raised during scalar execution."""


class PolicyTimeoutError(PolicyRuntimeError):
    """Candidate code exceeded the scalar-execution deadline."""


#: Wall-clock budget for one scalar candidate call. The whitelist admits
#: ``range`` loops the transpiler has not yet bounded, so a validated
#: candidate can still be a `for i in range(10**9)` bomb; the reference
#: arms SIGALRM for the same reason (safe_execution.py:81-96).
EXEC_TIMEOUT_S = 5.0


@contextlib.contextmanager
def _deadline(seconds: Optional[float]):
    """SIGALRM-backed wall-clock guard around candidate execution.

    Signals only arm in the main thread; elsewhere (e.g. the generation
    thread pool) this is a no-op — safe there because the generator
    transpiles BEFORE smoke-testing (llm.CandidateGenerator.generate), and
    the transpiler's MAX_UNROLL bound rejects unbounded loops first. The
    ordering is pinned by tests/test_funsearch_sandbox.py."""
    if (not seconds
            or threading.current_thread() is not threading.main_thread()):
        yield
        return

    def _onalarm(signum, frame):
        raise PolicyTimeoutError(
            f"candidate exceeded the {seconds:g}s scalar deadline")

    import time
    old = signal.signal(signal.SIGALRM, _onalarm)
    t0 = time.monotonic()
    prev_delay, prev_interval = signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        # Alarm-safe cleanup: a fire in the instants after the candidate
        # finishes must neither skip the handler restore nor surface as a
        # timeout for a call that completed in time. Block the signal for
        # the whole cleanup, consume any pending fire, then restore the
        # previous handler/timer (re-arming an outer watchdog minus our
        # elapsed time).
        try:
            signal.pthread_sigmask(signal.SIG_BLOCK, {signal.SIGALRM})
            masked = True
        except (AttributeError, OSError, ValueError):
            masked = False
        try:
            if prev_delay:
                signal.setitimer(
                    signal.ITIMER_REAL,
                    max(0.001, prev_delay - (time.monotonic() - t0)),
                    prev_interval)
            else:
                signal.setitimer(signal.ITIMER_REAL, 0.0)
            if masked and hasattr(signal, "sigtimedwait"):
                signal.sigtimedwait([signal.SIGALRM], 0)
        finally:
            if masked and not hasattr(signal, "sigtimedwait"):
                # no sigtimedwait (macOS): drain a pending fire into
                # SIG_IGN before the old disposition returns — otherwise
                # unblocking delivers it to SIG_DFL and kills the process
                signal.signal(signal.SIGALRM, signal.SIG_IGN)
                signal.pthread_sigmask(
                    signal.SIG_UNBLOCK, {signal.SIGALRM})
                signal.pthread_sigmask(
                    signal.SIG_BLOCK, {signal.SIGALRM})
            signal.signal(signal.SIGALRM, old)
            if masked:
                signal.pthread_sigmask(
                    signal.SIG_UNBLOCK, {signal.SIGALRM})


def compile_policy(code: str, entry_point: str = "priority_function"):
    """Validate then compile candidate source once in the restricted
    environment; returns the scalar ``(pod, node) -> number`` callable
    (reference: funsearch_integration.py:77-89 compile-once path)."""
    r = validate(code, entry_point)
    if not r:
        raise PolicyRuntimeError(f"validation failed: {r.reason}")
    env = safe_environment()
    try:
        exec(code, env)  # noqa: S102 — restricted env, validated source
    except Exception as e:
        raise PolicyRuntimeError(f"compile failed: {e}") from e
    fn = env.get(entry_point)
    if not callable(fn):
        raise PolicyRuntimeError(f"{entry_point} not defined by candidate")
    return fn


def execute_scalar(code: str, pod: ScalarPod, node: ScalarNode,
                   entry_point: str = "priority_function",
                   timeout_s: Optional[float] = EXEC_TIMEOUT_S) -> float:
    """One-shot validated scalar run returning a finite float (reference:
    safe_execution.py:126-168). Used for smoke tests and as the transpiler
    differential-test oracle. A SIGALRM deadline (main thread only, see
    ``_deadline``) fails a looping candidate fast instead of hanging the
    host; ``timeout_s=None`` disables it."""
    fn = compile_policy(code, entry_point)
    try:
        with _deadline(timeout_s):
            out = fn(pod, node)
    except PolicyTimeoutError:
        raise
    except Exception as e:
        raise PolicyRuntimeError(f"execution failed: {e}") from e
    if isinstance(out, bool) or not isinstance(out, (int, float)):
        raise PolicyRuntimeError(f"non-numeric result: {out!r}")
    if math.isnan(out) or math.isinf(out):
        raise PolicyRuntimeError("non-finite result")
    return float(out)


def smoke_test(code: str) -> Optional[str]:
    """Run the candidate on one tiny (pod, node) pair; None if healthy, else
    the failure reason (reference: safe_execution.py:319-328
    ``test_policy_safely``)."""
    pod = ScalarPod(cpu_milli=500, memory_mib=1024, num_gpu=1, gpu_milli=250)
    node = ScalarNode(
        cpu_milli_left=4000, cpu_milli_total=8000,
        memory_mib_left=8192, memory_mib_total=16384, gpu_left=2,
        gpus=(ScalarGPU(1000, 1000, 8000, 8000), ScalarGPU(500, 1000, 8000, 8000)))
    try:
        execute_scalar(code, pod, node)
    except PolicyRuntimeError as e:
        return str(e)
    return None
