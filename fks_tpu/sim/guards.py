"""Numerics watchdog guards: mask-and-flag NaN/Inf/range detection.

The watchdog's device half. ``jax.experimental.checkify`` lifts errors out
of jitted code but composes poorly with the repo's loop shapes on jax
0.4.37 (``vmap``-of-``while_loop`` bodies under ``shard_map`` — checkify
functionalization inserts per-lane error state the manual-axes audit
rejects), so guards are plain elementwise masks instead: ``isfinite`` +
``where`` survive ``vmap``/``shard_map`` trivially because they ARE the
ops the engines are built from. Violations accumulate as a sticky int32
bitmask in the engine carry (``SimState.numeric_flags`` /
``FlatState.numeric_flags``) and surface in ``SimResult.numeric_flags``;
per-lane under ``vmap`` because the flags live in the per-lane state
pytree, so one lane's NaN never poisons a sibling lane.

All guards are gated on the Python-static ``SimConfig.watchdog`` flag: the
branch resolves at trace time, so the disabled path contributes zero ops
to the compiled program and is bit-identical to a build without guards.
When a guard fires, the offending scores are masked to 0 ("refuse", the
engines' no-placement sentinel) — identity for finite inputs, so an
enabled watchdog is also bit-identical whenever no violation fires.

The host half (event emission, parity sentinel, divergence audit) lives in
``fks_tpu.obs.watchdog``, which re-exports these symbols.
"""
from __future__ import annotations

from typing import List

import jax.numpy as jnp

#: sticky violation bits carried in ``numeric_flags``
FLAG_NAN = 1    # a policy score or the fitness was NaN
FLAG_INF = 2    # ... was +/-Inf
FLAG_RANGE = 4  # the final fitness left [0, 1]

FLAG_NAMES = ((FLAG_NAN, "nan"), (FLAG_INF, "inf"), (FLAG_RANGE, "range"))


def describe_flags(mask: int) -> List[str]:
    """Human-readable names for a violation bitmask (host-side)."""
    return [name for bit, name in FLAG_NAMES if int(mask) & bit]


def score_flags(raw_scores, gate):
    """i32 violation bitmask for one policy invocation's node scores.

    ``gate`` is the step's "this score is consumed" predicate (the engines'
    ``create``): scores computed but discarded on deletion events must not
    flag. Integer score dtypes cannot hold NaN/Inf, so the check is a
    trace-time no-op there (returns a constant 0).
    """
    scores = jnp.asarray(raw_scores)
    if not jnp.issubdtype(scores.dtype, jnp.floating):
        return jnp.int32(0)
    flags = (jnp.any(jnp.isnan(scores)).astype(jnp.int32) * FLAG_NAN
             + jnp.any(jnp.isinf(scores)).astype(jnp.int32) * FLAG_INF)
    return jnp.where(gate, flags, 0).astype(jnp.int32)


def sanitize_scores(raw_scores):
    """Mask non-finite policy scores to 0 — the engines' "refuse placement"
    sentinel, so a NaN lane degrades to an unplaced pod instead of feeding
    an implementation-defined argmax. Identity for finite inputs (and for
    integer dtypes, statically)."""
    scores = jnp.asarray(raw_scores)
    if not jnp.issubdtype(scores.dtype, jnp.floating):
        return raw_scores
    return jnp.where(jnp.isfinite(scores), scores, jnp.zeros_like(scores))


def guard_scores(raw_scores, gate, numeric_flags, *, enabled: bool):
    """The engines' per-invocation watchdog step in one call: fold this
    invocation's violation bits into the sticky carry mask, then sanitize.
    Returns ``(scores, numeric_flags)`` — unchanged when ``enabled`` is
    False (Python-static, zero ops on the disabled path). Shared by the
    exact and flat engines so the guard semantics cannot drift; the score
    vector's length is irrelevant (flags are per-EVENT, any NaN anywhere
    in the scored view flags it), so the same call guards the dense [N]
    sweep and the prefiltered [k] candidate view — no index translation
    through the top-k gather is needed or wanted."""
    if not enabled:
        return raw_scores, numeric_flags
    return (sanitize_scores(raw_scores),
            numeric_flags | score_flags(raw_scores, gate))


def fitness_flags(score):
    """i32 violation bitmask for a final fitness scalar: NaN, Inf, or
    outside the paper's [0, 1] fitness range."""
    score = jnp.asarray(score)
    nan = jnp.isnan(score)
    inf = jnp.isinf(score)
    rng = ~nan & ~inf & ((score < 0) | (score > 1))
    return (nan.astype(jnp.int32) * FLAG_NAN
            + inf.astype(jnp.int32) * FLAG_INF
            + rng.astype(jnp.int32) * FLAG_RANGE)
