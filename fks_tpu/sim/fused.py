"""Fused Pallas TPU kernel: the whole parametric-population simulation in
ONE kernel, state resident in VMEM.

Why: the XLA flat engine (fks_tpu.sim.flat) is a while_loop whose body is
~3 fused HBM passes over the [lanes, Q] queue arrays per event — measured
bandwidth-bound at ~110 us/step for 256 lanes on a v5e chip (PROFILE.md).
Every one of those bytes moves HBM<->VMEM each step because XLA keeps
while_loop carries in HBM. The queue for 64 lanes is ~4 MB — it FITS in
VMEM (~16 MB/core). This kernel keeps it there: the full event loop runs
inside one ``pl.pallas_call``, so per-step traffic is zero HBM bytes and
the step cost is pure VPU/MXU work on resident arrays.

Semantics are the flat engine's, exactly (same pop order via tie-rank slot
ordering, same retry rule, same evaluator arithmetic — see
fks_tpu/sim/flat.py); the policy is the parametric feature-basis model
(fks_tpu/models/parametric.py), hard-wired so the feature pipeline fuses
into the step. Arbitrary-code candidates (VM / per-candidate jit tiers)
stay on the XLA engines.

Kernel shape notes (Mosaic/TPU constraints):
- per-lane scalars are [L, 1] columns; iotas via ``broadcasted_iota``
  (1-D iota does not lower on TPU);
- the popped pod's feature row is fetched with an MXU one-hot matmul
  ``mask_f32 [L,Q] @ feat_f32 [Q,8]`` — exact because every pod feature
  value is < 2**24 (asserted at build time); aux (which can exceed 2**24
  once node/gpu bits are packed) is fetched with an integer masked reduce;
- the GPU best-fit sub-allocation is G static rounds of lexicographic
  min-picking over the winner node's [L, N, G] milli row — same
  (milli, slot) order as ops/allocator.best_fit_gpus;
- grid = population chunks of ``lanes`` candidates; each grid step runs
  its chunk's whole simulation start-to-finish in VMEM.

Limits (asserted, with the XLA flat engine as the general fallback):
packed aux encoding must fit (node_bits + G <= 31), best_fit allocator,
no invariant audit, float32 scoring, and VMEM has to hold ~5 [L, Q] i32
arrays plus the [L, N, G] grids (small-N workloads; the default trace's
16x8 node grid is ideal). ``SimResult.pod_ctime`` reports the original
creation times (the throughput paths run ``track_ctime=False`` anyway).
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fks_tpu.data.entities import Workload
from fks_tpu.models.parametric import NUM_FEATURES, SCORE_SCALE
from fks_tpu.sim.engine import SimConfig, finalize_fields, loop_tables
from fks_tpu.sim.flat import (
    AUX_FRESH, AUX_WAITING, INF, _decode_assignment, _FinalView, _packable,
    _rank_perm,
)
from fks_tpu.sim.types import SimResult

_BIG = 2**30
_EXACT_F32 = 1 << 24  # one-hot matmul gathers are exact below this


def _iota(shape, dim):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


class _Plan(NamedTuple):
    """Static geometry + host-prepared constants for the kernel."""

    q: int            # slot count (p_padded rounded up to 128)
    n: int
    g: int
    hist: int
    klen: int
    max_steps: int
    pending0: int
    node_bits: int
    ev0: Any          # i32[1, q] initial slot times (tie-rank order)
    feat_f: Any       # f32[8, q] pod features (cpu, mem, ngpu, milli, dur)
                      # transposed: [q, 8] would tile-pad to 128 lanes (4 MB)
    ktable: Any       # i32[1, K]
    nrow: Any         # i32[6, n]: cpu_tot, mem_tot, gpu_declared, num_gpus,
                      #            node_mask, milli_tot(per node)
    gmt: Any          # i32[n, g] per-GPU milli totals
    gmask: Any        # i32[n, g]
    totals: tuple     # (total_cpu, total_mem, total_gc, total_gm) python ints


def _build_plan(workload: Workload, cfg: SimConfig) -> _Plan:
    c, p = workload.cluster, workload.pods
    n, g, pp = c.n_padded, c.g_padded, p.p_padded
    if not _packable(n, g):
        raise ValueError("fused kernel needs packed aux (node_bits+G<=31); "
                         "use the XLA flat engine")
    if cfg.gpu_allocator != "best_fit":
        raise ValueError("fused kernel implements best_fit only")
    if cfg.validate_invariants:
        raise ValueError("invariant audit is not supported in the fused "
                         "kernel; use engine='flat'")
    if cfg.decision_trace:
        raise ValueError("decision trace is not supported in the fused "
                         "kernel; replay with engine='exact' or 'flat' "
                         "(fks_tpu.obs.tracing / cli trace-diff)")
    if cfg.probe_score:
        raise ValueError("budget probe rungs (SimConfig.probe_score, "
                         "fks_tpu.funsearch.budget) are not supported in "
                         "the fused kernel; run budget-pruned suite "
                         "evaluation with engine='exact' or 'flat'")
    if workload.faults is not None:
        raise ValueError("fault-injected workloads (fks_tpu.scenarios "
                         "NODE_DOWN/NODE_UP events) are not supported in "
                         "the fused kernel; evaluate scenario suites with "
                         "engine='exact' or 'flat'")
    if cfg.node_prefilter_k:
        raise ValueError("top-k node prefiltering (SimConfig."
                         "node_prefilter_k) is not supported in the fused "
                         "kernel — its fixed-function policy already "
                         "sweeps nodes in one fused pass; use "
                         "engine='flat' for the large-cluster scale tier")
    if cfg.state_pack:
        raise ValueError("packed state dtypes (SimConfig.state_pack) are "
                         "not supported in the fused kernel; use "
                         "engine='flat' for the large-cluster scale tier")
    q = _round_up(pp, 128)

    pm = np.asarray(p.pod_mask)
    perm = _rank_perm(pm, np.asarray(p.tie_rank))
    r_mask = pm[perm]
    ev0 = np.where(r_mask, np.asarray(p.creation_time)[perm], INF)
    ev0 = np.pad(ev0, (0, q - pp), constant_values=INF).astype(np.int32)

    feat = np.zeros((8, q), np.float32)
    for k, arr in enumerate((p.cpu, p.mem, p.num_gpu, p.gpu_milli,
                             p.duration)):
        col = np.asarray(arr)[perm].astype(np.float64)
        if np.abs(col).max(initial=0) >= _EXACT_F32:
            raise ValueError("pod feature values must be < 2**24 for the "
                             "exact one-hot matmul gather")
        feat[k, :pp] = col

    ktable, max_steps = loop_tables(workload, cfg)
    ktable = np.asarray(ktable, np.int32)[None, :]

    gmt = np.asarray(c.gpu_milli_total, np.int32)
    gmask = np.asarray(c.gpu_mask).astype(np.int32)
    milli_tot = (gmt * gmask).sum(axis=1).astype(np.int32)
    nrow = np.stack([
        np.asarray(c.cpu_total, np.int32),
        np.asarray(c.mem_total, np.int32),
        np.asarray(c.gpu_declared, np.int32),
        np.asarray(c.num_gpus, np.int32),
        np.asarray(c.node_mask).astype(np.int32),
        milli_tot,
    ])

    max_milli = int(np.asarray(p.gpu_milli).max(initial=0))
    hist = (cfg.wait_hist_size if cfg.wait_hist_size is not None
            else max(1001, max_milli + 2))
    if hist <= max_milli:
        raise ValueError("wait_hist_size <= trace max gpu_milli")

    totals = (int(nrow[0].sum()), int(nrow[1].sum()),
              int(nrow[3].sum()), int(milli_tot.sum()))
    return _Plan(
        q=q, n=n, g=g, hist=hist, klen=ktable.shape[1],
        max_steps=int(max_steps), pending0=int(pm.sum()),
        node_bits=max(1, (max(n, 1) - 1).bit_length()),
        ev0=jnp.asarray(ev0)[None, :], feat_f=jnp.asarray(feat),
        ktable=jnp.asarray(ktable), nrow=jnp.asarray(nrow),
        gmt=jnp.asarray(gmt), gmask=jnp.asarray(gmask), totals=totals,
    )


def _kernel(plan: _Plan, lanes: int,
            # inputs
            params_ref, ev0_ref, feat_ref, ktable_ref, nrow_ref, gmt_ref,
            gmask_ref,
            # outputs
            aux_out, cpu_out, mem_out, gpu_out, gmil_out, acci_out, accf_out,
            # scratch
            ev, aux, cpu, mem, gpu, gmil, hist, acci, accf):
    L, Q, N, G = lanes, plan.q, plan.n, plan.g
    H, K = plan.hist, plan.klen
    t_cpu, t_mem, t_gc, t_gm = plan.totals
    f32 = jnp.float32

    # ---- init VMEM state
    ev[:] = jnp.broadcast_to(ev0_ref[0:1, :], (L, Q))
    aux[:] = jnp.full((L, Q), AUX_FRESH, jnp.int32)
    cpu[:] = jnp.broadcast_to(nrow_ref[0:1, :], (L, N))
    mem[:] = jnp.broadcast_to(nrow_ref[1:2, :], (L, N))
    gpu[:] = jnp.broadcast_to(nrow_ref[2:3, :], (L, N))
    gmil[:] = jnp.broadcast_to(gmt_ref[:][None, :, :], (L, N, G))
    hist[:] = jnp.zeros((L, H), jnp.int32)
    # iota/where blend, not ``.at[:, 0].set`` — basic-index .at updates
    # lower to lax.scatter, which Mosaic has no TPU lowering for (first
    # real-hardware compile, round-4 session stage fused64)
    acci[:] = jnp.where(_iota((L, 8), 1) == 0,
                        jnp.int32(plan.pending0), jnp.int32(0))
    accf[:] = jnp.zeros((L, 8), f32)

    w_all = params_ref[:]                     # [L, F]
    nmask_b = nrow_ref[4:5, :] > 0            # [1, N]
    cpu_tot = nrow_ref[0:1, :]                # [1, N] i32
    mem_tot = nrow_ref[1:2, :]
    gpu_dec = nrow_ref[2:3, :]
    num_gpus = nrow_ref[3:4, :]
    milli_tot = nrow_ref[5:6, :]
    gmask_b = gmask_ref[:][None, :, :] > 0    # [1, N, G]

    q_iota = _iota((L, Q), 1)
    n_iota = _iota((L, N), 1)
    g_iota3 = _iota((L, N, G), 2)
    h_iota = _iota((L, H), 1)
    k_iota = _iota((L, K), 1)

    def step(_):
        pending = acci[:, 0:1]
        steps = acci[:, 1:2]
        failed = acci[:, 6:7] > 0
        active = (pending > 0) & ~failed & (steps < plan.max_steps)  # [L,1]

        # ---- pop: min time, first-index (== lowest tie rank) slot
        evv = ev[:]
        auxv = aux[:]
        t = jnp.min(evv, axis=1, keepdims=True)                   # [L,1]
        sidx = jnp.min(jnp.where(evv == t, q_iota, Q), axis=1,
                       keepdims=True)                             # [L,1]
        next_del = jnp.min(jnp.where(auxv >= 0, evv, INF), axis=1,
                           keepdims=True)
        mask_b = q_iota == sidx                                   # [L,Q]

        # integer reductions pin dtype=i32: under x64 this jax's jnp.sum
        # widens i32 operands to i64, which the i32 VMEM refs (and real
        # Mosaic) reject
        aux_s = jnp.sum(jnp.where(mask_b, auxv, 0), axis=1,
                        keepdims=True, dtype=jnp.int32)           # [L,1]
        pf = jax.lax.dot_general(
            mask_b.astype(f32), feat_ref[:],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32)                           # [L,8]
        pcpu = pf[:, 0:1].astype(jnp.int32)
        pmem = pf[:, 1:2].astype(jnp.int32)
        pngpu = pf[:, 2:3].astype(jnp.int32)
        pmilli = pf[:, 3:4].astype(jnp.int32)
        pdur = pf[:, 4:5].astype(jnp.int32)

        is_del = active & (aux_s >= 0)
        create = active & (aux_s < 0)
        was_waiting = aux_s == AUX_WAITING

        # ---- DELETION refunds (dense one-hot over node axes)
        a = jnp.where(is_del, aux_s >> G, 0)                      # [L,1]
        di = is_del.astype(jnp.int32)
        oh_a = (n_iota == a).astype(jnp.int32) * di               # [L,N]
        cpu_v = cpu[:] + oh_a * pcpu
        mem_v = mem[:] + oh_a * pmem
        gpu_v = gpu[:] + oh_a * pngpu
        held_bits = jnp.where(is_del, aux_s & ((1 << G) - 1), 0)  # [L,1]
        selb = ((held_bits[:, :, None] >> g_iota3) & 1)           # [L,N,G]*
        gmil_v = gmil[:] + (oh_a[:, :, None] * pmilli[:, :, None]) * selb

        # ---- parametric policy (fks_tpu/models/parametric.py features,
        # same op order so scores match the XLA path)
        d = f32
        cpu_totf = jnp.maximum(cpu_tot, 1).astype(d)
        mem_totf = jnp.maximum(mem_tot, 1).astype(d)
        ngpusf = jnp.maximum(num_gpus, 1).astype(d)
        milli_totf = jnp.maximum(milli_tot, 1).astype(d)
        rem_cpu = (cpu_v - pcpu).astype(d) / cpu_totf             # [L,N]
        rem_mem = (mem_v - pmem).astype(d) / mem_totf
        rem_gpu = (gpu_v - pngpu).astype(d) / ngpusf
        cpu_util = 1 - cpu_v.astype(d) / cpu_totf
        mem_util = 1 - mem_v.astype(d) / mem_totf
        gpu_count_util = 1 - gpu_v.astype(d) / ngpusf
        free_milli = jnp.sum(jnp.where(gmask_b, gmil_v, 0), axis=2,
                             dtype=jnp.int32)
        gpu_milli_util = 1 - free_milli.astype(d) / milli_totf
        balance = 1 - jnp.abs(cpu_util - mem_util)
        pod_gpu = pngpu > 0                                       # [L,1]
        frag_mod = jnp.where(
            pod_gpu, (free_milli % jnp.maximum(pmilli, 1)).astype(d) / 1000.0,
            0.0)
        eligible = jnp.sum(
            (gmask_b & (gmil_v >= pmilli[:, :, None])).astype(jnp.int32),
            axis=2, dtype=jnp.int32)                              # [L,N]
        eligible_frac = eligible.astype(d) / ngpusf
        node_has_gpu = (num_gpus > 0).astype(d) + jnp.zeros((L, N), d)
        best_fitf = 1 - (rem_cpu * 0.33 + rem_mem * 0.33 + rem_gpu * 0.34)
        gmax = jnp.max(jnp.where(gmask_b, gmil_v, 0), axis=2)
        gmin = jnp.min(jnp.where(gmask_b, gmil_v, 2**30), axis=2)
        gpu_imbalance = jnp.where(
            num_gpus > 0, (gmax - jnp.minimum(gmin, gmax)).astype(d) / 1000.0,
            0.0)
        headroom = ((cpu_v > pcpu * 2) & (mem_v > pmem * 2)).astype(d)
        ones = jnp.ones((L, N), d)
        feats = jnp.stack([
            ones, rem_cpu, rem_mem, rem_gpu, cpu_util, mem_util,
            gpu_count_util, gpu_milli_util, balance, frag_mod, eligible_frac,
            jnp.where(pod_gpu, ones, 0.0), node_has_gpu, best_fitf,
            gpu_imbalance, headroom,
        ], axis=-1)                                               # [L,N,F]
        # explicit mul+reduce, NOT einsum: a batched dot_general (batch
        # dim l) is a known Mosaic rejection class, while a VPU
        # elementwise-multiply + small-axis reduce (F=16) always lowers
        raw = jnp.sum(feats * w_all[:, None, :], axis=-1) * SCORE_SCALE
        feasible = (nmask_b
                    & (pcpu <= cpu_v) & (pmem <= mem_v) & (pngpu <= gpu_v)
                    & jnp.where(pod_gpu, eligible >= pngpu, True))
        scores = jnp.where(feasible,
                           jnp.maximum(1, jnp.trunc(raw).astype(jnp.int32)),
                           0)                                     # [L,N]

        mx = jnp.max(scores, axis=1, keepdims=True)               # [L,1]
        wn = jnp.min(jnp.where(scores == mx, n_iota, N), axis=1,
                     keepdims=True)                               # [L,1]
        placed = create & (mx > 0)

        # ---- best-fit GPU pick on the winner node: G rounds of
        # lexicographic (milli, slot) minima (ops/allocator.py order)
        oh_w = (n_iota == wn).astype(jnp.int32)                   # [L,N]
        elig_w = (gmask_b & (gmil_v >= pmilli[:, :, None])
                  & (oh_w[:, :, None] > 0))                       # [L,N,G]
        n_elig = jnp.sum(elig_w.astype(jnp.int32), axis=(1, 2),
                         keepdims=False, dtype=jnp.int32)[:, None]  # [L,1]
        key = jnp.where(elig_w, gmil_v * G + g_iota3, _BIG)
        sel = jnp.zeros((L, N, G), bool)
        for k in range(G):
            cur = jnp.min(key, axis=(1, 2))[:, None, None]        # [L,1,1]
            take = (k < pngpu)[:, :, None] & (cur < _BIG)
            pick = (key == cur) & take
            sel = sel | pick
            key = jnp.where(pick, _BIG, key)
        ok = n_elig >= pngpu
        alloc_fail = placed & (pngpu > 0) & ~ok
        plc = placed & ~alloc_fail                                # [L,1]
        pli = plc.astype(jnp.int32)
        oh_p = oh_w * pli                                         # [L,N]
        cpu_v = cpu_v - oh_p * pcpu
        mem_v = mem_v - oh_p * pmem
        gpu_v = gpu_v - oh_p * pngpu
        gmil_v = gmil_v - (oh_p[:, :, None] * pmilli[:, :, None]
                           * sel.astype(jnp.int32))
        new_bits = jnp.sum(
            jnp.where(sel, jnp.int32(1) << g_iota3, 0), axis=(1, 2),
            dtype=jnp.int32)[:, None]

        # ---- failed creation: waiting histogram + fragmentation + retry
        failp = create & ~placed
        bucket = jnp.clip(pmilli, 0, H - 1)                       # [L,1]
        hdelta = ((failp & ~was_waiting & (pngpu > 0)).astype(jnp.int32)
                  - (plc & was_waiting & (pngpu > 0)).astype(jnp.int32))
        hist_v = hist[:] + (h_iota == bucket).astype(jnp.int32) * hdelta
        has_w = jnp.any(hist_v > 0, axis=1, keepdims=True)        # [L,1]
        mn = jnp.min(jnp.where(hist_v > 0, h_iota, _BIG), axis=1,
                     keepdims=True)
        mn = jnp.where(has_w, mn, 0)
        frag_free = jnp.where(
            gmask_b & (gmil_v > 0) & (gmil_v < mn[:, :, None]), gmil_v, 0)
        fsum = jnp.sum(frag_free, axis=(1, 2),
                       dtype=jnp.int32)[:, None]                  # [L,1] i32
        frag_score = jnp.where(
            has_w & (t_gm > 0), fsum.astype(f32) / f32(max(t_gm, 1)),
            f32(0))
        found = next_del < INF
        retry = failp & found
        dropped = failp & ~found
        rt = next_del + 1

        # ---- slot rewrite (one blended pass over the VMEM queue)
        new_t = jnp.where(plc, t + pdur, jnp.where(retry, rt, INF))
        enc = (wn << G) | new_bits
        new_aux = jnp.where(plc, enc, jnp.where(failp, AUX_WAITING, aux_s))
        wmask = mask_b & active
        ev[:] = jnp.where(wmask, new_t, evv)
        aux[:] = jnp.where(wmask, new_aux, auxv)
        cpu[:] = cpu_v
        mem[:] = mem_v
        gpu[:] = gpu_v
        gmil[:] = gmil_v
        hist[:] = hist_v

        # ---- evaluator bookkeeping (identical arithmetic to the engines)
        valid = active & ~alloc_fail
        events = acci[:, 2:3] + valid.astype(jnp.int32)
        snap_idx = acci[:, 3:4]
        kt_at = jnp.sum(
            jnp.where(k_iota == jnp.minimum(snap_idx, K - 1), ktable_ref[:],
                      0), axis=1, keepdims=True, dtype=jnp.int32)
        fire = valid & (snap_idx < K) & (events >= kt_at)
        firef = fire.astype(f32)
        u_cpu = f32(t_cpu) - jnp.sum(
            cpu_v, axis=1, dtype=jnp.int32)[:, None].astype(f32)
        u_mem = f32(t_mem) - jnp.sum(
            mem_v, axis=1, dtype=jnp.int32)[:, None].astype(f32)
        u_gc = jnp.sum(
            num_gpus - gpu_v, axis=1, dtype=jnp.int32)[:, None].astype(f32)
        u_gm = f32(t_gm) - jnp.sum(
            jnp.where(gmask_b, gmil_v, 0), axis=(1, 2),
            dtype=jnp.int32)[:, None].astype(f32)
        utils = jnp.concatenate([
            0.0 * u_cpu if t_cpu <= 0 else u_cpu / f32(max(t_cpu, 1)),
            0.0 * u_mem if t_mem <= 0 else u_mem / f32(max(t_mem, 1)),
            0.0 * u_gc if t_gc <= 0 else u_gc / f32(max(t_gc, 1)),
            0.0 * u_gm if t_gm <= 0 else u_gm / f32(max(t_gm, 1)),
        ], axis=1)                                                # [L,4]
        accf[:, 0:4] = accf[:, 0:4] + utils * firef
        accf[:, 4:5] = accf[:, 4:5] + jnp.where(failp, frag_score, 0)

        active_nodes = jnp.sum(
            (nmask_b & ((cpu_v < cpu_tot) | (mem_v < mem_tot)
                        | (gpu_v < gpu_dec))).astype(jnp.int32),
            axis=1, dtype=jnp.int32)[:, None]
        acci[:, 0:1] = acci[:, 0:1] - (is_del | dropped).astype(jnp.int32)
        acci[:, 1:2] = steps + active.astype(jnp.int32)
        acci[:, 2:3] = events
        acci[:, 3:4] = snap_idx + fire.astype(jnp.int32)
        acci[:, 4:5] = acci[:, 4:5] + failp.astype(jnp.int32)
        acci[:, 5:6] = jnp.maximum(acci[:, 5:6],
                                   jnp.where(valid, active_nodes, 0))
        acci[:, 6:7] = acci[:, 6:7] | alloc_fail.astype(jnp.int32)

        pending2 = acci[:, 0:1]
        failed2 = acci[:, 6:7] > 0
        return jnp.any((pending2 > 0) & ~failed2
                       & (acci[:, 1:2] < plan.max_steps))

    jax.lax.while_loop(lambda cont: cont, step, jnp.bool_(plan.pending0 > 0))

    # ---- write results
    aux_out[:] = aux[:]
    cpu_out[:] = cpu[:]
    mem_out[:] = mem[:]
    gpu_out[:] = gpu[:]
    gmil_out[:] = gmil[:]
    acci_out[:] = acci[:]
    accf_out[:] = accf[:]


def make_fused_population_run(workload: Workload,
                              cfg: SimConfig = SimConfig(),
                              lanes: int = 64,
                              interpret: bool | None = None):
    """``run(params[P, F]) -> SimResult`` (leading axis P) through the fused
    kernel. P is padded up to a multiple of ``lanes``; each chunk of
    ``lanes`` candidates is one grid step.

    ``interpret=None`` (default) auto-selects: Mosaic-compile on TPU,
    pallas interpreter elsewhere (slow — CPU callers should prefer
    engine="exact"; the interpreter exists for correctness tests)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    plan = _build_plan(workload, cfg)
    Q, N, G = plan.q, plan.n, plan.g
    p = workload.pods
    pp = p.p_padded

    shared = lambda *shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: tuple(0 for _ in shape), memory_space=pltpu.VMEM)
    blocked = lambda *shape: pl.BlockSpec(  # noqa: E731
        shape, lambda i: (i,) + tuple(0 for _ in shape[1:]),
        memory_space=pltpu.VMEM)

    def call(params_padded, L):
        chunks = params_padded.shape[0] // L
        return pl.pallas_call(
            functools.partial(_kernel, plan, L),
            grid=(chunks,),
            in_specs=[
                blocked(L, NUM_FEATURES),
                shared(1, Q), shared(8, Q), shared(1, plan.klen),
                shared(6, N), shared(N, G), shared(N, G),
            ],
            out_specs=[
                blocked(L, Q), blocked(L, N), blocked(L, N), blocked(L, N),
                blocked(L, N, G), blocked(L, 8), blocked(L, 8),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((chunks * L, Q), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, N), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, N), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, N), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, N, G), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, 8), jnp.int32),
                jax.ShapeDtypeStruct((chunks * L, 8), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((L, Q), jnp.int32),   # ev
                pltpu.VMEM((L, Q), jnp.int32),   # aux
                pltpu.VMEM((L, N), jnp.int32),   # cpu
                pltpu.VMEM((L, N), jnp.int32),   # mem
                pltpu.VMEM((L, N), jnp.int32),   # gpu
                pltpu.VMEM((L, N, G), jnp.int32),  # gmil
                pltpu.VMEM((L, plan.hist), jnp.int32),
                pltpu.VMEM((L, 8), jnp.int32),
                pltpu.VMEM((L, 8), jnp.float32),
            ],
            interpret=interpret,
        )(params_padded, plan.ev0, plan.feat_f, plan.ktable, plan.nrow,
          plan.gmt, plan.gmask)

    perm = _rank_perm(np.asarray(p.pod_mask), np.asarray(p.tie_rank))
    inv = jnp.asarray(np.argsort(perm))
    ctime0 = jnp.asarray(p.creation_time, jnp.int32)

    # VMEM feasibility: ~5 [L,q] i32 live arrays (ev, aux, blend mask +
    # fusion temps), the tile-padded [L,n,128] grids, the [L,hist]
    # waiting histogram, and slack for the small accumulators. Lanes
    # auto-shrink to fit (~14 of the ~16 MB/core VMEM); shapes that
    # cannot fit even 8 lanes are rejected up front instead of letting
    # Mosaic fail opaquely — the XLA flat engine handles them.
    per_lane_bytes = (5 * Q + 3 * N * 128 + plan.hist + 2048) * 4
    lanes_fit = (14 * 2**20 // per_lane_bytes) // 8 * 8
    if lanes_fit < 8:
        raise ValueError(
            f"workload too large for the fused kernel's VMEM plan "
            f"({per_lane_bytes >> 10} KB/lane for q={Q}, n={N}, "
            f"hist={plan.hist}; under 8 lanes fit); use the XLA flat "
            "engine for large-node/pod shapes")

    def run(params) -> SimResult:
        pop = params.shape[0]
        # lane width: the cap, the whole (8-aligned) population when
        # smaller — small shard sizes under shard_map stay cheap — or
        # whatever VMEM can hold
        L = min(lanes, _round_up(pop, 8), lanes_fit)
        padded = _round_up(pop, L)
        if padded != pop:
            params = jnp.concatenate(
                [params, jnp.broadcast_to(params[:1],
                                          (padded - pop,) + params.shape[1:])])
        aux, cpu, mem, gpu, gmil, acci, accf = call(
            jnp.asarray(params, jnp.float32), L)
        aux = aux[:pop, :pp]
        an, ag = jax.vmap(
            lambda a: _decode_assignment(a, None, G, True))(aux)
        view = _FinalView(
            assigned_node=an[:, inv], assigned_gpus=ag[:, inv],
            pod_ctime=jnp.broadcast_to(ctime0, (pop, pp)),
            cpu_left=cpu[:pop], mem_left=mem[:pop], gpu_left=gpu[:pop],
            gpu_milli_left=gmil[:pop],
            events_processed=acci[:pop, 2], snap_idx=acci[:pop, 3],
            snap_sums=accf[:pop, 0:4], frag_sum=accf[:pop, 4],
            frag_count=acci[:pop, 4], max_nodes=acci[:pop, 5],
            failed=acci[:pop, 6] > 0, violations=jnp.zeros(pop, jnp.int32),
            numeric_flags=jnp.zeros(pop, jnp.int32),
        )
        return jax.vmap(
            lambda v, pend: finalize_fields(workload, cfg, pending=pend, s=v)
        )(view, acci[:pop, 0] > 0)

    return run
