"""The flat (slot-per-pod) event-queue engine — the TPU throughput path.

Why a second engine: the exact engine (fks_tpu.sim.engine) replicates the
reference's CPython heap bit-for-bit (required for the layout-dependent
retry rule, reference: simulator/event_simulator.py:51-58), but heap sifts
are chains of ~14 dependent tiny gather/scatters per event — measured at
~11 us/lane/step on a v5e chip, they dominate the step and scale LINEARLY
with the vmapped population (tools/profile_step.py; PROFILE.md). TPUs are
throughput machines: they want contiguous slices and vector reduces, not
pointer-chasing.

This engine replaces the heap with a structure a TPU likes:

- **One slot per pod.** At any instant a pod has at most ONE pending event
  (its CREATE, a retried CREATE, or its DELETE) — so the queue is just
  ``ev_time[P]`` + ``ev_kind[P]``, and every step rewrites exactly one
  slot. No sifts, no layout.
- **Two-level min hierarchy.** Pop = lexicographic argmin over
  ``(time, tie_rank)``. Slots are grouped into B blocks of ``block`` pods;
  the carry holds each block's (min time, min rank) and min pending-DELETE
  time. A step touches one block: one contiguous ``dynamic_slice`` in,
  in-register recompute, one contiguous ``dynamic_update_slice`` out.
  Per-step HBM traffic is O(block), independent of P.
- **Pop order is EXACTLY the reference's** wherever the reference's own
  order is well-defined: keys ``(time, tie_rank)`` are unique per pod
  (tie_rank = pod-id rank, event_simulator.py:16-17), and a pod's CREATE
  always precedes its own DELETE because the DELETE only enters the queue
  when the CREATE is placed (event_simulator.py:45-49).

Divergence from the reference, by design (SURVEY.md §7 explicitly blesses
this): the retry time for an unplaceable pod is ``1 + (earliest pending
DELETE time)`` instead of ``1 + (first DELETE in raw heap-ARRAY order)``,
which is an artifact of CPython heapq's layout. Instrumenting the
reference shows its scan lands on the time-earliest pending delete in the
median case (mean rank 0.8), so the time-order rule is both principled
AND the closest match; residual fitness deltas on the default trace's
published policies are chaotic (any single different retry snowballs) and
measured at |d| <= 0.029 (PROFILE.md).
Everything else (placement, refunds, fragmentation, snapshot overshoot,
fitness) is shared with or identical to the exact engine, so:

- runs with ZERO failed placements are bit-identical to the exact engine
  (and therefore to the reference) — enforced by differential tests;
- runs with retries differ only in retry timing; the exact engine remains
  the parity/golden path (bench.py's parity gate uses it).

Like the reference, a pod that fails placement when NO deletion is pending
is silently dropped (event_simulator.py:51-58 falls through) -> unassigned
-> fitness 0.

Degenerate candidates that refuse many placements retry once per fired
deletion (quadratic event count — the reference grinds through the same
blowup without a cap); under the default ``max_steps_factor`` such runs
hit the step budget and score 0 with ``truncated=True``. The earliest-
delete rule reaches the cap somewhat more often than the exact engine's
array-order rule. Raise ``SimConfig.max_steps_factor`` when strict
handling of pathological candidates matters more than bounding their
cost.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import Workload
from fks_tpu.ops.allocator import best_fit_gpus, first_fit_gpus
from fks_tpu.sim.engine import (
    SimConfig, _audit, _node_view, finalize_fields, loop_tables,
    run_batched_lanes,
)
from fks_tpu.sim.types import FlatState, NodeView, PodView, PolicyFn, SimResult

INF = jnp.iinfo(jnp.int32).max  # empty-slot sentinel (also "rank" filler)

K_CREATE = 0   # original creation event
K_DELETE = 1   # pending deletion of a placed pod
K_RETRY = 2    # re-queued creation (pod is in the waiting set)


def _block_width(p_padded: int) -> int:
    return min(128, max(1, p_padded))


def _queue_size(p_padded: int) -> int:
    """Slot-array length: p_padded rounded up to a whole number of blocks.
    The queue pads internally (INF slots) so ANY workload padding works —
    callers are not required to pad pod counts to a block multiple."""
    bw = _block_width(p_padded)
    return ((p_padded + bw - 1) // bw) * bw


def _block_mins(bt, bk, br):
    """(min time, rank at that min, min DELETE time) of one block slice.
    Lexicographic (time, rank): ranks are unique, so the pair is unique."""
    mt = jnp.min(bt)
    mr = jnp.min(jnp.where(bt == mt, br, INF))
    mdel = jnp.min(jnp.where(bk == K_DELETE, bt, INF))
    return mt, mr, mdel


def initial_state(workload: Workload, cfg: SimConfig) -> FlatState:
    """t=0 carry: every real pod's slot holds its CREATE event."""
    c, p = workload.cluster, workload.pods
    pp = p.p_padded
    qp = _queue_size(pp)
    bw = _block_width(pp)
    pm = np.asarray(p.pod_mask)
    ev_time = np.full(qp, INF, np.int32)
    ev_time[:pp] = np.where(pm, np.asarray(p.creation_time), INF)
    ev_kind = np.zeros(qp, np.int32)
    rank = np.full(qp, INF, np.int32)
    rank[:pp] = np.where(pm, np.asarray(p.tie_rank), INF)
    tb = ev_time.reshape(-1, bw)
    rb = rank.reshape(-1, bw)
    bmin_t = tb.min(axis=1)
    bmin_r = np.where(tb == bmin_t[:, None], rb, INF).min(axis=1)

    max_milli = int(np.asarray(p.gpu_milli).max(initial=0))
    hist_size = (cfg.wait_hist_size if cfg.wait_hist_size is not None
                 else max(1001, max_milli + 2))
    if hist_size <= max_milli:
        raise ValueError(
            f"wait_hist_size {hist_size} <= trace max gpu_milli; "
            "fragmentation min_needed would be miscounted")
    f = cfg.score_dtype
    return FlatState(
        ev_time=jnp.asarray(ev_time),
        ev_kind=jnp.asarray(ev_kind),
        bmin_t=jnp.asarray(bmin_t, jnp.int32),
        bmin_r=jnp.asarray(bmin_r, jnp.int32),
        bdel_t=jnp.full(bmin_t.shape, INF, jnp.int32),
        cpu_left=jnp.asarray(c.cpu_total, jnp.int32),
        mem_left=jnp.asarray(c.mem_total, jnp.int32),
        gpu_left=jnp.asarray(c.gpu_declared, jnp.int32),
        gpu_milli_left=jnp.asarray(c.gpu_milli_total, jnp.int32),
        assigned_node=jnp.full(pp, -1, jnp.int32),
        assigned_gpus=jnp.zeros(pp, jnp.uint32),
        pod_ctime=jnp.asarray(p.creation_time, jnp.int32),
        wait_hist=jnp.zeros(hist_size, jnp.int32),
        events_processed=jnp.int32(0),
        snap_idx=jnp.int32(0),
        snap_sums=jnp.zeros(4, f),
        frag_sum=jnp.asarray(0, f),
        frag_count=jnp.int32(0),
        max_nodes=jnp.int32(0),
        failed=jnp.bool_(False),
        steps=jnp.int32(0),
        violations=jnp.int32(0),
    )


def lane_active(s: FlatState, max_steps: int):
    """Termination predicate (single source of truth for the loop cond and
    the step's self-masking, like engine.lane_active).

    The block-min reduction is over the LAST axis only: on the batched
    state ``bmin_t`` is [lanes, B] and the predicate must stay per-lane —
    a full reduction would let one truncated lane (pending events, step
    budget exhausted) hold the population loop's cond true through other
    lanes forever."""
    return ((jnp.min(s.bmin_t, axis=-1) < INF)
            & ~s.failed & (s.steps < max_steps))


def build_step(workload: Workload, policy: PolicyFn, cfg: SimConfig,
               ktable, max_steps: int) -> Callable[[FlatState], FlatState]:
    """One event. Self-masking like the exact engine's step, so the
    population layer can run ONE while_loop over vmapped lanes."""
    c, p = workload.cluster, workload.pods
    c = jax.tree_util.tree_map(jnp.asarray, c)
    p = jax.tree_util.tree_map(jnp.asarray, p)
    pp = p.p_padded
    qp = _queue_size(pp)
    bw = _block_width(pp)
    g = workload.cluster.g_padded
    f = cfg.score_dtype
    alloc = best_fit_gpus if cfg.gpu_allocator == "best_fit" else first_fit_gpus
    total_cpu = jnp.sum(c.cpu_total)
    total_mem = jnp.sum(c.mem_total)
    total_gc = jnp.sum(c.num_gpus)
    total_gm = jnp.sum(c.gpu_milli_total)
    g_iota = jnp.arange(g, dtype=jnp.uint32)
    bw_iota = jnp.arange(bw, dtype=jnp.int32)
    ktable = jnp.asarray(ktable, jnp.int32)
    klen = ktable.shape[0]
    rank_arr = jnp.full(qp, INF, jnp.int32).at[:pp].set(
        jnp.where(p.pod_mask, p.tie_rank, INF).astype(jnp.int32))

    def step(s: FlatState) -> FlatState:
        active = lane_active(s, max_steps)

        # ---- pop: two-level lexicographic argmin over (time, rank)
        gt = jnp.min(s.bmin_t)
        cand = s.bmin_t == gt
        gr = jnp.min(jnp.where(cand, s.bmin_r, INF))
        b = jnp.argmax(cand & (s.bmin_r == gr)).astype(jnp.int32)
        start = b * bw
        bt = jax.lax.dynamic_slice_in_dim(s.ev_time, start, bw)
        bk = jax.lax.dynamic_slice_in_dim(s.ev_kind, start, bw)
        br = jax.lax.dynamic_slice_in_dim(rank_arr, start, bw)
        off = jnp.argmax((bt == gt) & (br == gr)).astype(jnp.int32)
        pod = start + off
        t = gt
        kind = bk[off]
        is_del = active & (kind == K_DELETE)
        create = active & (kind != K_DELETE)
        was_waiting = kind == K_RETRY

        pcpu = p.cpu[pod]
        pmem = p.mem[pod]
        pngpu = p.num_gpu[pod]
        pmilli = p.gpu_milli[pod]
        pdur = p.duration[pod]

        # ---- DELETION: refund resources (reference main.py:74-99).
        # Node-array updates are DENSE one-hot adds, not scatters: N is
        # tiny (padded node count) and TPU scatters serialize per element
        # while a [N]-wide predicated add is one vector op.
        a = jnp.where(is_del, s.assigned_node[pod], 0)
        di = is_del.astype(jnp.int32)
        n_iota = jnp.arange(c.cpu_total.shape[0], dtype=jnp.int32)
        oh_a = (n_iota == a).astype(jnp.int32) * di  # [N]
        cpu_left = s.cpu_left + oh_a * pcpu
        mem_left = s.mem_left + oh_a * pmem
        gpu_left = s.gpu_left + oh_a * pngpu
        bits = s.assigned_gpus[pod]
        sel_bits = ((bits >> g_iota) & 1).astype(jnp.int32)  # [G]
        gpu_milli_left = s.gpu_milli_left + oh_a[:, None] * pmilli * sel_bits[None, :]

        # ---- CREATION: strict argmax placement (main.py:101-111)
        pod_view = PodView(pcpu, pmem, pngpu, pmilli, t, pdur)
        node_view = _node_view(c, cpu_left, mem_left, gpu_left, gpu_milli_left)
        if cfg.cond_policy:
            out = jax.eval_shape(policy, pod_view, node_view)
            raw_scores = jax.lax.cond(
                create, lambda: jnp.asarray(policy(pod_view, node_view)),
                lambda: jnp.zeros(out.shape, out.dtype))
        else:
            raw_scores = policy(pod_view, node_view)
        scores = jnp.where(c.node_mask, raw_scores, 0)
        w = jnp.argmax(scores).astype(jnp.int32)
        placed = create & (scores[w] > 0)

        sel, ok = alloc(gpu_milli_left[w], c.gpu_mask[w], pmilli, pngpu)
        alloc_fail = placed & (pngpu > 0) & ~ok  # reference raises here
        pl = placed & ~alloc_fail
        pli = pl.astype(jnp.int32)
        oh_w = (n_iota == w).astype(jnp.int32) * pli  # [N]
        cpu_left = cpu_left - oh_w * pcpu
        mem_left = mem_left - oh_w * pmem
        gpu_left = gpu_left - oh_w * pngpu
        gpu_milli_left = gpu_milli_left - (
            oh_w[:, None] * pmilli * sel.astype(jnp.int32)[None, :])

        assigned_node = s.assigned_node.at[pod].set(
            jnp.where(pl, w, s.assigned_node[pod]))
        new_bits = jnp.sum(jnp.where(sel, jnp.uint32(1) << g_iota,
                                     jnp.uint32(0)), dtype=jnp.uint32)
        assigned_gpus = s.assigned_gpus.at[pod].set(
            jnp.where(pl, new_bits, bits))

        # ---- failed creation: waiting set + fragmentation + retry
        failp = create & ~placed
        bucket = jnp.clip(pmilli, 0, s.wait_hist.shape[0] - 1)
        hist = s.wait_hist.at[bucket].add(
            (failp & ~was_waiting & (pngpu > 0)).astype(jnp.int32)
            - (pl & was_waiting & (pngpu > 0)).astype(jnp.int32))

        hvals = hist > 0
        has_gpu_waiting = jnp.any(hvals)
        min_needed = jnp.argmax(hvals).astype(jnp.int32)
        frag_free = jnp.where(
            c.gpu_mask & (gpu_milli_left > 0) & (gpu_milli_left < min_needed),
            gpu_milli_left, 0)
        frag_score = jnp.where(
            has_gpu_waiting & (total_gm > 0),
            jnp.sum(frag_free, dtype=jnp.int32).astype(f)
            / jnp.maximum(total_gm, 1).astype(f),
            jnp.asarray(0, f))
        frag_sum = s.frag_sum + jnp.where(failp, frag_score, 0)
        frag_count = s.frag_count + failp.astype(jnp.int32)

        # retry rule (defined semantics; see module docstring): 1 + the
        # EARLIEST pending DELETE time. Instrumenting the reference shows
        # its array-order scan picks the time-earliest pending delete in
        # the median case (mean rank 0.8 among pending deletes; measured
        # on the default trace), so this is also the closest principled
        # approximation of the reference's cadence.
        next_del = jnp.min(s.bdel_t)
        found = next_del < INF
        retry = failp & found
        rt = next_del + 1
        pod_ctime = s.pod_ctime.at[pod].set(
            jnp.where(retry, rt, s.pod_ctime[pod]))

        # ---- slot rewrite: the popped pod's next event
        new_t = jnp.where(pl, t + pdur, jnp.where(retry, rt, INF))
        new_k = jnp.where(pl, K_DELETE, K_RETRY)
        bt2 = jnp.where(active & (bw_iota == off), new_t, bt)
        bk2 = jnp.where(active & (bw_iota == off), new_k, bk)
        ev_time = jax.lax.dynamic_update_slice_in_dim(s.ev_time, bt2, start, 0)
        ev_kind = jax.lax.dynamic_update_slice_in_dim(s.ev_kind, bk2, start, 0)
        mt, mr, mdel = _block_mins(bt2, bk2, br)
        upd = active
        bmin_t = s.bmin_t.at[b].set(jnp.where(upd, mt, s.bmin_t[b]))
        bmin_r = s.bmin_r.at[b].set(jnp.where(upd, mr, s.bmin_r[b]))
        bdel_t = s.bdel_t.at[b].set(jnp.where(upd, mdel, s.bdel_t[b]))

        # ---- evaluator bookkeeping (identical to the exact engine)
        valid = active & ~alloc_fail
        events = s.events_processed + valid.astype(jnp.int32)
        fire = valid & (s.snap_idx < klen) & (
            events >= ktable[jnp.minimum(s.snap_idx, klen - 1)])
        used = jnp.stack([
            (total_cpu - jnp.sum(cpu_left)).astype(f),
            (total_mem - jnp.sum(mem_left)).astype(f),
            jnp.sum(c.num_gpus - gpu_left).astype(f),
            (total_gm - jnp.sum(gpu_milli_left)).astype(f),
        ])
        totals_vec = jnp.stack([total_cpu, total_mem, total_gc, total_gm])
        denom = jnp.maximum(totals_vec, 1).astype(f)
        utils = jnp.where(totals_vec <= 0, 0, used / denom)
        snap_sums = s.snap_sums + jnp.where(fire, utils, 0)
        snap_idx = s.snap_idx + fire.astype(jnp.int32)

        active_nodes = jnp.sum((c.node_mask & (
            (cpu_left < c.cpu_total) | (mem_left < c.mem_total)
            | (gpu_left < c.num_gpus))), dtype=jnp.int32)
        max_nodes = jnp.maximum(s.max_nodes, jnp.where(valid, active_nodes, 0))

        violations = s.violations
        if cfg.validate_invariants:
            # slice off the queue's block padding: the audit segment-sums
            # against [pp]-shaped per-pod request arrays
            active_pods = (ev_kind[:pp] == K_DELETE) & (ev_time[:pp] < INF)
            violations = violations + active.astype(jnp.int32) * _audit(
                c, p, active_pods, cpu_left, mem_left, gpu_left,
                gpu_milli_left, assigned_node, assigned_gpus)

        return FlatState(
            ev_time=ev_time, ev_kind=ev_kind,
            bmin_t=bmin_t, bmin_r=bmin_r, bdel_t=bdel_t,
            cpu_left=cpu_left, mem_left=mem_left, gpu_left=gpu_left,
            gpu_milli_left=gpu_milli_left, assigned_node=assigned_node,
            assigned_gpus=assigned_gpus, pod_ctime=pod_ctime,
            wait_hist=hist, events_processed=events, snap_idx=snap_idx,
            snap_sums=snap_sums, frag_sum=frag_sum, frag_count=frag_count,
            max_nodes=max_nodes, failed=s.failed | alloc_fail,
            steps=s.steps + active.astype(jnp.int32), violations=violations,
        )

    return step


def finalize(workload: Workload, cfg: SimConfig, s: FlatState) -> SimResult:
    return finalize_fields(
        workload, cfg, pending=jnp.min(s.bmin_t) < INF, s=s)


def make_param_run_fn(workload: Workload, param_policy,
                      cfg: SimConfig = SimConfig()):
    """``run(params, state) -> SimResult`` — flat-engine counterpart of
    engine.make_param_run_fn (same ktable/max_steps/finalize assembly)."""
    ktable, max_steps = loop_tables(workload, cfg)

    def cond(s: FlatState):
        return lane_active(s, max_steps)

    def run(params, state: FlatState) -> SimResult:
        step = build_step(
            workload, lambda pod, nodes: param_policy(params, pod, nodes),
            cfg, ktable, max_steps)
        final = jax.lax.while_loop(cond, step, state)
        return finalize(workload, cfg, final)

    return run


def make_run_fn(workload: Workload, policy: PolicyFn,
                cfg: SimConfig = SimConfig()):
    run = make_param_run_fn(
        workload, lambda _p, pod, nodes: policy(pod, nodes), cfg)
    return functools.partial(run, None)


def simulate(workload: Workload, policy: PolicyFn,
             cfg: SimConfig = SimConfig(), jit: bool = True) -> SimResult:
    """Host convenience API, mirroring engine.simulate."""
    run = make_run_fn(workload, policy, cfg)
    if jit:
        run = jax.jit(run)
    return run(initial_state(workload, cfg))


def broadcast_state(state0: FlatState, lanes: int) -> FlatState:
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x), (lanes,) + jnp.shape(x)),
        state0)


def make_population_run_fn(workload: Workload, param_policy,
                           cfg: SimConfig = SimConfig()):
    """``run(params[C, ...], state0) -> SimResult`` batched over candidates:
    ONE while_loop whose body is the vmapped self-masking step (finished
    lanes idle cheaply), exactly like engine.make_population_run_fn."""
    ktable, max_steps = loop_tables(workload, cfg)

    def run(params, state0: FlatState) -> SimResult:
        pop = jax.tree_util.tree_leaves(params)[0].shape[0]

        def step_one(prm, s):
            return build_step(
                workload, lambda pod, nodes: param_policy(prm, pod, nodes),
                cfg, ktable, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(0, 0))
        final = run_batched_lanes(
            lambda s: vstep(params, s), broadcast_state(state0, pop),
            max_steps, active_fn=lane_active)
        return jax.vmap(lambda s: finalize(workload, cfg, s))(final)

    return run
