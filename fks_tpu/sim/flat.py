"""The flat (slot-per-pod) event-queue engine — the TPU throughput path.

Why a second engine: the exact engine (fks_tpu.sim.engine) replicates the
reference's CPython heap bit-for-bit (required for the layout-dependent
retry rule, reference: simulator/event_simulator.py:51-58), but heap sifts
are chains of ~14 dependent tiny gather/scatters per event — the worst
possible shape for a TPU. Measurement on a v5e chip (tools/probe_ops.py,
PROFILE.md) showed something stronger: EVERY per-lane-indexed scatter or
gather in a vmapped loop body costs ~35 us/step of serialized latency,
while full-array vector passes (reduces, dense blends) run at HBM
bandwidth. So this engine is built from exactly two kinds of op:

- **Full-sweep pops.** One slot per pod (a pod has at most ONE pending
  event: CREATE / retried CREATE / DELETE), ``ev_time[Q]`` with INF for
  empty. Slots are ordered by ``tie_rank`` (pod-id string rank, the
  reference's equal-time tie-break, event_simulator.py:16-17), so the next
  event is simply ``argmin(ev_time)`` — argmin's first-index tie rule IS
  the reference's tie rule, with no rank array and no lexicographic
  two-pass reduce.
- **Dense one-hot blends.** Every state write (the popped slot's rewrite,
  node refunds/placements, the waiting histogram) is a predicated
  full-array ``where``, never a scatter. XLA fuses the blends that share a
  mask into single bandwidth-bound passes.

A companion ``aux[Q]`` array carries each pod's scheduling state in one
int32: -1 = CREATE pending / never placed, -2 = in the waiting set
(failed at least once), >= 0 = placed, packed ``(node << G) | gpu_bits``
(falls back to a separate gpu-bits array when node_bits + G > 31). The
pop's kind test, the pending-DELETE minimum for the retry rule, the
was-waiting flag, and the final assigned/unassigned verdict all read this
one array, so the whole step touches O(Q) bytes across ~3 fused passes.

Pop order is EXACTLY the reference's wherever the reference's own order is
well-defined: keys ``(time, tie_rank)`` are unique per pod, and a pod's
CREATE always precedes its own DELETE because the DELETE only enters the
queue when the CREATE is placed (event_simulator.py:45-49).

Divergence from the reference, by design (SURVEY.md §7 explicitly blesses
this): the retry time for an unplaceable pod is ``1 + (earliest pending
DELETE time)`` instead of ``1 + (first DELETE in raw heap-ARRAY order)``,
which is an artifact of CPython heapq's layout. Instrumenting the
reference shows its scan lands on the time-earliest pending delete in the
median case (mean rank 0.8), so the time-order rule is both principled
AND the closest match; residual fitness deltas on the default trace's
published policies are chaotic (any single different retry snowballs) and
measured at |d| <= 0.029 (PROFILE.md).
Everything else (placement, refunds, fragmentation, snapshot overshoot,
fitness) is shared with or identical to the exact engine, so:

- runs with ZERO failed placements are bit-identical to the exact engine
  (and therefore to the reference) — enforced by differential tests;
- runs with retries differ only in retry timing; the exact engine remains
  the parity/golden path (bench.py's parity gate uses it).

Like the reference, a pod that fails placement when NO deletion is pending
is silently dropped (event_simulator.py:51-58 falls through) -> unassigned
-> fitness 0.

Degenerate candidates that refuse many placements retry once per fired
deletion (quadratic event count — the reference grinds through the same
blowup without a cap); under the default ``max_steps_factor`` such runs
hit the step budget and score 0 with ``truncated=True``. The earliest-
delete rule reaches the cap somewhat more often than the exact engine's
array-order rule. Raise ``SimConfig.max_steps_factor`` when strict
handling of pathological candidates matters more than bounding their
cost.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import Workload
from fks_tpu.ops.allocator import best_fit_gpus, first_fit_gpus
from fks_tpu.ops.heap import KIND_NODE_UP
from fks_tpu.sim.engine import (
    SimConfig, _audit, _gather_node_view, _node_view, _prefilter_candidates,
    _trace_append, _widest_int, finalize_fields, loop_tables,
    run_batched_lanes,
)
from fks_tpu.sim.guards import guard_scores
from fks_tpu.sim.types import FlatState, PodView, PolicyFn, SimResult, empty_trace
from fks_tpu.utils.segments import segment_budget, validate_seg_steps

INF = jnp.iinfo(jnp.int32).max  # empty-slot sentinel

# aux[q] scheduling-state encoding (one int32 per pod)
AUX_FRESH = -1    # CREATE pending, never failed
AUX_WAITING = -2  # retried CREATE pending or dropped (in the waiting set)
# aux >= 0: placed -- (node << G) | gpu_bits when packable, else node index


def _node_bits(n_padded: int) -> int:
    return max(1, (max(n_padded, 1) - 1).bit_length())


def _packable(n_padded: int, g_padded: int) -> bool:
    """Can (node, gpu_bits) share one non-negative int32?"""
    return _node_bits(n_padded) + g_padded <= 31


def _pack_dtypes(cfg: SimConfig, c, p) -> dict:
    """Per-column carry dtypes under ``SimConfig.state_pack`` (flat engine
    only). Packing is strictly EXACT: a column narrows to 16 bits only
    when its full value range provably fits at this workload's shape —
    per-GPU milli capacity <= 32767 for ``gpu_milli_left``, declared GPU
    count for ``gpu_left``, pod count for ``wait_hist`` (bucket counts
    cannot exceed waiting pods), node/GPU encoding width for ``aux`` /
    ``aux_gpus`` (the -1/-2 sentinels need the sign bit, so the packed
    encoding must fit 14 value bits). Columns that cannot prove their
    range stay int32 — the knob degrades shape-by-shape to a no-op, never
    to wraparound. Step arithmetic still promotes to int32 (so policies
    always see int32 views); only the while_loop CARRY narrows, halving
    its bandwidth for these columns. With ``state_pack=False`` every
    entry is the historical int32/uint32 and the compiled program is
    bit-identical."""
    i32, u32 = jnp.int32, jnp.uint32
    if not cfg.state_pack:
        return dict(aux=i32, aux_gpus=u32, wait_hist=i32,
                    gpu_left=i32, gpu_milli_left=i32)
    n, g = c.n_padded, c.g_padded
    if _packable(n, g):
        aux_fits = _node_bits(n) + g <= 14
    else:
        aux_fits = n <= 32767  # unpacked aux holds a bare node index
    max_pg_milli = int(np.asarray(c.gpu_milli_total).max(initial=0))
    max_gd = int(np.asarray(c.gpu_declared).max(initial=0))
    num_real = int(np.asarray(p.pod_mask).sum())
    return dict(
        aux=jnp.int16 if aux_fits else i32,
        aux_gpus=jnp.uint16 if g <= 16 else u32,
        wait_hist=jnp.int16 if num_real <= 32767 else i32,
        gpu_left=jnp.int16 if max_gd <= 32767 else i32,
        gpu_milli_left=jnp.int16 if max_pg_milli <= 32767 else i32,
    )


def _rank_perm(pod_mask, tie_rank):
    """Slot order: real pods by ascending tie_rank, padding last. Stable
    argsort, so host (numpy) and device (jnp) agree for the same input."""
    if isinstance(pod_mask, np.ndarray):
        key = np.where(pod_mask, tie_rank, INF)
        return np.argsort(key, kind="stable").astype(np.int32)
    key = jnp.where(pod_mask, tie_rank, INF)
    return jnp.argsort(key, stable=True).astype(jnp.int32)


def initial_state(workload: Workload, cfg: SimConfig) -> FlatState:
    """t=0 carry: every real pod's slot (in tie-rank order) holds its
    CREATE time; ``aux`` starts at AUX_FRESH."""
    c, p = workload.cluster, workload.pods
    pp = p.p_padded
    pm = np.asarray(p.pod_mask)
    perm = _rank_perm(pm, np.asarray(p.tie_rank))
    r_mask = pm[perm]
    ev_time = np.where(r_mask, np.asarray(p.creation_time)[perm], INF)
    packed = _packable(c.n_padded, c.g_padded)

    max_milli = int(np.asarray(p.gpu_milli).max(initial=0))
    hist_size = (cfg.wait_hist_size if cfg.wait_hist_size is not None
                 else max(1001, max_milli + 2))
    if hist_size <= max_milli:
        raise ValueError(
            f"wait_hist_size {hist_size} <= trace max gpu_milli; "
            "fragmentation min_needed would be miscounted")
    f = cfg.score_dtype
    dt = _pack_dtypes(cfg, c, p)
    return FlatState(
        ev_time=jnp.asarray(ev_time, jnp.int32),
        aux=jnp.full(pp, AUX_FRESH, dt["aux"]),
        aux_gpus=None if packed else jnp.zeros(pp, dt["aux_gpus"]),
        pending=jnp.int32(int(pm.sum())),
        cpu_left=jnp.asarray(c.cpu_total, jnp.int32),
        mem_left=jnp.asarray(c.mem_total, jnp.int32),
        gpu_left=jnp.asarray(c.gpu_declared, dt["gpu_left"]),
        gpu_milli_left=jnp.asarray(c.gpu_milli_total, dt["gpu_milli_left"]),
        pod_ctime=jnp.asarray(np.asarray(p.creation_time)[perm], jnp.int32),
        wait_hist=jnp.zeros(hist_size, dt["wait_hist"]),
        events_processed=jnp.int32(0),
        snap_idx=jnp.int32(0),
        snap_sums=jnp.zeros(4, f),
        frag_sum=jnp.asarray(0, f),
        frag_count=jnp.int32(0),
        max_nodes=jnp.int32(0),
        failed=jnp.bool_(False),
        steps=jnp.int32(0),
        violations=jnp.int32(0),
        numeric_flags=jnp.int32(0),
        trace=(empty_trace(cfg.resolve_trace_len(workload.num_pods), f)
               if cfg.decision_trace else None),
        fault_time=None if workload.faults is None else jnp.where(
            jnp.asarray(workload.faults.mask),
            jnp.asarray(workload.faults.time, jnp.int32), INF),
        node_avail=(None if workload.faults is None
                    else jnp.ones(c.n_padded, bool)),
    )


def lane_active(s: FlatState, max_steps: int):
    """Termination predicate (single source of truth for the loop cond and
    the step's self-masking). ``pending`` counts live slots, maintained
    incrementally so neither the cond nor the predicate needs a full
    ev_time sweep. Unconsumed fault events keep the lane live too (the
    exact engine's heap counts them the same way), so trailing NODE_UP
    events drain in both engines."""
    live = s.pending > 0
    if s.fault_time is not None:
        live = live | (jnp.min(s.fault_time, axis=-1) < INF)
    return live & ~s.failed & (s.steps < max_steps)


def build_step(workload: Workload, policy: PolicyFn, cfg: SimConfig,
               ktable, max_steps: int) -> Callable[[FlatState], FlatState]:
    """One event. Self-masking like the exact engine's step, so the
    population layer can run ONE while_loop over vmapped lanes.

    ``workload`` arrays may be tracers (multi-trace batching); everything
    derived from them (the rank permutation, permuted pod features, totals)
    is loop-invariant, so XLA hoists it out of the while_loop either way.
    """
    c, p = workload.cluster, workload.pods
    c = jax.tree_util.tree_map(jnp.asarray, c)
    p = jax.tree_util.tree_map(jnp.asarray, p)
    pp = p.p_padded
    n = workload.cluster.n_padded
    g = workload.cluster.g_padded
    f = cfg.score_dtype
    alloc = best_fit_gpus if cfg.gpu_allocator == "best_fit" else first_fit_gpus
    packed = _packable(n, g)
    total_cpu = jnp.sum(c.cpu_total)
    total_mem = jnp.sum(c.mem_total)
    total_gc = jnp.sum(c.num_gpus)
    total_gm = jnp.sum(c.gpu_milli_total)
    g_iota = jnp.arange(g, dtype=jnp.uint32)
    n_iota = jnp.arange(n, dtype=jnp.int32)
    q_iota = jnp.arange(pp, dtype=jnp.int32)
    ktable = jnp.asarray(ktable, jnp.int32)
    klen = ktable.shape[0]

    # pod features permuted into slot (tie-rank) order, packed into one
    # gather table so the pop costs a single [8]-row read
    perm = _rank_perm(p.pod_mask, p.tie_rank)
    feat = jnp.stack([
        p.cpu[perm], p.mem[perm], p.num_gpu[perm], p.gpu_milli[perm],
        p.duration[perm], jnp.zeros(pp, jnp.int32), jnp.zeros(pp, jnp.int32),
        jnp.zeros(pp, jnp.int32)], axis=-1).astype(jnp.int32)  # [Q, 8]
    if cfg.validate_invariants:
        import dataclasses as _dc
        p_rank = _dc.replace(
            p, cpu=p.cpu[perm], mem=p.mem[perm], num_gpu=p.num_gpu[perm],
            gpu_milli=p.gpu_milli[perm], creation_time=p.creation_time[perm],
            duration=p.duration[perm], tie_rank=p.tie_rank[perm],
            pod_mask=p.pod_mask[perm])

    # Python-static fault gating (like watchdog/decision_trace): fault-free
    # workloads compile to the exact pre-scenario program.
    has_faults = workload.faults is not None
    if has_faults:
        flt = jax.tree_util.tree_map(jnp.asarray, workload.faults)
        f_iota = jnp.arange(flt.time.shape[0], dtype=jnp.int32)
    # large-cluster scale tier: 0 = dense sweep (bit-identical program)
    prefilter_k = cfg.resolve_prefilter_k(n)

    def step(s: FlatState) -> FlatState:
        active = lane_active(s, max_steps)

        # ---- pop + retry-rule minimum: ONE fused sweep over ev_time/aux.
        # Slot order == tie-rank order, so argmin's first-index tie-break
        # IS the reference's pod-id tie rule (event_simulator.py:16-17).
        t = jnp.min(s.ev_time)
        sidx = jnp.argmin(s.ev_time).astype(jnp.int32)
        next_del = jnp.min(jnp.where(s.aux >= 0, s.ev_time, INF))

        if has_faults:
            # fault-vs-pod arbitration: the earliest unconsumed fault wins
            # ties against equal-time pod events (the exact engine gives
            # faults negative tie ranks), and argmin's first-index rule
            # among equal-time faults matches their heap rank order
            fidx = jnp.argmin(s.fault_time).astype(jnp.int32)
            take_fault = active & (s.fault_time[fidx] <= t)
            fault_node = flt.node[fidx]
            fault_is_up = flt.kind[fidx] == KIND_NODE_UP
            pod_act = active & ~take_fault
        else:
            pod_act = active

        pf = feat[sidx]  # [8]
        pcpu, pmem, pngpu, pmilli, pdur = pf[0], pf[1], pf[2], pf[3], pf[4]
        aux_s = s.aux[sidx]
        is_del = pod_act & (aux_s >= 0)
        create = pod_act & (aux_s < 0)
        was_waiting = aux_s == AUX_WAITING

        if packed:
            held_node = aux_s >> g
            held_bits = (aux_s & ((1 << g) - 1)).astype(jnp.uint32)
        else:
            held_node = aux_s
            held_bits = s.aux_gpus[sidx]

        # ---- DELETION: refund resources (reference main.py:74-99).
        # Node-array updates are DENSE one-hot adds over the tiny node
        # axis, never scatters.
        a = jnp.where(is_del, held_node, 0)
        di = is_del.astype(jnp.int32)
        oh_a = (n_iota == a).astype(jnp.int32) * di  # [N]
        cpu_left = s.cpu_left + oh_a * pcpu
        mem_left = s.mem_left + oh_a * pmem
        gpu_left = s.gpu_left + oh_a * pngpu
        sel_bits = ((held_bits >> g_iota) & 1).astype(jnp.int32)  # [G]
        gpu_milli_left = s.gpu_milli_left + oh_a[:, None] * pmilli * sel_bits[None, :]

        # ---- FAULT: consume the event + flip the cordon bit (dense blends)
        fault_time = s.fault_time
        node_avail = s.node_avail
        if has_faults:
            fault_time = jnp.where((f_iota == fidx) & take_fault, INF,
                                   s.fault_time)
            oh_f = n_iota == jnp.where(take_fault, fault_node, jnp.int32(n))
            node_avail = jnp.where(oh_f, fault_is_up, node_avail)

        # ---- CREATION: strict argmax placement (main.py:101-111).
        # creation_time == pop time for both fresh and retried pods (the
        # reference mutates pod.creation_time to the retry time, so at pop
        # it always equals the event time).
        pod_view = PodView(pcpu, pmem, pngpu, pmilli, t, pdur)
        node_view = _node_view(c, cpu_left, mem_left, gpu_left, gpu_milli_left)
        if prefilter_k:
            # a cordoned (downed) node scores 0 until NODE_UP — under the
            # prefilter it must also never outrank a feasible candidate,
            # so the cordon mask feeds the ranking itself
            place_mask = c.node_mask & node_avail if has_faults else c.node_mask
            cand = _prefilter_candidates(
                pod_view, node_view, place_mask, prefilter_k)
            node_view = _gather_node_view(node_view, cand)
        if cfg.cond_policy:
            out = jax.eval_shape(policy, pod_view, node_view)
            raw_scores = jax.lax.cond(
                create, lambda: jnp.asarray(policy(pod_view, node_view)),
                lambda: jnp.zeros(out.shape, out.dtype))
        else:
            raw_scores = policy(pod_view, node_view)
        raw_scores, numeric_flags = guard_scores(
            raw_scores, create, s.numeric_flags, enabled=cfg.watchdog)
        if prefilter_k:
            # re-mask through the gather: when fewer than k nodes are
            # feasible the candidate tail is padding (cordoned nodes
            # included) — zero those slots whatever the policy scored
            scores = jnp.where(place_mask[cand], raw_scores, 0)
        else:
            # a cordoned (downed) node scores 0 — "cannot/refuse" — until NODE_UP
            place_mask = c.node_mask & node_avail if has_faults else c.node_mask
            scores = jnp.where(place_mask, raw_scores, 0)
        # wk indexes the scored view ([k] candidates or [N] nodes);
        # w is always the GLOBAL node index (gather-back through cand)
        wk = jnp.argmax(scores).astype(jnp.int32)
        w = cand[wk] if prefilter_k else wk
        placed = create & (scores[wk] > 0)

        sel, ok = alloc(gpu_milli_left[w], c.gpu_mask[w], pmilli, pngpu)
        alloc_fail = placed & (pngpu > 0) & ~ok  # reference raises here
        pl = placed & ~alloc_fail
        pli = pl.astype(jnp.int32)
        oh_w = (n_iota == w).astype(jnp.int32) * pli  # [N]
        cpu_left = cpu_left - oh_w * pcpu
        mem_left = mem_left - oh_w * pmem
        gpu_left = gpu_left - oh_w * pngpu
        gpu_milli_left = gpu_milli_left - (
            oh_w[:, None] * pmilli * sel.astype(jnp.int32)[None, :])
        new_bits = jnp.sum(jnp.where(sel, jnp.uint32(1) << g_iota,
                                     jnp.uint32(0)), dtype=jnp.uint32)
        # packed-carry handoff (SimConfig.state_pack): the refund/placement
        # arithmetic above promotes to int32 (policies always see int32
        # views); narrow back to the carry dtype. The Python guards keep
        # the unpacked path contributing zero jaxpr equations.
        if gpu_left.dtype != s.gpu_left.dtype:
            gpu_left = gpu_left.astype(s.gpu_left.dtype)
        if gpu_milli_left.dtype != s.gpu_milli_left.dtype:
            gpu_milli_left = gpu_milli_left.astype(s.gpu_milli_left.dtype)

        # ---- failed creation: waiting set + fragmentation + retry
        failp = create & ~placed
        bucket = jnp.clip(pmilli, 0, s.wait_hist.shape[0] - 1)
        hdelta = ((failp & ~was_waiting & (pngpu > 0)).astype(jnp.int32)
                  - (pl & was_waiting & (pngpu > 0)).astype(jnp.int32))
        h_iota = jnp.arange(s.wait_hist.shape[0], dtype=jnp.int32)
        hist = s.wait_hist + (h_iota == bucket).astype(jnp.int32) * hdelta
        if hist.dtype != s.wait_hist.dtype:  # state_pack carry handoff
            hist = hist.astype(s.wait_hist.dtype)

        hvals = hist > 0
        has_gpu_waiting = jnp.any(hvals)
        min_needed = jnp.argmax(hvals).astype(jnp.int32)
        frag_free = jnp.where(
            c.gpu_mask & (gpu_milli_left > 0) & (gpu_milli_left < min_needed),
            gpu_milli_left, 0)
        frag_score = jnp.where(
            has_gpu_waiting & (total_gm > 0),
            jnp.sum(frag_free, dtype=_widest_int()).astype(f)
            / jnp.maximum(total_gm, 1).astype(f),
            jnp.asarray(0, f))
        frag_sum = s.frag_sum + jnp.where(failp, frag_score, 0)
        frag_count = s.frag_count + failp.astype(jnp.int32)

        # retry rule (defined semantics; see module docstring): 1 + the
        # EARLIEST pending DELETE time. ``next_del`` is from the pre-step
        # sweep, which is exactly the post-pop pending-delete set (the
        # popped event is a CREATE here, and this step adds no deletes
        # before the reference's scan point).
        found = next_del < INF
        retry = failp & found
        dropped = failp & ~found
        rt = next_del + 1

        # ---- slot rewrite + pod bookkeeping: one fused blend pass
        new_t = jnp.where(pl, t + pdur, jnp.where(retry, rt, INF))
        if packed:
            enc = (w << g) | new_bits.astype(jnp.int32)
        else:
            enc = w
        new_aux = jnp.where(pl, enc, jnp.where(failp, AUX_WAITING, aux_s))
        if new_aux.dtype != s.aux.dtype:  # state_pack carry handoff
            new_aux = new_aux.astype(s.aux.dtype)
        m = (q_iota == sidx) & pod_act
        ev_time = jnp.where(m, new_t, s.ev_time)
        aux = jnp.where(m, new_aux, s.aux)
        aux_gpus = s.aux_gpus
        if not packed:
            upd_bits = jnp.where(pl, new_bits, held_bits)
            if upd_bits.dtype != s.aux_gpus.dtype:  # state_pack handoff
                upd_bits = upd_bits.astype(s.aux_gpus.dtype)
            aux_gpus = jnp.where(m, upd_bits, s.aux_gpus)
        pod_ctime = (jnp.where(m & retry, rt, s.pod_ctime)
                     if cfg.track_ctime else s.pod_ctime)
        pending = s.pending - (is_del | dropped).astype(jnp.int32)

        # ---- evaluator bookkeeping (identical to the exact engine).
        # Fault events are control events: excluded from events_processed,
        # snapshots, and max_nodes (pod_act is active outside fault steps).
        valid = pod_act & ~alloc_fail
        events = s.events_processed + valid.astype(jnp.int32)
        fire = valid & (s.snap_idx < klen) & (
            events >= ktable[jnp.minimum(s.snap_idx, klen - 1)])
        used = jnp.stack([
            (total_cpu - jnp.sum(cpu_left)).astype(f),
            (total_mem - jnp.sum(mem_left)).astype(f),
            jnp.sum(c.num_gpus - gpu_left).astype(f),
            (total_gm - jnp.sum(gpu_milli_left)).astype(f),
        ])
        totals_vec = jnp.stack([total_cpu, total_mem, total_gc, total_gm])
        denom = jnp.maximum(totals_vec, 1).astype(f)
        utils = jnp.where(totals_vec <= 0, 0, used / denom)
        snap_sums = s.snap_sums + jnp.where(fire, utils, 0)
        snap_idx = s.snap_idx + fire.astype(jnp.int32)

        active_nodes = jnp.sum((c.node_mask & (
            (cpu_left < c.cpu_total) | (mem_left < c.mem_total)
            | (gpu_left < c.num_gpus))), dtype=jnp.int32)
        max_nodes = jnp.maximum(s.max_nodes, jnp.where(valid, active_nodes, 0))

        violations = s.violations
        if cfg.validate_invariants:
            active_pods = (aux >= 0) & (ev_time < INF)
            an, ag = _decode_assignment(aux, aux_gpus, g, packed)
            violations = violations + active.astype(jnp.int32) * _audit(
                c, p_rank, active_pods, cpu_left, mem_left, gpu_left,
                gpu_milli_left, an, ag)

        trace = s.trace
        if cfg.decision_trace:
            # pod column holds perm[sidx] — the ORIGINAL input-order pod id
            # — so rows align with the exact engine's without un-permuting.
            # The pending column counts remaining fault events too, like
            # the exact engine's heap size (align_traces compares exactly).
            tpod = perm[sidx]
            tnode = jnp.where(is_del, held_node, jnp.where(pl, w, -1))
            trace_pending = pending
            fault_kw = {}
            if has_faults:
                tpod = jnp.where(take_fault, -1, tpod)
                tnode = jnp.where(take_fault, fault_node, tnode)
                trace_pending = pending + jnp.sum(
                    (fault_time < INF).astype(jnp.int32))
                fault_kw = dict(fault_down=take_fault & ~fault_is_up,
                                fault_up=take_fault & fault_is_up)
            trace = _trace_append(
                trace, active=active, create=create, is_del=is_del,
                was_waiting=was_waiting, pod=tpod, node=tnode,
                scores=scores, winner=w, pending=trace_pending,
                cpu_left=cpu_left, mem_left=mem_left, gpu_left=gpu_left,
                gpu_milli_left=gpu_milli_left, **fault_kw)

        return FlatState(
            ev_time=ev_time, aux=aux, aux_gpus=aux_gpus, pending=pending,
            cpu_left=cpu_left, mem_left=mem_left, gpu_left=gpu_left,
            gpu_milli_left=gpu_milli_left, pod_ctime=pod_ctime,
            wait_hist=hist, events_processed=events, snap_idx=snap_idx,
            snap_sums=snap_sums, frag_sum=frag_sum, frag_count=frag_count,
            max_nodes=max_nodes, failed=s.failed | alloc_fail,
            steps=s.steps + active.astype(jnp.int32), violations=violations,
            numeric_flags=numeric_flags, trace=trace,
            fault_time=fault_time, node_avail=node_avail,
        )

    return step


def _decode_assignment(aux, aux_gpus, g: int, packed: bool):
    """(assigned_node[Q], assigned_gpus[Q]) from the aux encoding (slot
    order). Placed pods keep aux >= 0 after their DELETE fires, so this is
    valid mid-run and at finalize."""
    if packed:
        an = jnp.where(aux >= 0, aux >> g, -1)
        ag = jnp.where(aux >= 0, (aux & ((1 << g) - 1)).astype(jnp.uint32),
                       jnp.uint32(0))
    else:
        an = jnp.where(aux >= 0, aux, -1)
        ag = jnp.where(aux >= 0, aux_gpus, jnp.uint32(0))
    # SimResult dtypes stay int32/uint32 whatever the carry dtypes were
    # (state_pack): a no-op convert when the carry is already wide
    return an.astype(jnp.int32), ag.astype(jnp.uint32)


class _FinalView(NamedTuple):
    """finalize_fields-compatible view of a FlatState with per-pod arrays
    decoded from aux and un-permuted back to input (CSV) order."""

    assigned_node: Any
    assigned_gpus: Any
    pod_ctime: Any
    cpu_left: Any
    mem_left: Any
    gpu_left: Any
    gpu_milli_left: Any
    events_processed: Any
    snap_idx: Any
    snap_sums: Any
    frag_sum: Any
    frag_count: Any
    max_nodes: Any
    failed: Any
    violations: Any
    numeric_flags: Any
    trace: Any = None


def finalize(workload: Workload, cfg: SimConfig, s: FlatState) -> SimResult:
    c, p = workload.cluster, workload.pods
    perm = _rank_perm(jnp.asarray(p.pod_mask), jnp.asarray(p.tie_rank))
    inv = jnp.argsort(perm)  # slot index of each input-order pod
    an, ag = _decode_assignment(
        s.aux, s.aux_gpus, c.g_padded, _packable(c.n_padded, c.g_padded))
    view = _FinalView(
        assigned_node=an[inv], assigned_gpus=ag[inv],
        pod_ctime=s.pod_ctime[inv],
        cpu_left=s.cpu_left, mem_left=s.mem_left,
        # widen packed carries so SimResult dtypes are config-independent
        gpu_left=s.gpu_left.astype(jnp.int32),
        gpu_milli_left=s.gpu_milli_left.astype(jnp.int32),
        events_processed=s.events_processed, snap_idx=s.snap_idx,
        snap_sums=s.snap_sums, frag_sum=s.frag_sum, frag_count=s.frag_count,
        max_nodes=s.max_nodes, failed=s.failed, violations=s.violations,
        numeric_flags=s.numeric_flags, trace=s.trace,
    )
    pend = s.pending > 0
    if s.fault_time is not None:
        # unconsumed fault events mean a truncated run, exactly as they
        # would still sit in the exact engine's heap
        pend = pend | (jnp.min(s.fault_time) < INF)
    return finalize_fields(workload, cfg, pending=pend, s=view)


def make_param_run_fn(workload: Workload, param_policy,
                      cfg: SimConfig = SimConfig()):
    """``run(params, state) -> SimResult`` — flat-engine counterpart of
    engine.make_param_run_fn (same ktable/max_steps/finalize assembly)."""
    ktable, max_steps = loop_tables(workload, cfg)

    def cond(s: FlatState):
        return lane_active(s, max_steps)

    def run(params, state: FlatState) -> SimResult:
        step = build_step(
            workload, lambda pod, nodes: param_policy(params, pod, nodes),
            cfg, ktable, max_steps)
        final = jax.lax.while_loop(cond, step, state)
        return finalize(workload, cfg, final)

    return run


def make_run_fn(workload: Workload, policy: PolicyFn,
                cfg: SimConfig = SimConfig()):
    run = make_param_run_fn(
        workload, lambda _p, pod, nodes: policy(pod, nodes), cfg)
    return functools.partial(run, None)


def simulate(workload: Workload, policy: PolicyFn,
             cfg: SimConfig = SimConfig(), jit: bool = True) -> SimResult:
    """Host convenience API, mirroring engine.simulate."""
    run = make_run_fn(workload, policy, cfg)
    if jit:
        run = jax.jit(run)
    return run(initial_state(workload, cfg))


def broadcast_state(state0: FlatState, lanes: int) -> FlatState:
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x), (lanes,) + jnp.shape(x)),
        state0)


#: jitted broadcast for host-loop callers (the segmented runner): one
#: dispatch, and XLA materializes the per-lane state in a single program
_broadcast_jit = jax.jit(broadcast_state, static_argnums=1)


def make_population_run_fn(workload: Workload, param_policy,
                           cfg: SimConfig = SimConfig()):
    """``run(params[C, ...], state0) -> SimResult`` batched over candidates:
    ONE while_loop whose body is the vmapped self-masking step (finished
    lanes idle cheaply), exactly like engine.make_population_run_fn."""
    ktable, max_steps = loop_tables(workload, cfg)

    def run(params, state0: FlatState) -> SimResult:
        pop = jax.tree_util.tree_leaves(params)[0].shape[0]

        def step_one(prm, s):
            return build_step(
                workload, lambda pod, nodes: param_policy(prm, pod, nodes),
                cfg, ktable, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(0, 0))
        final = run_batched_lanes(
            lambda s: vstep(params, s), broadcast_state(state0, pop),
            max_steps, active_fn=lane_active)
        return jax.vmap(lambda s: finalize(workload, cfg, s))(final)

    return run


def make_segmented_population_run(workload: Workload, param_policy,
                                  cfg: SimConfig = SimConfig(),
                                  seg_steps: int = 4096,
                                  on_segment=None,
                                  double_buffer: bool = True):
    """``make_population_run_fn`` with a bounded device-call length: the
    while_loop stops every ``seg_steps`` events and the carry returns to
    the host, which re-dispatches until every lane drains.

    Exists for runtimes that kill long single device executions (the axon
    TPU tunnel kills calls over ~60 s — bench.py protocol notes): a
    full-trace batched-VM launch or a 100k-pod scale run can exceed the
    window no matter the population size, since wall time scales with
    steps, not lanes. Active lanes advance in lockstep (the self-masking
    step freezes only finished lanes), so ``steps - start`` is uniform
    across active lanes and the segment bound is exact.

    ``double_buffer`` (default on) pipelines the segment handoff: segment
    i+1 is dispatched BEFORE segment i's any-lane-active flag is read, so
    the device never waits for the host's flag sync — JAX's async
    dispatch keeps the next segment's program (and its event-block carry)
    enqueued while the current one runs. The flag therefore lags one
    segment behind the dispatch front and the loop runs exactly one
    overrun segment past the draining one; drained lanes stay drained
    (``lane_active`` is monotonic), the overrun segment self-masks to a
    no-op, and results stay identical to the unsegmented runner — pinned
    by tests/test_flat_engine.py::test_segmented_population_matches.
    ``double_buffer=False`` restores the classic sync-per-segment loop
    (one scalar device->host sync per segment).

    ``on_segment`` (zero-arg callable) fires on the host after every
    segment dispatch — the flight recorder's segment counter
    (fks_tpu.obs); it runs between device calls, never inside them.

    The returned ``run`` exposes ``run.advance`` (the jitted one-segment
    program) and ``run.seg_steps`` so bench harnesses can AOT-lower the
    hot program for cost/memory introspection without a second compile.
    """
    seg_steps = validate_seg_steps(seg_steps, zero_disables=False)
    ktable, max_steps = loop_tables(workload, cfg)

    def step_one(prm, s):
        return build_step(
            workload, lambda pod, nodes: param_policy(prm, pod, nodes),
            cfg, ktable, max_steps)(s)

    vstep = jax.vmap(step_one, in_axes=(0, 0))

    @jax.jit
    def advance(params, bstate):
        start = bstate.steps  # frozen at segment entry

        def cond(s):
            return jnp.any(lane_active(s, max_steps)
                           & (s.steps - start < seg_steps))

        out = jax.lax.while_loop(
            cond, lambda s: vstep(params, s), bstate)
        return out, jnp.any(lane_active(out, max_steps))

    @jax.jit
    def finalize_pop(bstate):
        return jax.vmap(lambda s: finalize(workload, cfg, s))(bstate)

    def run(params, state0: FlatState) -> SimResult:
        pop = jax.tree_util.tree_leaves(params)[0].shape[0]
        # jitted broadcast: one dispatch for the whole per-lane state
        # instead of ~20 per-leaf broadcast ops (round-4 advisor note;
        # the compile is trivial — no loop in the program)
        bstate = _broadcast_jit(state0, pop)
        # segment count is bounded by the step budget, so a cond/step
        # divergence cannot spin the host loop forever. The double-
        # buffered loop reads a flag that lags one segment, so it needs
        # one extra observation slot in the budget (slack 2 vs 1).
        active = True
        prev = None
        for _ in range(segment_budget(max_steps, seg_steps,
                                      slack=2 if double_buffer else 1)):
            bstate, active = advance(params, bstate)
            if on_segment is not None:
                on_segment()
            if double_buffer:
                # sync on the PREVIOUS segment's flag only after this
                # segment is already in flight: the device pipeline never
                # stalls on the host round-trip
                if prev is not None and not bool(prev):
                    active = prev
                    break
                prev = active
            elif not bool(active):  # the only per-segment host sync
                break
        if bool(active):
            # the budget above is exact for lockstep lanes; reaching it
            # with live lanes means cond/step divergence — surface it
            # loudly instead of finalizing a partially-drained state
            # (round-4 advisor finding: silently-wrong SimResults)
            raise RuntimeError(
                "segmented runner exhausted its segment budget with lanes "
                "still active — cond/step divergence in the flat engine")
        return finalize_pop(bstate)

    run.advance = advance
    run.finalize_pop = finalize_pop
    run.seg_steps = seg_steps
    return run
