"""Simulation-facing views and state pytrees.

``PodView``/``NodeView`` are the policy interface -- the TPU-native
re-design of the reference's ``PodNodeScorer = Callable[[Pod, Node], int]``
(reference: simulator/main.py:8). Instead of one (pod, node) pair per call,
a policy scores ONE pod against ALL nodes at once: vectorized over the node
axis, jit-traceable, and therefore fusible into the simulation step.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax.lax
import jax.numpy as jnp

from fks_tpu.ops.heap import EventHeap


class PodView(NamedTuple):
    """Scalar features of the pod being scheduled (reference Pod fields,
    simulator/entities.py:29-43)."""

    cpu_milli: Any
    memory_mib: Any
    num_gpu: Any
    gpu_milli: Any
    creation_time: Any  # as mutated by retries (event_simulator.py:56)
    duration_time: Any


class NodeView(NamedTuple):
    """Per-node state arrays, axis N (+ per-GPU axis G).

    Mirrors reference Node/GPU observable fields (simulator/entities.py:4-21).
    ``gpu_mem_total`` never changes during simulation (the reference never
    allocates GPU memory, only milli), so there is no ``gpu_mem_left``.
    """

    cpu_milli_left: Any  # i32[N]
    cpu_milli_total: Any  # i32[N]
    memory_mib_left: Any  # i32[N]
    memory_mib_total: Any  # i32[N]
    gpu_left: Any  # i32[N] (starts at declared count, parser.py:56)
    num_gpus: Any  # i32[N] == len(node.gpus)
    gpu_milli_left: Any  # i32[N, G]
    gpu_milli_total: Any  # i32[N, G]
    gpu_mem_total: Any  # i32[N, G]
    gpu_mask: Any  # bool[N, G]
    node_mask: Any  # bool[N]


# A policy scores one pod against every node; 0 means "cannot/refuse"
# (strict-argmax > 0 gate, reference main.py:104-111).
PolicyFn = Callable[[PodView, NodeView], Any]  # -> i32[N]


# decision-trace event kinds (TraceBuffer COL_KIND values). RETRY marks a
# creation attempt of a pod that already failed at least once; NODE_DOWN /
# NODE_UP are scenario fault events (fks_tpu.scenarios — pod column -1,
# node column the cordoned node, score/margin 0).
TRACE_CREATE = 0
TRACE_DELETE = 1
TRACE_RETRY = 2
TRACE_NODE_DOWN = 3
TRACE_NODE_UP = 4
TRACE_KIND_NAMES = ("CREATE", "DELETE", "RETRY", "NODE_DOWN", "NODE_UP")


class TraceBuffer(NamedTuple):
    """Bounded per-step decision log carried in the engine state (see
    ``SimConfig.decision_trace``): one row per processed event, filled
    inside the jitted step and appended with a dropped out-of-range
    scatter once full. Integer observables live as COLUMNS of one
    ``i32[T, 8]`` matrix (single row-scatter per event, the ``pod_state``
    layout rationale); the two float observables (winning score,
    second-best margin) ride in a separate ``f[T, 2]`` so the score dtype
    survives. Rows are comparable ACROSS engines: pod ids are original
    input order (the flat engine un-permutes its slot index on write) and
    deletes record score/margin as 0."""

    data: Any  # i32[T, 8], columns below
    scores: Any  # f[T, 2]: (winning score, second-best margin)
    count: Any  # i32 rows written (saturates at T; appends then drop)

    # data column indices
    COL_KIND = 0  # TRACE_CREATE / TRACE_DELETE / TRACE_RETRY
    COL_POD = 1  # original input-order pod id
    # chosen node (-1 = failed/none); held node on DELETE. ALWAYS the
    # GLOBAL node index: under SimConfig.node_prefilter_k the winner is
    # gathered back through the candidate list before the row is written
    # (the local top-k slot never leaks), so cli trace-diff rows stay
    # comparable across prefilter configs.
    COL_NODE = 2
    COL_PENDING = 3  # post-step pending event count
    COL_FREE_CPU = 4  # post-step cluster-wide free aggregates
    COL_FREE_MEM = 5
    COL_FREE_GPU = 6
    COL_FREE_GPU_MILLI = 7


def empty_trace(length: int, score_dtype: Any = jnp.float32) -> TraceBuffer:
    """An all-zero ``TraceBuffer`` with ``length`` rows."""
    return TraceBuffer(
        data=jnp.zeros((length, 8), jnp.int32),
        scores=jnp.zeros((length, 2), score_dtype),
        count=jnp.int32(0),
    )


class SimState(NamedTuple):
    """The lax.while_loop carry: complete simulation + evaluator state.

    Per-pod scheduling state (reference Pod.assigned_* + waiting-set
    membership + retry-mutated creation time, entities.py:42-43,
    main.py:43, event_simulator.py:56) lives as COLUMNS of one
    ``i32[P, 4]`` matrix so each event's read and write are single
    row-gather/row-scatter instructions — per-lane-indexed scatters under
    vmap cost serialized latency per INSTRUCTION on TPU (PROFILE.md), so
    four separate arrays cost 4x. Columns: (assigned_node, gpu bitmask
    bit-cast to i32, pod_ctime, waiting flag). Use the ``assigned_node``/
    ``assigned_gpus``/``pod_ctime``/``waiting`` properties to read."""

    heap: EventHeap
    # cluster (reference Node/GPU mutable fields)
    cpu_left: Any  # i32[N]
    mem_left: Any  # i32[N]
    gpu_left: Any  # i32[N]
    gpu_milli_left: Any  # i32[N, G]
    pod_state: Any  # i32[P, 4] (see class docstring)
    wait_hist: Any  # i32[M] histogram of gpu_milli of waiting GPU pods
    # evaluator accumulators (reference SchedulingEvaluator)
    events_processed: Any  # i32
    snap_idx: Any  # i32 number of snapshots taken
    snap_sums: Any  # f[4] summed cpu/mem/gpu-count/gpu-milli utilization
    frag_sum: Any  # f[] sum of fragmentation event scores
    frag_count: Any  # i32
    max_nodes: Any  # i32 peak active-node count (main.py:67-72)
    # control
    failed: Any  # bool: GPU allocation raised in the reference -> abort
    steps: Any  # i32
    violations: Any  # i32: invariant-audit failures (0 unless enabled)
    numeric_flags: Any  # i32 watchdog bitmask (0 unless SimConfig.watchdog)
    # TraceBuffer, or None unless SimConfig.decision_trace. None adds zero
    # pytree leaves, so the disabled path's carry structure — and therefore
    # the compiled program — is bit-identical to a build without tracing.
    trace: Any = None
    # bool[N] node availability (cordon bit), or None unless the workload
    # carries FaultEvents — same zero-leaf gating as ``trace``.
    node_avail: Any = None

    # pod_state column indices
    COL_NODE = 0
    COL_BITS = 1
    COL_CTIME = 2
    COL_WAIT = 3

    @property
    def assigned_node(self):  # i32[P], -1 = unassigned
        return self.pod_state[..., SimState.COL_NODE]

    @property
    def assigned_gpus(self):  # u32[P] bitmask over G
        return jax.lax.bitcast_convert_type(
            self.pod_state[..., SimState.COL_BITS], jnp.uint32)

    @property
    def pod_ctime(self):  # i32[P] creation_time (mutated on retry)
        return self.pod_state[..., SimState.COL_CTIME]

    @property
    def waiting(self):  # bool[P] waiting_pods membership (main.py:43)
        return self.pod_state[..., SimState.COL_WAIT] != 0


class FlatState(NamedTuple):
    """The flat engine's while_loop carry (fks_tpu.sim.flat): slot-per-pod
    event queue in tie-rank order + the SAME cluster/evaluator fields as
    SimState. Per-pod arrays are in SLOT (tie-rank) order; finalize
    un-permutes them back to input order.

    Dtype annotations below are the defaults. Under ``SimConfig.
    state_pack`` the ``aux`` / ``aux_gpus`` / ``wait_hist`` / ``gpu_left``
    / ``gpu_milli_left`` columns narrow to 16 bits when their full value
    range provably fits at the workload's shape (see
    ``flat._pack_dtypes``) — exact integer packing, never accumulators,
    so results are bit-identical; finalize widens everything back so
    SimResult dtypes are config-independent."""

    # event queue: one slot per pod, slots sorted by tie_rank
    ev_time: Any  # i32[Q]; INF = no pending event
    # per-pod scheduling state in ONE int32: -1 fresh CREATE pending,
    # -2 waiting (failed at least once), >= 0 placed: (node << G)|gpu_bits
    # when packable, else the node index with bits in aux_gpus
    aux: Any  # i32[Q]
    aux_gpus: Any  # u32[Q] gpu bitmask, or None when packed into aux
    pending: Any  # i32 live-slot count (loop-cond scalar)
    # cluster state (as SimState)
    cpu_left: Any
    mem_left: Any
    gpu_left: Any
    gpu_milli_left: Any
    pod_ctime: Any  # i32[Q] creation time, retry-mutated (slot order)
    wait_hist: Any
    # evaluator accumulators (as SimState)
    events_processed: Any
    snap_idx: Any
    snap_sums: Any
    frag_sum: Any
    frag_count: Any
    max_nodes: Any
    failed: Any
    steps: Any
    violations: Any
    numeric_flags: Any  # i32 watchdog bitmask (0 unless SimConfig.watchdog)
    trace: Any = None  # TraceBuffer or None (see SimState.trace)
    # fault-event queue (None unless the workload carries FaultEvents):
    # per-event times, INF once consumed; and the cordon bit per node.
    fault_time: Any = None  # i32[F]
    node_avail: Any = None  # bool[N]


class SimResult(NamedTuple):
    """Final observables; superset of reference EvaluationResults
    (evaluator.py:16-25) + policy score + run metadata."""

    policy_score: Any
    avg_cpu_utilization: Any
    avg_memory_utilization: Any
    avg_gpu_count_utilization: Any
    avg_gpu_memory_utilization: Any
    gpu_fragmentation_score: Any
    num_snapshots: Any
    num_fragmentation_events: Any
    events_processed: Any
    scheduled_pods: Any
    max_nodes: Any
    assigned_node: Any  # i32[P]
    assigned_gpus: Any  # u32[P] bitmask
    pod_ctime: Any  # i32[P] final (retry-mutated) creation times
    cpu_left: Any  # i32[N] final node state
    mem_left: Any
    gpu_left: Any
    gpu_milli_left: Any  # i32[N, G]
    failed: Any  # bool
    truncated: Any  # bool: hit max_steps with events remaining
    invariant_violations: Any  # i32 (0 unless validate_invariants)
    # i32 watchdog bitmask (sim.guards.FLAG_*; 0 unless SimConfig.watchdog):
    # sticky OR of per-step policy-score violations + final fitness check
    numeric_flags: Any
    # decision TraceBuffer, or None unless SimConfig.decision_trace
    # (fks_tpu.obs.tracing extracts/aligns it)
    trace: Any = None
