"""Simulation engines.

Two engines share one semantics module (evaluator arithmetic, finalize,
SimConfig) and one policy interface (PodView/NodeView):

- ``exact`` (fks_tpu.sim.engine): replicates the reference bit-for-bit,
  including its heap-layout-dependent retry rule — the parity/golden path;
- ``flat`` (fks_tpu.sim.flat): the TPU throughput engine (slot-per-pod
  event queue; documented retry-rule divergence, see its module docstring
  and PROFILE.md).

``get_engine(name)`` is the single dispatch point — every caller that
offers an engine choice (population eval, mesh eval, code backend, CLI)
resolves the name here, so adding an engine is a one-place change.
"""


def get_engine(name: str):
    """Engine module for ``name`` ("exact" | "flat"). Both modules expose
    the same surface: initial_state, build_step, lane_active, finalize,
    make_run_fn, make_param_run_fn, make_population_run_fn, simulate."""
    if name == "exact":
        from fks_tpu.sim import engine
        return engine
    if name == "flat":
        from fks_tpu.sim import flat
        return flat
    if name == "fused":
        raise ValueError(
            "the fused Pallas kernel is not a general engine module — it "
            "hard-wires the parametric policy and has no single-policy "
            "surface. Use parallel.make_population_eval(engine='fused') "
            "(or fks_tpu.sim.fused directly) for parametric populations; "
            "'exact'/'flat' elsewhere.")
    raise ValueError(f"unknown engine {name!r}; expected 'exact' or 'flat'")
