"""Evaluator: utilization snapshots, fragmentation, scalar fitness.

TPU-native re-design of the reference ``SchedulingEvaluator``
(reference: simulator/evaluator.py:27-163). Instead of appending snapshot
objects, the simulation carries running sums; instead of float threshold
arithmetic on device, snapshot trigger points are precomputed on host as an
integer table, reproducing the reference's float64 semantics EXACTLY:

The reference fires a snapshot when ``events_processed / total_events >=
next_threshold`` where ``next_threshold`` is 0.05 accumulated by repeated
float64 addition (evaluator.py:60-67) -- and keeps firing past 100% because
every processed event (deletions and retried creations included) increments
the counter while ``total_events`` is the initial pod count
(main.py:46-48,63-65). ``snapshot_trigger_table`` computes, for each
snapshot ordinal m, the smallest integer event count k with
``float64(k / total) >= t_m``; on device the check is then just
``events_processed >= table[snap_idx]``.
"""
from __future__ import annotations

import numpy as np


def snapshot_trigger_table(total_events: int, max_snapshots: int,
                           interval: float = 0.05) -> np.ndarray:
    """int32[max_snapshots] event-count trigger points (see module doc)."""
    table = np.zeros(max_snapshots, dtype=np.int64)
    threshold = interval  # float64 accumulation, as the reference does
    for m in range(max_snapshots):
        if total_events > 0:
            k = int(np.ceil(threshold * total_events))
            k = max(k, 0)
            # correct for float64 rounding of k / total on either side
            while k > 0 and (k - 1) / total_events >= threshold:
                k -= 1
            while k / total_events < threshold:
                k += 1
        else:
            k = np.iinfo(np.int32).max  # progress pinned to 0 -> never fires
        table[m] = min(k, np.iinfo(np.int32).max)
        threshold += interval
    return table.astype(np.int32)


def max_snapshot_count(max_steps: int, total_events: int,
                       interval: float = 0.05) -> int:
    """Upper bound on snapshots a run of <= max_steps events can take."""
    if total_events <= 0:
        return 1
    return int(np.ceil(max_steps / (interval * total_events))) + 2
