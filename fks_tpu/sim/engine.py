"""The jit-compiled discrete-event simulation engine.

TPU-native re-design of the reference's Python event loop
(reference: simulator/main.py:28-199 ``KubernetesSimulator`` +
simulator/event_simulator.py ``DiscreteEventSimulator``): one
``lax.while_loop`` whose body pops the next event from the exact on-device
heap replica, applies the deletion-refund or creation-placement rule
branchlessly, and folds the evaluator into the carry. Everything is fixed
shape; the only data-dependent quantity is the trip count (== number of
events processed, capped by ``max_steps``).

Semantics replicated exactly (SURVEY.md §2 fine print):
- strict-argmax placement with ``> 0`` gate, ties to the lowest node index
  (main.py:104-111; node axis order == CSV order)
- best-fit GPU sub-allocation, stable (milli, index) order (main.py:150-177)
- retry re-push at (first DELETION in raw heap-array order).time + 1,
  silently dropping the pod when no deletion exists (event_simulator.py:51-58)
- pod.creation_time mutated on retry, so a delayed pod keeps its full
  duration (event_simulator.py:45-58)
- snapshot overshoot past 100% progress (see fks_tpu.sim.evaluator)
- fragmentation event on every failed creation, scored over waiting GPU
  pods' minimum gpu_milli (evaluator.py:69-75,144-163)
- GPU-allocation shortfall aborts the run (reference raises ValueError,
  main.py:164-165 -> caller maps to score 0, funsearch_integration.py:63-64)

The policy is a vectorized ``PolicyFn`` scoring all nodes at once; the
population axis is added OUTSIDE via ``vmap`` (see fks_tpu.parallel).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload
from fks_tpu.ops.allocator import best_fit_gpus, first_fit_gpus
from fks_tpu.ops.heap import (
    KIND_CREATE, KIND_DELETE, KIND_NODE_DOWN, KIND_NODE_UP, EventHeap,
    first_deletion_in_array_order, heap_from_events, heap_pop, heap_push,
)
from fks_tpu.sim.evaluator import max_snapshot_count, snapshot_trigger_table
from fks_tpu.sim.guards import fitness_flags, guard_scores
from fks_tpu.sim.types import (
    TRACE_CREATE, TRACE_DELETE, TRACE_NODE_DOWN, TRACE_NODE_UP, TRACE_RETRY,
    NodeView, PodView, PolicyFn, SimResult, SimState, TraceBuffer, empty_trace,
)


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static simulation knobs (constructor args in the reference:
    main.py:29-48, evaluator.py:30)."""

    max_steps_factor: int = 8  # runaway guard: max events = factor * num_pods
    max_steps: Optional[int] = None  # overrides the factor when set
    snapshot_interval: float = 0.05
    gpu_allocator: str = "best_fit"  # or "first_fit" (main.py:133-134)
    score_dtype: Any = jnp.float32  # evaluator accumulation dtype
    validate_invariants: bool = False  # reference main.py:201-272 (opt-in)
    # wait-histogram width override (buckets = gpu_milli values of waiting
    # GPU pods; must exceed the trace's max gpu_milli). Set it when batching
    # traces whose derived sizes differ so the stacked states share a shape.
    wait_hist_size: Optional[int] = None
    # skip the policy on non-creation events via lax.cond. A win when the
    # policy is expensive (the funsearch VM interpreter) AND the loop runs
    # unbatched — under vmap, cond degenerates to executing both branches,
    # so batched paths should keep this off.
    cond_policy: bool = False
    # maintain SimResult.pod_ctime (retry-mutated creation times, reference
    # event_simulator.py:56). Pure bookkeeping — nothing downstream of the
    # simulation reads it — but in the flat engine the write is a full
    # [P]-wide blend per event, so throughput-only paths (bench, population
    # fitness) turn it off. When off, SimResult.pod_ctime holds the
    # original creation times. The exact engine always tracks (its scatter
    # write is not on the critical path).
    track_ctime: bool = True
    # numerics watchdog (sim.guards): flag NaN/Inf policy scores into the
    # carry (masking them to "refuse") and audit the final fitness for
    # NaN/Inf/out-of-[0,1]. Python-static, so the disabled path compiles
    # to the exact same program as a build without guards.
    watchdog: bool = False
    # decision-trace instrument (fks_tpu.obs.tracing): log one row per
    # processed event — kind (CREATE/DELETE/RETRY), pod id, chosen node,
    # winning score + second-best margin, pending count, post-step free
    # aggregates — into a bounded TraceBuffer carried in the engine state.
    # Python-static like ``watchdog``: disabled, the state's trace field is
    # None (zero pytree leaves) and the compiled program is identical.
    decision_trace: bool = False
    trace_len: Optional[int] = None  # trace rows; default resolve_max_steps
    # probe scoring (fks_tpu.funsearch.budget): score a truncated prefix.
    # The normal gate zeroes any run that still has pending events or
    # unassigned pods — correct for full evaluations, useless for a budget
    # probe that deliberately stops at ``probe_steps``. With probe_score
    # the fitness is the utilization integral over the consumed prefix
    # (still zeroed on failure / zero snapshots), and SimResult.truncated
    # keeps reporting the truth. Python-static like ``watchdog``: the
    # default-off path selects the same jnp.where gate expression as
    # before, compiling the identical program.
    probe_score: bool = False
    # large-cluster scale tier (README "Large-cluster scale tier"): top-k
    # candidate-node prefiltering. 0 (the default) sweeps every node per
    # event exactly as before — Python-static like ``watchdog``, so the
    # disabled path compiles the bit-identical program. k > 0 ranks nodes
    # by a cheap static feasibility score (free CPU/mem/GPU fit under the
    # cordon mask, ties to the LOWEST node index — dense argmax's tie
    # rule), gathers the top k into a [k, ...] NodeView, runs the policy
    # on that view only, and maps the winner back to the global node
    # index. Exact (placement-sequence-preserving) for policies that
    # refuse infeasible nodes and prefer lower indices among equal scores
    # (first_fit and every zoo/parametric feasibility-gated policy on its
    # preferred node); for other policies the winner is the argmax over
    # the candidate set, so fitness parity vs the dense sweep must be
    # validated per policy (tests/test_scale_tier.py). k >= n_padded
    # falls back to the dense sweep (a full gather is strictly slower).
    node_prefilter_k: int = 0
    # packed state dtypes (flat engine only; the exact engine ignores the
    # flag). True narrows FlatState columns whose full value range is
    # exactly representable at this workload's shape — gpu_milli_left /
    # gpu_left / wait_hist / aux to int16, aux_gpus to uint16 — halving
    # the while_loop carry bandwidth for those columns with ZERO fitness
    # drift (integer packing is exact; columns whose range cannot be
    # proven at this shape stay int32, so the knob degrades to a no-op
    # rather than wrapping). bfloat16 accumulators were REJECTED by the
    # parity sweep (PROFILE.md round 11: ~1e-3 fitness drift vs the 1e-5
    # bar), so snap_sums/frag_sum stay at ``score_dtype``. Python-static:
    # the default-off path compiles the bit-identical program.
    state_pack: bool = False

    def resolve_prefilter_k(self, n_padded: int) -> int:
        """Static candidate count for top-k node prefiltering: 0 means
        dense sweep. Values >= n_padded fall back to 0 (gathering every
        node in rank order is strictly slower than the dense sweep and
        would perturb argmax tie-breaks for nothing)."""
        k = self.node_prefilter_k
        if k < 0:
            raise ValueError(
                f"node_prefilter_k must be >= 0 (0 disables prefiltering), "
                f"got {k}")
        return k if 0 < k < n_padded else 0

    def resolve_max_steps(self, num_pods: int) -> int:
        if self.max_steps is not None:
            return self.max_steps
        return max(64, self.max_steps_factor * num_pods)

    def resolve_trace_len(self, num_pods: int) -> int:
        if self.trace_len is not None:
            return self.trace_len
        return self.resolve_max_steps(num_pods)


def initial_state(workload: Workload, cfg: SimConfig) -> SimState:
    """Build the t=0 carry. Host-side; the initial heap layout is produced
    by real CPython heapq so it matches the reference bit-for-bit."""
    c, p = workload.cluster, workload.pods
    n_real = p.num_pods
    pm = np.asarray(p.pod_mask)
    times = np.asarray(p.creation_time)[pm]
    ranks = np.asarray(p.tie_rank)[pm]
    kinds = np.zeros(n_real, np.int32)
    payload = np.nonzero(pm)[0].astype(np.int32)
    capacity = p.p_padded
    fe = workload.faults
    if fe is not None:
        # Fault events ride the same heap: payload column = node index,
        # rank = (row index - F_pad) < 0, so at equal time every fault
        # sorts BEFORE every pod event (tie_rank >= 0) and faults among
        # themselves keep array order — the flat engine's argmin-first-
        # index arbitration reproduces both orderings exactly.
        fm = np.asarray(fe.mask)
        fpad = int(fm.shape[0])
        times = np.concatenate([times, np.asarray(fe.time)[fm]])
        ranks = np.concatenate(
            [ranks, np.nonzero(fm)[0].astype(np.int32) - fpad])
        kinds = np.concatenate([kinds, np.asarray(fe.kind)[fm]])
        payload = np.concatenate([payload, np.asarray(fe.node)[fm]])
        capacity = p.p_padded + fpad
    heap = heap_from_events(times, ranks, kinds, payload, capacity=capacity)
    n, g, pp = c.n_padded, c.g_padded, p.p_padded
    max_milli = int(np.asarray(p.gpu_milli).max(initial=0))
    hist_size = (cfg.wait_hist_size if cfg.wait_hist_size is not None
                 else max(1001, max_milli + 2))
    if hist_size <= max_milli:
        raise ValueError(
            f"wait_hist_size {hist_size} <= trace max gpu_milli; "
            "fragmentation min_needed would be miscounted")
    f = cfg.score_dtype
    pod_state = jnp.stack([
        jnp.full(pp, -1, jnp.int32),                     # assigned node
        jnp.zeros(pp, jnp.int32),                        # gpu bitmask
        jnp.asarray(p.creation_time, jnp.int32),         # pod_ctime
        jnp.zeros(pp, jnp.int32),                        # waiting flag
    ], axis=-1)
    return SimState(
        heap=heap,
        cpu_left=jnp.asarray(c.cpu_total, jnp.int32),
        mem_left=jnp.asarray(c.mem_total, jnp.int32),
        gpu_left=jnp.asarray(c.gpu_declared, jnp.int32),
        gpu_milli_left=jnp.asarray(c.gpu_milli_total, jnp.int32),
        pod_state=pod_state,
        wait_hist=jnp.zeros(hist_size, jnp.int32),
        events_processed=jnp.int32(0),
        snap_idx=jnp.int32(0),
        snap_sums=jnp.zeros(4, f),
        frag_sum=jnp.asarray(0, f),
        frag_count=jnp.int32(0),
        max_nodes=jnp.int32(0),
        failed=jnp.bool_(False),
        steps=jnp.int32(0),
        violations=jnp.int32(0),
        numeric_flags=jnp.int32(0),
        trace=(empty_trace(cfg.resolve_trace_len(workload.num_pods), f)
               if cfg.decision_trace else None),
        node_avail=None if fe is None else jnp.ones(n, bool),
    )


def _widest_int():
    """Accumulation dtype for cluster-wide integer sums: int64 when x64 is
    enabled, else int32 (on by default on TPU, where 64-bit is emulated)."""
    return jnp.int64 if jax.config.jax_enable_x64 else jnp.int32


def _trace_append(trace: TraceBuffer, *, active, create, is_del, was_waiting,
                  pod, node, scores, winner, pending,
                  cpu_left, mem_left, gpu_left, gpu_milli_left,
                  fault_down=None, fault_up=None) -> TraceBuffer:
    """Append one decision row (see TraceBuffer column docs). Shared by the
    exact and flat engines so the recorded vocabulary cannot drift between
    them. Self-masking: an inactive step, or a full buffer, appends via an
    out-of-range index whose scatter drops. Deletes record score/margin 0
    (the step's score vector is undefined on non-creation events under
    ``cond_policy``), keeping row content engine-deterministic. Fault rows
    (``fault_down``/``fault_up`` predicates, fault-carrying workloads only)
    override the kind; their node column is the cordoned node and their
    score/margin are 0 like deletes."""
    tlen = trace.data.shape[0]
    kind = jnp.where(is_del, TRACE_DELETE,
                     jnp.where(was_waiting, TRACE_RETRY, TRACE_CREATE))
    if fault_down is not None:
        kind = jnp.where(fault_down, TRACE_NODE_DOWN,
                         jnp.where(fault_up, TRACE_NODE_UP, kind))
    wi = _widest_int()
    row = jnp.stack([
        kind.astype(jnp.int32), pod.astype(jnp.int32),
        node.astype(jnp.int32), pending.astype(jnp.int32),
        jnp.sum(cpu_left, dtype=wi).astype(jnp.int32),
        jnp.sum(mem_left, dtype=wi).astype(jnp.int32),
        jnp.sum(gpu_left, dtype=wi).astype(jnp.int32),
        jnp.sum(gpu_milli_left, dtype=wi).astype(jnp.int32),
    ])
    sdt = trace.scores.dtype
    win = scores[winner].astype(sdt)
    if scores.shape[0] > 1:
        others = jnp.where(jnp.arange(scores.shape[0]) == winner,
                           -jnp.inf, scores.astype(sdt))
        margin = win - jnp.max(others)
    else:
        margin = jnp.zeros_like(win)
    win = jnp.where(create, win, 0)
    margin = jnp.where(create, margin, 0)
    write = active & (trace.count < tlen)
    idx = jnp.where(write, trace.count, tlen)
    return TraceBuffer(
        data=trace.data.at[idx].set(row, mode="drop"),
        scores=trace.scores.at[idx].set(jnp.stack([win, margin]), mode="drop"),
        count=trace.count + write.astype(jnp.int32),
    )


def _node_view(c: ClusterArrays, cpu_left, mem_left, gpu_left, gpu_milli_left):
    return NodeView(
        cpu_milli_left=cpu_left, cpu_milli_total=c.cpu_total,
        memory_mib_left=mem_left, memory_mib_total=c.mem_total,
        gpu_left=gpu_left, num_gpus=c.num_gpus,
        gpu_milli_left=gpu_milli_left, gpu_milli_total=c.gpu_milli_total,
        gpu_mem_total=c.gpu_mem_total, gpu_mask=c.gpu_mask,
        node_mask=c.node_mask,
    )


def _prefilter_candidates(pod: PodView, nodes: NodeView, place_mask, k: int):
    """Top-k candidate nodes for one creation event (SimConfig
    ``node_prefilter_k``): rank every node by a cheap static feasibility
    test — the same free CPU/mem/GPU-count/GPU-milli fit the zoo policies
    gate on (fks_tpu.models.zoo.feasible_mask), under ``place_mask`` so a
    cordoned or padding node can NEVER enter a candidate slot — and keep
    the k best, i.e. the first k FEASIBLE nodes in ascending global
    index: argmax over the gathered view then preserves the dense sweep's
    lowest-index tie rule exactly. Selection is a cumsum + one-hot argmax
    (candidate slot j = first node whose running feasible-count is j),
    NOT ``jax.lax.top_k``: the rank order is already "feasible by
    ascending index", so a full selection sort buys nothing — and a
    vmapped top_k(1000, 64) measures ~1.2 ms/call on CPU, 4x an entire
    dense step — while the one-hot form is O(N*k) dense vectorized work
    and stays scatter-free (the TPU design rule every state write in this
    engine follows). When fewer than k nodes are feasible, the unmatched
    tail repeats the FIRST candidate, so whenever any feasible node
    exists every slot holds a feasible one (cordoned/padding nodes never
    enter the list) and duplicates tie in the winner argmax at the same
    global node. Only when NO node is feasible does the list degrade to
    node 0 — callers re-mask through the gather (``place_mask[cand]``
    with the ``> 0`` placement gate), so that event fails exactly like
    the dense sweep. Returns i32[k] global node indices."""
    eligible = jnp.sum(
        (nodes.gpu_mask & (nodes.gpu_milli_left >= pod.gpu_milli)
         ).astype(jnp.int32), axis=1)
    gpu_ok = jnp.where(pod.num_gpu > 0, eligible >= pod.num_gpu, True)
    feasible = (place_mask
                & (pod.cpu_milli <= nodes.cpu_milli_left)
                & (pod.memory_mib <= nodes.memory_mib_left)
                & (pod.num_gpu <= nodes.gpu_left) & gpu_ok)
    # slot of node i among feasibles = #feasible before it; infeasible
    # nodes get an out-of-range slot so they match no candidate column
    slot = jnp.where(feasible,
                     jnp.cumsum(feasible.astype(jnp.int32)) - 1,
                     jnp.int32(-1))
    k_iota = jnp.arange(k, dtype=jnp.int32)
    onehot = slot[:, None] == k_iota[None, :]
    cand = jnp.argmax(onehot, axis=0).astype(jnp.int32)
    return jnp.where(k_iota < jnp.sum(feasible.astype(jnp.int32)),
                     cand, cand[0])


def _gather_node_view(nodes: NodeView, cand) -> NodeView:
    """The [k, ...] candidate view: every NodeView leaf gathered along the
    node axis (leaves are [N] or [N, G]; a row gather covers both)."""
    return NodeView(*(leaf[cand] for leaf in nodes))


def lane_active(s: SimState, max_steps: int):
    """THE termination predicate: a lane keeps stepping while events remain,
    no GPU-allocation abort happened, and the runaway guard holds. Single
    source of truth for both the step's self-masking and every loop cond —
    if they ever diverged, a loop whose cond is any(lane_active) over
    no-op'ing lanes would spin forever."""
    return (s.heap.size > 0) & ~s.failed & (s.steps < max_steps)


def build_step(workload: Workload, policy: PolicyFn, cfg: SimConfig,
               ktable, max_steps: int) -> Callable[[SimState], SimState]:
    """One event: the body of the while_loop. See module docstring.

    ``workload`` arrays and ``ktable`` may be tracers (the multi-trace path
    passes them as jit/vmap arguments so one compiled program serves every
    same-shape trace); all totals are therefore computed with jnp ops, which
    XLA constant-folds when the workload is a compile-time constant.

    The step is *self-masking*: it computes its own ``active`` predicate
    (same condition as the loop guard) and becomes a no-op when inactive --
    every mutation is either a dropped scatter or a predicate-gated add.
    That lets the population layer run ONE ``while_loop`` whose body is the
    vmapped step and whose cond is ``any(active)``: finished lanes idle for
    O(log n) dropped scatters instead of the full-carry per-lane select that
    ``vmap(while_loop)`` would insert every iteration."""
    c, p = workload.cluster, workload.pods
    # device-resident copies (parser emits numpy; tracers can't index numpy)
    c = jax.tree_util.tree_map(jnp.asarray, c)
    p = jax.tree_util.tree_map(jnp.asarray, p)
    n, g = workload.cluster.n_padded, workload.cluster.g_padded
    f = cfg.score_dtype
    alloc = best_fit_gpus if cfg.gpu_allocator == "best_fit" else first_fit_gpus
    # cluster-wide capacity totals (reference: evaluator.py:35-38); padding
    # rows are zero so plain sums are exact
    total_cpu = jnp.sum(c.cpu_total)
    total_mem = jnp.sum(c.mem_total)
    total_gc = jnp.sum(c.num_gpus)
    total_gm = jnp.sum(c.gpu_milli_total)
    g_iota = jnp.arange(g, dtype=jnp.uint32)
    ktable = jnp.asarray(ktable, jnp.int32)
    klen = ktable.shape[0]
    # pod features packed into one gather table so reading the popped
    # pod's request costs a single row-gather (per-lane-indexed gathers
    # cost serialized latency per INSTRUCTION under vmap; PROFILE.md).
    # Padded 5 -> 8 columns: power-of-two rows keep the gather's slice
    # aligned to the TPU lane tiling (same layout as flat.py's table).
    feat = jnp.stack([p.cpu, p.mem, p.num_gpu, p.gpu_milli, p.duration,
                      jnp.zeros_like(p.cpu), jnp.zeros_like(p.cpu),
                      jnp.zeros_like(p.cpu)], axis=-1).astype(jnp.int32)
    # Python-static fault gating (like watchdog/decision_trace): fault-free
    # workloads compile to the exact pre-scenario program.
    has_faults = workload.faults is not None
    # large-cluster scale tier: 0 = dense sweep (bit-identical program)
    prefilter_k = cfg.resolve_prefilter_k(n)

    def step(s: SimState) -> SimState:
        active = lane_active(s, max_steps)
        h, (t, rk, kind, pod) = heap_pop(s.heap, pred=active)
        is_del = active & (kind == KIND_DELETE)
        if has_faults:
            # fault events (pod column = node index): flip the cordon bit,
            # touch nothing else. Every pod-event mutation below is gated
            # on is_del/create, so a fault step is a pure availability flip.
            fault_down = active & (kind == KIND_NODE_DOWN)
            fault_up = active & (kind == KIND_NODE_UP)
            is_fault = fault_down | fault_up
            create = active & (kind == KIND_CREATE)
        else:
            create = active & ~(kind == KIND_DELETE)

        pf = feat[pod]  # [8], one gather
        pcpu, pmem, pngpu, pmilli, pdur = pf[0], pf[1], pf[2], pf[3], pf[4]
        ps_row = s.pod_state[pod]  # [4], one gather
        held_node = ps_row[SimState.COL_NODE]
        bits = jax.lax.bitcast_convert_type(
            ps_row[SimState.COL_BITS], jnp.uint32)
        pod_ct = ps_row[SimState.COL_CTIME]
        was_waiting = ps_row[SimState.COL_WAIT] != 0

        # ---- DELETION: refund resources (reference main.py:74-99).
        # Dense one-hot adds over the tiny node axis, not scatters — TPU
        # scatters serialize per element (PROFILE.md).
        a = jnp.where(is_del, held_node, 0)
        di = is_del.astype(jnp.int32)
        n_iota = jnp.arange(n, dtype=jnp.int32)
        oh_a = (n_iota == a).astype(jnp.int32) * di  # [N]
        cpu_left = s.cpu_left + oh_a * pcpu
        mem_left = s.mem_left + oh_a * pmem
        gpu_left = s.gpu_left + oh_a * pngpu
        sel_bits = ((bits >> g_iota) & 1).astype(jnp.int32)  # [G]
        gpu_milli_left = s.gpu_milli_left + oh_a[:, None] * pmilli * sel_bits[None, :]

        # ---- FAULT: cordon/uncordon via one dense one-hot blend
        node_avail = s.node_avail
        if has_faults:
            oh_f = n_iota == jnp.where(is_fault, pod, jnp.int32(n))
            node_avail = jnp.where(oh_f, fault_up, node_avail)

        # ---- CREATION: score every node, strict argmax (main.py:101-111)
        pod_view = PodView(pcpu, pmem, pngpu, pmilli, pod_ct, pdur)
        node_view = _node_view(c, cpu_left, mem_left, gpu_left, gpu_milli_left)
        if prefilter_k:
            # a cordoned (downed) node scores 0 until NODE_UP — under the
            # prefilter it must also never outrank a feasible candidate,
            # so the cordon mask feeds the ranking itself
            place_mask = c.node_mask & node_avail if has_faults else c.node_mask
            cand = _prefilter_candidates(
                pod_view, node_view, place_mask, prefilter_k)
            node_view = _gather_node_view(node_view, cand)
        if cfg.cond_policy:
            out = jax.eval_shape(policy, pod_view, node_view)
            raw_scores = jax.lax.cond(
                create, lambda: jnp.asarray(policy(pod_view, node_view)),
                lambda: jnp.zeros(out.shape, out.dtype))
        else:
            raw_scores = policy(pod_view, node_view)
        raw_scores, numeric_flags = guard_scores(
            raw_scores, create, s.numeric_flags, enabled=cfg.watchdog)
        if prefilter_k:
            # re-mask through the gather: when fewer than k nodes are
            # feasible the candidate tail is padding (cordoned nodes
            # included) — zero those slots whatever the policy scored
            scores = jnp.where(place_mask[cand], raw_scores, 0)
        else:
            # a cordoned (downed) node scores 0 — "cannot/refuse" — until NODE_UP
            place_mask = c.node_mask & node_avail if has_faults else c.node_mask
            scores = jnp.where(place_mask, raw_scores, 0)
        # wk indexes the scored view ([k] candidates or [N] nodes);
        # b is always the GLOBAL node index (gather-back through cand)
        wk = jnp.argmax(scores).astype(jnp.int32)
        b = cand[wk] if prefilter_k else wk
        placed = create & (scores[wk] > 0)

        # GPU sub-allocation on the winner (main.py:125-145)
        sel, ok = alloc(gpu_milli_left[b], c.gpu_mask[b], pmilli, pngpu)
        alloc_fail = placed & (pngpu > 0) & ~ok  # reference raises here
        pl = placed & ~alloc_fail
        pli = pl.astype(jnp.int32)
        oh_b = (n_iota == b).astype(jnp.int32) * pli  # [N]
        cpu_left = cpu_left - oh_b * pcpu
        mem_left = mem_left - oh_b * pmem
        gpu_left = gpu_left - oh_b * pngpu
        gpu_milli_left = gpu_milli_left - (
            oh_b[:, None] * pmilli * sel.astype(jnp.int32)[None, :])

        new_bits = jnp.sum(jnp.where(sel, jnp.uint32(1) << g_iota, jnp.uint32(0)),
                           dtype=jnp.uint32)

        # ---- failed creation: waiting set + fragmentation + retry
        # (main.py:113-123, evaluator.py:69-75,144-163, event_simulator.py:51-58)
        failp = create & ~placed
        bucket = jnp.clip(pmilli, 0, s.wait_hist.shape[0] - 1)
        hdelta = ((failp & ~was_waiting & (pngpu > 0)).astype(jnp.int32)
                  - (pl & was_waiting & (pngpu > 0)).astype(jnp.int32))
        # dense one-hot blend over the small histogram axis, not a scatter
        h_iota = jnp.arange(s.wait_hist.shape[0], dtype=jnp.int32)
        hist = s.wait_hist + (h_iota == bucket).astype(jnp.int32) * hdelta

        hvals = hist > 0
        has_gpu_waiting = jnp.any(hvals)
        min_needed = jnp.argmax(hvals).astype(jnp.int32)  # first nonzero bucket
        frag_free = jnp.where(
            c.gpu_mask & (gpu_milli_left > 0) & (gpu_milli_left < min_needed),
            gpu_milli_left, 0)
        frag_score = jnp.where(
            has_gpu_waiting & (total_gm > 0),
            jnp.sum(frag_free, dtype=_widest_int()).astype(f)
            / jnp.maximum(total_gm, 1).astype(f),
            jnp.asarray(0, f))
        frag_sum = s.frag_sum + jnp.where(failp, frag_score, 0)
        frag_count = s.frag_count + failp.astype(jnp.int32)

        found, dt = first_deletion_in_array_order(h)
        retry = failp & found
        rt = dt + 1
        # ONE merged push serves both outcomes — they are mutually
        # exclusive (pl => placed; retry => not placed): DELETE at t+dur
        # when placed, retried CREATE at rt on a failed placement with a
        # pending deletion. Scanning ``h`` (the post-pop heap) is exactly
        # the reference's scan point: when its repush scans, no DELETE
        # was pushed for this event (the pod was not placed), so the
        # pre-delete-push and post-delete-push heaps are identical.
        heap3 = heap_push(
            h, jnp.where(pl, t + pdur, rt), rk,
            jnp.where(pl, KIND_DELETE, KIND_CREATE), pod, pred=pl | retry)

        # ---- pod bookkeeping: ONE row scatter updates assignment, GPU
        # bits, retry-mutated creation time, and waiting-set membership
        new_row = jnp.stack([
            jnp.where(pl, b, held_node),
            jax.lax.bitcast_convert_type(
                jnp.where(pl, new_bits, bits), jnp.int32),
            jnp.where(retry, rt, pod_ct),
            ((was_waiting | failp) & ~pl).astype(jnp.int32)])
        pod_state = s.pod_state.at[pod].set(new_row)

        # ---- evaluator bookkeeping (main.py:63-72, evaluator.py:55-67).
        # On alloc_fail the reference raises BEFORE record_event_processed.
        # Fault events are control events, not scheduling events: they are
        # excluded from events_processed (snapshot cadence), max_nodes, and
        # the trace-step 'valid' accounting in BOTH engines.
        valid = active & ~alloc_fail
        if has_faults:
            valid = valid & ~is_fault
        events = s.events_processed + valid.astype(jnp.int32)
        fire = valid & (s.snap_idx < klen) & (
            events >= ktable[jnp.minimum(s.snap_idx, klen - 1)])
        used = jnp.stack([
            (total_cpu - jnp.sum(cpu_left)).astype(f),
            (total_mem - jnp.sum(mem_left)).astype(f),
            jnp.sum(c.num_gpus - gpu_left).astype(f),
            (total_gm - jnp.sum(gpu_milli_left)).astype(f),
        ])
        totals_vec = jnp.stack([total_cpu, total_mem, total_gc, total_gm])
        denom = jnp.maximum(totals_vec, 1).astype(f)
        utils = jnp.where(totals_vec <= 0, 0, used / denom)
        snap_sums = s.snap_sums + jnp.where(fire, utils, 0)
        snap_idx = s.snap_idx + fire.astype(jnp.int32)

        active_nodes = jnp.sum((c.node_mask & (
            (cpu_left < c.cpu_total) | (mem_left < c.mem_total)
            | (gpu_left < c.num_gpus))), dtype=jnp.int32)
        max_nodes = jnp.maximum(s.max_nodes, jnp.where(valid, active_nodes, 0))

        violations = s.violations
        if cfg.validate_invariants:
            hi = jnp.arange(heap3.pod.shape[0])
            pend_del = (hi < heap3.size) & (heap3.kind == KIND_DELETE)
            active_pods = jnp.zeros(
                pod_state.shape[0], bool).at[heap3.pod].max(pend_del)
            violations = violations + active.astype(jnp.int32) * _audit(
                c, p, active_pods, cpu_left, mem_left, gpu_left,
                gpu_milli_left, pod_state[:, SimState.COL_NODE],
                jax.lax.bitcast_convert_type(
                    pod_state[:, SimState.COL_BITS], jnp.uint32))

        trace = s.trace
        if cfg.decision_trace:
            tpod = pod
            tnode = jnp.where(is_del, held_node, jnp.where(pl, b, -1))
            fault_kw = {}
            if has_faults:
                tpod = jnp.where(is_fault, -1, tpod)
                tnode = jnp.where(is_fault, pod, tnode)
                fault_kw = dict(fault_down=fault_down, fault_up=fault_up)
            # winner indexes the scored view (local top-k slot when
            # prefiltered); tnode above already carries the GLOBAL index b
            trace = _trace_append(
                trace, active=active, create=create, is_del=is_del,
                was_waiting=was_waiting, pod=tpod, node=tnode,
                scores=scores, winner=wk, pending=heap3.size,
                cpu_left=cpu_left, mem_left=mem_left, gpu_left=gpu_left,
                gpu_milli_left=gpu_milli_left, **fault_kw)

        return SimState(
            heap=heap3, cpu_left=cpu_left, mem_left=mem_left,
            gpu_left=gpu_left, gpu_milli_left=gpu_milli_left,
            pod_state=pod_state, wait_hist=hist,
            events_processed=events, snap_idx=snap_idx, snap_sums=snap_sums,
            frag_sum=frag_sum, frag_count=frag_count, max_nodes=max_nodes,
            failed=s.failed | alloc_fail, steps=s.steps + active.astype(jnp.int32),
            violations=violations, numeric_flags=numeric_flags,
            trace=trace, node_avail=node_avail,
        )

    return step


def _audit(c: ClusterArrays, p: PodArrays, active_pods, cpu_left, mem_left,
           gpu_left, gpu_milli_left, assigned_node, assigned_gpus):
    """Opt-in full-state audit after every event — the reference's
    invariant checker semantics (reference: simulator/main.py:201-272):
    non-negative remnants, remnant <= total, and conservation
    (used == total - remaining) at node and per-GPU granularity,
    cross-checked against ``active_pods`` — the engine's "DELETE still
    pending" mask (heap-derived here, slot-derived in the flat engine).
    Returns i32 1 if any invariant fails at this step.

    The reference raises on first violation; a jitted loop cannot, so
    violations are counted into the carry instead (checkify-style)."""
    n, g = c.gpu_mask.shape
    pp = assigned_node.shape[0]

    nm = c.node_mask
    neg = (jnp.any(nm & (cpu_left < 0)) | jnp.any(nm & (mem_left < 0))
           | jnp.any(nm & (gpu_left < 0))
           | jnp.any(c.gpu_mask & (gpu_milli_left < 0)))
    over = (jnp.any(nm & (cpu_left > c.cpu_total))
            | jnp.any(nm & (mem_left > c.mem_total))
            | jnp.any(nm & (gpu_left > c.gpu_declared))
            | jnp.any(c.gpu_mask & (gpu_milli_left > c.gpu_milli_total)))

    active = active_pods & (assigned_node >= 0)
    seg = jnp.clip(assigned_node, 0, n - 1)

    def used_by_node(req):
        return jax.ops.segment_sum(
            jnp.where(active, req, 0), seg, num_segments=n)

    cons = (jnp.any(nm & (c.cpu_total - cpu_left != used_by_node(p.cpu)))
            | jnp.any(nm & (c.mem_total - mem_left != used_by_node(p.mem)))
            | jnp.any(nm & (c.gpu_declared - gpu_left != used_by_node(p.num_gpu))))

    # per-GPU milli conservation: expand each active pod's GPU bitmask
    g_iota = jnp.arange(g, dtype=jnp.uint32)
    bits = ((assigned_gpus[:, None] >> g_iota[None, :]) & 1).astype(jnp.int32)
    contrib = jnp.where(active[:, None], bits * p.gpu_milli[:, None], 0)  # [P,G]
    used_milli = jax.ops.segment_sum(contrib, seg, num_segments=n)  # [N,G]
    cons_g = jnp.any(c.gpu_mask & (c.gpu_milli_total - gpu_milli_left != used_milli))

    return (neg | over | cons | cons_g).astype(jnp.int32)


def _gpu_count_used(c: ClusterArrays, gpu_left):
    return jnp.sum(c.num_gpus - gpu_left)


def finalize_fields(workload: Workload, cfg: SimConfig, *, pending, s) -> SimResult:
    """Fitness + results (reference evaluator.py:77-127) from any engine
    state carrying the shared evaluator fields. ``pending`` is that
    engine's "events remain unprocessed" predicate (the exact engine's
    heap size, the flat engine's live-slot test) — sharing everything else
    keeps the two engines' fitness semantics identical by construction."""
    p = workload.pods
    f = cfg.score_dtype
    pod_mask = jnp.asarray(p.pod_mask)
    n_snap = s.snap_idx
    denom = jnp.maximum(n_snap, 1).astype(f)
    avg = s.snap_sums / denom
    frag_mean = jnp.where(
        s.frag_count > 0, s.frag_sum / jnp.maximum(s.frag_count, 1).astype(f),
        jnp.asarray(0, f))
    all_assigned = jnp.all((s.assigned_node >= 0) | ~pod_mask)
    truncated = pending & ~s.failed
    overall = jnp.sum(avg) / 4
    raw = jnp.clip(overall - jnp.minimum(jnp.asarray(0.1, f), frag_mean), 0.0, 1.0)
    if cfg.probe_score:
        gate = (n_snap > 0) & ~s.failed
    else:
        gate = (n_snap > 0) & all_assigned & ~s.failed & ~truncated
    score = jnp.where(gate, raw, jnp.asarray(0, f))
    scheduled = jnp.sum((s.assigned_node >= 0) & pod_mask, dtype=jnp.int32)
    numeric_flags = s.numeric_flags
    if cfg.watchdog:
        numeric_flags = numeric_flags | fitness_flags(score)
    return SimResult(
        policy_score=score,
        avg_cpu_utilization=avg[0], avg_memory_utilization=avg[1],
        avg_gpu_count_utilization=avg[2], avg_gpu_memory_utilization=avg[3],
        gpu_fragmentation_score=frag_mean,
        num_snapshots=n_snap, num_fragmentation_events=s.frag_count,
        events_processed=s.events_processed, scheduled_pods=scheduled,
        max_nodes=s.max_nodes, assigned_node=s.assigned_node,
        assigned_gpus=s.assigned_gpus, pod_ctime=s.pod_ctime,
        cpu_left=s.cpu_left, mem_left=s.mem_left, gpu_left=s.gpu_left,
        gpu_milli_left=s.gpu_milli_left, failed=s.failed, truncated=truncated,
        invariant_violations=s.violations, numeric_flags=numeric_flags,
        trace=getattr(s, "trace", None),
    )


def finalize(workload: Workload, cfg: SimConfig, s: SimState) -> SimResult:
    """Fitness + results (reference evaluator.py:77-127)."""
    return finalize_fields(workload, cfg, pending=s.heap.size > 0, s=s)


def make_param_run_fn(workload: Workload, param_policy, cfg: SimConfig = SimConfig()):
    """Build ``run(params, state) -> SimResult`` for a parameterized policy
    ``(params, PodView, NodeView) -> i32[N]``.

    Single-lane loop assembly: ``loop_tables`` sizing + ``lane_active``
    cond + while_loop + finalize. Batched paths (population/trace-batch/
    mesh) share the same pieces via ``make_population_run_fn`` /
    ``run_batched_lanes``, so fitness semantics cannot diverge between
    them. ``params`` may be a tracer: the step closure is rebuilt under
    the caller's trace.
    """
    ktable, max_steps = loop_tables(workload, cfg)

    def cond(s: SimState):
        return lane_active(s, max_steps)

    def run(params, state: SimState) -> SimResult:
        step = build_step(
            workload, lambda pod, nodes: param_policy(params, pod, nodes),
            cfg, ktable, max_steps)
        final = jax.lax.while_loop(cond, step, state)
        return finalize(workload, cfg, final)

    return run


def loop_tables(workload: Workload, cfg: SimConfig):
    """(ktable, max_steps) for a workload — the static loop-sizing half of
    loop assembly, shared by every runner so snapshot semantics can't
    diverge between the plain, population, trace-batch, and mesh paths."""
    num_pods = workload.num_pods
    max_steps = cfg.resolve_max_steps(num_pods)
    ktable = snapshot_trigger_table(
        num_pods, max_snapshot_count(max_steps, num_pods, cfg.snapshot_interval),
        cfg.snapshot_interval)
    return ktable, max_steps


def broadcast_state(state0: SimState, lanes: int) -> SimState:
    """Broadcast one initial state to ``lanes`` identical device-resident
    copies (vs. the reference's per-subprocess re-parse + deepcopy,
    funsearch_integration.py:38-48)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(jnp.asarray(x), (lanes,) + jnp.shape(x)),
        state0)


def run_batched_lanes(vstep, bstate, max_steps: int, active_fn=None):
    """Drive any stack of self-masking lanes to completion.

    NOT ``vmap(while_loop)``: that would select the entire per-lane carry
    (queue arrays included) every iteration to freeze finished lanes.
    Instead the vmapped self-masking step runs INSIDE one ``while_loop``
    whose cond is "any lane active", so a finished lane costs only dropped
    writes. ``vstep`` must wrap an engine's ``build_step`` lanes (any
    nesting of vmaps); ``active_fn`` is that engine's ``lane_active`` —
    the EXACT predicate the step masks with (a cond/step divergence would
    spin forever). Defaults to this module's. The single shared scaffold
    for the population, flat-population, and multi-trace paths."""
    if active_fn is None:
        active_fn = lane_active
    return jax.lax.while_loop(
        lambda s: jnp.any(active_fn(s, max_steps)), vstep, bstate)


def make_population_run_fn(workload: Workload, param_policy,
                           cfg: SimConfig = SimConfig()):
    """Build ``run(params[C, ...], state0) -> SimResult`` batched over the
    candidate axis — the TPU-native replacement for the reference's
    per-candidate subprocess fan-out (funsearch_integration.py:535-562).
    Loop scaffold: ``run_batched_lanes`` over the vmapped self-masking step.
    """
    ktable, max_steps = loop_tables(workload, cfg)

    def run(params, state0: SimState) -> SimResult:
        pop = jax.tree_util.tree_leaves(params)[0].shape[0]

        def step_one(p, s):
            return build_step(
                workload, lambda pod, nodes: param_policy(p, pod, nodes),
                cfg, ktable, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(0, 0))
        final = run_batched_lanes(
            lambda s: vstep(params, s), broadcast_state(state0, pop), max_steps)
        return jax.vmap(lambda s: finalize(workload, cfg, s))(final)

    return run


def make_run_fn(workload: Workload, policy: PolicyFn,
                cfg: SimConfig = SimConfig()):
    """Build the jittable end-to-end run: initial state -> SimResult.

    The returned fn takes the initial SimState (so callers can vmap over
    batched states or donate buffers) and returns a SimResult.
    """
    run = make_param_run_fn(workload, lambda _p, pod, nodes: policy(pod, nodes), cfg)
    return functools.partial(run, None)


def simulate(workload: Workload, policy: PolicyFn,
             cfg: SimConfig = SimConfig(), jit: bool = True) -> SimResult:
    """Host convenience API: the reference's 'build simulator, run_schedule,
    get results' flow (main.py:29-72 + evaluator read-out) in one call."""
    run = make_run_fn(workload, policy, cfg)
    if jit:
        run = jax.jit(run)
    return run(initial_state(workload, cfg))


# ------------------------------------------------- prefilter auto-enable
#
# PR 7's measurement (PROFILE.md round 11): top-k node prefiltering pays
# 13-16x when the per-node policy is expensive (the VM code-candidate
# tier) and LOSES (~0.6x) when it is cheap (parametric dot products)
# because the step is then queue-dominated and the candidate gather is
# pure overhead. The break-even is a property of the policy's
# per-invocation cost, not of any static code attribute — so the
# auto-enable heuristic keys on a measured probe.

#: k chosen when the heuristic enables prefiltering (the PROFILE round-11
#: sweep's winning setting at 1k nodes)
PREFILTER_AUTO_K = 64
#: policy cost above which prefiltering wins. The round-11 data points on
#: flat CPU: parametric ~2e-5 s/invocation (prefilter loses), VM code
#: candidates ~1e-3 s (prefilter wins 13-16x); the threshold sits an
#: order of magnitude clear of both.
PREFILTER_COST_THRESHOLD_S = 2e-4
#: below this node count the dense sweep is cheap regardless of policy
#: cost and the gather bookkeeping cannot win it back
PREFILTER_MIN_NODES = 256
#: static per-node work bound (fks_tpu.analysis CostEstimate.work) below
#: which a policy is trivially cheap — a handful of fused elementwise ops
#: lands orders of magnitude under PREFILTER_COST_THRESHOLD_S, so the
#: timing probe (which costs a full XLA compile) can be skipped outright.
#: Template-derived code candidates (gpu loop + prologue) sit well above.
PREFILTER_WORK_HINT_MIN = 16


def probe_policy_cost(param_policy, params, n_padded: int, g_padded: int,
                      reps: int = 5) -> float:
    """Steady-state wall seconds of ONE policy invocation at the padded
    cluster shape: jit the bare policy on all-ones dummy views, discard
    the compile call, return the min over ``reps`` timed calls. Host-side
    and backend-agnostic; the one-time compile is the probe's only real
    cost (the timed calls are microseconds)."""
    import time as _time

    i = jnp.zeros((), jnp.int32)
    vn = jnp.ones(n_padded, jnp.int32)
    vg = jnp.ones((n_padded, g_padded), jnp.int32)
    pod = PodView(i, i, i, i, i, i)
    nodes = NodeView(vn, vn, vn, vn, vn, vn, vg, vg, vg,
                     jnp.ones((n_padded, g_padded), bool),
                     jnp.ones(n_padded, bool))
    fn = jax.jit(lambda p: param_policy(p, pod, nodes))
    jax.block_until_ready(fn(params))  # compile, excluded from timing
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = _time.perf_counter()
        jax.block_until_ready(fn(params))
        best = min(best, _time.perf_counter() - t0)
    return best


def auto_prefilter_k(n_padded: int, policy_cost_s: Optional[float], *,
                     override: Optional[int] = None,
                     k: int = PREFILTER_AUTO_K,
                     threshold_s: float = PREFILTER_COST_THRESHOLD_S,
                     min_nodes: int = PREFILTER_MIN_NODES) -> int:
    """Pick ``SimConfig.node_prefilter_k`` from a measured policy cost.

    Pure decision function (timing-free, unit-testable): an explicit
    ``override`` always wins; otherwise prefiltering turns on iff the
    node axis is large enough (``min_nodes``) AND one policy invocation
    costs more than ``threshold_s``. ``policy_cost_s`` of None reads as
    "unknown" and keeps the conservative dense sweep."""
    if override is not None:
        return int(override)
    if n_padded < min_nodes:
        return 0
    if policy_cost_s is None or policy_cost_s <= threshold_s:
        return 0
    return k


def resolve_auto_prefilter(param_policy, params, n_padded: int,
                           g_padded: int, *, override: Optional[int] = None,
                           recorder=None, work_hint: Optional[int] = None,
                           **heuristic_kw) -> int:
    """``auto_prefilter_k`` with the timing probe run only when its answer
    can matter: an explicit override or a small node axis skips the
    (compile-costing) probe entirely, and so does a static ``work_hint``
    (fks_tpu.analysis ``CostEstimate.work``) proving the policy trivially
    cheap — prefiltering never pays for cheap policies (PROFILE.md round
    11), so there is nothing to measure. Records a ``prefilter_auto``
    event on the given recorder so run dirs show why k was chosen."""
    if override is not None:
        return int(override)
    min_nodes = heuristic_kw.get("min_nodes", PREFILTER_MIN_NODES)
    if n_padded < min_nodes:
        return 0
    if work_hint is not None and work_hint < PREFILTER_WORK_HINT_MIN:
        if recorder is not None:
            recorder.event("prefilter_auto", policy_cost_s=None,
                           work_hint=int(work_hint), chosen_k=0,
                           n_padded=n_padded)
        return 0
    cost = probe_policy_cost(param_policy, params, n_padded, g_padded)
    chosen = auto_prefilter_k(n_padded, cost, **heuristic_kw)
    if recorder is not None:
        recorder.event("prefilter_auto", policy_cost_s=round(cost, 7),
                       chosen_k=chosen, n_padded=n_padded)
    return chosen
