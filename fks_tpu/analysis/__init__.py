"""Static analysis: candidate pre-flight (pillar A) + repo linter (pillar B).

``candidate`` is imported eagerly (pure stdlib + the funsearch tables);
``lint`` is NOT — it lowers jitted entry points and therefore pulls in
jax, which callers on the evolve hot path never need.
"""
from fks_tpu.analysis.candidate import (
    REJECT_TAXONOMY, CostEstimate, PreflightReport, fingerprint,
    preflight_check,
)

__all__ = [
    "REJECT_TAXONOMY", "CostEstimate", "PreflightReport", "fingerprint",
    "preflight_check",
]
