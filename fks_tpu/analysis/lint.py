"""Repo-wide JAX-invariant linter + jaxpr-fingerprint pinner.

Two gates, both wired into ``cli lint`` (and ``tools/fks_lint.py``):

**AST lints** (``lint_paths``) — stdlib-only static checks over the
repo's own sources for the trace-safety invariants the engine relies on.
The scope is deliberately *syntactic*: a function is "jitted" when its
decorator list contains ``jax.jit`` / ``jit`` / ``partial(jax.jit, ...)``
(the repo's only jit idioms), and only constructs that are wrong under
tracing in every context are flagged, so a clean repo stays clean without
per-site waivers:

- FKS101: a Python ``while`` loop inside a jitted function — its
  condition would be a traced value; use ``jax.lax.while_loop``.
- FKS102: a Python ``if`` whose test reads a *traced argument* of the
  jitted function (``static_argnums``/``static_argnames`` params are
  excluded). Closure reads of Python-static config are the sanctioned
  pattern and are not flagged.
- FKS103: ``.item()`` / ``.tolist()`` inside a jitted function — a
  device->host sync that fails under tracing.
- FKS104: a ``numpy`` call (via any imported alias) inside a jitted
  function — host arrays silently break tracing or constant-fold.
- FKS105: an attribute read of a ``SimConfig``-typed *argument* inside a
  jitted function. SimConfig knobs are Python-static by contract
  (engine.SimConfig docstrings); passing one as a traced jit argument
  would turn every flag read into FKS102. The static pattern — cfg
  captured by closure at build time — is untouched.
- FKS106: an AOT ``.lower(...).compile()`` call whose enclosing function
  never touches the footprint ledger (``record_footprint`` /
  ``footprint_of`` / ``memory_analysis``). Module-wide — not limited to
  decorator-jitted functions — because every cached executable claims
  device memory for its lifetime, and an unpriced one is invisible to
  ``cli mem`` and the memory budget gate.
- FKS107: a ``shard_map`` site (direct call or ``partial(shard_map,
  ...)`` decorator) whose enclosing function never touches the layout
  ledger (``record_layout`` / ``tag_layout`` / ``_resolve_layout`` / a
  ``layout_key``) and carries no ``layout-exempt`` docstring waiver —
  an untagged device schedule is invisible to ``cli layout`` and the
  layout explorer (mirrors FKS106's footprint-coverage rule).

**Jaxpr pins** (``compute_pins`` / ``check_pins`` / ``write_pins``) —
the dynamic half of the same contract. Every Python-static SimConfig
flag promises "the disabled path compiles the identical program"; the
pinner makes that falsifiable by lowering the key entry points (flat
step under each flag, the segmented population ``advance``, one serve
bucket) on the micro workload and hashing ``str(jax.make_jaxpr(...))``
into ``tests/fixtures/jaxpr_pins.json``. A refactor that silently
changes a lowered program — e.g. turning a static flag into a traced
read — shows up as pin drift and fails the gate; intentional program
changes re-pin with ``cli lint --write-pins``.

x64 is forced before lowering so the pins are stable across entry
points (tests/conftest.py runs the suite under x64; a subprocess ``cli
lint`` must hash the same programs).
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: the pinned-jaxpr manifest checked by ``cli lint`` and CI
PIN_MANIFEST = os.path.join(REPO_ROOT, "tests", "fixtures",
                            "jaxpr_pins.json")

LINT_CODES = {
    "FKS101": "python while loop inside a jitted function",
    "FKS102": "data-dependent if on a traced jit argument",
    "FKS103": "host sync (.item()/.tolist()) inside a jitted function",
    "FKS104": "numpy usage inside a jitted function",
    "FKS105": "SimConfig passed as a traced jit argument",
    "FKS106": "AOT .lower(...).compile() without a footprint record",
    "FKS107": "shard_map site without a layout key tag",
}

#: names whose presence in the enclosing function waives FKS106 — the
#: compile site is priced into the footprint ledger (fks_tpu.obs.memory)
_FOOTPRINT_MARKS = {"record_footprint", "footprint_of", "memory_analysis"}

#: names whose presence in the enclosing function waives FKS107 — the
#: shard_map site is attributed to a named layout in the layout ledger
#: (fks_tpu.obs.layout); ``layout-exempt`` in the enclosing function's
#: docstring waives intentionally untagged internals (a builder whose
#: caller tags the returned runner)
_LAYOUT_MARKS = {"record_layout", "tag_layout", "layout_key",
                 "_resolve_layout", "_layout_eval_wrapper"}
_LAYOUT_WAIVER = "layout-exempt"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit: machine fields plus the gcc-style rendering."""

    path: str
    line: int
    code: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ------------------------------------------------------------- AST lints


def _numpy_aliases(tree: ast.Module) -> Set[str]:
    """Names the module binds to the numpy package (``import numpy as
    np`` -> {"np"}). ``from numpy import x`` is not aliased to the
    package and is caught per-name only if the package itself is."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy" or a.name.startswith("numpy."):
                    out.add(a.asname or a.name.split(".")[0])
    return out


def _is_jit_expr(node: ast.expr) -> bool:
    """``jit`` / ``jax.jit`` as an expression."""
    if isinstance(node, ast.Name):
        return node.id == "jit"
    if isinstance(node, ast.Attribute):
        return (node.attr == "jit" and isinstance(node.value, ast.Name)
                and node.value.id == "jax")
    return False


def _jit_decorator(dec: ast.expr) -> Optional[ast.expr]:
    """The decorator expression when ``dec`` marks the function jitted:
    bare ``jax.jit``, a ``jax.jit(...)`` call, or ``partial(jax.jit,
    ...)``. Returns the *call* node (for static_arg* extraction) or the
    bare expression; None when not a jit decorator."""
    if _is_jit_expr(dec):
        return dec
    if isinstance(dec, ast.Call):
        if _is_jit_expr(dec.func):
            return dec
        fn = dec.func
        is_partial = (isinstance(fn, ast.Name) and fn.id == "partial") or (
            isinstance(fn, ast.Attribute) and fn.attr == "partial")
        if is_partial and dec.args and _is_jit_expr(dec.args[0]):
            return dec
    return None


def _static_params(dec: ast.expr, fn: ast.FunctionDef) -> Set[str]:
    """Parameter names excluded from tracing by ``static_argnums`` /
    ``static_argnames`` literals on the jit decorator call. Non-literal
    specs conservatively mark ALL params static (no false positives on
    code the linter cannot resolve)."""
    if not isinstance(dec, ast.Call):
        return set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    out: Set[str] = set()
    for kw in dec.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        try:
            spec = ast.literal_eval(kw.value)
        except ValueError:
            return set(params)
        items = spec if isinstance(spec, (tuple, list)) else (spec,)
        for it in items:
            if isinstance(it, str):
                out.add(it)
            elif isinstance(it, int) and 0 <= it < len(params):
                out.add(params[it])
    return out


def _param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    for extra in (args.vararg, args.kwarg):
        if extra is not None:
            names.append(extra.arg)
    return names


def _simconfig_params(fn: ast.FunctionDef) -> Set[str]:
    """Params annotated SimConfig (``cfg: SimConfig`` / ``sim.SimConfig``)."""
    out: Set[str] = set()
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        ann = a.annotation
        name = None
        if isinstance(ann, ast.Name):
            name = ann.id
        elif isinstance(ann, ast.Attribute):
            name = ann.attr
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            name = ann.value.rsplit(".", 1)[-1]
        if name == "SimConfig":
            out.add(a.arg)
    return out


def _reads(node: ast.AST, names: Set[str]) -> Optional[ast.Name]:
    """The first Name in ``node``'s subtree drawn from ``names``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return sub
    return None


def _lint_jitted(path: str, fn: ast.FunctionDef, np_aliases: Set[str],
                 traced: Set[str], simcfg: Set[str],
                 findings: List[Finding]) -> None:
    """All rule checks over one jitted function's body."""

    def hit(code: str, node: ast.AST, detail: str) -> None:
        findings.append(Finding(path, getattr(node, "lineno", fn.lineno),
                                code, f"{LINT_CODES[code]}: {detail}"))

    for scfg in sorted(simcfg & traced):
        hit("FKS105", fn,
            f"'{scfg}' in '{fn.name}' — SimConfig knobs are Python-static; "
            f"close over the config instead of tracing it")

    for node in ast.walk(fn):
        if isinstance(node, ast.While):
            hit("FKS101", node,
                f"in '{fn.name}' — use jax.lax.while_loop")
        elif isinstance(node, ast.If):
            read = _reads(node.test, traced)
            if read is not None:
                hit("FKS102", node,
                    f"'{read.id}' in '{fn.name}' — use jnp.where or "
                    f"jax.lax.cond")
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in ("item", "tolist"):
                hit("FKS103", node, f".{f.attr}() in '{fn.name}'")
            elif _reads(f, np_aliases) is not None:
                hit("FKS104", node,
                    f"in '{fn.name}' — use jnp (host numpy does not trace)")


def _compile_sites(tree: ast.Module) -> Iterable[ast.Call]:
    """``<expr>.lower(...).compile(...)`` chains — the AOT idiom whose
    executable claims device memory for its whole cache lifetime."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "compile"):
            inner = node.func.value
            if (isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "lower"):
                yield node


def _references_footprint(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and sub.id in _FOOTPRINT_MARKS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _FOOTPRINT_MARKS:
            return True
    return False


def _lint_compile_sites(path: str, tree: ast.Module,
                        findings: List[Finding]) -> None:
    """FKS106: every AOT ``.lower(...).compile()`` site must be priced
    into the footprint ledger — waived when the innermost enclosing
    function also references ``record_footprint`` / ``footprint_of`` /
    ``memory_analysis`` (it files or prices the executable itself).
    Unpriced executables are invisible to ``cli mem`` and the memory
    budget gate, which is exactly how an HBM regression hides."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for site in _compile_sites(tree):
        enclosing = None
        for fn in funcs:
            end = getattr(fn, "end_lineno", None) or fn.lineno
            if fn.lineno <= site.lineno <= end:
                # innermost wins: the latest-starting containing span
                if enclosing is None or fn.lineno > enclosing.lineno:
                    enclosing = fn
        if enclosing is not None and _references_footprint(enclosing):
            continue
        where = (f"in '{enclosing.name}'" if enclosing is not None
                 else "at module scope")
        findings.append(Finding(
            path, site.lineno, "FKS106",
            f"{LINT_CODES['FKS106']}: {where} — call "
            f"obs.memory.record_footprint on the compiled executable "
            f"(or price it via footprint_of/memory_analysis)"))


def _is_shard_map(expr: ast.expr) -> bool:
    return ((isinstance(expr, ast.Name) and expr.id == "shard_map")
            or (isinstance(expr, ast.Attribute)
                and expr.attr == "shard_map"))


def _shard_map_sites(tree: ast.Module) -> Iterable[ast.Call]:
    """Both shard_map idioms the repo uses: a direct ``shard_map(fn,
    mesh=...)`` call, and the ``functools.partial(shard_map, mesh=...)``
    decorator form."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        if _is_shard_map(node.func):
            yield node
        elif ((isinstance(node.func, ast.Name)
               and node.func.id == "partial")
              or (isinstance(node.func, ast.Attribute)
                  and node.func.attr == "partial")) \
                and node.args and _is_shard_map(node.args[0]):
            yield node


def _references_layout(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Name) and sub.id in _LAYOUT_MARKS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _LAYOUT_MARKS:
            return True
    return False


def _lint_shard_map_sites(path: str, tree: ast.Module,
                          findings: List[Finding]) -> None:
    """FKS107: every shard_map site must be attributed to a named layout
    — waived when the innermost enclosing function references the layout
    ledger (``record_layout`` / ``tag_layout`` / ``_resolve_layout`` /
    a ``layout_key``), or carries ``layout-exempt`` in its docstring
    (an internal builder whose CALLER tags the returned runner). An
    untagged site is a device schedule the layout explorer cannot see —
    exactly how a pad-waste or collective regression hides from
    ``cli layout``. The compat shim (``fks_tpu.utils.compat``) is not a
    site: it forwards to the underlying implementation by another name."""
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for site in _shard_map_sites(tree):
        enclosing = None
        for fn in funcs:
            end = getattr(fn, "end_lineno", None) or fn.lineno
            if fn.lineno <= site.lineno <= end:
                if enclosing is None or fn.lineno > enclosing.lineno:
                    enclosing = fn
        if enclosing is not None:
            if _references_layout(enclosing):
                continue
            doc = ast.get_docstring(enclosing) or ""
            if _LAYOUT_WAIVER in doc:
                continue
        where = (f"in '{enclosing.name}'" if enclosing is not None
                 else "at module scope")
        findings.append(Finding(
            path, site.lineno, "FKS107",
            f"{LINT_CODES['FKS107']}: {where} — resolve a LayoutSpec "
            f"(obs.layout) and tag_layout/record_layout the runner, or "
            f"mark the function '{_LAYOUT_WAIVER}' when its caller tags "
            f"the returned runner"))


def lint_source(path: str, source: str) -> List[Finding]:
    """Lint one module's source. Syntax errors surface as a finding (the
    gate must not crash on a broken tree mid-refactor)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, "FKS100",
                        f"syntax error: {e.msg}")]
    np_aliases = _numpy_aliases(tree)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            jd = _jit_decorator(dec)
            if jd is None:
                continue
            traced = set(_param_names(node)) - _static_params(jd, node)
            _lint_jitted(path, node, np_aliases, traced,
                         _simconfig_params(node), findings)
            break
    _lint_compile_sites(path, tree, findings)
    _lint_shard_map_sites(path, tree, findings)
    return findings


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    """Lint every ``*.py`` under the given files/directories, sorted by
    location. The default gate target is the package root."""
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        else:
            files.append(path)
    findings: List[Finding] = []
    for f in files:
        findings.extend(lint_source(str(f), f.read_text()))
    findings.sort(key=lambda x: (x.path, x.line, x.code))
    return findings


# ------------------------------------------------------------ jaxpr pins

#: SimConfig single-flag variants lowered for the flat step — one pin per
#: Python-static knob, so flipping any flag's implementation from static
#: to traced (or vice versa) moves at least one hash
FLAT_VARIANTS: Tuple[Tuple[str, Dict[str, object]], ...] = (
    ("baseline", {}),
    ("watchdog", {"watchdog": True}),
    ("decision_trace", {"decision_trace": True}),
    ("probe_score", {"probe_score": True}),
    ("prefilter_k1", {"node_prefilter_k": 1}),
    ("no_track_ctime", {"track_ctime": False}),
    ("state_pack", {"state_pack": True}),
    ("cond_policy", {"cond_policy": True}),
)

#: deterministic micro-champion for the serve-bucket pin (tier does not
#: matter — the lowered program is what is pinned)
_SERVE_CHAMPION = '''def priority_function(pod, node):
    """Constant-priority first-fit, pinned for the serve-bucket jaxpr."""
    return 1000
'''


def _micro_workload():
    """The tests/conftest.py micro recipe (2 nodes x 6 pods, padded to
    2x2x8) — duplicated here because the pinner must be runnable outside
    pytest (``cli lint`` subprocess); test_analysis pins the two copies
    against each other."""
    from fks_tpu.data.build import make_workload

    nodes = [{"node_id": "n0", "cpu_milli": 4000, "memory_mib": 8000,
              "gpus": [1000, 1000]},
             {"node_id": "n1", "cpu_milli": 2000, "memory_mib": 4000,
              "gpus": []}]
    pods = [{"pod_id": f"p{i}", "cpu_milli": 500, "memory_mib": 500,
             "num_gpu": i % 2, "gpu_milli": 300 * (i % 2),
             "creation_time": i, "duration_time": 5} for i in range(6)]
    return make_workload(nodes, pods, pad_nodes_to=2, pad_gpus_to=2,
                         pad_pods_to=8)


def _jaxpr_hash(fn, *args) -> str:
    import jax

    return hashlib.sha256(
        str(jax.make_jaxpr(fn)(*args)).encode()).hexdigest()


def compute_pins() -> Dict[str, object]:
    """Lower + hash every pinned entry point. Trace-only (make_jaxpr) —
    no XLA compiles — so the full sweep stays in seconds."""
    import jax

    jax.config.update("jax_enable_x64", True)  # match the pytest config
    import jax.numpy as jnp

    from fks_tpu.models import zoo
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig, loop_tables

    wl = _micro_workload()
    policy = zoo.first_fit()
    pins: Dict[str, str] = {}

    for name, kw in FLAT_VARIANTS:
        cfg = SimConfig(**kw)
        ktable, max_steps = loop_tables(wl, cfg)
        step = flat.build_step(wl, policy, cfg, ktable, max_steps)
        pins[f"flat_step/{name}"] = _jaxpr_hash(
            step, flat.initial_state(wl, cfg))

    # the StageProfiler is host-side only: the baseline step traced
    # INSIDE an active profiler stage must hash identically to
    # flat_step/baseline — pinned so a future profiler edit that leaks
    # into tracing (a fence, a callback, a donated buffer) trips lint
    from fks_tpu.obs.profiler import StageProfiler

    cfg = SimConfig()
    ktable, max_steps = loop_tables(wl, cfg)
    step = flat.build_step(wl, policy, cfg, ktable, max_steps)
    with StageProfiler(scope="lint") as _prof, _prof.stage("pin"):
        pins["flat_step/profiled"] = _jaxpr_hash(
            step, flat.initial_state(wl, cfg))

    # the WatermarkSampler is likewise host-side only: the baseline step
    # traced while an ENABLED sampler is live (and has just sampled) must
    # hash identically to flat_step/baseline — the disabled path is
    # covered a fortiori (NULL_SAMPLER does strictly nothing)
    from fks_tpu.obs.memory import WatermarkSampler

    with WatermarkSampler(enabled=True) as _samp:
        _samp.sample(stage="pin")
        pins["flat_step/mem_sampled"] = _jaxpr_hash(
            step, flat.initial_state(wl, cfg))

    # probe_score gates finalize (not the step program), so the flag's
    # off/on pair is pinned on the finalize lowering
    for name, kw in (("baseline", {}), ("probe_score", {"probe_score": True})):
        cfg = SimConfig(**kw)
        pins[f"flat_finalize/{name}"] = _jaxpr_hash(
            lambda s, _cfg=cfg: flat.finalize(wl, _cfg, s),
            flat.initial_state(wl, cfg))

    cfg = SimConfig()
    run = flat.make_segmented_population_run(
        wl, lambda _p, pod, nodes: policy(pod, nodes), cfg, seg_steps=8)
    params = jnp.zeros((2, 1), jnp.float32)
    bstate = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (2,) + x.shape),
        flat.initial_state(wl, cfg))
    pins["segmented_advance/baseline"] = _jaxpr_hash(
        run.advance, params, bstate)

    # the default LayoutSpec must lower the identical program as the
    # pre-LayoutSpec hard-coded behavior (obs.layout): pinned on the
    # sharded population eval over a 1-device mesh so a refactor that
    # quietly changes the default schedule (a different in_spec, an
    # extra collective) trips lint — intentional layout changes re-pin
    from fks_tpu.models import parametric
    from fks_tpu.parallel.mesh import make_sharded_eval, population_mesh

    mesh1 = population_mesh(jax.devices()[:1])
    sharded = make_sharded_eval(wl, mesh1, cfg=SimConfig(), elite_k=2,
                                engine="flat")
    params2 = parametric.init_population(jax.random.PRNGKey(0), 2)
    pins["sharded_eval/default_layout"] = _jaxpr_hash(sharded, params2)

    from fks_tpu.serve.artifact import (
        ChampionSpec, ServeEngine, ShapeEnvelope,
    )

    env = ShapeEnvelope(max_pods=16, max_batch=1, min_pod_bucket=16)
    eng = ServeEngine(ChampionSpec(code=_SERVE_CHAMPION), wl,
                      envelope=env, engine="exact")
    pb = env.pod_buckets()[0]
    pins["serve_bucket/exact_l1_p16"] = _jaxpr_hash(
        eng._make_serve_fn(pb), *eng._example_batch(1, pb))

    return {"jax": jax.__version__, "x64": True, "pins": pins}


def check_pins(manifest_path: str = PIN_MANIFEST,
               current: Optional[Dict[str, object]] = None) -> List[str]:
    """Drift messages vs the manifest (empty == green). ``current`` lets
    tests inject a precomputed sweep instead of re-lowering."""
    if not os.path.exists(manifest_path):
        return [f"{manifest_path}: pin manifest missing "
                f"(generate with `python -m fks_tpu.cli lint --write-pins`)"]
    with open(manifest_path) as f:
        want = json.load(f)
    got = current if current is not None else compute_pins()
    msgs: List[str] = []
    if want.get("jax") != got["jax"]:
        msgs.append(f"jax version changed: pins from {want.get('jax')}, "
                    f"running {got['jax']} — re-pin with --write-pins")
    pinned: Dict[str, str] = dict(want.get("pins", {}))
    for name, h in got["pins"].items():
        p = pinned.pop(name, None)
        if p is None:
            msgs.append(f"unpinned entry point {name} "
                        f"(re-pin with --write-pins)")
        elif p != h:
            msgs.append(f"jaxpr drift: {name}: pinned {p[:12]} != "
                        f"current {h[:12]} — a lowered program changed; "
                        f"re-pin only if intentional")
    for name in sorted(pinned):
        msgs.append(f"stale pin {name}: entry point no longer lowered")
    return msgs


def write_pins(manifest_path: str = PIN_MANIFEST) -> Dict[str, object]:
    """Recompute and persist the manifest; returns it."""
    man = compute_pins()
    os.makedirs(os.path.dirname(manifest_path), exist_ok=True)
    with open(manifest_path, "w") as f:
        json.dump(man, f, indent=2, sort_keys=True)
        f.write("\n")
    return man
